"""Jitted RT-1 eval policy with persistent rolling network state.

Parity source: reference `language_table/train/policy.py:32-112`
(`BCJaxPyPolicyRT1`): feed the LAST frame of the history observation,
keep `network_state` across steps, rescale (std=1, mean=0) and clip the
predicted delta to +/-0.03.

TPU-native differences: the whole control step is ONE jitted call
(`model.infer_step` does a single transformer pass instead of the
reference's tokens_per_action full passes), observations are padded to
fixed shapes so there is exactly one compile, and the network state is
donated to avoid a device copy per step (SURVEY.md §7 hard part 3 — the
10 Hz control loop budget).

The jitted step itself lives in `rt1_tpu/serve/engine.py:PolicyEngine` —
the serving layer's multi-session batched engine. `RT1EvalPolicy` is its
single-slot wrapper: same donated-state semantics, same one-compile
contract (AOT-lowered), with the eval harness's observation unpacking and
action de-normalization on top.
"""

import numpy as np

EPS = np.finfo(np.float32).eps


class RT1EvalPolicy:
    """Closed-loop policy bridging env observations to the jitted model."""

    _SESSION = "eval"

    def __init__(
        self,
        model,
        variables,
        action_mean=0.0,
        action_std=1.0,
        action_minimum=-0.03,
        action_maximum=0.03,
    ):
        from rt1_tpu.serve.engine import PolicyEngine

        self._engine = PolicyEngine(
            model,
            variables,
            max_sessions=1,
            action_mean=action_mean,
            action_std=action_std,
            action_minimum=action_minimum,
            action_maximum=action_maximum,
        )
        self.reset()

    # De-normalization now lives in the engine; read-only views keep the
    # old attribute API without a silently-ignored mutable copy.
    @property
    def action_mean(self):
        return self._engine.action_mean

    @property
    def action_std(self):
        return self._engine.action_std

    @property
    def action_minimum(self):
        return self._engine.action_minimum

    @property
    def action_maximum(self):
        return self._engine.action_maximum

    def reset(self):
        """Zero the rolling window (reference `main_rt1.py:158-160`)."""
        self._engine.reset(self._SESSION)

    @property
    def network_state(self):
        """The session's rolling state, unbatched and on host (diagnostics;
        the live state stays donated on device inside the engine)."""
        return self._engine.session_state(self._SESSION)

    def action(self, observation):
        """One control step. `observation` is the history-stacked obs dict;
        only the last frame is consumed (reference `policy.py:65-66`)."""
        output = self._engine.act(
            self._SESSION,
            {
                "image": np.asarray(
                    observation["rgb_sequence"][-1], np.float32
                ),
                "natural_language_embedding": np.asarray(
                    observation["natural_language_embedding"][-1], np.float32
                ),
            },
        )
        return output["action"]


class LavaEvalPolicy:
    """Closed-loop policy for the LAVA family (Stack B's `BCJaxPyPolicy`,
    reference `train/policy.py:114-173` commented impl + `eval/main.py:54-145`).

    Consumes the history-stacked observation (the last `sequence_length`
    frames), runs one jitted `SequenceLAVMSE` forward, and clips the MSE
    head's action. Stateless between steps — the temporal context lives in
    the history wrapper, not a rolling network state (unlike RT-1's
    `infer_step` cache).
    """

    def __init__(
        self,
        model,
        variables,
        sequence_length,
        clip_tokenizer=None,
        action_mean=0.0,
        action_std=1.0,
        action_minimum=-0.03,
        action_maximum=0.03,
    ):
        import jax

        self._model = model
        self._sequence_length = sequence_length
        self._clip_tokenizer = clip_tokenizer
        self.action_mean = action_mean
        self.action_std = action_std
        self.action_minimum = action_minimum
        self.action_maximum = action_maximum

        @jax.jit
        def _forward(observation):
            return model.apply(variables, observation, train=False)

        self._forward = _forward
        self._token_cache_key = None
        self._token_cache = None

    def reset(self):
        pass  # stateless: history comes from the wrapper

    def _tokens_for(self, instruction_bytes):
        """Tokenize once per episode: the instruction is reset-constant, and
        BPE on the 10 Hz control path would be repeated host work."""
        key = instruction_bytes.tobytes()
        if key != self._token_cache_key:
            from rt1_tpu.data.convert_rlds import decode_instruction_bytes

            text = decode_instruction_bytes(instruction_bytes)
            tokens = self._clip_tokenizer.tokenize_text(text)[0]
            self._token_cache = np.tile(
                tokens[None, None, :], (1, self._sequence_length, 1)
            )
            self._token_cache_key = key
        return self._token_cache

    def action(self, observation):
        t = self._sequence_length
        obs = {
            "rgb": observation["rgb_sequence"][-t:][None].astype(np.float32),
            "natural_language_embedding": observation[
                "natural_language_embedding"
            ][-t:][None].astype(np.float32),
        }
        if self._clip_tokenizer is not None:
            obs["instruction_tokenized_clip"] = self._tokens_for(
                observation["instruction"][-1]
            )
        action = np.asarray(self._forward(obs)[0])
        action = action * max(self.action_std, EPS) + self.action_mean
        return np.clip(action, self.action_minimum, self.action_maximum)
