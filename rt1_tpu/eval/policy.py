"""Jitted RT-1 eval policy with persistent rolling network state.

Parity source: reference `language_table/train/policy.py:32-112`
(`BCJaxPyPolicyRT1`): feed the LAST frame of the history observation,
keep `network_state` across steps, rescale (std=1, mean=0) and clip the
predicted delta to +/-0.03.

TPU-native differences: the whole control step is ONE jitted call
(`model.infer_step` does a single transformer pass instead of the
reference's tokens_per_action full passes), observations are padded to
fixed shapes so there is exactly one compile, and the network state is
donated to avoid a device copy per step (SURVEY.md §7 hard part 3 — the
10 Hz control loop budget).
"""

import functools

import numpy as np

EPS = np.finfo(np.float32).eps


class RT1EvalPolicy:
    """Closed-loop policy bridging env observations to the jitted model."""

    def __init__(
        self,
        model,
        variables,
        action_mean=0.0,
        action_std=1.0,
        action_minimum=-0.03,
        action_maximum=0.03,
    ):
        import jax

        self._model = model
        self._variables = variables
        self.action_mean = action_mean
        self.action_std = action_std
        self.action_minimum = action_minimum
        self.action_maximum = action_maximum

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _step(observation, state):
            return model.apply(
                variables, observation, state, method=model.infer_step
            )

        self._step = _step
        self.network_state = None
        self.reset()

    def reset(self):
        """Zero the rolling window (reference `main_rt1.py:158-160`)."""
        self.network_state = self._model.initial_state(batch_size=1)

    def action(self, observation):
        """One control step. `observation` is the history-stacked obs dict;
        only the last frame is consumed (reference `policy.py:65-66`)."""
        image = observation["rgb_sequence"][-1][None]  # (1, H, W, 3)
        embedding = observation["natural_language_embedding"][-1][None]
        model_obs = {
            "image": image.astype(np.float32),
            "natural_language_embedding": embedding.astype(np.float32),
        }
        output, self.network_state = self._step(model_obs, self.network_state)
        action = np.asarray(output["action"][0])
        action = action * max(self.action_std, EPS) + self.action_mean
        return np.clip(action, self.action_minimum, self.action_maximum)
