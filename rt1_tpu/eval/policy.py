"""Jitted RT-1 eval policy with persistent rolling network state.

Parity source: reference `language_table/train/policy.py:32-112`
(`BCJaxPyPolicyRT1`): feed the LAST frame of the history observation,
keep `network_state` across steps, rescale (std=1, mean=0) and clip the
predicted delta to +/-0.03.

TPU-native differences: the whole control step is ONE jitted call
(`model.infer_step` does a single transformer pass instead of the
reference's tokens_per_action full passes), observations are padded to
fixed shapes so there is exactly one compile, and the network state is
donated to avoid a device copy per step (SURVEY.md §7 hard part 3 — the
10 Hz control loop budget).
"""

import functools

import numpy as np

EPS = np.finfo(np.float32).eps


class RT1EvalPolicy:
    """Closed-loop policy bridging env observations to the jitted model."""

    def __init__(
        self,
        model,
        variables,
        action_mean=0.0,
        action_std=1.0,
        action_minimum=-0.03,
        action_maximum=0.03,
    ):
        import jax

        self._model = model
        self._variables = variables
        self.action_mean = action_mean
        self.action_std = action_std
        self.action_minimum = action_minimum
        self.action_maximum = action_maximum

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _step(observation, state):
            return model.apply(
                variables, observation, state, method=model.infer_step
            )

        self._step = _step
        self.network_state = None
        self.reset()

    def reset(self):
        """Zero the rolling window (reference `main_rt1.py:158-160`)."""
        self.network_state = self._model.initial_state(batch_size=1)

    def action(self, observation):
        """One control step. `observation` is the history-stacked obs dict;
        only the last frame is consumed (reference `policy.py:65-66`)."""
        image = observation["rgb_sequence"][-1][None]  # (1, H, W, 3)
        embedding = observation["natural_language_embedding"][-1][None]
        model_obs = {
            "image": image.astype(np.float32),
            "natural_language_embedding": embedding.astype(np.float32),
        }
        output, self.network_state = self._step(model_obs, self.network_state)
        action = np.asarray(output["action"][0])
        action = action * max(self.action_std, EPS) + self.action_mean
        return np.clip(action, self.action_minimum, self.action_maximum)


class LavaEvalPolicy:
    """Closed-loop policy for the LAVA family (Stack B's `BCJaxPyPolicy`,
    reference `train/policy.py:114-173` commented impl + `eval/main.py:54-145`).

    Consumes the history-stacked observation (the last `sequence_length`
    frames), runs one jitted `SequenceLAVMSE` forward, and clips the MSE
    head's action. Stateless between steps — the temporal context lives in
    the history wrapper, not a rolling network state (unlike RT-1's
    `infer_step` cache).
    """

    def __init__(
        self,
        model,
        variables,
        sequence_length,
        clip_tokenizer=None,
        action_mean=0.0,
        action_std=1.0,
        action_minimum=-0.03,
        action_maximum=0.03,
    ):
        import jax

        self._model = model
        self._sequence_length = sequence_length
        self._clip_tokenizer = clip_tokenizer
        self.action_mean = action_mean
        self.action_std = action_std
        self.action_minimum = action_minimum
        self.action_maximum = action_maximum

        @jax.jit
        def _forward(observation):
            return model.apply(variables, observation, train=False)

        self._forward = _forward
        self._token_cache_key = None
        self._token_cache = None

    def reset(self):
        pass  # stateless: history comes from the wrapper

    def _tokens_for(self, instruction_bytes):
        """Tokenize once per episode: the instruction is reset-constant, and
        BPE on the 10 Hz control path would be repeated host work."""
        key = instruction_bytes.tobytes()
        if key != self._token_cache_key:
            from rt1_tpu.data.convert_rlds import decode_instruction_bytes

            text = decode_instruction_bytes(instruction_bytes)
            tokens = self._clip_tokenizer.tokenize_text(text)[0]
            self._token_cache = np.tile(
                tokens[None, None, :], (1, self._sequence_length, 1)
            )
            self._token_cache_key = key
        return self._token_cache

    def action(self, observation):
        t = self._sequence_length
        obs = {
            "rgb": observation["rgb_sequence"][-t:][None].astype(np.float32),
            "natural_language_embedding": observation[
                "natural_language_embedding"
            ][-t:][None].astype(np.float32),
        }
        if self._clip_tokenizer is not None:
            obs["instruction_tokenized_clip"] = self._tokens_for(
                observation["instruction"][-1]
            )
        action = np.asarray(self._forward(obs)[0])
        action = action * max(self.action_std, EPS) + self.action_mean
        return np.clip(action, self.action_minimum, self.action_maximum)
