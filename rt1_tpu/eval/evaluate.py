"""Closed-loop evaluation protocol.

Parity source: reference `language_table/eval/main_rt1.py:100-221`:
per reward family build the wrapped env chain, validate each episode init by
requiring the RRT oracle to find a plan, roll out up to `max_episode_steps`,
count success via the sparse reward, optionally write per-episode mp4s.
"""

import collections
import os

import numpy as np

from rt1_tpu.envs import LanguageTable, blocks
from rt1_tpu.envs import rewards as rewards_module
from rt1_tpu.envs.oracles import RRTPushOracle
from rt1_tpu.eval.embedding import get_embedder
from rt1_tpu.eval.wrappers import (
    CentralCropImageWrapper,
    HistoryWrapper,
    InstructionEmbeddingWrapper,
)

# Default protocol constants (reference `main_rt1.py:118-119`).
DEFAULT_REWARDS = ("block2block",)
NUM_EVALS_PER_REWARD = 10
MAX_EPISODE_STEPS = 80


class RandomEvalPolicy:
    """Uniform actions in the eval policy's clip range — the chance
    baseline every learning proof is read against."""

    def __init__(self, seed=0, low=-0.03, high=0.03):
        self._rng = np.random.default_rng(seed)
        self._low, self._high = low, high

    def reset(self):
        pass

    def action(self, observation):
        del observation
        return self._rng.uniform(self._low, self._high, 2).astype("float32")


class OracleEvalPolicy:
    """The scripted RRT expert run under the *identical* eval protocol.

    The protocol's ceiling is far below 100%: the oracle solves only a
    fraction of oracle-validated inits within the reference's 80-step
    budget (round-3 diagnosis — demos keep only <=80-step successes, so the
    corpus is the easy subset). Trained-policy success rates must be read
    against this expert baseline, not against 1.0.

    Uses privileged simulator state (`env.compute_state()`), which the
    observation-driven policy interface doesn't carry, so `evaluate_policy`
    hands the freshly built env to any policy exposing `bind_env`. No
    explicit planning here: the oracle plans lazily inside `action` (and
    replans on instruction change), which is exactly right given that
    `run_episode` resets the policy *before* the env exists in its
    episode-final state.
    """

    def __init__(self, seed=0):
        self._seed = seed
        self._env = None
        self._oracle = None

    def bind_env(self, env):
        self._env = env
        self._oracle = RRTPushOracle(env, use_ee_planner=True, seed=self._seed)

    def reset(self):
        if self._oracle is None:
            raise RuntimeError(
                "OracleEvalPolicy requires evaluate_policy (bind_env) to "
                "attach the env before rollouts."
            )
        self._oracle.reset()

    def action(self, observation):
        del observation  # privileged: reads simulator state directly
        return np.asarray(
            self._oracle.action(self._env.compute_state()), np.float32
        )


def build_eval_env(
    reward_name="block2block",
    block_mode=blocks.BlockMode.BLOCK_8,
    seed=0,
    embedder="hash",
    target_height=256,
    target_width=456,
    random_crop_factor=0.95,
    sequence_length=6,
    backend="kinematic",
    history_keys=None,
):
    """The reference env chain (`main_rt1.py:130-142`), our wrappers.

    `history_keys` extends/overrides the stacked observation keys (e.g.
    include "instruction" for the LAVA clip-tokenizer policy).
    """
    env = LanguageTable(
        block_mode=block_mode,
        reward_factory=rewards_module.get_reward_factory(reward_name),
        seed=seed,
        backend=backend,
    )
    env = InstructionEmbeddingWrapper(env, get_embedder(embedder))
    env = CentralCropImageWrapper(
        env,
        target_height=target_height,
        target_width=target_width,
        random_crop_factor=random_crop_factor,
    )
    if history_keys is None:
        history_keys = (
            "rgb_sequence", "natural_language_embedding",
            "effector_translation", "effector_target_translation",
        )
    env = HistoryWrapper(
        env, history_length=sequence_length, keys=tuple(history_keys)
    )
    return env


def run_episode(
    env, policy, max_episode_steps=MAX_EPISODE_STEPS, collect_frames=False
):
    """One oracle-validated episode. Returns (success, steps, frames)."""
    policy.reset()
    oracle = RRTPushOracle(env, use_ee_planner=True)
    while True:
        obs = env.reset()
        if oracle.get_plan(env.compute_state()):
            break
        # Init invalid: no collision-free plan exists; re-randomize
        # (reference `main_rt1.py:163-172`).
    frames = [env.render()] if collect_frames else []
    done = False
    steps = 0
    while not done and steps < max_episode_steps:
        action = policy.action(obs)
        obs, _, done, _ = env.step(action)
        if collect_frames:
            frames.append(env.render())
        steps += 1
    return bool(env.succeeded), steps, frames


def _write_video(path_stem, frames, fps=10):
    """mp4 via imageio-ffmpeg when available, else animated GIF."""
    import imageio

    try:
        imageio.mimsave(path_stem + ".mp4", frames, fps=fps)
    except (ValueError, ImportError):
        imageio.mimsave(path_stem + ".gif", frames, duration=1000 / fps)


def evaluate_policy(
    policy,
    workdir=None,
    reward_names=DEFAULT_REWARDS,
    num_evals_per_reward=NUM_EVALS_PER_REWARD,
    max_episode_steps=MAX_EPISODE_STEPS,
    block_mode=blocks.BlockMode.BLOCK_8,
    seed=0,
    embedder="hash",
    write_videos=False,
    env_kwargs=None,
    video_tag="",
):
    """Full protocol over reward families; returns {reward: successes}.

    `video_tag` namespaces the video directory per policy identity
    (baseline name / checkpoint step): filenames alone are
    {reward}_{ep}_{success|failure}, so two different policies evaluated
    against the same workdir would otherwise interleave — and overwrite —
    each other's outcome videos (ADVICE r3).
    """
    video_dir = None
    if write_videos and workdir is not None:
        video_dir = os.path.join(
            workdir, f"videos_{video_tag}" if video_tag else "videos"
        )
        os.makedirs(video_dir, exist_ok=True)

    results = collections.defaultdict(int)
    episode_lengths = collections.defaultdict(list)
    for reward_name in reward_names:
        env = build_eval_env(
            reward_name=reward_name,
            block_mode=block_mode,
            seed=seed,
            embedder=embedder,
            **(env_kwargs or {}),
        )
        if hasattr(policy, "bind_env"):  # privileged policies (oracle)
            policy.bind_env(env)
        for ep in range(num_evals_per_reward):
            success, steps, frames = run_episode(
                env,
                policy,
                max_episode_steps=max_episode_steps,
                collect_frames=video_dir is not None,
            )
            results[reward_name] += int(success)
            episode_lengths[reward_name].append(steps)
            if video_dir is not None:
                tag = "success" if success else "failure"
                _write_video(
                    os.path.join(video_dir, f"{reward_name}_{ep}_{tag}"),
                    frames,
                )
    return {
        "successes": dict(results),
        "episodes_per_reward": num_evals_per_reward,
        "mean_episode_length": {
            k: float(np.mean(v)) for k, v in episode_lengths.items()
        },
    }
