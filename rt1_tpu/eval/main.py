"""Checkpoint evaluation CLI.

Parity source: reference `language_table/eval/main_rt1.py:204-221` (__main__:
load checkpoint, run the closed-loop protocol, print per-reward successes).

Run:
  python -m rt1_tpu.eval.main --config rt1_tpu/train/configs/tiny.py \
      --workdir /tmp/vt --rewards block2block
"""

from __future__ import annotations

import json


def load_policy_from_workdir(config, workdir):
    """Rebuild the model and restore the newest checkpoint into an eval
    policy — RT-1 (`RT1EvalPolicy`, rolling network state) or LAVA
    (`LavaEvalPolicy`, history-window forward; reference Stack B
    `eval/main.py:54-145`) per `config.model.family`."""
    from rt1_tpu.eval.policy import LavaEvalPolicy, RT1EvalPolicy
    from rt1_tpu.eval.restore import restore_variables

    # restore_variables raises FileNotFoundError on an empty workdir —
    # evaluating randomly initialized weights silently would be worse
    # than failing.
    model, variables, step, family, lava_clip = restore_variables(
        config, workdir
    )
    t = config.model.time_sequence_length
    # The history keys the policy's observation contract requires — kept
    # here, next to the policy construction, so env setup can't drift.
    history_keys = None  # evaluate.build_eval_env default
    if lava_clip:
        history_keys = (
            "rgb_sequence", "natural_language_embedding", "instruction",
            "effector_translation", "effector_target_translation",
        )
    if family == "lava":
        clip_tokenizer = None
        if lava_clip:
            from rt1_tpu.train.train import _make_clip_tokenizer

            clip_tokenizer = _make_clip_tokenizer(config)
        policy = LavaEvalPolicy(
            model, variables, sequence_length=t,
            clip_tokenizer=clip_tokenizer,
        )
    else:
        policy = RT1EvalPolicy(model, variables)
    return policy, step, history_keys


def main(argv):
    del argv
    from absl import flags

    from rt1_tpu import compilation_cache
    from rt1_tpu.envs import blocks
    from rt1_tpu.eval.evaluate import evaluate_policy

    # Persistent XLA cache (same setup as bench.py / __graft_entry__.py):
    # checkpoint evals re-run per round, but the jitted infer_step only
    # changes when the model config does — later runs skip the compile.
    compilation_cache.enable_persistent_cache()

    FLAGS = flags.FLAGS
    config = FLAGS.config
    if not FLAGS.allow_embedder_mismatch and not FLAGS.baseline:
        # (Baselines never consume instruction embeddings — and need no
        # checkpoint, so there may be no data_manifest to check against.)
        # The train CLI stamped the training data's embedder next to the
        # checkpoints; evaluating with a different provider would feed the
        # policy embeddings from a foreign domain and silently score ~random.
        from rt1_tpu.data.collect import check_embedder_compatibility

        check_embedder_compatibility(
            FLAGS.workdir,
            FLAGS.embedder,
            context="checkpoint data_manifest; pass "
            "--allow_embedder_mismatch to override",
            manifest_name="data_manifest.json",
        )
    if FLAGS.baseline == "oracle":
        from rt1_tpu.eval.evaluate import OracleEvalPolicy

        policy, step, history_keys = OracleEvalPolicy(seed=FLAGS.seed), -1, None
    elif FLAGS.baseline == "random":
        from rt1_tpu.eval.evaluate import RandomEvalPolicy

        policy, step, history_keys = RandomEvalPolicy(seed=FLAGS.seed), -1, None
    else:
        policy, step, history_keys = load_policy_from_workdir(
            config, FLAGS.workdir
        )
    env_kwargs = dict(
        target_height=config.data.height,
        target_width=config.data.width,
        random_crop_factor=config.data.crop_factor,
        sequence_length=config.model.time_sequence_length,
        backend=FLAGS.backend,
    )
    if history_keys is not None:
        env_kwargs["history_keys"] = history_keys
    results = evaluate_policy(
        policy,
        workdir=FLAGS.workdir,
        reward_names=tuple(FLAGS.rewards),
        num_evals_per_reward=FLAGS.episodes,
        max_episode_steps=FLAGS.max_steps,
        block_mode=blocks.BlockMode(FLAGS.block_mode),
        seed=FLAGS.seed,
        embedder=FLAGS.embedder,
        write_videos=FLAGS.videos,
        env_kwargs=env_kwargs,
        # Namespace videos by policy identity: a --baseline oracle run must
        # not overwrite a trained-policy eval's videos in the same workdir.
        video_tag=FLAGS.baseline if FLAGS.baseline else f"ckpt{step}",
    )
    results["checkpoint_step"] = step
    print(json.dumps(results))


if __name__ == "__main__":
    from absl import app, flags
    from ml_collections import config_flags

    config_flags.DEFINE_config_file("config", None, "Model/data config.")
    flags.DEFINE_string("workdir", "/tmp/rt1_tpu", "Checkpoint directory.")
    flags.DEFINE_multi_string("rewards", ["block2block"], "Reward families.")
    flags.DEFINE_integer("episodes", 10, "Episodes per reward.")
    flags.DEFINE_integer("max_steps", 80, "Max steps per episode.")
    flags.DEFINE_string("block_mode", "BLOCK_8", "Block variant.")
    flags.DEFINE_integer("seed", 0, "Env seed.")
    flags.DEFINE_string("embedder", "hash", "Instruction embedder spec.")
    flags.DEFINE_string(
        "backend", "kinematic",
        "Physics backend: kinematic | kinematic_arm (xArm6 IK in the "
        "loop) | auto.")
    flags.DEFINE_bool("videos", False, "Write episode videos.")
    flags.DEFINE_bool(
        "allow_embedder_mismatch", False,
        "Evaluate even if the checkpoint's data manifest records a "
        "different instruction embedder.")
    flags.DEFINE_enum(
        "baseline", "", ["", "oracle", "random"],
        "Evaluate a baseline instead of the checkpoint: 'oracle' = the "
        "scripted RRT expert under the identical protocol (the success "
        "ceiling — well below 100% inside the 80-step budget), 'random' = "
        "uniform +-0.03 actions (chance). Checkpoint restore is skipped.")
    flags.mark_flags_as_required(["config"])
    app.run(main)
