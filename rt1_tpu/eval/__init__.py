"""Closed-loop evaluation harness.

Parity source: reference `language_table/eval/main_rt1.py` (protocol:
N episodes per reward family, oracle-validated inits, <=80 steps, success =
sparse reward > 0, per-episode mp4s) and `language_table/eval/wrappers.py`
(instruction embedding + center-crop + history wrappers).
"""

from rt1_tpu.eval.embedding import (
    HashInstructionEmbedder,
    TableInstructionEmbedder,
    get_embedder,
)
from rt1_tpu.eval.evaluate import evaluate_policy
from rt1_tpu.eval.policy import RT1EvalPolicy
from rt1_tpu.eval.wrappers import (
    CentralCropImageWrapper,
    HistoryWrapper,
    InstructionEmbeddingWrapper,
)

__all__ = [
    "HashInstructionEmbedder",
    "TableInstructionEmbedder",
    "get_embedder",
    "evaluate_policy",
    "RT1EvalPolicy",
    "CentralCropImageWrapper",
    "HistoryWrapper",
    "InstructionEmbeddingWrapper",
]
