"""Instruction -> 512-d embedding providers.

The reference embeds instructions with the TF-hub Universal Sentence Encoder
both offline (`rlds_np_convert.py:48`) and at eval reset
(`language_table/common/rt1_tokenizer.py:4-8`). TF-hub and its weights are
not available in this image, so embedding is a pluggable provider:

* `TableInstructionEmbedder` — lookup into a precomputed {instruction: vec}
  table (the closed instruction set is enumerable, SURVEY.md §7.7), saved as
  an .npz. This is the production path: compute the table once with USE
  offline, ship it with the checkpoint.
* `HashInstructionEmbedder` — deterministic seeded-Gaussian embedding per
  instruction string. Self-contained: train-time conversion and eval use the
  same mapping, so policies trained in this framework are consistent end to
  end even without USE weights.
* `NgramInstructionEmbedder` — feature-hashed bag of word n-grams. Unlike the
  per-string hash, this is COMPOSITIONAL: instructions sharing words ("red
  moon", "blue cube") share feature vectors, so a policy generalizes to
  phrasings never seen in training — the property USE provides in the
  reference (`rlds_np_convert.py:48`) and the one that matters for
  closed-loop eval, where the grammar samples from thousands of strings.
* `UniversalSentenceEncoder` — the real TF-hub model, import-gated.
"""

import hashlib
import re

import numpy as np

EMBEDDING_DIM = 512


class HashInstructionEmbedder:
    """Deterministic pseudo-embedding: unit Gaussian seeded by the text hash."""

    name = "hash"

    def __init__(self, dim=EMBEDDING_DIM):
        self.dim = dim
        self._cache = {}

    def __call__(self, text):
        vec = self._cache.get(text)
        if vec is None:
            digest = hashlib.sha256(text.encode("utf-8")).digest()
            seed = int.from_bytes(digest[:8], "little") % (2**32)
            rng = np.random.RandomState(seed)
            vec = rng.randn(self.dim).astype(np.float32)
            vec /= np.linalg.norm(vec)
            self._cache[text] = vec
        return vec


class NgramInstructionEmbedder:
    """Feature-hashed word n-gram embedding (a classical HashingVectorizer
    composed with a fixed Gaussian random projection).

    Each word n-gram (n = 1..max_n) is hashed to a deterministic unit
    Gaussian in R^dim; the instruction embedding is the normalized sum.
    Unigrams carry content words, bigrams/trigrams carry enough order to
    separate "push the red moon to the blue cube" from its reverse
    ("moon_to" vs "cube_to", "to_the_blue" vs "to_the_red").
    """

    name = "ngram"

    def __init__(self, dim=EMBEDDING_DIM, max_n=3):
        self.dim = dim
        self.max_n = max_n
        self._feature_cache = {}
        self._cache = {}

    def _feature_vec(self, feat):
        vec = self._feature_cache.get(feat)
        if vec is None:
            digest = hashlib.sha256(feat.encode("utf-8")).digest()
            seed = int.from_bytes(digest[:8], "little") % (2**32)
            rng = np.random.RandomState(seed)
            vec = rng.randn(self.dim).astype(np.float32)
            vec /= np.linalg.norm(vec)
            self._feature_cache[feat] = vec
        return vec

    def __call__(self, text):
        vec = self._cache.get(text)
        if vec is None:
            words = re.findall(r"[a-z0-9]+", text.lower())
            feats = [
                "_".join(words[i : i + n])
                for n in range(1, self.max_n + 1)
                for i in range(len(words) - n + 1)
            ]
            if not feats:
                feats = ["<empty>"]
            vec = np.sum([self._feature_vec(f) for f in feats], axis=0)
            vec = (vec / np.linalg.norm(vec)).astype(np.float32)
            self._cache[text] = vec
        return vec


class TableInstructionEmbedder:
    """Precomputed lookup table (npz with 'instructions' + 'embeddings')."""

    name = "table"

    def __init__(self, path_or_table):
        if isinstance(path_or_table, dict):
            self._table = dict(path_or_table)
        else:
            with np.load(path_or_table, allow_pickle=False) as z:
                instructions = [str(s) for s in z["instructions"]]
                embeddings = np.asarray(z["embeddings"], np.float32)
            self._table = dict(zip(instructions, embeddings))

    def __call__(self, text):
        try:
            return self._table[text]
        except KeyError as e:
            raise KeyError(
                f"Instruction not in embedding table: {text!r}. Rebuild the "
                "table over rewards.generate_runtime_instructions(...) — "
                "`python -m rt1_tpu.eval.embedding --output table.npz` — "
                "which covers the samplers' full synonym/verb space."
            ) from e

    @staticmethod
    def build(instructions, embed_fn, path=None):
        """Precompute a table over an instruction list with any embed fn."""
        embeddings = np.stack([embed_fn(s) for s in instructions]).astype(
            np.float32
        )
        if path is not None:
            np.savez_compressed(
                path,
                instructions=np.array(instructions),
                embeddings=embeddings,
            )
        return TableInstructionEmbedder(
            dict(zip(instructions, embeddings))
        )


class UniversalSentenceEncoder:  # pragma: no cover - needs tf-hub weights
    """The reference's USE embedding, available when tf-hub is installed."""

    name = "use"

    def __init__(self, model_path="https://tfhub.dev/google/universal-sentence-encoder/4"):
        try:
            import tensorflow_hub as hub
        except ImportError as e:
            raise ImportError(
                "UniversalSentenceEncoder requires tensorflow_hub; use the "
                "'hash' or 'table' embedder instead."
            ) from e
        self._model = hub.load(model_path)

    def __call__(self, text):
        return np.asarray(self._model([text])[0], np.float32)


def get_embedder(spec="hash"):
    """Resolve an embedder from a spec string or pass through an instance."""
    if callable(spec):
        return spec
    if spec == "hash":
        return HashInstructionEmbedder()
    if spec == "ngram":
        return NgramInstructionEmbedder()
    if spec == "use":
        return UniversalSentenceEncoder()
    if spec.endswith(".npz"):
        return TableInstructionEmbedder(spec)
    raise ValueError(f"Unknown embedder spec: {spec}")


def build_table_cli():
    """CLI: precompute an embedding table over the full instruction grammar.

    The production path from the module docstring made concrete: enumerate
    every instruction the reward samplers can emit at runtime
    (`rewards.generate_runtime_instructions` — a superset of the
    reference-parity enumeration, covering the sampler/enumeration verb
    divergences and the corner family), embed each with the chosen
    provider, save as an .npz usable anywhere an embedder spec is accepted
    (`--embedder /path/table.npz`). The play family's BLOCK_8 generator is
    open-ended and not table-coverable — use a string-level provider
    (ngram/hash/use) for it.

      python -m rt1_tpu.eval.embedding --output /tmp/table.npz \\
          --block_mode BLOCK_4 --embedder ngram
    """
    import argparse

    from rt1_tpu.envs import blocks, rewards

    parser = argparse.ArgumentParser(description=build_table_cli.__doc__)
    parser.add_argument("--output", required=True, help="Output .npz path.")
    parser.add_argument("--block_mode", default="BLOCK_8")
    parser.add_argument(
        "--embedder", default="ngram",
        help="Provider to precompute with (hash | ngram | use).")
    args = parser.parse_args()

    mode = blocks.BlockMode(args.block_mode)
    if mode == blocks.BlockMode.N_CHOOSE_K:
        raise SystemExit(
            "N_CHOOSE_K's runtime instruction space (16-block synonym "
            "avoid-lists) is too large to table; use a string-level "
            "embedder (ngram/hash/use) instead."
        )
    instructions = rewards.generate_runtime_instructions(mode)
    embed_fn = get_embedder(args.embedder)
    TableInstructionEmbedder.build(instructions, embed_fn, path=args.output)
    print(
        f"wrote {len(instructions)} instruction embeddings "
        f"({args.embedder}, {args.block_mode}) to {args.output}"
    )


if __name__ == "__main__":
    build_table_cli()
