"""Plain-python env wrappers for the eval stack.

Parity source: reference `language_table/eval/wrappers.py` (UseTokenWrapper,
CentralCropImageWrapper) and tf-agents `HistoryWrapper(history_length,
tile_first_step_obs=True)` as configured in `eval/main_rt1.py:141-142`.
Ours wrap the gym-style (obs, reward, done, info) API directly — no
tf-agents TimeStep plumbing.
"""

import collections

import numpy as np

from rt1_tpu.envs.language_table import LanguageTable


class EnvWrapper:
    """Minimal pass-through wrapper base."""

    def __init__(self, env):
        self._env = env

    def __getattr__(self, name):
        return getattr(self._env, name)

    def reset(self):
        return self._env.reset()

    def step(self, action):
        return self._env.step(action)


class InstructionEmbeddingWrapper(EnvWrapper):
    """Embeds the byte instruction once per episode into the obs.

    Reference `UseTokenWrapper` (`eval/wrappers.py:26-61`): decode the byte
    array, embed with USE, cache for the whole episode under a dedicated key.
    Key name follows our data pipeline ('natural_language_embedding').
    """

    def __init__(self, env, embedder, key="natural_language_embedding"):
        super().__init__(env)
        self._embedder = embedder
        self._key = key
        self._current = None

    def reset(self):
        obs = self._env.reset()
        text = LanguageTable.decode_instruction(obs["instruction"])
        self._current = np.asarray(self._embedder(text), np.float32)
        obs[self._key] = self._current
        return obs

    def step(self, action):
        obs, reward, done, info = self._env.step(action)
        obs[self._key] = self._current
        return obs, reward, done, info


class CentralCropImageWrapper(EnvWrapper):
    """Deterministic center-crop + resize, the eval twin of train-time
    random cropping (reference `eval/wrappers.py:64-137`): crop the central
    `crop_factor` box (the *average* random crop) and resize to
    (height, width), float32 in [0, 1], stored as 'rgb_sequence'."""

    def __init__(self, env, target_height, target_width, random_crop_factor):
        super().__init__(env)
        self._h = target_height
        self._w = target_width
        self._factor = random_crop_factor

    def _process(self, obs):
        import cv2

        rgb = obs["rgb"]
        if self._factor is not None:
            h, w = rgb.shape[:2]
            ch, cw = int(h * self._factor), int(w * self._factor)
            top, left = (h - ch) // 2, (w - cw) // 2
            rgb = rgb[top : top + ch, left : left + cw]
        out = cv2.resize(rgb, (self._w, self._h), interpolation=cv2.INTER_LINEAR)
        obs["rgb_sequence"] = out.astype(np.float32) / 255.0
        return obs

    def reset(self):
        return self._process(self._env.reset())

    def step(self, action):
        obs, reward, done, info = self._env.step(action)
        return self._process(obs), reward, done, info


class HistoryWrapper(EnvWrapper):
    """Stacks the last `history_length` observations along a leading axis.

    tf-agents `HistoryWrapper(history_length=k, tile_first_step_obs=True)`
    semantics: at reset the first observation is tiled k times; each step
    appends and drops the oldest.
    """

    def __init__(self, env, history_length, keys=None):
        super().__init__(env)
        self._k = history_length
        self._keys = keys
        self._buffer = None

    def _stack(self):
        out = {}
        for key in self._buffer[0]:
            out[key] = np.stack([o[key] for o in self._buffer])
        return out

    def reset(self):
        obs = self._env.reset()
        if self._keys is not None:
            obs = {k: obs[k] for k in self._keys}
        self._buffer = collections.deque([obs] * self._k, maxlen=self._k)
        return self._stack()

    def step(self, action):
        obs, reward, done, info = self._env.step(action)
        if self._keys is not None:
            obs = {k: obs[k] for k in self._keys}
        self._buffer.append(obs)
        return self._stack(), reward, done, info
