"""Batched async inference service for the RT-1 policy.

The train→eval→serve third leg (docs/serving.md): `PolicyEngine` holds many
sessions' rolling network state as slots of one donated device batch and
steps them in a single AOT-compiled call; `MicroBatcher` coalesces
concurrent requests under a latency deadline with bounded-queue
backpressure; `server.py` exposes the stdlib HTTP frontend
(`python -m rt1_tpu.serve`); `metrics.py` tracks latency/occupancy/
throughput in `trainer/metrics.py` writer conventions.
"""

from rt1_tpu.serve.batcher import BusyError, DrainingError, MicroBatcher
from rt1_tpu.serve.engine import PolicyEngine, SessionError
from rt1_tpu.serve.metrics import LatencyHistogram, ServeMetrics
from rt1_tpu.serve.server import (
    ServeApp,
    install_signal_handlers,
    make_server,
    parse_observation,
)

__all__ = [
    "BusyError",
    "DrainingError",
    "MicroBatcher",
    "PolicyEngine",
    "SessionError",
    "LatencyHistogram",
    "ServeMetrics",
    "ServeApp",
    "install_signal_handlers",
    "make_server",
    "parse_observation",
]
