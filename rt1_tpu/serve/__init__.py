"""Batched async inference service for the RT-1 policy.

The train→eval→serve third leg (docs/serving.md): `PolicyEngine` holds many
sessions' rolling network state as slots of one donated device batch and
steps them through a pinned set of AOT-compiled batch-size buckets (params
are a swappable input — `swap_variables` hot-swaps checkpoints with zero
downtime; `dispatch_batch`/`collect_batch` split the step for the
double-buffered device pipeline); `ContinuousBatcher` rolls requests into
the next device step the moment they land with up to `pipeline_depth`
batches in flight, while `MicroBatcher` keeps the legacy
deadline-or-full cycle for A/B baselines — both with bounded-queue
backpressure; `server.py` exposes the stdlib HTTP frontend
(`python -m rt1_tpu.serve`); `metrics.py` tracks latency/occupancy/
throughput in `trainer/metrics.py` writer conventions.

Fleet layer (docs/serving.md "Fleet"): `router.py` routes sessions across
N replicas with affinity, tier-aware health-aware placement, bounded
failover, rolling reload, and opt-in admission control
(`AdmissionController`: per-client token buckets + a global shed
threshold — overload becomes fast 429s in the `rejected` SLO class);
`fleet.py` (`python -m rt1_tpu.serve.fleet`) spawns and supervises the
replica processes with deterministic chaos injection from
`rt1_tpu/resilience/faults.py` and, with `--min_replicas/--max_replicas`,
scales the fleet elastically from router-observed signals via the
hysteretic `autoscale.py` policy (int8 surge tier, graceful
drain-and-reap, per-dtype replica-second cost ledger — docs/serving.md
"Elastic fleet"); `stub.py` is the model-free replica double the fleet
tests and accelerator-less rehearsals run against.
"""

from rt1_tpu.serve.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    FleetSignals,
    ScaleDecision,
)
from rt1_tpu.serve.batcher import (
    BusyError,
    ContinuousBatcher,
    DrainingError,
    MicroBatcher,
)
from rt1_tpu.serve.engine import (
    PolicyEngine,
    SessionError,
    SlotContentionError,
    pow2_buckets,
)
from rt1_tpu.serve.metrics import LatencyHistogram, ServeMetrics
from rt1_tpu.serve.router import (
    AdmissionController,
    Replica,
    Router,
    make_router_server,
)
from rt1_tpu.serve.server import (
    ReloadInProgressError,
    ServeApp,
    install_signal_handlers,
    make_server,
    parse_observation,
)

__all__ = [
    "AdmissionController",
    "Autoscaler",
    "AutoscalePolicy",
    "FleetSignals",
    "ScaleDecision",
    "BusyError",
    "ContinuousBatcher",
    "DrainingError",
    "MicroBatcher",
    "PolicyEngine",
    "SessionError",
    "SlotContentionError",
    "pow2_buckets",
    "LatencyHistogram",
    "ServeMetrics",
    "Replica",
    "Router",
    "make_router_server",
    "ReloadInProgressError",
    "ServeApp",
    "install_signal_handlers",
    "make_server",
    "parse_observation",
]
