"""Batched async inference service for the RT-1 policy.

The train→eval→serve third leg (docs/serving.md): `PolicyEngine` holds many
sessions' rolling network state as slots of one donated device batch and
steps them in a single AOT-compiled call (params are a swappable input —
`swap_variables` hot-swaps checkpoints with zero downtime); `MicroBatcher`
coalesces concurrent requests under a latency deadline with bounded-queue
backpressure; `server.py` exposes the stdlib HTTP frontend
(`python -m rt1_tpu.serve`); `metrics.py` tracks latency/occupancy/
throughput in `trainer/metrics.py` writer conventions.

Fleet layer (docs/serving.md "Fleet"): `router.py` routes sessions across
N replicas with affinity, health-aware placement, bounded failover, and
rolling reload; `fleet.py` (`python -m rt1_tpu.serve.fleet`) spawns and
supervises the replica processes with deterministic chaos injection from
`rt1_tpu/resilience/faults.py`; `stub.py` is the model-free replica double
the fleet tests and accelerator-less rehearsals run against.
"""

from rt1_tpu.serve.batcher import BusyError, DrainingError, MicroBatcher
from rt1_tpu.serve.engine import PolicyEngine, SessionError
from rt1_tpu.serve.metrics import LatencyHistogram, ServeMetrics
from rt1_tpu.serve.router import Replica, Router, make_router_server
from rt1_tpu.serve.server import (
    ReloadInProgressError,
    ServeApp,
    install_signal_handlers,
    make_server,
    parse_observation,
)

__all__ = [
    "BusyError",
    "DrainingError",
    "MicroBatcher",
    "PolicyEngine",
    "SessionError",
    "LatencyHistogram",
    "ServeMetrics",
    "Replica",
    "Router",
    "make_router_server",
    "ReloadInProgressError",
    "ServeApp",
    "install_signal_handlers",
    "make_server",
    "parse_observation",
]
