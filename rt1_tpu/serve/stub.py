"""Stub serving replica: the replica HTTP contract with no model behind it.

`python -m rt1_tpu.serve.stub` speaks exactly the protocol a real replica
(`python -m rt1_tpu.serve`) speaks — the JSON ready-line on stdout, then
`/act /reset /release /reload /healthz /readyz /metrics` — but its "engine"
is a dict of per-session step counters and its "checkpoint reload" is a
sleep. That makes it the router/fleet test double: `serve/fleet.py` spawns
it with `--stub`, and the tier-1 fleet tests (spawn, kill, re-home,
rolling reload) run in seconds instead of paying a jax import plus an XLA
compile per replica. Chaos rehearsal against a laptop with no accelerator
uses the same path.

Deliberately model-free and jax-free (stdlib + the shared `ServeMetrics`):
the stub must stay cheap enough that killing and respawning it in a loop
is free, and it doubles as the executable specification of the replica
protocol — if a field moves in `serve/server.py`, the fleet tests against
the stub catch the drift.

Actions are deterministic in (session, step): ``action[i] = ((step * 7 + i)
% 13 - 6) / 300`` — enough structure for a test to assert that a re-homed
session restarted from step 0.

Tracing parity: the stub resolves the same `X-RT1-Request-Id`, stamps the
same `serve/reqtrace.py` phase ledger, emits the same `replica_act` /
`batch_wait` / `device_step` spans, keeps the same slow-request exemplar
ring behind `GET /slow_requests`, and echoes `request_id` (+ `phases`
under `"debug": true`) — so the tier-1 fleet tests prove end-to-end id
propagation without booting a model. `GET /trace` returns the process's
Chrome-trace ring (test-double introspection hook; the real replica dumps
traces to disk instead).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Tuple

from rt1_tpu.obs import prometheus as obs_prometheus
from rt1_tpu.obs import trace as obs_trace
from rt1_tpu.obs.recorder import ExemplarRing
from rt1_tpu.resilience import faults
from rt1_tpu.serve import migrate, reqtrace
from rt1_tpu.serve.metrics import ServeMetrics

IMAGE_SHAPE = (8, 14, 3)  # tiny but nonzero: loadgen reads this contract
EMBED_DIM = 16
# Advertised rolling-window length (protocol double for the real
# engine's model.time_sequence_length): part of the snapshot
# compatibility surface, so fleet tests can prove window-mismatch
# refusal with no model.
STUB_WINDOW = 6
# The stub's one-leaf snapshot schema: its whole session state is the
# step counter, shipped as a plain JSON list (`data`) so migration
# round-trips with zero numpy.
STUB_SCHEMA = [("stub_step", (), "int64")]


def stub_action(step: int, dims: int = 2):
    return [((step * 7 + i) % 13 - 6) / 300.0 for i in range(dims)]


class StubReplicaApp:
    """Session counters + lifecycle flags behind the replica contract."""

    def __init__(
        self,
        replica_id: int = 0,
        max_sessions: int = 8,
        act_delay_s: float = 0.0,
        reload_delay_s: float = 0.05,
        slow_threshold_ms: float = 0.0,
        inference_dtype: str = "f32",
        buckets=None,
        scheduler: str = "continuous",
        act_concurrency: int = 0,
        cached_inference: bool = False,
        mimic_capture: bool = False,
        session_snapshot_dir=None,
        snapshot_max_age_s: float = 600.0,
    ):
        self.replica_id = replica_id
        self.max_sessions = max_sessions
        # ISSUE 12 scheduling contract, mimicked jax-free: the stub
        # advertises its bucket ladder and scheduler, pins
        # compile_count == len(buckets), and books every (batch-of-1)
        # act into the per-bucket occupancy families — so the tier-1
        # fleet tests prove the aggregation plumbing without a model.
        self.buckets = sorted({int(b) for b in (buckets or [1])})
        self.scheduler = scheduler
        self.compile_count = len(self.buckets)
        # Advertised low-precision mode (the real replica's engine gauge);
        # lets tier-1 prove mixed-dtype fleet aggregation with no jax.
        self.inference_dtype = inference_dtype
        self.act_delay_s = act_delay_s
        # Elastic rehearsals (ISSUE 15): a real replica's device serializes
        # its batched steps, so replica count moves latency under load.
        # `act_concurrency > 0` mimics that — at most N simulated device
        # steps run at once per stub, the rest queue (and their queue wait
        # lands in the latency histogram, as it would on a real replica).
        # 0 = unlimited, the legacy fully-concurrent behavior.
        self._device_gate = (
            threading.BoundedSemaphore(act_concurrency)
            if act_concurrency > 0
            else None
        )
        self.reload_delay_s = reload_delay_s
        # KV-cached incremental decode, mimicked jax-free (protocol
        # double for the real replica's --cached_inference): the flag is
        # advertised in /healthz + the ready-line and the cache counter
        # families move the way the real engine moves them — acts count
        # as cached steps, resets/reloads/slot reclaims invalidate, a
        # reload "rebuilds" every live session's cache.
        self.cached_inference = cached_inference
        # Flywheel-capture gauge mimicry (default off — the real stub
        # captures nothing, and an unarmed stub's /metrics must stay
        # byte-identical): episode boundaries (reset/release of a known
        # session) count as written episodes, open sessions mirror the
        # session table, and the error/prune counters exist at zero so
        # the fleet fan-out renders every rt1_serve_replica_capture_*
        # family the ISSUE-18 alert rules watch.
        self.mimic_capture = mimic_capture
        self.capture_episodes = 0
        self.cache_invalidations = {"swap": 0, "reset": 0, "evict": 0}
        self.cache_cached_steps = 0
        self.cache_rebuild_steps = 0
        self.metrics = ServeMetrics()
        self.exemplars = ExemplarRing(threshold_ms=slow_threshold_ms)
        self.ready = True
        self.draining = False
        self.reloading = False
        self.reloads = 0
        self.checkpoint_step = -1
        # Durable sessions, mimicked exactly (protocol double for
        # rt1_tpu/serve/migrate.py on the real replica): the snapshot is
        # the session's step counter under the same versioned wire schema
        # — so the tier-1 fleet tests prove live migration, affinity
        # remap, crash restore, and the failed-import fallback with zero
        # jax boots. `checkpoint_generation` tracks /reload's step so a
        # test can manufacture cross-generation refusals.
        self.checkpoint_generation = -1
        self.snapshot_max_age_s = float(snapshot_max_age_s)
        self.snapshot_ring = (
            migrate.SnapshotRing(session_snapshot_dir)
            if session_snapshot_dir
            else None
        )
        self.migration_exports = 0
        self.migration_imports = 0
        self.migration_import_failures = 0
        self.migration_restores = 0
        self.migration_restore_failures = 0
        self._lock = threading.Lock()
        self._reload_lock = threading.Lock()  # one reload at a time (409)
        self._sessions: Dict[str, int] = {}  # session -> next step index

    # ------------------------------------------------------------- handlers

    def act(
        self, payload: Dict[str, Any], headers=None
    ) -> Tuple[int, Dict[str, Any]]:
        """Same request-tracing contract as the real `/act`: one resolved
        request id spanning a `replica_act` span, a phase ledger stamped
        through the (instantaneous) queue and the simulated device step,
        the exemplar ring, and the id echoed in every response."""
        phases = reqtrace.RequestPhases(
            reqtrace.request_id_from(headers, payload)
        )
        with obs_trace.span(
            "replica_act",
            request_id=phases.request_id,
            replica=self.replica_id,
        ):
            code, body = self._act_inner(payload, phases)
        body["request_id"] = phases.request_id
        phases.t_done = obs_trace.now_us()
        if code == 200:
            phases.emit_trace(payload.get("session_id"))
            outcome = "ok"
        else:
            outcome = "rejected" if code == 503 else "failed"
        breakdown = phases.phases_ms()
        self.exemplars.offer(
            breakdown["total_ms"] or 0.0,
            request_id=phases.request_id,
            session=payload.get("session_id"),
            outcome=outcome,
            error=body.get("error"),
            phases=breakdown,
        )
        if code == 200 and payload.get(reqtrace.DEBUG_KEY):
            body["phases"] = breakdown
        return code, body

    def _act_inner(
        self, payload: Dict[str, Any], phases: reqtrace.RequestPhases
    ) -> Tuple[int, Dict[str, Any]]:
        session_id = payload.get("session_id")
        if not isinstance(session_id, str) or not session_id:
            return 400, {"error": "'session_id' must be a non-empty string"}
        if "image" not in payload and "image_b64" not in payload:
            return 400, {"error": "payload needs 'image' or 'image_b64'"}
        if self.draining:
            return 503, {"error": "draining"}
        # Crash durability, mimicked: an unknown session with a ring
        # snapshot resumes mid-episode instead of restarting at step 0.
        restored = (
            self._maybe_restore(session_id)
            if self.snapshot_ring is not None
            else None
        )
        t0 = time.perf_counter()
        # The stub has no real batcher: admission, queue, and formation
        # collapse to back-to-back stamps (their deltas read ~0 ms, which
        # is the truthful value for a model-free replica).
        phases.t_enqueue = obs_trace.now_us()
        phases.t_formed = obs_trace.now_us()
        phases.t_device0 = obs_trace.now_us()
        if self._device_gate is not None:
            self._device_gate.acquire()  # simulated device: steps serialize
        try:
            with reqtrace.device_step_span(1, [phases.request_id]):
                if self.act_delay_s:
                    time.sleep(self.act_delay_s)  # inside the timer: the
                    #   latency histogram must reflect the simulated step
                    #   cost (and, gated, the queue wait for the device)
                with self._lock:
                    started = session_id not in self._sessions
                    if (
                        self.cached_inference
                        and started
                        and len(self._sessions) >= self.max_sessions
                    ):
                        # Mimic the engine's LRU slot reclaim: the oldest
                        # session's cache is invalidated for the newcomer.
                        self._sessions.pop(next(iter(self._sessions)))
                        self.cache_invalidations["evict"] += 1
                    step = self._sessions.get(session_id, 0)
                    self._sessions[session_id] = step + 1
                    if self.cached_inference:
                        self.cache_cached_steps += 1
        finally:
            if self._device_gate is not None:
                self._device_gate.release()
        phases.t_device1 = obs_trace.now_us()
        self.metrics.observe_request(time.perf_counter() - t0)
        self.metrics.observe_batch(1, queued=0)
        # Per-task serve labels, mimicked exactly (the real replica counts
        # in ServeApp.act): tier-1 fleet tests prove the task-label
        # aggregation plumbing with zero jax boots.
        task = payload.get("task")
        self.metrics.observe_task_request(
            task if isinstance(task, str) else None, new_session=started
        )
        # Smallest advertised bucket that fits a batch of 1 — the same
        # selection rule PolicyEngine.bucket_for applies.
        self.metrics.observe_bucket(
            next((b for b in self.buckets if b >= 1), 1), 1
        )
        if self.snapshot_ring is not None:
            try:
                self.snapshot_ring.save(self._build_snapshot(session_id))
            except Exception:
                pass  # durability is advisory; the answer already shipped
        body = {
            "action": stub_action(step),
            "action_tokens": [0, step % 256, (step * 3) % 256],
            "session_started": started,
            # Test hook: which process+step actually served this act.
            "replica_id": self.replica_id,
            "step_index": step,
        }
        if restored:
            body.update(restored)
        return 200, body

    def reset(self, payload) -> Tuple[int, Dict[str, Any]]:
        session_id = payload.get("session_id")
        if not isinstance(session_id, str) or not session_id:
            return 400, {"error": "'session_id' must be a non-empty string"}
        with self._lock:
            if session_id in self._sessions:
                if self.cached_inference:
                    self.cache_invalidations["reset"] += 1
                if self.mimic_capture:
                    self.capture_episodes += 1  # episode boundary
            self._sessions[session_id] = 0
            slot = list(self._sessions).index(session_id)
        if self.snapshot_ring is not None:
            self.snapshot_ring.drop(session_id)
        self.metrics.observe_reset()
        return 200, {"ok": True, "slot": slot}

    def release(self, payload) -> Tuple[int, Dict[str, Any]]:
        session_id = payload.get("session_id")
        with self._lock:
            known = self._sessions.pop(session_id, None)
            if known is not None and self.mimic_capture:
                self.capture_episodes += 1  # episode boundary
        if known is None:
            return 404, {"error": f"unknown session {session_id!r}"}
        # keep_snapshot: migration cleanup releasing the source's stale
        # copy — the shared ring file now backs the importer's session.
        if self.snapshot_ring is not None and not payload.get(
            "keep_snapshot"
        ):
            self.snapshot_ring.drop(session_id)
        return 200, {"ok": True}

    # ------------------------------------------------- durable sessions

    def _build_snapshot(self, session_id: str) -> Dict[str, Any]:
        with self._lock:
            if session_id not in self._sessions:
                raise KeyError(f"unknown session {session_id!r}")
            next_step = self._sessions[session_id]
        return {
            "version": migrate.SNAPSHOT_VERSION,
            "session_id": session_id,
            "step_index": next_step,
            "checkpoint_generation": self.checkpoint_generation,
            "window": STUB_WINDOW,
            "cached_inference": self.cached_inference,
            "schema": [
                [name, list(shape), dtype]
                for name, shape, dtype in STUB_SCHEMA
            ],
            "state": {"stub_step": {"data": [next_step]}},
        }

    def session_export(self, payload) -> Tuple[int, Dict[str, Any]]:
        session_id = payload.get("session_id")
        if not isinstance(session_id, str) or not session_id:
            return 400, {"error": "'session_id' must be a non-empty string"}
        try:
            snapshot = self._build_snapshot(session_id)
        except KeyError as exc:
            return 404, {"error": str(exc)}
        with self._lock:
            self.migration_exports += 1
        return 200, {"ok": True, "snapshot": snapshot}

    def import_session(
        self,
        snapshot: Dict[str, Any],
        session_id=None,
        _count: bool = True,
    ) -> Dict[str, Any]:
        """Validate a wire snapshot against this stub's generation /
        window / mode / schema — the same refusal surface as the real
        replica — then resume the session's step counter. Raises
        SnapshotCompatibilityError on refusal (HTTP 409)."""
        try:
            migrate.check_compatibility(
                snapshot,
                checkpoint_generation=self.checkpoint_generation,
                window=STUB_WINDOW,
                cached_inference=self.cached_inference,
                schema=STUB_SCHEMA,
            )
            step_index = int(snapshot.get("step_index", 0))
        except Exception:
            if _count:
                with self._lock:
                    self.migration_import_failures += 1
            raise
        sid = session_id or str(snapshot["session_id"])
        with self._lock:
            self._sessions[sid] = step_index
            slot = list(self._sessions).index(sid)
            if _count:
                self.migration_imports += 1
        return {"session_id": sid, "slot": slot, "step_index": step_index}

    def session_import(self, payload) -> Tuple[int, Dict[str, Any]]:
        snapshot = payload.get("snapshot")
        if not isinstance(snapshot, dict):
            return 400, {"error": "'snapshot' must be a JSON object"}
        session_id = payload.get("session_id")
        if session_id is not None and (
            not isinstance(session_id, str) or not session_id
        ):
            return 400, {"error": "'session_id' must be a non-empty "
                                  "string when given"}
        try:
            result = self.import_session(snapshot, session_id=session_id)
        except migrate.SnapshotCompatibilityError as exc:
            return 409, {"error": str(exc)}
        except (ValueError, KeyError) as exc:
            return 400, {"error": str(exc)}
        return 200, {"ok": True, **result}

    def _maybe_restore(self, session_id: str):
        with self._lock:
            if session_id in self._sessions:
                return None
        loaded = self.snapshot_ring.load(session_id)
        if loaded is None:
            return None
        snapshot, age_s = loaded
        try:
            faults.maybe_fail("session_restore", what=session_id)
            if age_s is not None and age_s > self.snapshot_max_age_s:
                raise migrate.SnapshotCompatibilityError(
                    "session snapshot for %r is %.1fs old, past the "
                    "%.1fs staleness bound" % (
                        session_id, age_s, self.snapshot_max_age_s)
                )
            result = self.import_session(
                snapshot, session_id=session_id, _count=False
            )
        except Exception:
            with self._lock:
                self.migration_restore_failures += 1
            self.snapshot_ring.drop(session_id)
            return None
        with self._lock:
            self.migration_restores += 1
        out = {
            "session_restored": True,
            "step_index_restored": result["step_index"],
        }
        if age_s is not None:
            out["snapshot_age_s"] = round(float(age_s), 3)
        return out

    def reload(self, payload) -> Tuple[int, Dict[str, Any]]:
        # Same one-reload-at-a-time contract as ServeApp._reload_lock —
        # handlers run concurrently, a bare flag check would race.
        if not self._reload_lock.acquire(blocking=False):
            return 409, {"error": "a reload is already in progress",
                         "retry": True}
        self.reloading = True
        try:
            time.sleep(self.reload_delay_s)  # the restore-and-validate cost
            self.reloads += 1
            self.checkpoint_step = payload.get("step", -1)
            # New weights, new snapshot generation — same contract as the
            # real replica: imports of old-generation snapshots are
            # refused by name after a reload lands a different step.
            self.checkpoint_generation = self.checkpoint_step
            self.metrics.observe_reload()
            caches_rebuilt = 0
            if self.cached_inference:
                with self._lock:
                    caches_rebuilt = len(self._sessions)
                self.cache_invalidations["swap"] += 1
                self.cache_rebuild_steps += caches_rebuilt
            return 200, {
                "ok": True,
                "checkpoint_step": self.checkpoint_step,
                "reloads_total": self.reloads,
                "params_swapped": 0,
                **(
                    {"caches_rebuilt": caches_rebuilt}
                    if self.cached_inference
                    else {}
                ),
            }
        finally:
            self.reloading = False
            self._reload_lock.release()

    def healthz(self) -> Dict[str, Any]:
        with self._lock:
            active = len(self._sessions)
        return {
            "status": "draining" if self.draining else "ok",
            "stub": True,
            "replica_id": self.replica_id,
            "image_shape": list(IMAGE_SHAPE),
            "embed_dim": EMBED_DIM,
            "max_sessions": self.max_sessions,
            "active_sessions": active,
            # The contract field; nothing compiles here, but the invariant
            # (compile_count == bucket count) is mimicked exactly.
            "compile_count": self.compile_count,
            "buckets": list(self.buckets),
            "scheduler": self.scheduler,
            "reloads": self.reloads,
            "inference_dtype": self.inference_dtype,
            "cached_inference": self.cached_inference,
            # Migration compatibility surface (same keys as the real
            # replica): a router compares these before shipping a
            # session snapshot here.
            "checkpoint_generation": self.checkpoint_generation,
            "window": STUB_WINDOW,
            "session_snapshots": self.snapshot_ring is not None,
        }

    def readyz(self) -> Tuple[int, Dict[str, Any]]:
        if self.draining:
            return 503, {"ready": False, "reason": "draining"}
        if self.reloading:
            return 503, {"ready": False, "reason": "reloading"}
        if not self.ready:
            return 503, {"ready": False, "reason": "warming"}
        return 200, {"ready": True}

    def _gauges(self) -> Dict[str, Any]:
        with self._lock:
            active = len(self._sessions)
        return {
            "active_sessions": active,
            "compile_count": self.compile_count,
            "bucket_count": len(self.buckets),
            "draining": int(self.draining),
            "ready": int(self.ready),
            "reloading": int(self.reloading),
            "replica_id": self.replica_id,
            "slow_exemplars": len(self.exemplars),
            "inference_dtype": self.inference_dtype,
            # Deterministic stand-in bytes: a mixed-dtype fleet test can
            # assert the per-replica gauge plumbing end to end.
            "param_bytes_device": 1000 + self.replica_id,
            "param_bytes_master": 4000,
            # KV-cache gauge mimicry (deterministic stand-in bytes): the
            # fleet tests assert the rt1_serve_cache_* plumbing end to
            # end with zero jax boots.
            "cache_enabled": int(self.cached_inference),
            "cache_bytes_per_slot": (
                2048 if self.cached_inference else 0
            ),
            "cache_cached_steps_total": self.cache_cached_steps,
            "cache_rebuild_steps_total": self.cache_rebuild_steps,
            "cache_invalidations": dict(self.cache_invalidations),
            # Durable-session counters ride only once migration is armed
            # or has happened (same conditional-spread rule as capture):
            # an unarmed stub's /metrics stays byte-identical, while any
            # fleet that migrates/restores renders every
            # rt1_serve_replica_migration_* family the alert rules watch.
            **(
                {
                    "migration_exports_total": self.migration_exports,
                    "migration_imports_total": self.migration_imports,
                    "migration_import_failures_total": (
                        self.migration_import_failures
                    ),
                    "migration_restores_total": self.migration_restores,
                    "migration_restore_failures_total": (
                        self.migration_restore_failures
                    ),
                }
                if (
                    self.snapshot_ring is not None
                    or self.migration_exports
                    or self.migration_imports
                    or self.migration_import_failures
                    or self.migration_restores
                    or self.migration_restore_failures
                )
                else {}
            ),
            # Capture-family mimicry rides ONLY behind the flag: keys
            # absent by default keeps the unarmed stub's /metrics (and
            # the fleet fan-out built from it) byte-identical.
            **(
                {
                    "capture_enabled": 1,
                    "capture_episodes_total": self.capture_episodes,
                    "capture_open_sessions": active,
                    "capture_write_errors_total": 0,
                    "capture_pruned_total": 0,
                }
                if self.mimic_capture
                else {}
            ),
        }

    def metrics_snapshot(self) -> Dict[str, Any]:
        return self.metrics.snapshot(**self._gauges())

    def metrics_prometheus(self) -> str:
        return self.metrics.prometheus_text(**self._gauges())


class _StubHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    app: StubReplicaApp = None

    def log_message(self, fmt, *args):  # noqa: D102 - stdlib hook
        pass

    def _reply(self, code, payload):
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib casing
        if self.path == "/healthz":
            self._reply(200, self.app.healthz())
        elif self.path == "/readyz":
            code, payload = self.app.readyz()
            self._reply(code, payload)
        elif self.path == "/metrics":
            if obs_prometheus.accepts_text(self.headers.get("Accept")):
                text = self.app.metrics_prometheus().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", obs_prometheus.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            else:
                self._reply(200, self.app.metrics_snapshot())
        elif self.path == "/slow_requests":
            self._reply(
                200,
                {
                    **self.app.exemplars.stats(),
                    "slow_requests": self.app.exemplars.snapshot(),
                },
            )
        elif self.path == "/trace":
            # Test-double introspection: the process's Chrome-trace ring
            # (empty when no recorder is installed). Lets a fleet test
            # assert the replica-side spans carry the propagated request
            # id without reaching into a subprocess's memory.
            tracer = obs_trace.active()
            self._reply(
                200, tracer.to_dict() if tracer else {"traceEvents": []}
            )
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802 - stdlib casing
        length = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(length)) if length else {}
        except json.JSONDecodeError as exc:
            self._reply(400, {"error": f"invalid JSON body: {exc}"})
            return
        if self.path == "/act":
            code, body = self.app.act(payload, headers=self.headers)
            self._reply(code, body)
            return
        ops = {
            "/reset": self.app.reset,
            "/release": self.app.release,
            "/reload": self.app.reload,
            "/session/export": self.app.session_export,
            "/session/import": self.app.session_import,
        }
        op = ops.get(self.path)
        if op is None:
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        code, body = op(payload)
        self._reply(code, body)


def make_stub_server(
    app: StubReplicaApp, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    handler = type("BoundStubHandler", (_StubHandler,), {"app": app})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_threads = True
    return httpd


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--replica_id", type=int, default=0)
    parser.add_argument("--max_sessions", type=int, default=8)
    parser.add_argument(
        "--startup_delay_s", type=float, default=0.0,
        help="Simulated warm-up: /readyz says 'warming' this long.")
    parser.add_argument(
        "--act_delay_s", type=float, default=0.0,
        help="Simulated device-step latency per /act.")
    parser.add_argument(
        "--act_concurrency", type=int, default=0,
        help="Serialize at most N simulated device steps at once "
             "(elastic-fleet rehearsals; 0 = unlimited).")
    parser.add_argument("--reload_delay_s", type=float, default=0.05)
    parser.add_argument(
        "--slow_threshold_ms", type=float, default=0.0,
        help="Exemplar-ring threshold (0 keeps the most recent window).")
    parser.add_argument(
        "--inference_dtype", default="f32",
        choices=["f32", "bf16", "int8"],
        help="Advertised low-precision mode (protocol double for the "
             "real replica's --inference_dtype).")
    parser.add_argument(
        "--buckets", default="1",
        help="Advertised AOT batch-size buckets (comma ints; protocol "
             "double for the real replica's --buckets; compile_count is "
             "reported as the bucket count).")
    parser.add_argument(
        "--scheduler", default="continuous",
        choices=["continuous", "cycle"],
        help="Advertised batch scheduler (protocol double only).")
    parser.add_argument(
        "--mimic_capture", action="store_true",
        help="Advertise the flywheel-capture gauge families with "
             "deterministic values (protocol double for a capture-armed "
             "replica; lets fleet tests and ops rehearsals exercise the "
             "rt1_serve_replica_capture_* fan-out with no model).")
    parser.add_argument(
        "--cached_inference", action="store_true",
        help="Advertise KV-cached incremental decode and mimic its "
             "counter families (protocol double for the real replica's "
             "--cached_inference).")
    parser.add_argument(
        "--session_snapshot_dir", default="",
        help="Durable sessions: bounded on-disk snapshot ring (protocol "
             "double for the real replica's --session_snapshot_dir; "
             "SIGKILL'd sessions restore mid-episode at re-home time).")
    parser.add_argument(
        "--snapshot_max_age_s", type=float, default=600.0,
        help="Staleness bound for crash restores (snapshots older than "
             "this start a fresh window).")
    args = parser.parse_args(argv)

    # Arm chaos sites from the environment (RT1_FAULTS): the fleet
    # supervisor exports its combined fault spec before spawning so
    # replica-side sites (session_restore) fire inside this process.
    faults.install_from("")

    # Bounded in-process trace ring so GET /trace (and the fleet tests'
    # span-propagation assertions) see real replica-side spans.
    obs_trace.enable(max_events=4096)
    app = StubReplicaApp(
        replica_id=args.replica_id,
        max_sessions=args.max_sessions,
        act_delay_s=args.act_delay_s,
        reload_delay_s=args.reload_delay_s,
        slow_threshold_ms=args.slow_threshold_ms,
        inference_dtype=args.inference_dtype,
        buckets=[int(b) for b in args.buckets.split(",") if b.strip()],
        scheduler=args.scheduler,
        act_concurrency=args.act_concurrency,
        cached_inference=args.cached_inference,
        mimic_capture=args.mimic_capture,
        session_snapshot_dir=args.session_snapshot_dir or None,
        snapshot_max_age_s=args.snapshot_max_age_s,
    )
    httpd = make_stub_server(app, host=args.host, port=args.port)
    # Graceful drain on SIGTERM — the same contract the real replica's
    # install_signal_handlers provides, so a scale-down reclaim (router
    # de-placement -> SIGTERM -> reap) finishes in-flight acts and exits
    # 0 instead of dying rc=-15 mid-response. ThreadingHTTPServer's
    # block_on_close joins the in-flight handler threads in server_close.
    import signal as _signal

    def _drain(signum, frame):  # noqa: ARG001 - signal signature
        app.draining = True
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    _signal.signal(_signal.SIGTERM, _drain)
    if args.startup_delay_s:
        app.ready = False

        def _warm():
            time.sleep(args.startup_delay_s)
            app.ready = True

        threading.Thread(target=_warm, daemon=True).start()
    # The same ready-line contract as python -m rt1_tpu.serve: the fleet
    # supervisor learns the ephemeral port from this one stdout line.
    print(
        json.dumps(
            {
                "status": "serving",
                "stub": True,
                "host": httpd.server_address[0],
                "port": httpd.server_address[1],
                "replica_id": args.replica_id,
                "checkpoint_step": -1,
                "max_sessions": args.max_sessions,
                "compile_count": app.compile_count,
                "buckets": list(app.buckets),
                "scheduler": app.scheduler,
                "inference_dtype": args.inference_dtype,
                "cached_inference": app.cached_inference,
            }
        ),
        flush=True,
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
