"""Multi-session RT-1 policy engine: one batched, AOT-compiled control step.

`RT1Policy.infer_step` keeps a rolling per-stream window (context image
tokens, action tokens, seq_idx) whose roll-vs-insert decision depends on
that stream's `seq_idx` — a scalar in the model's state pytree, so a naive
batched call would force every stream to the same phase. The engine instead
`vmap`s a single-stream step over a fixed number of **slots**: every leaf of
the engine state carries a leading slot axis (`seq_idx` becomes `(N,)`),
each session owns one slot, and sessions at different points of their
episode coexist in one device batch.

Fixed shapes, pinned compiles: the engine compiles a small set of
**batch-size buckets** (config-driven, default just `[max_sessions]`) and
every batch rides the smallest bucket that fits, so light traffic stops
paying the full-batch step cost. Each bucket executable gathers its lanes'
rows out of the full `(max_sessions, ...)` state tree by slot index, steps
them, and scatters the (active-gated) results back — padding lanes ride
distinct unused slots and write their old value back, so no batch
composition can corrupt a neighbour. Every bucket is lowered and compiled
**ahead of time** (`jax.jit(...).lower(...).compile()`), `compile_count`
is pinned at exactly `len(buckets)` for the engine lifetime, and a later
shape mismatch is a hard error, not a silent recompile. The state argument
is donated: the rolling window updates in place on device, no per-step copy.

The hot path is split into `dispatch_batch` (host work + async device
dispatch, under the lock) and `collect_batch` (the blocking device→host
fetch, outside the lock), so a serving frontend can **double-buffer**:
prepare and dispatch batch N+1 while batch N still executes — XLA orders
the two steps through the donated state dependency, and sessions riding an
in-flight step are protected from LRU eviction until their results land.
`act_batch` remains the dispatch-then-collect composition.

The model parameters are an **argument** of the compiled step, not a
closure capture — a captured array would be baked into the executable as a
constant, making a checkpoint reload a recompile. Because they are an
input (undonated, so they survive every call), `swap_variables` can
hot-swap a newly restored checkpoint between two batches: validate the new
tree in a standby host buffer (structure, shapes, dtypes, finiteness),
transfer it to the device off the request path, then atomically repoint
the engine under the lock. In-flight batches finish on the old params, the
next batch runs on the new ones, and the pinned-compile invariant
(`compile_count == len(buckets)`) holds across any number of reloads.

Host-side the engine adds the serving conveniences the eval policy never
needed: session→slot assignment with LRU reclaim, per-slot reset, action
de-normalization/clipping, and an LRU instruction-embedding cache keyed by
`ClipBPETokenizer` output so textual variants of one instruction ("Push the
red moon" / "push  the red moon") hit one cache line and skip the text
tower / embedder entirely.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from rt1_tpu.obs import trace as obs_trace

EPS = np.finfo(np.float32).eps
EMBEDDING_DIM = 512


class SessionError(RuntimeError):
    """Invalid session usage (duplicate id in one batch, unknown release)."""


class SlotContentionError(SessionError):
    """No slot can be reclaimed for a new session right now — every slot
    belongs to this batch or to a step still in flight. Transient under
    double-buffered oversubscription; the HTTP layer maps it to a
    retryable 503 (busy), never a hard failure."""


def pow2_buckets(max_sessions: int) -> List[int]:
    """The default AOT bucket ladder: powers of two up to (and always
    including) `max_sessions` — e.g. 8 -> [1, 2, 4, 8], 6 -> [1, 2, 4, 6]."""
    out = []
    b = 1
    while b < max_sessions:
        out.append(b)
        b *= 2
    out.append(max_sessions)
    return out


def normalize_buckets(buckets, max_sessions: int) -> Tuple[int, ...]:
    """Validate/canonicalize a bucket list: sorted, unique, within
    [1, max_sessions], and always topped by `max_sessions` so every legal
    batch has a bucket to ride."""
    if buckets is None:
        return (max_sessions,)
    out = sorted({int(b) for b in buckets})
    if not out or out[0] < 1 or out[-1] > max_sessions:
        raise ValueError(
            f"buckets {list(buckets)} must be within [1, {max_sessions}]"
        )
    if out[-1] != max_sessions:
        out.append(max_sessions)
    return tuple(out)


class StepHandle:
    """One in-flight batched step: everything `collect_batch` needs to
    turn the (possibly still executing) device output into per-item
    results. Created by `dispatch_batch`; single-use."""

    __slots__ = (
        "items", "errors", "slots_by_sid", "lane_by_sid", "fresh",
        "bucket", "active_count", "out", "collected",
    )

    def __init__(self, items):
        self.items = list(items)
        self.errors: List[Optional[Exception]] = [None] * len(self.items)
        self.slots_by_sid: Dict[str, int] = {}
        self.lane_by_sid: Dict[str, int] = {}
        self.fresh: set = set()
        self.bucket: Optional[int] = None  # None: nothing was dispatched
        self.active_count = 0
        self.out = None
        self.collected = False


class PolicyEngine:
    """Holds N session slots of rolling network state in one device batch."""

    def __init__(
        self,
        model,
        variables,
        *,
        max_sessions: int = 8,
        action_mean: float = 0.0,
        action_std: float = 1.0,
        action_minimum: float = -0.03,
        action_maximum: float = 0.03,
        embedder: Optional[Callable[[str], np.ndarray]] = None,
        embed_cache_size: int = 256,
        tokenizer=None,
        plan=None,
        buckets: Optional[Sequence[int]] = None,
        inference_dtype: str = "f32",
        prepare_variables: Optional[Callable[[Any], Any]] = None,
        master_variables=None,
        cached_inference: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        if max_sessions < 1:
            raise ValueError(f"max_sessions must be >= 1, got {max_sessions}")
        # AOT batch-size buckets: a batch of k active items rides the
        # smallest bucket >= k. Default is the single full-size bucket —
        # the pre-bucket padding semantics, one compile.
        self.buckets = normalize_buckets(buckets, max_sessions)
        self._jax = jax
        self._model = model
        self._plan = plan
        # Low-precision serving (rt1_tpu/models/quant.py): `variables` is
        # the SERVING tree (already cast/quantized by the restore path);
        # `prepare_variables` re-derives it from an f32 master checkpoint,
        # so `swap_variables` can requantize every standby reload; the
        # master spec (paths/shapes/dtypes of the PRE-quantization tree,
        # from `master_variables` when given) is what standby buffers are
        # validated against — a hot-swap always receives masters, never a
        # pre-quantized tree.
        self.inference_dtype = inference_dtype
        self._prepare = prepare_variables
        spec_src = (
            master_variables if master_variables is not None else variables
        )
        from jax import tree_util as _tree_util

        self._master_spec = [
            (
                _tree_util.keystr(path),
                tuple(leaf.shape),
                np.dtype(leaf.dtype),
            )
            for path, leaf in _tree_util.tree_flatten_with_path(spec_src)[0]
        ]
        # Device-resident params, passed to the compiled step as an
        # argument (see swap_variables). With a `plan`
        # (rt1_tpu/parallel/plan.py — the same declarative layout train
        # resolves from config.parallel) each leaf lands per its plan rule
        # on the plan's mesh, so a tensor-parallel serve mesh is a config
        # switch; without one, device_put is a no-op for arrays already on
        # device. Either way `swap_variables` re-places a new checkpoint
        # with each leaf's CURRENT sharding, keeping layout stable across
        # reloads.
        if plan is not None:
            self._variables = plan.place_variables(variables)
        else:
            self._variables = jax.device_put(variables)
        self.max_sessions = max_sessions
        self.action_mean = action_mean
        self.action_std = action_std
        self.action_minimum = action_minimum
        self.action_maximum = action_maximum
        self._embedder = embedder
        self._embed_cache_size = embed_cache_size
        self._embed_cache: collections.OrderedDict = collections.OrderedDict()
        self._tokenizer = tokenizer
        self.embed_calls = 0  # embedder invocations (cache misses)

        # Incremental inference (docs/serving.md "Incremental inference"):
        # with cached_inference the slot state additionally holds per-layer
        # transformer K/V caches, the compiled step is infer_step_cached
        # (one frame's tokens attend the cached prefix instead of a full-
        # window transformer pass), and every invalidation event (params
        # swap) rebuilds caches via an AOT `rebuild` program. Off (the
        # default) the state schema and the compiled program are the
        # pre-cache ones, byte for byte.
        self.cached_inference = bool(cached_inference)
        self._rebuild = None  # AOT cache-rebuild executable (cached only)
        # Invalidation bookkeeping: reset/evict zero the slot (cache gone
        # with the window); swap rebuilds every cache from the retained
        # image tokens under the new params.
        self.cache_invalidations = {"swap": 0, "reset": 0, "evict": 0}
        self.cache_cached_steps = 0   # lanes stepped through the cached program
        self.cache_rebuild_steps = 0  # per-slot full-window cache rebuilds

        # Engine state: per-slot leaves stacked on a leading slot axis. The
        # model's initial_state(batch_size=1) provides per-leaf shapes/dtypes;
        # seq_idx is its only unbatched (scalar) leaf.
        single = model.initial_state(batch_size=1, cached=self.cached_inference) \
            if self.cached_inference else model.initial_state(batch_size=1)
        self._state = jax.tree.map(
            lambda x: jnp.zeros(
                (max_sessions,) + (x.shape[1:] if x.ndim else ()), x.dtype
            ),
            single,
        )
        if plan is not None:
            # Slot state rides the same mesh as the params (replicated —
            # slots are sessions, not data shards); mixing a mesh-placed
            # param tree with default-device state would fail at dispatch.
            self._state = jax.device_put(
                self._state,
                jax.tree.map(lambda _: plan.replicated(), self._state),
            )

        # Session bookkeeping. OrderedDict doubles as the LRU order:
        # move_to_end on every act, popitem(last=False) to reclaim.
        self._lock = threading.RLock()
        self._embed_lock = threading.Lock()
        self._sessions: collections.OrderedDict = collections.OrderedDict()
        self._free: List[int] = list(range(max_sessions))
        self.evictions = 0  # LRU slot reclaims (oversubscription signal)
        # Sessions riding a dispatched-but-uncollected step: protected
        # from LRU eviction so a double-buffered frontend can never zero
        # a slot whose result is still on the wire.
        self._inflight_sessions: collections.Counter = collections.Counter()
        self.batches_in_flight = 0  # dispatched, not yet collected

        # AOT compilation of EVERY bucket happens lazily at the first act
        # (or explicit warmup()) because only then are H, W and the
        # embedding dim known. compile_count is pinned at len(buckets).
        self._compiled: Dict[int, Any] = {}
        self._compiled_obs_shapes: Optional[Dict[str, Tuple]] = None
        self.compile_count = 0
        self.reloads = 0  # successful swap_variables hot-swaps

    # ------------------------------------------------------------ embedding

    def _embed_instruction(self, text: str) -> np.ndarray:
        """Instruction text -> embedding, LRU-cached on the BPE token ids.

        Keying on `ClipBPETokenizer` output (not the raw string) folds
        case/whitespace/punctuation variants that tokenize identically into
        one entry, so a fleet of clients phrasing the same command slightly
        differently still skips the embedder after the first hit.
        """
        if self._embedder is None:
            raise SessionError(
                "request carried an 'instruction' string but the engine has "
                "no embedder; pass embedder= (rt1_tpu.eval.embedding."
                "get_embedder) or send 'natural_language_embedding' directly"
            )
        if self._tokenizer is None:
            from rt1_tpu.text.clip_bpe import default_tokenizer

            self._tokenizer = default_tokenizer()
        try:
            key = self._tokenizer.tokenize_text(text).tobytes()
        except ValueError:  # longer than the 77-token CLIP context
            key = b"raw\x00" + text.encode("utf-8")
        with self._embed_lock:
            cached = self._embed_cache.get(key)
            if cached is not None:
                self._embed_cache.move_to_end(key)
                return cached
        vec = np.asarray(self._embedder(text), np.float32)
        with self._embed_lock:
            self.embed_calls += 1
            self._embed_cache[key] = vec
            while len(self._embed_cache) > self._embed_cache_size:
                self._embed_cache.popitem(last=False)
        return vec

    def _embed_key(self, text: str) -> bytes:
        """The embed-cache key for `text` (BPE token ids, raw-bytes
        fallback past the CLIP context) — shared by the hit path and the
        migration seed/peek helpers so they can never disagree."""
        if self._tokenizer is None:
            from rt1_tpu.text.clip_bpe import default_tokenizer

            self._tokenizer = default_tokenizer()
        try:
            return self._tokenizer.tokenize_text(text).tobytes()
        except ValueError:  # longer than the 77-token CLIP context
            return b"raw\x00" + text.encode("utf-8")

    def cached_embedding(self, text: str) -> Optional[np.ndarray]:
        """The LRU-cached embedding for `text`, or None on a miss. Pure
        read for the session exporter: no embedder call, no LRU refresh —
        exporting a session must not change what gets evicted next."""
        if self._embedder is None:
            return None
        key = self._embed_key(text)
        with self._embed_lock:
            cached = self._embed_cache.get(key)
        return None if cached is None else np.asarray(cached, np.float32)

    def seed_embedding(self, text: str, vec) -> None:
        """Warm the embed LRU with a migrated (instruction, embedding)
        pair, so the imported session's next text-bearing /act skips the
        embedder exactly as it would have on its old replica. Does not
        bump `embed_calls` — nothing was computed here."""
        if self._embedder is None:
            return
        key = self._embed_key(text)
        value = np.asarray(vec, np.float32)
        with self._embed_lock:
            if key not in self._embed_cache:
                self._embed_cache[key] = value
                while len(self._embed_cache) > self._embed_cache_size:
                    self._embed_cache.popitem(last=False)

    # ------------------------------------------------------------ compile

    def bucket_for(self, active: int) -> int:
        """Deterministic bucket selection: the smallest configured bucket
        that fits `active` items (monotone in `active`)."""
        if active < 1 or active > self.max_sessions:
            raise ValueError(
                f"active={active} outside [1, {self.max_sessions}]"
            )
        for b in self.buckets:
            if b >= active:
                return b
        return self.buckets[-1]  # unreachable: buckets top at max_sessions

    def _build_step(self, obs_shapes: Dict[str, Tuple[int, ...]]):
        """Lower + compile the batched step for EVERY bucket at fixed
        per-item obs shapes — compile_count lands at len(buckets) and
        never moves again."""
        import jax
        import jax.numpy as jnp

        model = self._model
        step_method = (
            model.infer_step_cached if self.cached_inference else model.infer_step
        )

        def single_step(variables, obs, state):
            # One lane == one batch-1 infer step; vmap gives each lane its
            # own scalar seq_idx (per-slot roll phase), which the batched
            # state pytree cannot express directly. State members are
            # threaded by key so the cached path's kv_cache leaf rides the
            # same (donated) chain without per-member plumbing; seq_idx is
            # the one unbatched scalar.
            obs_b = {k: v[None] for k, v in obs.items()}
            state_b = {
                k: (v if k == "seq_idx" else v[None]) for k, v in state.items()
            }
            out, new_state = model.apply(
                variables, obs_b, state_b, method=step_method
            )
            out = jax.tree.map(lambda x: x[0], out)
            new_state = {
                k: (v if k == "seq_idx" else v[0]) for k, v in new_state.items()
            }
            return out, new_state

        def bucket_step(variables, obs, slots, active, state):
            # Params are an argument (broadcast over lanes, NOT donated) so
            # swap_variables can hand the same executable a new checkpoint.
            # `slots` are host-guaranteed DISTINCT rows of the full state
            # tree (padding lanes ride unused slots), so gather → step →
            # scatter is race-free and the donated full state updates in
            # place.
            lanes = jax.tree.map(lambda x: x[slots], state)
            out, stepped = jax.vmap(single_step, in_axes=(None, 0, 0))(
                variables, obs, lanes
            )

            def gate(new, old):
                mask = active.reshape(
                    active.shape + (1,) * (new.ndim - 1)
                )
                return jnp.where(mask, new, old)

            # Padding lanes ran on garbage; gate their old row back before
            # the scatter so their slots' rolling state does not advance.
            gated = jax.tree.map(gate, stepped, lanes)
            new_state = jax.tree.map(
                lambda full, rows: full.at[slots].set(rows), state, gated
            )
            return out, new_state

        # With a plan the lowered step carries each argument's mesh
        # placement, so XLA partitions the batched step (GSPMD) instead of
        # assuming one default device; without one the specs are placement-
        # free, exactly as before.
        repl = self._plan.replicated() if self._plan is not None else None

        def spec_of(x):
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=getattr(x, "sharding", None)
                if self._plan is not None else None,
            )

        var_spec = jax.tree.map(spec_of, self._variables)
        state_spec = jax.tree.map(spec_of, self._state)
        for b in self.buckets:
            obs_spec = {
                k: jax.ShapeDtypeStruct(
                    (b,) + tuple(shape), np.float32, sharding=repl
                )
                for k, shape in obs_shapes.items()
            }
            slots_spec = jax.ShapeDtypeStruct((b,), np.int32, sharding=repl)
            active_spec = jax.ShapeDtypeStruct((b,), np.bool_, sharding=repl)
            lowered = jax.jit(bucket_step, donate_argnums=(4,)).lower(
                var_spec, obs_spec, slots_spec, active_spec, state_spec
            )
            self._compiled[b] = lowered.compile()
            self.compile_count += 1
        self._compiled_obs_shapes = dict(obs_shapes)

        if self.cached_inference:
            # The cache invalidation primitive, AOT-compiled alongside the
            # ladder: recompute every slot's K/V rows from its retained
            # per-frame image tokens (model.rebuild_cache — one full-window
            # transformer pass per slot, no tokenizer work). One fixed
            # shape (the whole slot batch), donated state, compiled once at
            # the same moment as the buckets — `compile_count` stays pinned
            # at len(buckets) and no swap ever pays an XLA compile.

            def single_rebuild(variables, state):
                state_b = {
                    k: (v if k == "seq_idx" else v[None])
                    for k, v in state.items()
                }
                new_state = model.apply(
                    variables, state_b, method=model.rebuild_cache
                )
                return {
                    k: (v if k == "seq_idx" else v[0])
                    for k, v in new_state.items()
                }

            def rebuild_all(variables, state):
                return jax.vmap(single_rebuild, in_axes=(None, 0))(
                    variables, state
                )

            self._rebuild = jax.jit(rebuild_all, donate_argnums=(1,)).lower(
                var_spec, state_spec
            ).compile()

    def warmup(
        self,
        image_shape: Sequence[int],
        embed_dim: int = EMBEDDING_DIM,
    ) -> None:
        """AOT-compile every configured bucket before traffic arrives —
        no live request ever pays an XLA compile.

        `image_shape` is the per-item (H, W, 3); pair with
        `compilation_cache.enable_persistent_cache()` at process startup so
        even the pinned compiles are served from disk on restarts.
        """
        with self._lock:
            self._ensure_compiled(
                {
                    "image": tuple(image_shape),
                    "natural_language_embedding": (embed_dim,),
                }
            )

    def _ensure_compiled(self, obs_shapes: Dict[str, Tuple[int, ...]]):
        if not self._compiled:
            self._build_step(obs_shapes)
        elif self._compiled_obs_shapes != obs_shapes:
            raise ValueError(
                f"observation shapes {obs_shapes} do not match the compiled "
                f"step {self._compiled_obs_shapes}; the engine serves one "
                "fixed shape per process (pad/resize client-side)"
            )

    # ------------------------------------------------------------ hot-swap

    @property
    def model(self):
        """The served RT1 module (read-only — parity gates and tooling need
        its window length / token geometry, never its apply state)."""
        return self._model

    @property
    def serving_param_bytes(self) -> int:
        """Device-resident serving-tree bytes (int8 kernels + scales count
        at their quantized size — THE memory win the quant bench records)."""
        jax = self._jax
        return int(
            sum(leaf.nbytes for leaf in jax.tree.leaves(self._variables))
        )

    @property
    def cache_bytes_per_slot(self) -> int:
        """Device bytes of ONE session's K/V cache rows (0 with caching
        off) — the per-slot memory price of incremental inference that the
        `rt1_serve_cache_slot_bytes` gauge exports."""
        if not self.cached_inference:
            return 0
        kv = self._state.get("kv_cache")
        if kv is None:
            return 0
        return int(kv.nbytes // self.max_sessions)

    @property
    def master_param_bytes(self) -> int:
        """Bytes of the f32 master tree this engine restores/reloads from
        (= the serving bytes of an f32 engine of the same model)."""
        return int(
            sum(
                int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
                for _, shape, dtype in self._master_spec
            )
        )

    def swap_variables(self, new_variables) -> Dict[str, Any]:
        """Zero-downtime checkpoint hot-swap: validate `new_variables` in a
        standby host buffer, move them to the device, then atomically
        repoint the compiled step's param argument between batches.

        The expensive phases (host validation, quantization, H2D transfer)
        run OUTSIDE the engine lock, so in-flight `act_batch` calls are
        never stalled; only the final pointer swap takes the lock. Because
        the params are an undonated input of the AOT-compiled executable —
        identical shapes/dtypes are enforced here — no recompile can
        occur: the pinned-compile invariant survives any number of
        reloads. Raises ValueError (engine untouched, old params keep
        serving) on a structure/shape/dtype mismatch or a non-finite leaf.

        Validation is against the MASTER spec, not the serving tree's
        dtypes: a standby always arrives as the f32 master checkpoint
        (eval/restore.load_standby_variables contract) — under bf16/int8
        serving the engine re-runs the same deterministic
        `prepare_variables` transform quantize-at-restore used, landing on
        the exact dtypes the step was compiled for. A standby pre-cast to
        a compute/serving dtype is rejected rather than silently
        recompiled or served.
        """
        import numpy as np
        from jax import tree_util

        jax = self._jax
        standby = [
            (tree_util.keystr(path), np.asarray(leaf))
            for path, leaf in tree_util.tree_flatten_with_path(
                new_variables
            )[0]
        ]
        if [p for p, _ in standby] != [p for p, _, _ in self._master_spec]:
            raise ValueError(
                "swap_variables: parameter tree structure differs from the "
                f"master tree ({len(standby)} vs {len(self._master_spec)} "
                "leaves); hot-swap requires a checkpoint of the same model"
            )
        for (path, new), (_, shape, dtype) in zip(standby, self._master_spec):
            if tuple(new.shape) != shape or new.dtype != dtype:
                raise ValueError(
                    f"swap_variables: leaf {path!r} is "
                    f"{new.shape}/{new.dtype}, master spec "
                    f"{shape}/{dtype} — hot-swap expects the f32 master "
                    "checkpoint (a shape/dtype drift would force a "
                    "recompile); rejected"
                )
        bad = [
            path
            for path, leaf in standby
            if np.issubdtype(leaf.dtype, np.floating)
            and not np.isfinite(leaf).all()
        ]
        if bad:
            raise ValueError(
                f"swap_variables: non-finite values in {bad[:4]} "
                f"({len(bad)} leaves) — refusing to serve a corrupt "
                "checkpoint; old params stay live"
            )
        # Re-derive the serving tree from the validated masters (cast /
        # per-channel int8 quantization — deterministic, so the result's
        # dtypes match the compiled step exactly), still off the lock.
        if self._prepare is not None:
            serving = self._prepare(new_variables)
        else:
            serving = new_variables
        serving_flat = [
            (tree_util.keystr(path), leaf)
            for path, leaf in tree_util.tree_flatten_with_path(serving)[0]
        ]
        current = [
            (tree_util.keystr(path), leaf)
            for path, leaf in tree_util.tree_flatten_with_path(
                self._variables
            )[0]
        ]
        # Final no-recompile gate on the SERVING tree: the prepared tree
        # must be leaf-for-leaf compatible with what the step compiled
        # against (catches a quant-rule edit racing a live engine).
        if [p for p, _ in serving_flat] != [p for p, _ in current]:
            raise ValueError(
                "swap_variables: prepared serving tree structure differs "
                "from the compiled serving tree — quant rules changed "
                "under a live engine?"
            )
        for (path, new), (_, old) in zip(serving_flat, current):
            if tuple(new.shape) != tuple(old.shape) or new.dtype != old.dtype:
                raise ValueError(
                    f"swap_variables: prepared serving leaf {path!r} is "
                    f"{tuple(new.shape)}/{new.dtype}, compiled "
                    f"{tuple(old.shape)}/{old.dtype} — rejected to keep "
                    "the pinned-compile invariant"
                )
        # Rebuild on the SERVING treedef (a restored checkpoint may arrive
        # as plain dicts while the engine was built from a FrozenDict —
        # the AOT executable matches treedefs exactly, not just key paths)
        # and re-place each leaf with its CURRENT sharding: under a plan
        # the swapped-in checkpoint keeps the exact mesh layout the step
        # was compiled for, so the no-recompile guarantee holds for
        # sharded serving too.
        treedef = jax.tree.structure(self._variables)
        device = jax.device_put(
            jax.tree.unflatten(treedef, [leaf for _, leaf in serving_flat]),
            jax.tree.map(lambda x: x.sharding, self._variables),
        )
        jax.block_until_ready(device)  # pay the H2D cost off the swap
        caches_rebuilt = 0
        with self._lock:
            self._variables = device
            self.reloads += 1
            # A params swap makes every cached K/V row stale (it was
            # computed by the OLD transformer). Rebuild all slots' caches
            # from their retained image tokens under the new params — the
            # same full-window math infer_step would do — instead of
            # serving poisoned caches. Under the lock: the rebuild must
            # order against dispatches on the donated state chain.
            if self.cached_inference and self._rebuild is not None:
                self._state = self._rebuild(self._variables, self._state)
                self.cache_invalidations["swap"] += 1
                caches_rebuilt = len(self._sessions)
                self.cache_rebuild_steps += caches_rebuilt
        result = {
            "params_swapped": len(serving_flat),
            "param_bytes": int(
                sum(np.asarray(leaf).nbytes for _, leaf in serving_flat)
            ),
            "inference_dtype": self.inference_dtype,
        }
        if self.cached_inference:
            # Only the cached engine reports rebuilds — the windowed swap
            # response stays byte-identical to the pre-cache engine's.
            result["caches_rebuilt"] = caches_rebuilt
        return result

    # ------------------------------------------------------------ sessions

    def _slot_for(
        self, session_id: str, create: bool = True, protected: frozenset = frozenset()
    ) -> int:
        slot = self._sessions.get(session_id)
        if slot is not None:
            self._sessions.move_to_end(session_id)
            return slot
        if not create:
            raise SessionError(f"unknown session {session_id!r}")
        if self._free:
            slot = self._free.pop()
        else:
            # Reclaim the least-recently-used session's slot. The evicted
            # session is forgotten; if it comes back it starts a fresh
            # window (clients idle past the slot budget should /reset).
            # `protected` holds the current batch's session ids plus every
            # session riding a still-in-flight step — a session being
            # stepped right now must never be the eviction victim.
            victim = next(
                (s for s in self._sessions if s not in protected), None
            )
            if victim is None:
                raise SlotContentionError(
                    f"no reclaimable slot for session {session_id!r}: all "
                    f"{self.max_sessions} slots belong to this batch or an "
                    "in-flight step; retry after the step completes"
                )
            slot = self._sessions.pop(victim)
            self.evictions += 1
            if self.cached_inference:
                # The victim's K/V rows die with its window (_zero_slot
                # below) — booked as a cache invalidation so the scrape
                # plane can tell churn-driven cache loss from swaps.
                self.cache_invalidations["evict"] += 1
        self._sessions[session_id] = slot
        self._zero_slot(slot)
        return slot

    def _zero_slot(self, slot: int) -> None:
        self._state = self._jax.tree.map(
            lambda x: x.at[slot].set(0), self._state
        )

    def reset(self, session_id: str) -> int:
        """Zero a session's rolling window (allocating a slot if new).
        A new session's slot claim honors the same in-flight protection
        as /act: it must not evict a session riding a dispatched-but-
        uncollected step (retryable SlotContentionError instead)."""
        with self._lock:
            known = session_id in self._sessions
            slot = self._slot_for(
                session_id, protected=frozenset(self._inflight_sessions)
            )
            self._zero_slot(slot)
            if self.cached_inference and known:
                self.cache_invalidations["reset"] += 1
            return slot

    def release(self, session_id: str) -> None:
        """Forget a session and return its slot to the free list."""
        with self._lock:
            slot = self._sessions.pop(session_id, None)
            if slot is None:
                raise SessionError(f"unknown session {session_id!r}")
            self._free.append(slot)

    @property
    def active_sessions(self) -> int:
        with self._lock:
            return len(self._sessions)

    def session_ids(self) -> List[str]:
        with self._lock:
            return list(self._sessions)

    def session_state(self, session_id: str) -> Dict[str, np.ndarray]:
        """One session's unbatched state pytree, pulled to host (debug/tests).
        Pure read: does NOT refresh the session's LRU recency — inspecting
        a session must not change which one gets evicted next."""
        with self._lock:
            slot = self._sessions.get(session_id)
            if slot is None:
                raise SessionError(f"unknown session {session_id!r}")
            return self._jax.tree.map(
                lambda x: np.asarray(x[slot]), self._state
            )

    # ------------------------------------------------------- state migration

    @property
    def window(self) -> int:
        """The rolling context window length (model time_sequence_length)
        — part of the session-snapshot compatibility contract: a snapshot
        exported under one window length must not land in another."""
        return int(getattr(self._model, "time_sequence_length", 0))

    def state_schema(self) -> List[Tuple[str, Tuple[int, ...], str]]:
        """The per-slot network-state contract: (leaf name, per-slot shape,
        dtype) triples, sorted by name. With cached_inference this includes
        the `kv_cache` leaf — the cache defines the session state schema,
        which is exactly why the migration seam lands with it."""
        return sorted(
            (k, tuple(v.shape[1:]), str(np.dtype(v.dtype)))
            for k, v in self._state.items()
        )

    def export_session(self, session_id: str) -> Dict[str, Any]:
        """Migration seam (ROADMAP item 3): gather one slot's full rolling
        network_state — window tokens, action tokens, seq_idx, and (when
        cached) the K/V cache rows — to host, with the schema header
        `import_session` validates against. Pure read (no LRU refresh);
        the snapshot is self-describing so a peer replica can refuse a
        mismatched model before touching device memory."""
        return {
            "session_id": session_id,
            "cached_inference": self.cached_inference,
            "schema": self.state_schema(),
            "state": self.session_state(session_id),
        }

    def import_session(self, snapshot: Dict[str, Any], session_id: Optional[str] = None) -> int:
        """Restore an exported session into a slot of THIS engine.

        Validation mirrors `swap_variables`' master-spec discipline, but
        against the engine's state schema: leaf names, per-slot shapes and
        dtypes must match exactly (so a windowed snapshot cannot land in a
        cached engine and vice versa), and float leaves must be finite.
        Raises ValueError with the first mismatch (engine untouched);
        returns the slot on success. Caches travel verbatim — the intended
        use is migrating a session between replicas serving the SAME
        checkpoint (scale-down drain, re-home); after a cross-checkpoint
        move, hot-swap semantics apply and the importer should reset or
        rely on its own swap-time rebuild.
        """
        sid = session_id or snapshot.get("session_id")
        if not sid:
            raise SessionError("import_session: no session id in snapshot or argument")
        state = snapshot.get("state")
        if not isinstance(state, dict):
            raise ValueError("import_session: snapshot has no 'state' pytree")
        expected = self.state_schema()
        got = sorted(
            (k, tuple(np.asarray(v).shape), str(np.asarray(v).dtype))
            for k, v in state.items()
        )
        if [k for k, _, _ in got] != [k for k, _, _ in expected]:
            raise ValueError(
                f"import_session: state leaves {[k for k, _, _ in got]} do "
                f"not match this engine's schema "
                f"{[k for k, _, _ in expected]} — cached_inference or model "
                "mismatch between exporter and importer"
            )
        for (k, shape, dtype), (_, eshape, edtype) in zip(got, expected):
            if shape != eshape or dtype != edtype:
                raise ValueError(
                    f"import_session: leaf {k!r} is {shape}/{dtype}, this "
                    f"engine expects {eshape}/{edtype} — refusing a "
                    "mismatched session snapshot"
                )
        bad = [
            k
            for k, v in state.items()
            if np.issubdtype(np.asarray(v).dtype, np.floating)
            and not np.isfinite(np.asarray(v)).all()
        ]
        if bad:
            raise ValueError(
                f"import_session: non-finite values in {bad} — refusing a "
                "corrupt session snapshot"
            )
        with self._lock:
            slot = self._slot_for(
                sid, protected=frozenset(self._inflight_sessions)
            )
            for k, v in state.items():
                self._state[k] = self._state[k].at[slot].set(
                    np.asarray(v)
                )
            return slot

    # ------------------------------------------------------------ stepping

    def _resolve_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        image = np.asarray(obs["image"], np.float32)
        if "natural_language_embedding" in obs:
            embedding = np.asarray(
                obs["natural_language_embedding"], np.float32
            )
        else:
            embedding = self._embed_instruction(obs["instruction"])
        return {"image": image, "natural_language_embedding": embedding}

    def dispatch_batch(
        self, items: Sequence[Tuple[str, Dict[str, Any]]]
    ) -> StepHandle:
        """Phase 1 of a batched control step: resolve observations, assign
        slots, and **asynchronously dispatch** the smallest bucket that
        fits. Returns a `StepHandle` the caller hands to `collect_batch`.

        The device may still be executing when this returns — that is the
        point: a double-buffering caller dispatches batch N+1 while batch
        N's collect blocks, and XLA serializes the two steps through the
        donated state dependency. Sessions riding this handle are
        protected from LRU eviction until collected.
        """
        handle = StepHandle(items)
        if not handle.items:
            return handle
        if len(handle.items) > self.max_sessions:
            raise SessionError(
                f"batch of {len(handle.items)} exceeds max_sessions="
                f"{self.max_sessions}"
            )
        ids = [sid for sid, _ in handle.items]
        if len(set(ids)) != len(ids):
            raise SessionError(
                f"duplicate session ids in one batch: {ids} — a "
                "session's rolling state must step one obs at a time"
            )

        # Resolve (and possibly embed) OUTSIDE the lock: an embedder cache
        # miss may be an expensive text-tower forward, and gauge readers
        # (/healthz, /metrics) must not stall behind it. Per-item failures
        # become per-item error results, not a poisoned batch.
        resolved: List[Optional[Dict[str, np.ndarray]]] = []
        # obs: an embedder cache miss (full text-tower forward) shows up
        # as engine_resolve dwarfing engine_dispatch, instead of being
        # booked as device time.
        with obs_trace.span("engine_resolve", batch=len(handle.items)):
            for i, (sid, obs) in enumerate(handle.items):
                try:
                    resolved.append(self._resolve_obs(obs))
                except Exception as exc:  # noqa: BLE001 - isolated per item
                    resolved.append(None)
                    handle.errors[i] = exc

        good = [
            (i, sid, obs)
            for i, ((sid, _), obs) in enumerate(zip(handle.items, resolved))
            if obs is not None
        ]
        if not good:
            return handle
        with self._lock:
            # First use compiles every bucket (shapes come from the first
            # item); afterwards mismatches are handled per item below.
            if not self._compiled:
                self._build_step({k: v.shape for k, v in good[0][2].items()})

            # Per-item shape check BEFORE any slot is assigned: a
            # mismatched item becomes its own error result instead of
            # poisoning the batch (and allocates no slot).
            kept = []
            for i, sid, obs in good:
                bad_key = next(
                    (
                        k
                        for k, v in obs.items()
                        if v.shape != self._compiled_obs_shapes[k]
                    ),
                    None,
                )
                if bad_key is not None:
                    handle.errors[i] = ValueError(
                        f"session {sid!r} obs {bad_key!r} shape "
                        f"{obs[bad_key].shape} != compiled "
                        f"{self._compiled_obs_shapes[bad_key]}"
                    )
                else:
                    kept.append((i, sid, obs))

            # Slot assignment in one pass; eviction safety comes from the
            # `protected` set (every batchmate's id plus every session
            # riding a still-in-flight step), NOT from assignment order —
            # a newcomer's LRU reclaim skips protected sessions and fails
            # with a retryable SlotContentionError when none is left.
            # `fresh` marks sessions starting a new (zeroed) window this
            # step — surfaced in the result so a client whose session was
            # LRU-evicted can detect the silent context reset instead of
            # acting on it unaware.
            handle.fresh.update(
                sid for _, sid, _ in kept if sid not in self._sessions
            )
            batch_ids = frozenset(sid for _, sid, _ in kept)
            protected = batch_ids | frozenset(self._inflight_sessions)
            for idx, sid, _ in list(kept):
                try:
                    if sid in self._sessions:
                        handle.slots_by_sid[sid] = self._slot_for(sid)
                    else:
                        handle.slots_by_sid[sid] = self._slot_for(
                            sid, protected=protected
                        )
                except SlotContentionError as exc:
                    # Transient: every slot is riding this batch or an
                    # in-flight step. Fail THIS item retryably (503 busy
                    # upstream); its batchmates still step.
                    handle.errors[idx] = exc
                    handle.fresh.discard(sid)
                    kept = [k for k in kept if k[1] != sid]

            if not kept:
                return handle
            bucket = self.bucket_for(len(kept))
            batch_obs = {
                k: np.zeros((bucket,) + tuple(shape), np.float32)
                for k, shape in self._compiled_obs_shapes.items()
            }
            active = np.zeros((bucket,), np.bool_)
            slots = np.zeros((bucket,), np.int32)
            for lane, (_, sid, obs) in enumerate(kept):
                handle.lane_by_sid[sid] = lane
                slots[lane] = handle.slots_by_sid[sid]
                for k, v in obs.items():
                    batch_obs[k][lane] = v
                active[lane] = True
            # Padding lanes ride DISTINCT unused slots (there are always
            # enough: bucket <= max_sessions) and scatter their old row
            # back — a no-op write, so duplicate-index scatter hazards
            # cannot exist by construction.
            used = set(int(s) for s in slots[: len(kept)])
            pads = [s for s in range(self.max_sessions) if s not in used]
            for lane in range(len(kept), bucket):
                slots[lane] = pads[lane - len(kept)]

            # obs: async dispatch only — the blocking device→host fetch
            # lands in collect_batch's engine_fetch span, making the
            # double-buffer overlap visible on the trace timeline.
            with obs_trace.span(
                "engine_dispatch", active=len(kept), bucket=bucket
            ):
                handle.out, self._state = self._compiled[bucket](
                    self._variables, batch_obs, slots, active, self._state
                )
            handle.bucket = bucket
            handle.active_count = len(kept)
            if self.cached_inference:
                self.cache_cached_steps += len(kept)
            for _, sid, _ in kept:
                self._inflight_sessions[sid] += 1
            self.batches_in_flight += 1
        return handle

    def collect_batch(self, handle: StepHandle) -> List[Dict[str, Any]]:
        """Phase 2: block on the handle's device step (outside the lock)
        and build one result dict per item — the de-normalized, clipped
        `action` and the raw `action_tokens`, or `{"error": ...}` for an
        item whose observation failed to resolve/validate (a bad request
        must not poison its batchmates; its session state does not
        advance)."""
        if handle.collected:
            raise RuntimeError("StepHandle already collected")
        handle.collected = True
        actions = tokens = terminate = None
        if handle.out is not None:
            try:
                # obs: the blocking fetch — under double-buffering this
                # span overlaps the NEXT batch's engine_dispatch.
                with obs_trace.span(
                    "engine_fetch", active=handle.active_count,
                    bucket=handle.bucket,
                ):
                    actions = np.asarray(handle.out["action"])
                    tokens = np.asarray(handle.out["action_tokens"])
                    terminate = (
                        np.asarray(handle.out["terminate_episode"])
                        if "terminate_episode" in handle.out
                        else None
                    )
            finally:
                # ALWAYS release the eviction protection, even when the
                # fetch itself fails (device fault mid-step): a leaked
                # in-flight count would permanently pin its sessions'
                # slots and starve every future newcomer.
                with self._lock:
                    for sid in handle.lane_by_sid:
                        self._inflight_sessions[sid] -= 1
                        if self._inflight_sessions[sid] <= 0:
                            del self._inflight_sessions[sid]
                    self.batches_in_flight -= 1

        results: List[Dict[str, Any]] = []
        for (sid, _), error in zip(handle.items, handle.errors):
            if error is not None:
                results.append({"error": error})
                continue
            lane = handle.lane_by_sid[sid]
            action = actions[lane] * max(self.action_std, EPS) + self.action_mean
            action = np.clip(action, self.action_minimum, self.action_maximum)
            result = {
                "action": action.astype(np.float32),
                "action_tokens": tokens[lane],
                "session_started": sid in handle.fresh,
            }
            if terminate is not None:
                result["terminate_episode"] = int(terminate[lane])
            results.append(result)
        return results

    def act_batch(
        self, items: Sequence[Tuple[str, Dict[str, Any]]]
    ) -> List[Dict[str, Any]]:
        """Run one batched control step for `items` = [(session_id, obs)]:
        `dispatch_batch` then `collect_batch`, back to back. Each obs
        carries `image` (H, W, 3) float32 in [0, 1] plus either
        `natural_language_embedding` (D,) or `instruction` (str). Session
        ids must be unique within one batch (the batcher's `batch_key`
        guarantees it in the serving path)."""
        return self.collect_batch(self.dispatch_batch(items))

    def act(self, session_id: str, obs: Dict[str, Any]) -> Dict[str, Any]:
        """Single-session convenience wrapper over `act_batch`; re-raises
        the item's error (act_batch's markers exist for batchmates)."""
        result = self.act_batch([(session_id, obs)])[0]
        if "error" in result:
            raise result["error"]
        return result
