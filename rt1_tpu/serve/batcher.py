"""Async batching queues for the serving frontend.

The closed-loop eval policy is batch-size-1 by construction (one env, 10 Hz);
a serving process instead sees many concurrent sessions whose `act` requests
arrive independently. Two schedulers share the admission/backpressure/drain
contract:

* `MicroBatcher` — the original **cycle** scheduler: hold each request
  briefly (up to `max_batch` requests or a `max_delay_s` deadline), hand
  the whole batch to `process_fn`, block until it completes, repeat. The
  device idles during every host phase, and a request that misses a batch
  waits a full cycle.
* `ContinuousBatcher` — the **rolling** scheduler (the Orca/vLLM
  continuous-batching shape, scaled to a fixed-slot policy engine): a
  batch dispatches the moment requests and a pipeline slot are available,
  with up to `pipeline_depth` batches in flight. While device step N runs,
  the batcher keeps admitting; any request present when a slot frees rides
  step N+1 immediately — no deadline wait at low occupancy (p50 = step
  time), and occupancy emerges naturally at high load because requests
  accumulate exactly while the device is busy. Per-key exclusion extends
  across in-flight batches: a key riding step N cannot join step N+1 until
  N's results land, preserving per-session FIFO under overlap.

  One anti-fragmentation refinement: closed-loop clients re-arrive in a
  burst right after their batch completes, and dispatching at the first
  arrival would shatter that burst into 1-2-request steps. The scheduler
  therefore coalesces toward **observed demand**: it tracks the distinct
  keys (sessions) seen in the last `demand_window_s` and holds a dispatch
  while fewer requests are eligible than that demand suggests. The hold
  is bounded by `coalesce_delay_s` when the device is idle, and by the
  in-flight step's completion when one is running (its riders rearrive
  at that moment and re-form the herd — capping that wait would
  re-fragment it). A lone client's demand is 1, so low-occupancy
  dispatch stays immediate; under steady 8-client load the target is 8
  and each step re-forms the full batch within the arrival jitter, not
  the deadline. Demand decays with the window, so a ramp-down pays at
  most a few bounded waits before the target follows.

Design points:

* **Bounded queue + explicit backpressure.** `submit` raises `BusyError`
  the moment the queue holds `max_queue` requests; the HTTP layer maps it
  to 503 so load sheds at admission instead of growing unbounded latency.
* **Per-key exclusion.** `batch_key` (the session id in production) keeps
  two requests for the same key out of one batch: a session's rolling
  network state must see its observations in order, one step at a time.
  The second request stays queued for the next flush; requests for other
  sessions may overtake it, but per-key FIFO order is preserved.
* **Drain, not abort.** `drain()` rejects new submissions (`DrainingError`)
  but flushes everything already admitted before returning — SIGTERM never
  drops an accepted request.

`process_fn` runs in a thread-pool executor (one worker for the cycle
scheduler, `pipeline_depth` for the continuous one) so the (blocking,
device-bound) batched step never stalls the event loop; requests keep
accumulating for the next batch while the current one computes.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
from typing import Any, Callable, Dict, List, Optional, Sequence


class BusyError(RuntimeError):
    """Queue is at `max_queue`; the caller should shed load (HTTP 503)."""


class DrainingError(RuntimeError):
    """The batcher is shutting down and no longer admits requests."""


class _BatcherBase:
    """Admission/backpressure/drain scaffolding shared by both
    schedulers: the bounded pending queue, `submit` (BusyError /
    DrainingError / cancelled-future semantics), executor lifecycle, and
    batch formation routed through one `_excluded` eligibility rule."""

    _WORKERS = 1

    def __init__(
        self,
        process_fn: Callable[[List[Any]], Sequence[Any]],
        *,
        max_batch: int = 8,
        max_queue: int = 64,
        batch_key: Optional[Callable[[Any], Any]] = None,
        metrics: Optional[Any] = None,
        on_batch: Optional[Callable[[List[Any]], None]] = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._process_fn = process_fn
        self._max_batch = max_batch
        self._max_queue = max_queue
        self._batch_key = batch_key
        self._metrics = metrics
        # Called on the loop thread with each formed batch's items before
        # dispatch — the serve app stamps per-request "popped into a
        # batch" timestamps here (queue wait ends, batch formation
        # begins). Exceptions are the caller's bug; keep it trivial.
        self._on_batch = on_batch
        self._pending: collections.deque = collections.deque()
        self._draining = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._event: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._executor: Optional[concurrent.futures.ThreadPoolExecutor] = None

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        """Bind to the running loop and start the scheduler."""
        if self._task is not None:
            raise RuntimeError(f"{type(self).__name__} already started")
        self._loop = asyncio.get_running_loop()
        self._event = asyncio.Event()
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._WORKERS, thread_name_prefix="rt1-serve-step"
        )
        self._task = self._loop.create_task(self._run())

    async def drain(self) -> None:
        """Stop admitting; flush every queued request (and, under the
        continuous scheduler, every batch in flight), then stop."""
        self._draining = True
        if self._event is not None:
            self._event.set()
        if self._task is not None:
            await self._task
            self._task = None
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None

    @property
    def draining(self) -> bool:
        return self._draining

    def qsize(self) -> int:
        return len(self._pending)

    # ------------------------------------------------------------ admission

    async def submit(self, item: Any) -> Any:
        """Queue one request; resolves with its element of `process_fn`'s
        result list. Raises `BusyError`/`DrainingError` at admission."""
        if self._draining:
            raise DrainingError("batcher is draining; not accepting requests")
        if self._task is None:
            raise RuntimeError(
                f"{type(self).__name__} not started (call start())"
            )
        if len(self._pending) >= self._max_queue:
            if self._metrics is not None:
                self._metrics.observe_rejected()
            raise BusyError(
                f"queue full ({self._max_queue} pending requests)"
            )
        self._note_submit(item)
        future = self._loop.create_future()
        self._pending.append((item, future))
        self._event.set()
        try:
            return await future
        except asyncio.CancelledError:
            # Abandoned caller (e.g. the HTTP bridge timed out and
            # cancelled us): cancel the queued request so _take_batch
            # drops it instead of stepping state for a dead client.
            future.cancel()
            raise

    def _note_submit(self, item: Any) -> None:
        """Subclass hook: bookkeeping per admitted request."""

    # ------------------------------------------------------------ formation

    def _excluded(self, item: Any, batch_keys: set) -> bool:
        """THE eligibility rule: an item cannot board when its key is
        already in the forming batch (a session's rolling state steps one
        obs at a time). The continuous scheduler extends it to keys
        riding in-flight batches."""
        if self._batch_key is None:
            return False
        return self._batch_key(item) in batch_keys

    def _take_batch(self) -> List[Any]:
        """Pop up to `max_batch` requests, skipping (not reordering
        within) `_excluded` ones — they wait for a later flush."""
        taken = []
        keys = set()
        i = 0
        while i < len(self._pending) and len(taken) < self._max_batch:
            item, future = self._pending[i]
            if future.done():  # cancelled by an abandoned submitter
                del self._pending[i]
                continue
            if self._excluded(item, keys):
                i += 1
                continue
            del self._pending[i]
            if self._batch_key is not None:
                keys.add(self._batch_key(item))
            taken.append((item, future))
        return taken

    async def _run(self) -> None:
        raise NotImplementedError


class MicroBatcher(_BatcherBase):
    """Collects concurrent requests into deadline- or size-triggered
    batches (the legacy cycle scheduler; one batch in flight, ever).

    One executor worker: the device executes batches serially anyway, and
    a single thread keeps engine state access naturally ordered."""

    def __init__(
        self,
        process_fn: Callable[[List[Any]], Sequence[Any]],
        *,
        max_batch: int = 8,
        max_delay_s: float = 0.010,
        max_queue: int = 64,
        batch_key: Optional[Callable[[Any], Any]] = None,
        metrics: Optional[Any] = None,
        on_batch: Optional[Callable[[List[Any]], None]] = None,
    ):
        super().__init__(
            process_fn,
            max_batch=max_batch,
            max_queue=max_queue,
            batch_key=batch_key,
            metrics=metrics,
            on_batch=on_batch,
        )
        self._max_delay_s = max_delay_s

    async def _wait_for_deadline(self) -> None:
        deadline = self._loop.time() + self._max_delay_s
        while len(self._pending) < self._max_batch and not self._draining:
            remaining = deadline - self._loop.time()
            if remaining <= 0:
                return
            self._event.clear()
            if len(self._pending) >= self._max_batch or self._draining:
                return  # recheck after clear: a submit may have raced
            try:
                await asyncio.wait_for(self._event.wait(), remaining)
            except asyncio.TimeoutError:
                return

    async def _run(self) -> None:
        while True:
            if not self._pending:
                if self._draining:
                    return
                self._event.clear()
                if self._pending or self._draining:
                    continue
                await self._event.wait()
                continue
            if not self._draining and len(self._pending) < self._max_batch:
                await self._wait_for_deadline()
            batch = self._take_batch()
            if not batch:
                continue
            if self._on_batch is not None:
                self._on_batch([item for item, _ in batch])
            if self._metrics is not None:
                # Scheduler-metric parity with ContinuousBatcher: the
                # cycle scheduler runs exactly one batch in flight and
                # nothing ever joins mid-cycle — emit those facts (1 and
                # +0) explicitly so the joined_mid_cycle/in-flight
                # dashboard families read identically under
                # `--scheduler cycle` instead of going silent.
                self._metrics.observe_batch(
                    len(batch),
                    queued=len(self._pending),
                    in_flight=1,
                    joined_mid_cycle=0,
                )
            items = [item for item, _ in batch]
            try:
                results = await self._loop.run_in_executor(
                    self._executor, self._process_fn, items
                )
                if len(results) != len(items):
                    raise RuntimeError(
                        f"process_fn returned {len(results)} results for "
                        f"{len(items)} requests"
                    )
            except Exception as exc:  # noqa: BLE001 - forwarded per-request
                for _, future in batch:
                    if not future.done():
                        future.set_exception(exc)
                continue
            finally:
                if self._metrics is not None:
                    self._metrics.observe_inflight(0)
            for (_, future), result in zip(batch, results):
                if not future.done():
                    future.set_result(result)


class ContinuousBatcher(_BatcherBase):
    """Rolling scheduler: dispatch as soon as work and a pipeline slot
    exist, keep up to `pipeline_depth` batches in flight.

    Same `submit`/`drain` surface and backpressure semantics as
    `MicroBatcher` (the shared `_BatcherBase` scaffolding), but no fixed
    deadline: batching emerges from device busy time plus the
    demand-aware coalesce. `process_fn` should split its device work
    into async-dispatch + blocking-collect (PolicyEngine.dispatch_batch/
    collect_batch) so two executor workers actually overlap — the
    executor has `pipeline_depth` workers for exactly that reason.
    """

    def __init__(
        self,
        process_fn: Callable[[List[Any]], Sequence[Any]],
        *,
        max_batch: int = 8,
        max_queue: int = 64,
        pipeline_depth: int = 2,
        coalesce_delay_s: float = 0.010,
        demand_window_s: float = 1.0,
        batch_key: Optional[Callable[[Any], Any]] = None,
        metrics: Optional[Any] = None,
        on_batch: Optional[Callable[[List[Any]], None]] = None,
    ):
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}"
            )
        super().__init__(
            process_fn,
            max_batch=max_batch,
            max_queue=max_queue,
            batch_key=batch_key,
            metrics=metrics,
            on_batch=on_batch,
        )
        self._pipeline_depth = pipeline_depth
        # pipeline_depth executor workers: while one blocks collecting
        # step N, another dispatches step N+1 under the engine lock.
        self._WORKERS = pipeline_depth
        self._coalesce_s = max(coalesce_delay_s, 0.0)
        self._inflight: set = set()          # asyncio.Tasks of live batches
        self._inflight_keys: collections.Counter = collections.Counter()
        # Demand estimator: distinct keys (sessions) with a request in
        # the last `demand_window_s` — the expected occupancy of the next
        # step. Below it, dispatch waits up to `coalesce_delay_s` for the
        # rearrival burst to re-form instead of shattering it. Keyless
        # traffic has no session identity to anticipate, so it dispatches
        # greedily (demand == what is already pending).
        self._demand_window_s = max(demand_window_s, 0.0)
        self._key_seen: Dict[Any, float] = {}
        self._coalesce_until: Optional[float] = None

    def inflight(self) -> int:
        return len(self._inflight)

    def _note_submit(self, item: Any) -> None:
        if self._batch_key is not None:
            self._key_seen[self._batch_key(item)] = self._loop.time()

    # ------------------------------------------------------------ scheduler

    def _demand(self) -> int:
        """Expected occupancy of the next step: distinct keys seen within
        the demand window. Always prunes the window state, so it stays
        bounded by live traffic. Keyless: just what is pending — no
        identity means no rearrival anticipation, so dispatch greedily
        and let the pipeline overlap."""
        if self._batch_key is None:
            return len(self._pending)
        horizon = self._loop.time() - self._demand_window_s
        stale = [k for k, t in self._key_seen.items() if t < horizon]
        for k in stale:
            del self._key_seen[k]
        return len(self._key_seen)

    def _excluded(self, item: Any, batch_keys: set) -> bool:
        """Extends the base rule across overlap: a key riding an
        in-flight batch cannot board the next one (per-key FIFO)."""
        if self._batch_key is None:
            return False
        key = self._batch_key(item)
        return key in batch_keys or key in self._inflight_keys

    def _eligible_count(self, limit: Optional[int] = None) -> int:
        """How many pending requests `_take_batch` could take right now
        (same `_excluded` rule, read-only). Bounded at `limit` (default
        `max_batch`) — beyond a full batch the exact count never changes
        a scheduling decision."""
        bound = self._max_batch if limit is None else limit
        n = 0
        keys = set()
        for item, future in self._pending:
            if future.done():
                continue
            if self._excluded(item, keys):
                continue
            if self._batch_key is not None:
                keys.add(self._batch_key(item))
            n += 1
            if n >= bound:
                return n
        return n

    def _coalescing(self) -> bool:
        """True while dispatch should hold for the rearrival burst: fewer
        eligible requests than observed demand suggests, and the bounded
        coalesce window has not expired. Draining never waits."""
        # Demand first, unconditionally: _demand() also prunes the key
        # window, so the estimator state stays bounded even when
        # coalescing is disabled (coalesce_delay_s=0) or draining.
        # Keyless traffic never coalesces — no session identity means no
        # rearrival burst to anticipate; dispatch greedily.
        target = max(1, min(self._demand(), self._max_batch))
        if (
            self._batch_key is None
            or self._draining
            or self._coalesce_s <= 0.0
        ):
            self._coalesce_until = None
            return False
        eligible = self._eligible_count()
        if eligible == 0:
            self._coalesce_until = None
            return False
        if eligible >= target:
            self._coalesce_until = None
            return False
        if self._inflight:
            # Below target with a batch still in flight: its riders
            # rearrive the moment it completes, so dispatching now would
            # shatter the herd into sub-target steps that perpetuate
            # themselves (each fragment's completion re-fragments the
            # next). Hold — completion sets the event and re-evaluates;
            # a genuinely oversubscribed queue reaches `target` eligible
            # and still boards mid-cycle above.
            self._coalesce_until = None
            return True
        now = self._loop.time()
        if self._coalesce_until is None:
            self._coalesce_until = now + self._coalesce_s
            # Wake the scheduler at the deadline even with no new events.
            self._loop.call_at(self._coalesce_until, self._event.set)
        return now < self._coalesce_until

    def _dispatch_ready(self) -> None:
        """Form and launch batches while work and pipeline slots exist."""
        while len(self._inflight) < self._pipeline_depth:
            if self._coalescing():
                return
            batch = self._take_batch()
            if not batch:
                return
            self._coalesce_until = None
            if self._on_batch is not None:
                self._on_batch([item for item, _ in batch])
            if self._batch_key is not None:
                for item, _ in batch:
                    self._inflight_keys[self._batch_key(item)] += 1
            overlapped = len(self._inflight) > 0
            task = self._loop.create_task(
                self._run_batch(batch, overlapped)
            )
            self._inflight.add(task)
            task.add_done_callback(self._on_batch_done)
            if self._metrics is not None:
                self._metrics.observe_batch(
                    len(batch),
                    queued=len(self._pending),
                    in_flight=len(self._inflight),
                    joined_mid_cycle=len(batch) if overlapped else 0,
                )

    def _on_batch_done(self, task: asyncio.Task) -> None:
        self._inflight.discard(task)
        if self._metrics is not None:
            self._metrics.observe_inflight(len(self._inflight))
        self._event.set()  # a slot freed; maybe dispatch the next batch

    async def _run_batch(self, batch, overlapped: bool) -> None:
        items = [item for item, _ in batch]
        try:
            results = await self._loop.run_in_executor(
                self._executor, self._process_fn, items
            )
            if len(results) != len(items):
                raise RuntimeError(
                    f"process_fn returned {len(results)} results for "
                    f"{len(items)} requests"
                )
        except Exception as exc:  # noqa: BLE001 - forwarded per-request
            for _, future in batch:
                if not future.done():
                    future.set_exception(exc)
            return
        finally:
            if self._batch_key is not None:
                for item, _ in batch:
                    key = self._batch_key(item)
                    self._inflight_keys[key] -= 1
                    if self._inflight_keys[key] <= 0:
                        del self._inflight_keys[key]
        for (_, future), result in zip(batch, results):
            if not future.done():
                future.set_result(result)

    def _has_eligible(self) -> bool:
        """True if `_take_batch` would take at least one request now."""
        return self._eligible_count(limit=1) > 0

    async def _run(self) -> None:
        while True:
            self._dispatch_ready()
            if (
                self._draining
                and not self._pending
                and not self._inflight
            ):
                return
            self._event.clear()
            # Recheck after clear: a submit/completion may have raced the
            # clear, and drain must not sleep past the last completion.
            # While coalescing, sleep — the call_at timer (or the next
            # submit) wakes the scheduler, never a hot spin.
            if (
                self._has_eligible()
                and len(self._inflight) < self._pipeline_depth
                and not self._coalescing()
            ):
                continue
            if (
                self._draining
                and not self._pending
                and not self._inflight
            ):
                return
            await self._event.wait()
