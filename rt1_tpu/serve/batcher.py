"""Async micro-batching queue for the serving frontend.

The closed-loop eval policy is batch-size-1 by construction (one env, 10 Hz);
a serving process instead sees many concurrent sessions whose `act` requests
arrive independently. Running them one-by-one leaves the accelerator idle
between dispatches, so the batcher holds each request briefly — up to
`max_batch` requests or a `max_delay_s` deadline, whichever comes first — and
hands the whole batch to `process_fn` in one call (the continuous-batching
scheduler shape of Orca/vLLM-style servers, scaled down to a fixed-slot
policy engine).

Design points:

* **Bounded queue + explicit backpressure.** `submit` raises `BusyError`
  the moment the queue holds `max_queue` requests; the HTTP layer maps it
  to 503 so load sheds at admission instead of growing unbounded latency.
* **Per-key exclusion.** `batch_key` (the session id in production) keeps
  two requests for the same key out of one batch: a session's rolling
  network state must see its observations in order, one step at a time.
  The second request stays queued for the next flush; requests for other
  sessions may overtake it, but per-key FIFO order is preserved.
* **Drain, not abort.** `drain()` rejects new submissions (`DrainingError`)
  but flushes everything already admitted before returning — SIGTERM never
  drops an accepted request.

`process_fn` runs in a single-worker executor so the (blocking, device-
bound) batched step never stalls the event loop; requests keep accumulating
for the next batch while the current one computes.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
from typing import Any, Callable, List, Optional, Sequence


class BusyError(RuntimeError):
    """Queue is at `max_queue`; the caller should shed load (HTTP 503)."""


class DrainingError(RuntimeError):
    """The batcher is shutting down and no longer admits requests."""


class MicroBatcher:
    """Collects concurrent requests into deadline- or size-triggered batches."""

    def __init__(
        self,
        process_fn: Callable[[List[Any]], Sequence[Any]],
        *,
        max_batch: int = 8,
        max_delay_s: float = 0.010,
        max_queue: int = 64,
        batch_key: Optional[Callable[[Any], Any]] = None,
        metrics: Optional[Any] = None,
        on_batch: Optional[Callable[[List[Any]], None]] = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._process_fn = process_fn
        self._max_batch = max_batch
        self._max_delay_s = max_delay_s
        self._max_queue = max_queue
        self._batch_key = batch_key
        self._metrics = metrics
        # Called on the loop thread with each formed batch's items before
        # dispatch — the serve app stamps per-request "popped into a
        # batch" timestamps here (queue wait ends, batch formation
        # begins). Exceptions are the caller's bug; keep it trivial.
        self._on_batch = on_batch
        self._pending: collections.deque = collections.deque()
        self._draining = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._event: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._executor: Optional[concurrent.futures.ThreadPoolExecutor] = None

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        """Bind to the running loop and start the flush worker."""
        if self._task is not None:
            raise RuntimeError("MicroBatcher already started")
        self._loop = asyncio.get_running_loop()
        self._event = asyncio.Event()
        # One worker: the device executes batches serially anyway, and a
        # single thread keeps engine state access naturally ordered.
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="rt1-serve-step"
        )
        self._task = self._loop.create_task(self._run())

    async def drain(self) -> None:
        """Stop admitting, flush every queued request, stop the worker."""
        self._draining = True
        if self._event is not None:
            self._event.set()
        if self._task is not None:
            await self._task
            self._task = None
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None

    @property
    def draining(self) -> bool:
        return self._draining

    def qsize(self) -> int:
        return len(self._pending)

    # ------------------------------------------------------------ admission

    async def submit(self, item: Any) -> Any:
        """Queue one request; resolves with its element of `process_fn`'s
        result list. Raises `BusyError`/`DrainingError` at admission."""
        if self._draining:
            raise DrainingError("batcher is draining; not accepting requests")
        if self._task is None:
            raise RuntimeError("MicroBatcher not started (call start())")
        if len(self._pending) >= self._max_queue:
            if self._metrics is not None:
                self._metrics.observe_rejected()
            raise BusyError(
                f"queue full ({self._max_queue} pending requests)"
            )
        future = self._loop.create_future()
        self._pending.append((item, future))
        self._event.set()
        try:
            return await future
        except asyncio.CancelledError:
            # Abandoned caller (e.g. the HTTP bridge timed out and
            # cancelled us): cancel the queued request so _take_batch
            # drops it instead of stepping state for a dead client.
            future.cancel()
            raise

    # ------------------------------------------------------------ worker

    def _take_batch(self) -> List[Any]:
        """Pop up to `max_batch` requests, skipping (not reordering within)
        duplicate `batch_key`s — they wait for the next flush."""
        taken = []
        keys = set()
        i = 0
        while i < len(self._pending) and len(taken) < self._max_batch:
            item, future = self._pending[i]
            if future.done():  # cancelled by an abandoned submitter
                del self._pending[i]
                continue
            key = self._batch_key(item) if self._batch_key else None
            if key is not None and key in keys:
                i += 1
                continue
            del self._pending[i]
            if key is not None:
                keys.add(key)
            taken.append((item, future))
        return taken

    async def _wait_for_deadline(self) -> None:
        deadline = self._loop.time() + self._max_delay_s
        while len(self._pending) < self._max_batch and not self._draining:
            remaining = deadline - self._loop.time()
            if remaining <= 0:
                return
            self._event.clear()
            if len(self._pending) >= self._max_batch or self._draining:
                return  # recheck after clear: a submit may have raced
            try:
                await asyncio.wait_for(self._event.wait(), remaining)
            except asyncio.TimeoutError:
                return

    async def _run(self) -> None:
        while True:
            if not self._pending:
                if self._draining:
                    return
                self._event.clear()
                if self._pending or self._draining:
                    continue
                await self._event.wait()
                continue
            if not self._draining and len(self._pending) < self._max_batch:
                await self._wait_for_deadline()
            batch = self._take_batch()
            if not batch:
                continue
            if self._on_batch is not None:
                self._on_batch([item for item, _ in batch])
            if self._metrics is not None:
                self._metrics.observe_batch(
                    len(batch), queued=len(self._pending)
                )
            items = [item for item, _ in batch]
            try:
                results = await self._loop.run_in_executor(
                    self._executor, self._process_fn, items
                )
                if len(results) != len(items):
                    raise RuntimeError(
                        f"process_fn returned {len(results)} results for "
                        f"{len(items)} requests"
                    )
            except Exception as exc:  # noqa: BLE001 - forwarded per-request
                for _, future in batch:
                    if not future.done():
                        future.set_exception(exc)
                continue
            for (_, future), result in zip(batch, results):
                if not future.done():
                    future.set_result(result)
