"""Per-request tracing: ids, phase stamps, and linked serve spans.

A request that crosses router -> replica -> micro-batcher -> engine used
to leave four uncorrelated log lines. This module gives every request one
id and one phase ledger:

* **Request id.** Assigned at the first hop that sees the request (the
  router, or the replica for direct traffic); clients may supply their
  own via the ``X-RT1-Request-Id`` header and get it echoed back in the
  ``request_id`` response field, so a client-side timeout can be joined
  against server-side spans after the fact.
* **Phase stamps.** `RequestPhases` collects one `obs.trace.now_us()`
  timestamp per boundary as the request moves through admission ->
  batcher queue -> batch formation -> device step -> serialization.
  Stamping is unconditional (a perf_counter read per boundary — the
  loadgen A/B pins the cost under the 2% tracing budget); *emission* into
  the Chrome-trace ring and the `/act` response stays gated.
* **Linked spans.** `emit_trace` turns the stamps into `batch_wait` and
  `device_step` complete-events on the shared host timeline, each tagged
  with the request id — the same id the router's `router_route` and the
  replica's `replica_act` spans carry, so Perfetto shows one request's
  whole path across processes and threads.

The phase breakdown is returned in the `/act` response (``"phases"``)
when the request carries ``"debug": true``, and recorded in the bounded
slow-request `ExemplarRing` (`rt1_tpu/obs/recorder.py`) regardless, so
the exemplars a post-mortem needs exist even when no client asked for
debug output. Stdlib + obs only — the router process stays clu/TF-free.
"""

from __future__ import annotations

import re
import uuid
from typing import Any, Dict, Optional

from rt1_tpu.obs import trace as obs_trace

REQUEST_ID_HEADER = "X-RT1-Request-Id"
# Payload key (not a header) so the flag rides through the router's
# verbatim /act forwarding with zero router logic.
DEBUG_KEY = "debug"


def new_request_id() -> str:
    """16 hex chars: unique enough for correlating a fleet's in-flight
    window, short enough to read in a trace viewer."""
    return uuid.uuid4().hex[:16]


# The id is client-controlled input that the router re-emits as an HTTP
# header on the replica hop: anything outside this set (CR/LF, non-latin-1)
# would make urllib reject the forwarded request, which the router cannot
# tell apart from a replica transport death.
_RID_SAFE = re.compile(r"[^A-Za-z0-9._:-]")


def request_id_from(headers, payload: Optional[Dict[str, Any]] = None) -> str:
    """Resolve the request id: client header wins, else payload field
    (the router forwards it in-band), else mint one."""
    rid = headers.get(REQUEST_ID_HEADER) if headers is not None else None
    if not rid and payload:
        rid = payload.get("request_id")
    if isinstance(rid, str) and rid:
        rid = _RID_SAFE.sub("", rid)[:64]
    if not isinstance(rid, str) or not rid:
        rid = new_request_id()
    return rid


class RequestPhases:
    """One request's boundary timestamps on the shared trace clock (µs).

    Stamps are written by three different threads (HTTP handler, batcher
    loop, executor) but each field has exactly one writer and is read
    only after the request's future resolves — no lock needed.
    """

    __slots__ = (
        "request_id",
        "t_admit",     # handler: request parsed, about to submit
        "t_enqueue",   # handler: submitted to the batcher queue
        "t_formed",    # batcher loop: popped into a batch
        "t_device0",   # executor: device step begins
        "t_device1",   # executor: device step ends
        "t_done",      # handler: response about to serialize
    )

    def __init__(self, request_id: Optional[str] = None):
        self.request_id = request_id or new_request_id()
        now = obs_trace.now_us()
        self.t_admit = now
        self.t_enqueue = None
        self.t_formed = None
        self.t_device0 = None
        self.t_device1 = None
        self.t_done = None

    @staticmethod
    def _delta_ms(a: Optional[float], b: Optional[float]) -> Optional[float]:
        if a is None or b is None:
            return None
        return round(max(b - a, 0.0) / 1e3, 3)

    def phases_ms(self) -> Dict[str, Any]:
        """The per-request breakdown: where this request's milliseconds
        went inside the replica. Phases a failed request never reached
        are None, not fabricated zeros."""
        end = self.t_done if self.t_done is not None else obs_trace.now_us()
        return {
            "request_id": self.request_id,
            # admission: JSON parse + validation + the draining check.
            "admission_ms": self._delta_ms(self.t_admit, self.t_enqueue),
            # queue wait: sat in the batcher's pending deque.
            "queue_wait_ms": self._delta_ms(self.t_enqueue, self.t_formed),
            # batch formation: popped -> executor start (handoff +
            # numpy batch assembly begins).
            "batch_form_ms": self._delta_ms(self.t_formed, self.t_device0),
            # device: the batched engine step this request rode in.
            "device_ms": self._delta_ms(self.t_device0, self.t_device1),
            # serialization: result future resolution -> response write.
            "serialize_ms": self._delta_ms(self.t_device1, end),
            "total_ms": self._delta_ms(self.t_admit, end),
        }

    def emit_trace(self, session_id: Optional[str] = None) -> None:
        """Write the cross-thread phases as linked complete-events (no-op
        when no trace recorder is installed)."""
        if not obs_trace.enabled():
            return
        if self.t_enqueue is not None and self.t_formed is not None:
            obs_trace.complete(
                "batch_wait",
                self.t_enqueue,
                self.t_formed - self.t_enqueue,
                request_id=self.request_id,
                **({"session": session_id} if session_id else {}),
            )


def device_step_span(batch_size: int, request_ids) -> Any:
    """`device_step` span around one batched engine step, tagged with
    every rider's request id (ISSUE-named; replaces the anonymous
    serve_batch_step span)."""
    return obs_trace.span(
        "device_step", batch=batch_size, request_ids=list(request_ids)
    )
