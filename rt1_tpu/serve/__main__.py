"""Serving entry point: `python -m rt1_tpu.serve`.

Run (tiny smoke config, random weights, CPU):

  JAX_PLATFORMS=cpu python -m rt1_tpu.serve \
      --config rt1_tpu/train/configs/tiny.py --random_init --port 8321

Run (trained checkpoint):

  python -m rt1_tpu.serve --config rt1_tpu/train/configs/language_table.py \
      --workdir /tmp/vt --port 8321 --embedder ngram

Prints one JSON ready-line (`{"status": "serving", "port": ...}`) once the
batched step is AOT-compiled and the socket is bound, then serves until
SIGTERM/SIGINT, which drains accepted requests before exiting.
"""

from __future__ import annotations

import json
import sys
import threading
import time


def _start_checkpoint_watcher(
    app, workdir: str, interval_s: float, served_step
) -> None:
    """Poll the checkpoint dir; hot-swap when a newer step appears.

    The push-free alternative to `POST /reload`: a training job saving into
    `workdir` rolls onto the fleet automatically. `served_step` is the step
    the server actually restored at boot — seeding from a fresh
    latest_step() here would silently skip a checkpoint saved during the
    (long) jax boot + AOT warmup. Daemon thread, restore errors
    logged-and-skipped (the old params keep serving; the next poll
    retries).
    """
    import os

    from rt1_tpu.trainer.checkpoints import latest_step

    directory = os.path.join(os.path.abspath(workdir), "checkpoints")

    def _watch():
        served = served_step if served_step is not None and served_step >= 0 \
            else None
        while True:
            time.sleep(interval_s)
            try:
                newest = latest_step(directory)
                if newest is not None and (served is None or newest > served):
                    result = app.reload(newest)
                    served = result["checkpoint_step"]
                    print(
                        json.dumps({"status": "reloaded", **result}),
                        flush=True,
                    )
            except Exception as exc:  # noqa: BLE001 - keep watching
                print(
                    json.dumps(
                        {"status": "reload_failed", "error": str(exc)}
                    ),
                    flush=True,
                )

    threading.Thread(
        target=_watch, name="rt1-serve-ckpt-watcher", daemon=True
    ).start()


def main(argv):
    del argv
    from absl import flags

    # Persistent XLA cache BEFORE any jax compile: the serving process's
    # single batched-step compile is served from disk on restarts.
    from rt1_tpu import compilation_cache

    compilation_cache.enable_persistent_cache()

    from rt1_tpu.eval.embedding import get_embedder
    from rt1_tpu.eval.restore import build_serve_engine
    from rt1_tpu.serve.server import (
        ServeApp,
        install_signal_handlers,
        make_server,
    )

    FLAGS = flags.FLAGS
    config = FLAGS.config
    if not FLAGS.random_init and not FLAGS.allow_embedder_mismatch:
        # Same guard as eval/main.py: serving a checkpoint with a different
        # instruction embedder than it was trained on would hand the policy
        # foreign-domain embeddings and score ~random with 200 OK.
        from rt1_tpu.data.collect import check_embedder_compatibility

        check_embedder_compatibility(
            FLAGS.workdir,
            FLAGS.embedder,
            context="checkpoint data_manifest; pass "
            "--allow_embedder_mismatch to override",
            manifest_name="data_manifest.json",
        )
    from rt1_tpu.serve.engine import pow2_buckets

    if FLAGS.buckets.strip() == "auto":
        buckets = pow2_buckets(FLAGS.max_sessions)
    else:
        buckets = [
            int(b) for b in FLAGS.buckets.split(",") if b.strip()
        ] or None
    embedder = get_embedder(FLAGS.embedder)
    engine, step = build_serve_engine(
        config,
        workdir=None if FLAGS.random_init else FLAGS.workdir,
        inference_dtype=FLAGS.inference_dtype,
        max_sessions=FLAGS.max_sessions,
        buckets=buckets,
        embedder=embedder,
        cached_inference=FLAGS.cached_inference,
    )

    # Standby restore source for zero-downtime hot-swap (POST /reload and
    # the optional watcher). Random-init replicas rebuild the same
    # deterministic init — the chaos harness hot-swaps bit-identical
    # params to prove the mechanism without a trained checkpoint.
    from rt1_tpu.eval.restore import load_standby_variables

    reload_workdir = None if FLAGS.random_init else FLAGS.workdir

    def reload_fn(reload_step):
        return load_standby_variables(
            config, workdir=reload_workdir, step=reload_step
        )

    # Data-flywheel episode capture (rt1_tpu/flywheel/): opt-in via
    # --capture_dir. The sink shares the engine's embedder instance so
    # text-only clients still yield embeddable episodes without loading
    # the embedding model a second time.
    capture = None
    if FLAGS.capture_dir:
        from rt1_tpu.flywheel import EpisodeCaptureSink

        capture = EpisodeCaptureSink(
            FLAGS.capture_dir,
            max_episodes=FLAGS.capture_max_episodes,
            max_steps=FLAGS.capture_max_steps,
            embed_fn=embedder,
        )

    # Arm chaos sites from the environment (RT1_FAULTS): the fleet
    # supervisor exports its combined fault spec before spawning so
    # replica-side sites (session_restore) fire inside this process.
    from rt1_tpu.resilience import faults

    faults.install_from("")

    app = ServeApp(
        engine,
        image_shape=(config.data.height, config.data.width, 3),
        max_batch=FLAGS.max_batch or None,
        max_delay_s=FLAGS.max_delay_ms / 1e3,
        max_queue=FLAGS.max_queue,
        scheduler=FLAGS.scheduler,
        pipeline_depth=FLAGS.pipeline_depth,
        request_timeout_s=FLAGS.request_timeout_s,
        replica_id=FLAGS.replica_id,
        reload_fn=reload_fn,
        slow_threshold_ms=FLAGS.slow_threshold_ms,
        exemplar_path=FLAGS.exemplar_path or None,
        capture=capture,
        checkpoint_step=step if step is not None else -1,
        session_snapshot_dir=FLAGS.session_snapshot_dir or None,
        snapshot_max_age_s=FLAGS.snapshot_max_age_s,
        snapshot_every=FLAGS.session_snapshot_every,
    )
    app.start(warmup=True)
    if FLAGS.watch_checkpoints_s > 0 and not FLAGS.random_init:
        _start_checkpoint_watcher(app, FLAGS.workdir,
                                  FLAGS.watch_checkpoints_s,
                                  served_step=step)
    httpd = make_server(app, host=FLAGS.host, port=FLAGS.port,
                        quiet=not FLAGS.verbose)
    install_signal_handlers(app, httpd)
    print(
        json.dumps(
            {
                "status": "serving",
                "host": httpd.server_address[0],
                "port": httpd.server_address[1],
                "replica_id": FLAGS.replica_id,
                "checkpoint_step": step,
                "max_sessions": engine.max_sessions,
                "compile_count": engine.compile_count,
                "buckets": [int(b) for b in engine.buckets],
                "scheduler": FLAGS.scheduler,
                "inference_dtype": engine.inference_dtype,
                "cached_inference": engine.cached_inference,
                "param_bytes_device": engine.serving_param_bytes,
            }
        ),
        flush=True,
    )
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        if not app.draining:
            app.drain()
    print(json.dumps({"status": "drained", **app.metrics_snapshot()}),
          flush=True)
    return 0


if __name__ == "__main__":
    from absl import app as absl_app
    from absl import flags
    from ml_collections import config_flags

    config_flags.DEFINE_config_file("config", None, "Model/data config.")
    flags.DEFINE_string("workdir", "/tmp/rt1_tpu", "Checkpoint directory.")
    flags.DEFINE_bool(
        "random_init", False,
        "Serve randomly initialized weights (smoke tests / load generation; "
        "no checkpoint needed).")
    flags.DEFINE_string("host", "127.0.0.1", "Bind address.")
    flags.DEFINE_integer("port", 8321, "Bind port (0 = ephemeral).")
    flags.DEFINE_integer(
        "max_sessions", 8,
        "Concurrent session slots = fixed device batch size.")
    flags.DEFINE_integer(
        "max_batch", 0,
        "Micro-batch flush size (0 = max_sessions).")
    flags.DEFINE_float(
        "max_delay_ms", 10.0,
        "[cycle scheduler] Micro-batching deadline: longest a request "
        "waits for batchmates. The continuous scheduler never waits — "
        "batching emerges from device busy time.")
    flags.DEFINE_integer(
        "max_queue", 64,
        "Bounded admission queue; beyond this /act returns 503 busy.")
    flags.DEFINE_enum(
        "scheduler", "continuous", ["continuous", "cycle"],
        "Batch scheduler: 'continuous' rolls requests into the next "
        "device step the moment they land (double-buffered pipeline); "
        "'cycle' is the legacy wait-for-deadline-or-full loop (A/B "
        "baseline).")
    flags.DEFINE_integer(
        "pipeline_depth", 2,
        "[continuous] Max batches in flight: 2 = prepare/upload batch "
        "N+1 while N executes (double buffering).")
    flags.DEFINE_string(
        "buckets", "auto",
        "AOT batch-size buckets, comma-separated (e.g. '1,2,4,8'); "
        "'auto' = powers of two up to max_sessions. Every bucket is "
        "compiled at warm-up; compile_count is pinned at the bucket "
        "count for the process lifetime.")
    flags.DEFINE_float(
        "request_timeout_s", 60.0, "Server-side per-request timeout.")
    flags.DEFINE_integer(
        "replica_id", 0,
        "This replica's id within a fleet (rt1_tpu.serve.fleet sets it); "
        "surfaced in /healthz and the replica_id metrics gauge.")
    flags.DEFINE_float(
        "watch_checkpoints_s", 0.0,
        "Poll the workdir checkpoint dir this often and hot-swap newer "
        "steps automatically (0 = off; ignored with --random_init).")
    flags.DEFINE_enum(
        "inference_dtype", "f32", ["f32", "bf16", "int8"],
        "Low-precision serving mode (rt1_tpu/models/quant.py): bf16 casts "
        "weights+compute once at restore; int8 quantizes the FiLM-"
        "EfficientNet and transformer matmul weights per-output-channel "
        "(norms/embeddings/action head stay f32). /reload requantizes "
        "standby checkpoints — compile_count stays 1.")
    flags.DEFINE_bool(
        "cached_inference", False,
        "Incremental decode: keep per-session transformer K/V caches on "
        "device so a step attends one frame against cached keys instead "
        "of re-running the full window (rt1_tpu/serve/engine.py). Exact "
        "while a session's window fills; after roll-over, cache entries "
        "keep their insertion-time positions (staleness bounded at "
        "window-1 rolls; parity gated by serve/parity.py). Hot-swap "
        "rebuilds all caches from retained context. OFF by default — "
        "the default path is byte-identical to the windowed engine.")
    flags.DEFINE_string(
        "embedder", "hash",
        "Instruction embedder spec (hash | ngram | use | table.npz).")
    flags.DEFINE_bool(
        "allow_embedder_mismatch", False,
        "Serve even if the checkpoint's data manifest records a different "
        "instruction embedder.")
    flags.DEFINE_float(
        "slow_threshold_ms", 0.0,
        "Keep requests at least this slow in the exemplar ring "
        "(GET /slow_requests); 0 keeps the most recent window of all.")
    flags.DEFINE_string(
        "exemplar_path", "",
        "Dump the slow-request exemplar ring here (JSONL) on drain.")
    flags.DEFINE_string(
        "session_snapshot_dir", "",
        "Durable sessions: write a bounded on-disk snapshot ring of live "
        "session windows here (rt1_tpu/serve/migrate.py) so a SIGKILL'd "
        "replica's sessions restore mid-episode at re-home time instead "
        "of resetting. OFF by default — no disk writes unless an "
        "operator opts in.")
    flags.DEFINE_float(
        "snapshot_max_age_s", 600.0,
        "Staleness bound for crash restores: a ring snapshot older than "
        "this starts a fresh window instead (age surfaced as "
        "snapshot_age_s in the restoring /act response).")
    flags.DEFINE_integer(
        "session_snapshot_every", 1,
        "Write a session's ring snapshot every N served steps (1 = every "
        "step; higher trades restore staleness for snapshot I/O).")
    flags.DEFINE_string(
        "capture_dir", "",
        "Data flywheel: capture completed sessions as episode .npz files "
        "into this directory (rt1_tpu/flywheel/capture.py). OFF by "
        "default — serving records nothing unless an operator opts in.")
    flags.DEFINE_integer(
        "capture_max_episodes", 512,
        "Capture disk ring: keep at most this many episode files "
        "(oldest pruned).")
    flags.DEFINE_integer(
        "capture_max_steps", 512,
        "Capture per-session step bound; steps beyond it are dropped.")
    flags.DEFINE_bool("verbose", False, "Log per-request lines.")
    flags.mark_flags_as_required(["config"])
    sys.exit(absl_app.run(main))
