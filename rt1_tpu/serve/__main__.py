"""Serving entry point: `python -m rt1_tpu.serve`.

Run (tiny smoke config, random weights, CPU):

  JAX_PLATFORMS=cpu python -m rt1_tpu.serve \
      --config rt1_tpu/train/configs/tiny.py --random_init --port 8321

Run (trained checkpoint):

  python -m rt1_tpu.serve --config rt1_tpu/train/configs/language_table.py \
      --workdir /tmp/vt --port 8321 --embedder ngram

Prints one JSON ready-line (`{"status": "serving", "port": ...}`) once the
batched step is AOT-compiled and the socket is bound, then serves until
SIGTERM/SIGINT, which drains accepted requests before exiting.
"""

from __future__ import annotations

import json
import sys


def main(argv):
    del argv
    from absl import flags

    # Persistent XLA cache BEFORE any jax compile: the serving process's
    # single batched-step compile is served from disk on restarts.
    from rt1_tpu import compilation_cache

    compilation_cache.enable_persistent_cache()

    from rt1_tpu.eval.embedding import get_embedder
    from rt1_tpu.eval.restore import build_serve_engine
    from rt1_tpu.serve.server import (
        ServeApp,
        install_signal_handlers,
        make_server,
    )

    FLAGS = flags.FLAGS
    config = FLAGS.config
    if not FLAGS.random_init and not FLAGS.allow_embedder_mismatch:
        # Same guard as eval/main.py: serving a checkpoint with a different
        # instruction embedder than it was trained on would hand the policy
        # foreign-domain embeddings and score ~random with 200 OK.
        from rt1_tpu.data.collect import check_embedder_compatibility

        check_embedder_compatibility(
            FLAGS.workdir,
            FLAGS.embedder,
            context="checkpoint data_manifest; pass "
            "--allow_embedder_mismatch to override",
            manifest_name="data_manifest.json",
        )
    engine, step = build_serve_engine(
        config,
        workdir=None if FLAGS.random_init else FLAGS.workdir,
        max_sessions=FLAGS.max_sessions,
        embedder=get_embedder(FLAGS.embedder),
    )
    app = ServeApp(
        engine,
        image_shape=(config.data.height, config.data.width, 3),
        max_batch=FLAGS.max_batch or None,
        max_delay_s=FLAGS.max_delay_ms / 1e3,
        max_queue=FLAGS.max_queue,
        request_timeout_s=FLAGS.request_timeout_s,
    )
    app.start(warmup=True)
    httpd = make_server(app, host=FLAGS.host, port=FLAGS.port,
                        quiet=not FLAGS.verbose)
    install_signal_handlers(app, httpd)
    print(
        json.dumps(
            {
                "status": "serving",
                "host": httpd.server_address[0],
                "port": httpd.server_address[1],
                "checkpoint_step": step,
                "max_sessions": engine.max_sessions,
                "compile_count": engine.compile_count,
            }
        ),
        flush=True,
    )
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        if not app.draining:
            app.drain()
    print(json.dumps({"status": "drained", **app.metrics_snapshot()}),
          flush=True)
    return 0


if __name__ == "__main__":
    from absl import app as absl_app
    from absl import flags
    from ml_collections import config_flags

    config_flags.DEFINE_config_file("config", None, "Model/data config.")
    flags.DEFINE_string("workdir", "/tmp/rt1_tpu", "Checkpoint directory.")
    flags.DEFINE_bool(
        "random_init", False,
        "Serve randomly initialized weights (smoke tests / load generation; "
        "no checkpoint needed).")
    flags.DEFINE_string("host", "127.0.0.1", "Bind address.")
    flags.DEFINE_integer("port", 8321, "Bind port (0 = ephemeral).")
    flags.DEFINE_integer(
        "max_sessions", 8,
        "Concurrent session slots = fixed device batch size.")
    flags.DEFINE_integer(
        "max_batch", 0,
        "Micro-batch flush size (0 = max_sessions).")
    flags.DEFINE_float(
        "max_delay_ms", 10.0,
        "Micro-batching deadline: longest a request waits for batchmates.")
    flags.DEFINE_integer(
        "max_queue", 64,
        "Bounded admission queue; beyond this /act returns 503 busy.")
    flags.DEFINE_float(
        "request_timeout_s", 60.0, "Server-side per-request timeout.")
    flags.DEFINE_string(
        "embedder", "hash",
        "Instruction embedder spec (hash | ngram | use | table.npz).")
    flags.DEFINE_bool(
        "allow_embedder_mismatch", False,
        "Serve even if the checkpoint's data manifest records a different "
        "instruction embedder.")
    flags.DEFINE_bool("verbose", False, "Log per-request lines.")
    flags.mark_flags_as_required(["config"])
    sys.exit(absl_app.run(main))
