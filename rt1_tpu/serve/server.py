"""Stdlib-only HTTP frontend for the batched policy engine.

Threading model: `ThreadingHTTPServer` gives every connection a handler
thread (stdlib does the HTTP parsing); one background thread runs an
asyncio loop that owns the `MicroBatcher`; the batcher's single-worker
executor calls `PolicyEngine.act_batch`. Handler threads bridge into the
loop with `run_coroutine_threadsafe` and block on the future — the batching
concurrency lives in the loop, not in the handler count.

Endpoints (all JSON):

* `POST /act`    {"session_id", "image" | "image_b64", "instruction" |
                  "embedding"} -> {"action", "action_tokens", ...}
* `POST /reset`  {"session_id"} -> {"ok": true, "slot": i}
* `POST /release` {"session_id"} -> {"ok": true}
* `POST /reload`  {"step"?} -> zero-downtime checkpoint hot-swap: restore
                  into a standby buffer, validate, atomically swap device
                  params with no recompile and no dropped requests; 409
                  while another reload runs, `/readyz` says `reloading`.
* `GET /healthz` liveness + model/input contract (clients read the
                  expected image shape from here). Always 200 while the
                  process serves HTTP — restart-deciders watch this.
* `GET /readyz`  readiness: 503 before the first AOT compile completes and
                  while draining after SIGTERM, 200 otherwise — load
                  balancers stop routing BEFORE shutdown and never route to
                  a replica still paying XLA latency. Liveness and
                  readiness are deliberately separate endpoints: a draining
                  replica is alive (do not restart it) but not ready (do
                  not send it traffic).
* `GET /metrics` `ServeMetrics.snapshot()` + engine gauges as JSON; with
                  `Accept: text/plain` (or openmetrics) the same numbers in
                  Prometheus exposition format (rt1_tpu/obs/prometheus.py);
                  includes the `draining` and `ready` gauges.

Backpressure maps to HTTP: queue full -> 503 `busy`, draining -> 503
`draining`. `install_signal_handlers` wires SIGTERM/SIGINT to a graceful
drain: stop admitting, flush every accepted request, then stop serving.
"""

from __future__ import annotations

import asyncio
import base64
import binascii
import concurrent.futures
import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from rt1_tpu.obs import prometheus as obs_prometheus
from rt1_tpu.obs import trace as obs_trace
from rt1_tpu.serve.batcher import BusyError, DrainingError, MicroBatcher
from rt1_tpu.serve.engine import PolicyEngine, SessionError
from rt1_tpu.serve.metrics import ServeMetrics


class RequestError(ValueError):
    """Malformed client payload -> HTTP 400."""


class ReloadInProgressError(RuntimeError):
    """A checkpoint hot-swap is already running -> HTTP 409."""


def parse_observation(
    payload: Dict[str, Any],
    image_shape: Sequence[int],
    embed_dim: Optional[int] = None,
) -> Dict[str, Any]:
    """Decode one /act payload into an engine observation.

    Images arrive either as a nested float list (already [0, 1]) or as
    `image_b64` — base64 of raw uint8 H*W*3 bytes, the compact path the
    load generator uses (a 32x56 frame is ~7 KB vs ~60 KB as JSON floats).
    """
    if "image_b64" in payload:
        try:
            raw = base64.b64decode(payload["image_b64"], validate=True)
        except (binascii.Error, ValueError) as exc:
            raise RequestError(f"image_b64 is not valid base64: {exc}") from exc
        flat = np.frombuffer(raw, np.uint8)
        expected = int(np.prod(image_shape))
        if flat.size != expected:
            raise RequestError(
                f"image_b64 decodes to {flat.size} bytes, expected "
                f"{expected} for shape {tuple(image_shape)}"
            )
        image = flat.reshape(image_shape).astype(np.float32) / 255.0
    elif "image" in payload:
        image = np.asarray(payload["image"], np.float32)
        if image.shape != tuple(image_shape):
            raise RequestError(
                f"image shape {image.shape} != server shape "
                f"{tuple(image_shape)}"
            )
    else:
        raise RequestError("payload needs 'image' or 'image_b64'")
    obs: Dict[str, Any] = {"image": image}
    if "embedding" in payload:
        embedding = np.asarray(payload["embedding"], np.float32)
        if embed_dim is not None and embedding.shape != (embed_dim,):
            raise RequestError(
                f"embedding shape {embedding.shape} != ({embed_dim},)"
            )
        obs["natural_language_embedding"] = embedding
    elif "instruction" in payload:
        if not isinstance(payload["instruction"], str):
            raise RequestError("'instruction' must be a string")
        obs["instruction"] = payload["instruction"]
    else:
        raise RequestError("payload needs 'instruction' or 'embedding'")
    return obs


class ServeApp:
    """Engine + batcher + metrics behind a thread-safe facade."""

    def __init__(
        self,
        engine: PolicyEngine,
        *,
        image_shape: Sequence[int],
        embed_dim: int = 512,
        max_batch: Optional[int] = None,
        max_delay_s: float = 0.010,
        max_queue: int = 64,
        request_timeout_s: float = 60.0,
        metrics: Optional[ServeMetrics] = None,
        replica_id: int = 0,
        reload_fn=None,
    ):
        self.engine = engine
        self.image_shape = tuple(image_shape)
        self.embed_dim = embed_dim
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.request_timeout_s = request_timeout_s
        self.replica_id = replica_id
        # reload_fn(step|None) -> (variables, checkpoint_step): the standby
        # restore path behind POST /reload (eval/restore.py
        # load_standby_variables closed over config+workdir).
        self._reload_fn = reload_fn
        self._reload_lock = threading.Lock()
        self.reloading = False
        self.draining = False
        # Guards the draining-check + batcher-submit pair in act() against
        # drain(): a request that passed the check is guaranteed to be
        # scheduled on the loop BEFORE batcher.drain() is, so FIFO loop
        # ordering flushes it instead of 503ing an admitted request.
        self._admit_lock = threading.Lock()
        # Flipped by start() once the batcher runs and the AOT warmup
        # compile finished — /readyz gates on it.
        self.ready = False
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, name="rt1-serve-loop", daemon=True
        )
        self.batcher = MicroBatcher(
            self._process,
            # A flush larger than the slot count would make act_batch
            # reject the whole batch — clamp, don't trust the flag.
            max_batch=min(max_batch or engine.max_sessions,
                          engine.max_sessions),
            max_delay_s=max_delay_s,
            max_queue=max_queue,
            batch_key=lambda item: item[0],  # one in-flight step per session
            metrics=self.metrics,
        )

    def _process(self, items):
        t0 = time.perf_counter()
        # obs: span on the batcher's executor thread — the serve leg of the
        # shared host timeline (train loop + feeder workers + this).
        with obs_trace.span("serve_batch_step", batch=len(items)):
            results = self.engine.act_batch(items)
        self.metrics.observe_step(time.perf_counter() - t0)
        return results

    def start(self, warmup: bool = True) -> None:
        """Start the batcher loop; AOT-compile the batched step up front so
        the first request pays network latency, not XLA latency."""
        self._loop_thread.start()
        asyncio.run_coroutine_threadsafe(
            self.batcher.start(), self._loop
        ).result(timeout=10)
        if warmup:
            self.engine.warmup(self.image_shape, self.embed_dim)
        self.ready = True

    def act(self, session_id: str, obs: Dict[str, Any]) -> Dict[str, Any]:
        """Blocking bridge used by HTTP handler threads."""
        with self._admit_lock:
            # Atomic with drain()'s flag flip: once a request passes this
            # check it is scheduled on the loop ahead of batcher.drain(),
            # so SIGTERM flushes it — admitted work is never answered 503.
            if self.draining:
                raise DrainingError("draining; not accepting requests")
            future = asyncio.run_coroutine_threadsafe(
                self.batcher.submit((session_id, obs)), self._loop
            )
        try:
            result = future.result(timeout=self.request_timeout_s)
        except concurrent.futures.TimeoutError:
            # Nobody is waiting for this request anymore — cancel it so a
            # still-queued entry is dropped instead of stepping the
            # session's rolling state for a dead client.
            future.cancel()
            raise
        if "error" in result:
            # The engine isolates a bad item as a per-item marker so its
            # batchmates still step; surface it to THIS request only.
            raise result["error"]
        return result

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: reject new work, flush everything admitted.

        The `_admit_lock` handshake closes the drain/in-flight race: any
        act() that saw `draining == False` has already scheduled its submit
        coroutine, and the loop runs callbacks FIFO — `batcher.drain()` is
        scheduled after it, so the batcher only starts refusing once every
        admitted request sits in its pending queue, where drain flushes it.
        """
        with self._admit_lock:
            self.draining = True
            self.ready = False  # /readyz flips 503 as draining starts
        if self._loop_thread.is_alive():
            asyncio.run_coroutine_threadsafe(
                self.batcher.drain(), self._loop
            ).result(timeout=timeout)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=timeout)

    def reload(self, step: Optional[int] = None) -> Dict[str, Any]:
        """Zero-downtime checkpoint hot-swap: restore into a standby host
        buffer via `reload_fn`, validate, atomically swap into the engine.

        Serving continues throughout — in-flight and concurrent requests
        run on the old params until the swap lands between two batches.
        `/readyz` reports 503 `reloading` for the duration so a router
        pauses NEW session placement (rolling-reload drain semantics)
        while existing sessions keep flowing. One reload at a time
        (`ReloadInProgressError` -> 409).
        """
        if self._reload_fn is None:
            raise RequestError(
                "this replica has no reload source: started without a "
                "checkpoint workdir (pass reload_fn= to ServeApp)"
            )
        if not self._reload_lock.acquire(blocking=False):
            raise ReloadInProgressError(
                "a checkpoint reload is already in progress"
            )
        try:
            self.reloading = True
            variables, restored_step = self._reload_fn(step)
            info = self.engine.swap_variables(variables)
            self.metrics.observe_reload()
            return {
                "ok": True,
                "checkpoint_step": restored_step,
                "reloads_total": self.engine.reloads,
                **info,
            }
        finally:
            self.reloading = False
            self._reload_lock.release()

    def healthz(self) -> Dict[str, Any]:
        return {
            "status": "draining" if self.draining else "ok",
            "replica_id": self.replica_id,
            "image_shape": list(self.image_shape),
            "embed_dim": self.embed_dim,
            "max_sessions": self.engine.max_sessions,
            "active_sessions": self.engine.active_sessions,
            "compile_count": self.engine.compile_count,
            "reloads": self.engine.reloads,
        }

    def readyz(self) -> Tuple[int, Dict[str, Any]]:
        """(http_code, payload) for the readiness probe: 503 unless the
        first AOT compile finished AND no drain/reload is in progress.
        `reloading` is a soft not-ready: the replica still serves /act
        (existing sessions keep flowing through a session-affine router),
        but new placement should wait out the swap."""
        if self.draining:
            return 503, {"ready": False, "reason": "draining"}
        if self.reloading:
            return 503, {"ready": False, "reason": "reloading"}
        if not self.ready:
            return 503, {"ready": False, "reason": "warming"}
        return 200, {"ready": True}

    def _engine_gauges(self) -> Dict[str, Any]:
        return {
            "active_sessions": self.engine.active_sessions,
            "compile_count": self.engine.compile_count,
            "embed_cache_misses": self.engine.embed_calls,
            # Nonzero while serving steady traffic = more live sessions
            # than slots; their context windows are thrashing to zero.
            "session_evictions": self.engine.evictions,
            # 1 while the batcher drains after SIGTERM (scrapers see the
            # shutdown even if their LB already stopped routing /readyz).
            "draining": int(self.draining),
            "ready": int(self.ready),
            "reloading": int(self.reloading),
            "replica_id": self.replica_id,
        }

    def metrics_snapshot(self) -> Dict[str, Any]:
        return self.metrics.snapshot(**self._engine_gauges())

    def metrics_prometheus(self) -> str:
        """The same numbers in exposition text (scraper-negotiated path)."""
        return self.metrics.prometheus_text(**self._engine_gauges())


class _Handler(BaseHTTPRequestHandler):
    # Accurate Content-Length is set on every response, so HTTP/1.1
    # keep-alive is safe and saves the load generator a handshake per step.
    protocol_version = "HTTP/1.1"
    app: ServeApp = None  # bound by make_server
    quiet: bool = True

    def log_message(self, fmt, *args):  # noqa: D102 - stdlib hook
        if not self.quiet:
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            raise RequestError("missing request body")
        try:
            payload = json.loads(self.rfile.read(length))
        except json.JSONDecodeError as exc:
            raise RequestError(f"invalid JSON body: {exc}") from exc
        if not isinstance(payload, dict):
            raise RequestError("request body must be a JSON object")
        return payload

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib casing
        if self.path == "/healthz":
            self._reply(200, self.app.healthz())
        elif self.path == "/readyz":
            code, payload = self.app.readyz()
            self._reply(code, payload)
        elif self.path == "/metrics":
            # Content negotiation: JSON stays the default (loadgen,
            # existing automation); a Prometheus scraper's Accept header
            # (`text/plain` / openmetrics) gets the exposition format.
            if obs_prometheus.accepts_text(self.headers.get("Accept")):
                self._reply_text(
                    200,
                    self.app.metrics_prometheus(),
                    obs_prometheus.CONTENT_TYPE,
                )
            else:
                self._reply(200, self.app.metrics_snapshot())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802 - stdlib casing
        try:
            payload = self._read_json()
        except RequestError as exc:
            self._reply(400, {"error": str(exc)})
            return
        if self.path == "/act":
            self._act(payload)
        elif self.path == "/reset":
            self._session_op(payload, self.app.engine.reset, "slot",
                             count_reset=True)
        elif self.path == "/release":
            self._session_op(payload, self.app.engine.release, None)
        elif self.path == "/reload":
            self._reload(payload)
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def _reload(self, payload):
        step = payload.get("step")
        if step is not None and not isinstance(step, int):
            self._reply(400, {"error": "'step' must be an integer"})
            return
        try:
            self._reply(200, self.app.reload(step))
        except RequestError as exc:
            self._reply(400, {"error": str(exc)})
        except ReloadInProgressError as exc:
            self._reply(409, {"error": str(exc), "retry": True})
        except Exception as exc:  # noqa: BLE001 - restore/validate failure
            # Old params are still serving (swap_variables rejects without
            # touching the engine) — report, don't crash the replica.
            self._reply(500, {"error": f"reload failed: {exc}"})

    def _session_id(self, payload) -> str:
        session_id = payload.get("session_id")
        if not isinstance(session_id, str) or not session_id:
            raise RequestError("'session_id' must be a non-empty string")
        return session_id

    def _session_op(self, payload, op, result_key, count_reset=False):
        try:
            value = op(self._session_id(payload))
        except RequestError as exc:
            self._reply(400, {"error": str(exc)})
            return
        except SessionError as exc:
            self._reply(404, {"error": str(exc)})
            return
        out = {"ok": True}
        if result_key is not None:
            out[result_key] = value
        if count_reset:
            self.app.metrics.observe_reset()
        self._reply(200, out)

    def _act(self, payload):
        if self.app.draining:
            self._reply(503, {"error": "draining"})
            return
        t0 = time.perf_counter()
        try:
            session_id = self._session_id(payload)
            obs = parse_observation(
                payload, self.app.image_shape, self.app.embed_dim
            )
            result = self.app.act(session_id, obs)
        except RequestError as exc:
            self.app.metrics.observe_request(
                time.perf_counter() - t0, ok=False
            )
            self._reply(400, {"error": str(exc)})
            return
        except BusyError:
            self._reply(503, {"error": "busy", "retry": True})
            return
        except DrainingError:
            self._reply(503, {"error": "draining"})
            return
        except concurrent.futures.TimeoutError:
            self.app.metrics.observe_request(
                time.perf_counter() - t0, ok=False
            )
            self._reply(504, {"error": "request timed out in the server"})
            return
        except (SessionError, ValueError, KeyError) as exc:
            # KeyError: a TableInstructionEmbedder miss. The engine turned
            # per-item failures into markers; app.act re-raised this one —
            # batchmates were unaffected.
            self.app.metrics.observe_request(
                time.perf_counter() - t0, ok=False
            )
            self._reply(400, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 - last-resort HTTP 500
            self.app.metrics.observe_request(
                time.perf_counter() - t0, ok=False
            )
            self._reply(500, {"error": f"internal error: {exc}"})
            return
        self.app.metrics.observe_request(time.perf_counter() - t0)
        out = {
            "action": [float(x) for x in result["action"]],
            "action_tokens": [int(x) for x in result["action_tokens"]],
            # True when this step started a fresh (zeroed) window — a
            # client that did not /reset just lost its slot to LRU reclaim.
            "session_started": result.get("session_started", False),
        }
        if "terminate_episode" in result:
            out["terminate_episode"] = result["terminate_episode"]
        self._reply(200, out)


def make_server(
    app: ServeApp, host: str = "127.0.0.1", port: int = 0, quiet: bool = True
) -> ThreadingHTTPServer:
    """Bind a ThreadingHTTPServer to `app` (port 0 = ephemeral)."""
    handler = type("BoundHandler", (_Handler,), {"app": app, "quiet": quiet})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_threads = True
    return httpd


def install_signal_handlers(
    app: ServeApp, httpd: ThreadingHTTPServer
) -> None:
    """SIGTERM/SIGINT -> drain accepted requests, then stop the server."""

    def _shutdown(signum, frame):  # noqa: ARG001 - signal signature
        def _run():
            app.drain()
            httpd.shutdown()

        threading.Thread(target=_run, name="rt1-serve-drain").start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
