"""Stdlib-only HTTP frontend for the batched policy engine.

Threading model: `ThreadingHTTPServer` gives every connection a handler
thread (stdlib does the HTTP parsing); one background thread runs an
asyncio loop that owns the `MicroBatcher`; the batcher's single-worker
executor calls `PolicyEngine.act_batch`. Handler threads bridge into the
loop with `run_coroutine_threadsafe` and block on the future — the batching
concurrency lives in the loop, not in the handler count.

Endpoints (all JSON):

* `POST /act`    {"session_id", "image" | "image_b64", "instruction" |
                  "embedding"} -> {"action", "action_tokens", ...}
* `POST /reset`  {"session_id"} -> {"ok": true, "slot": i}
* `POST /release` {"session_id"} -> {"ok": true}
* `POST /reload`  {"step"?} -> zero-downtime checkpoint hot-swap: restore
                  into a standby buffer, validate, atomically swap device
                  params with no recompile and no dropped requests; 409
                  while another reload runs, `/readyz` says `reloading`.
* `GET /healthz` liveness + model/input contract (clients read the
                  expected image shape from here). Always 200 while the
                  process serves HTTP — restart-deciders watch this.
* `GET /readyz`  readiness: 503 before the first AOT compile completes and
                  while draining after SIGTERM, 200 otherwise — load
                  balancers stop routing BEFORE shutdown and never route to
                  a replica still paying XLA latency. Liveness and
                  readiness are deliberately separate endpoints: a draining
                  replica is alive (do not restart it) but not ready (do
                  not send it traffic).
* `GET /metrics` `ServeMetrics.snapshot()` + engine gauges as JSON; with
                  `Accept: text/plain` (or openmetrics) the same numbers in
                  Prometheus exposition format (rt1_tpu/obs/prometheus.py);
                  includes the `draining` and `ready` gauges.
* `GET /slow_requests` the bounded slow-request exemplar ring: request
                  ids + per-phase breakdowns of every request past the
                  slow threshold (serve/reqtrace.py; dumped to JSONL on
                  drain when `exemplar_path` is configured).

Request tracing: every `/act` resolves a request id (client/router
`X-RT1-Request-Id` header, else minted) that is echoed as `request_id`
in the response, stamped through admission -> queue -> batch -> device ->
serialization (`serve/reqtrace.py`), emitted as linked `replica_act` /
`batch_wait` / `device_step` spans on the shared obs timeline, and —
with `"debug": true` in the payload — returned as a `phases` breakdown.

Backpressure maps to HTTP: queue full -> 503 `busy`, draining -> 503
`draining`. `install_signal_handlers` wires SIGTERM/SIGINT to a graceful
drain: stop admitting, flush every accepted request, then stop serving.
"""

from __future__ import annotations

import asyncio
import base64
import binascii
import concurrent.futures
import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from rt1_tpu.obs import prometheus as obs_prometheus
from rt1_tpu.obs import trace as obs_trace
from rt1_tpu.obs.recorder import ExemplarRing
from rt1_tpu.resilience import faults
from rt1_tpu.serve import migrate, reqtrace
from rt1_tpu.serve.batcher import (
    BusyError,
    ContinuousBatcher,
    DrainingError,
    MicroBatcher,
)
from rt1_tpu.serve.engine import (
    PolicyEngine,
    SessionError,
    SlotContentionError,
)
from rt1_tpu.serve.metrics import ServeMetrics


class RequestError(ValueError):
    """Malformed client payload -> HTTP 400."""


class ReloadInProgressError(RuntimeError):
    """A checkpoint hot-swap is already running -> HTTP 409."""


def parse_observation(
    payload: Dict[str, Any],
    image_shape: Sequence[int],
    embed_dim: Optional[int] = None,
) -> Dict[str, Any]:
    """Decode one /act payload into an engine observation.

    Images arrive either as a nested float list (already [0, 1]) or as
    `image_b64` — base64 of raw uint8 H*W*3 bytes, the compact path the
    load generator uses (a 32x56 frame is ~7 KB vs ~60 KB as JSON floats).
    """
    if "image_b64" in payload:
        try:
            raw = base64.b64decode(payload["image_b64"], validate=True)
        except (binascii.Error, ValueError) as exc:
            raise RequestError(f"image_b64 is not valid base64: {exc}") from exc
        flat = np.frombuffer(raw, np.uint8)
        expected = int(np.prod(image_shape))
        if flat.size != expected:
            raise RequestError(
                f"image_b64 decodes to {flat.size} bytes, expected "
                f"{expected} for shape {tuple(image_shape)}"
            )
        image = flat.reshape(image_shape).astype(np.float32) / 255.0
    elif "image" in payload:
        image = np.asarray(payload["image"], np.float32)
        if image.shape != tuple(image_shape):
            raise RequestError(
                f"image shape {image.shape} != server shape "
                f"{tuple(image_shape)}"
            )
    else:
        raise RequestError("payload needs 'image' or 'image_b64'")
    obs: Dict[str, Any] = {"image": image}
    if "embedding" in payload:
        embedding = np.asarray(payload["embedding"], np.float32)
        if embed_dim is not None and embedding.shape != (embed_dim,):
            raise RequestError(
                f"embedding shape {embedding.shape} != ({embed_dim},)"
            )
        obs["natural_language_embedding"] = embedding
    elif "instruction" in payload:
        if not isinstance(payload["instruction"], str):
            raise RequestError("'instruction' must be a string")
        obs["instruction"] = payload["instruction"]
    else:
        raise RequestError("payload needs 'instruction' or 'embedding'")
    return obs


class ServeApp:
    """Engine + batcher + metrics behind a thread-safe facade."""

    def __init__(
        self,
        engine: PolicyEngine,
        *,
        image_shape: Sequence[int],
        embed_dim: int = 512,
        max_batch: Optional[int] = None,
        max_delay_s: float = 0.010,
        max_queue: int = 64,
        scheduler: str = "continuous",
        pipeline_depth: int = 2,
        request_timeout_s: float = 60.0,
        metrics: Optional[ServeMetrics] = None,
        replica_id: int = 0,
        reload_fn=None,
        slow_threshold_ms: float = 0.0,
        slow_capacity: int = 128,
        exemplar_path: Optional[str] = None,
        capture=None,
        checkpoint_step: int = -1,
        session_snapshot_dir: Optional[str] = None,
        snapshot_max_age_s: float = 600.0,
        snapshot_every: int = 1,
    ):
        self.engine = engine
        # Opt-in data-flywheel episode capture
        # (rt1_tpu/flywheel/capture.py::EpisodeCaptureSink, wired from
        # `--capture_dir`). None — the default — leaves every serve path
        # byte-identical: the hot path pays one `is None` check.
        self.capture = capture
        self.image_shape = tuple(image_shape)
        self.embed_dim = embed_dim
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.request_timeout_s = request_timeout_s
        self.replica_id = replica_id
        # Slow-request exemplar ring — the serve-side flight recorder:
        # request id + phase breakdown for every request past the
        # threshold (0 = all, ring-bounded), served on GET /slow_requests
        # and dumped to `exemplar_path` on drain/SIGTERM.
        self.exemplars = ExemplarRing(
            capacity=slow_capacity, threshold_ms=slow_threshold_ms
        )
        self.exemplar_path = exemplar_path
        # Durable sessions (rt1_tpu/serve/migrate.py): the checkpoint
        # generation stamps exported snapshots and gates imports (a
        # snapshot from another generation is refused by name); the
        # optional on-disk snapshot ring gives SIGKILL failover a window
        # to restore instead of reset, staleness-bounded. Per-session
        # metadata (step counter, last instruction) rides the snapshot so
        # the importer can resume bookkeeping and warm its embed cache.
        self.checkpoint_generation = int(checkpoint_step)
        self.snapshot_max_age_s = float(snapshot_max_age_s)
        self.snapshot_every = max(1, int(snapshot_every))
        self.snapshot_ring = (
            migrate.SnapshotRing(session_snapshot_dir)
            if session_snapshot_dir
            else None
        )
        self._meta_lock = threading.Lock()
        self._session_meta: Dict[str, Dict[str, Any]] = {}
        self.migration_exports = 0
        self.migration_imports = 0
        self.migration_import_failures = 0
        self.migration_restores = 0
        self.migration_restore_failures = 0
        # reload_fn(step|None) -> (variables, checkpoint_step): the standby
        # restore path behind POST /reload (eval/restore.py
        # load_standby_variables closed over config+workdir).
        self._reload_fn = reload_fn
        self._reload_lock = threading.Lock()
        self.reloading = False
        self.draining = False
        # Guards the draining-check + batcher-submit pair in act() against
        # drain(): a request that passed the check is guaranteed to be
        # scheduled on the loop BEFORE batcher.drain() is, so FIFO loop
        # ordering flushes it instead of 503ing an admitted request.
        self._admit_lock = threading.Lock()
        # Flipped by start() once the batcher runs and the AOT warmup
        # compile finished — /readyz gates on it.
        self.ready = False
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, name="rt1-serve-loop", daemon=True
        )
        if scheduler not in ("continuous", "cycle"):
            raise ValueError(
                f"scheduler must be 'continuous' or 'cycle', got "
                f"{scheduler!r}"
            )
        self.scheduler = scheduler
        self.pipeline_depth = pipeline_depth
        # A flush larger than the slot count would make act_batch reject
        # the whole batch — clamp, don't trust the flag.
        clamped_batch = min(max_batch or engine.max_sessions,
                            engine.max_sessions)
        if scheduler == "continuous":
            # Rolling scheduler + double-buffered engine pipeline: a
            # request joins the NEXT device step the moment it lands, and
            # batch N+1 dispatches while batch N's fetch blocks.
            self.batcher = ContinuousBatcher(
                self._process,
                max_batch=clamped_batch,
                max_queue=max_queue,
                pipeline_depth=pipeline_depth,
                # Reused as the demand-coalesce CAP, not a fixed
                # deadline: a lone client still dispatches immediately;
                # only a re-forming burst (eligible < distinct sessions
                # seen lately) waits — at most this long on an idle
                # device, or until the in-flight step completes when one
                # is running (its riders rearrive at that moment).
                coalesce_delay_s=max_delay_s,
                batch_key=lambda item: item[0],  # session exclusion spans
                #   in-flight batches: per-session FIFO under overlap
                metrics=self.metrics,
                on_batch=self._mark_batch_formed,
            )
        else:
            # Legacy cycle scheduler (the A/B baseline): wait for
            # deadline-or-full, one batch in flight, ever.
            self.batcher = MicroBatcher(
                self._process,
                max_batch=clamped_batch,
                max_delay_s=max_delay_s,
                max_queue=max_queue,
                batch_key=lambda item: item[0],
                metrics=self.metrics,
                on_batch=self._mark_batch_formed,
            )

    @staticmethod
    def _mark_batch_formed(items) -> None:
        """Batcher-loop hook: these requests just left the queue (queue
        wait ends, batch formation begins)."""
        now = obs_trace.now_us()
        for _, _, phases in items:
            phases.t_formed = now

    def _process(self, items):
        t0 = time.perf_counter()
        now = obs_trace.now_us()
        for _, _, phases in items:
            phases.t_device0 = now
        # obs: `device_step` span on the batcher's executor thread — the
        # serve leg of the shared host timeline, tagged with every rider's
        # request id so Perfetto links it to router_route/replica_act.
        with reqtrace.device_step_span(
            len(items), (ph.request_id for _, _, ph in items)
        ):
            batch = [(sid, obs) for sid, obs, _ in items]
            if hasattr(self.engine, "dispatch_batch"):
                # Two-phase step: the async dispatch returns immediately
                # (under the engine lock) and the blocking fetch runs
                # outside it — with the continuous batcher's second
                # executor worker, batch N+1 dispatches while this fetch
                # blocks (the double-buffered device pipeline). Nothing
                # may sit between dispatch and collect: a dropped handle
                # would leak its sessions' in-flight eviction protection.
                handle = self.engine.dispatch_batch(batch)
                results = self.engine.collect_batch(handle)
                if handle.bucket is not None:
                    self.metrics.observe_bucket(
                        handle.bucket, handle.active_count
                    )
            else:
                results = self.engine.act_batch(batch)
        now = obs_trace.now_us()
        for _, _, phases in items:
            phases.t_device1 = now
        self.metrics.observe_step(time.perf_counter() - t0)
        return results

    def start(self, warmup: bool = True) -> None:
        """Start the batcher loop; AOT-compile the batched step up front so
        the first request pays network latency, not XLA latency."""
        self._loop_thread.start()
        asyncio.run_coroutine_threadsafe(
            self.batcher.start(), self._loop
        ).result(timeout=10)
        if warmup:
            self.engine.warmup(self.image_shape, self.embed_dim)
        self.ready = True

    def act(
        self,
        session_id: str,
        obs: Dict[str, Any],
        phases: Optional[reqtrace.RequestPhases] = None,
        task: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Blocking bridge used by HTTP handler threads. `phases` rides
        the batcher item so every boundary thread stamps the same ledger
        (a direct caller without one still gets a fresh ledger — the
        batcher hooks unconditionally dereference it). `task` is the
        client-declared workload tag the capture sink stamps into
        flywheel episodes."""
        if phases is None:
            phases = reqtrace.RequestPhases()
        # Crash durability: if this session has no live window here but a
        # ring snapshot exists (we re-homed after a replica died), restore
        # the window before stepping — the client continues mid-episode
        # instead of silently restarting at step 0. Best-effort: any
        # failure (stale, incompatible, injected fault) falls back to the
        # legacy fresh-window path.
        restored = (
            self.maybe_restore_session(session_id)
            if self.snapshot_ring is not None
            else None
        )
        t_entry = time.perf_counter()
        while True:
            with self._admit_lock:
                # Atomic with drain()'s flag flip: once a request passes
                # this check it is scheduled on the loop ahead of
                # batcher.drain(), so SIGTERM flushes it — admitted work
                # is never answered 503.
                if self.draining:
                    raise DrainingError("draining; not accepting requests")
                phases.t_enqueue = obs_trace.now_us()
                future = asyncio.run_coroutine_threadsafe(
                    self.batcher.submit((session_id, obs, phases)),
                    self._loop,
                )
            try:
                # Remaining budget, not a fresh one: contention retries
                # must never stretch a request past request_timeout_s.
                remaining = self.request_timeout_s - (
                    time.perf_counter() - t_entry
                )
                result = future.result(timeout=max(remaining, 0.001))
            except concurrent.futures.TimeoutError:
                # Nobody is waiting for this request anymore — cancel it
                # so a still-queued entry is dropped instead of stepping
                # the session's rolling state for a dead client.
                future.cancel()
                raise
            if (
                isinstance(result.get("error"), SlotContentionError)
                and time.perf_counter() - t_entry
                < self.request_timeout_s / 2
            ):
                # Every slot was riding this batch or an in-flight step
                # (double-buffered oversubscription). Transient by
                # construction — re-ride the next batch server-side
                # instead of bouncing a 503 retry loop through HTTP;
                # surfaced as 503 busy only if half the request budget
                # burns without a slot freeing.
                time.sleep(0.002)
                continue
            break
        if "error" in result:
            # The engine isolates a bad item as a per-item marker so its
            # batchmates still step; surface it to THIS request only.
            raise result["error"]
        # Per-task serve labels (rt1_serve_task_*): every successfully
        # served step lands in exactly one task bucket (the client tag, or
        # "unlabeled"), and the step that opened a fresh session window
        # counts the session — independent of whether capture is on.
        self.metrics.observe_task_request(
            task, new_session=result.get("session_started", False)
        )
        if self.capture is not None:
            # After the engine answered: capture sees only successfully
            # served steps, and a sink failure can never fail the request
            # (record_step swallows its own errors into a counter).
            self.capture.record_step(
                session_id,
                image=obs["image"],
                action=result["action"],
                action_tokens=result.get("action_tokens"),
                embedding=obs.get("natural_language_embedding"),
                instruction=obs.get("instruction"),
                task=task,
                session_started=result.get("session_started", False),
                terminate=bool(result.get("terminate_episode", 0)),
            )
        self._note_act(session_id, obs, result)
        if restored:
            result.update(restored)
        return result

    def reset(self, session_id: str) -> int:
        """Engine reset + capture boundary: a client-requested fresh
        window ends the captured episode in flight."""
        slot = self.engine.reset(session_id)
        if self.capture is not None:
            self.capture.finalize(session_id, "reset")
        with self._meta_lock:
            meta = self._session_meta.get(session_id)
            if meta is not None:
                meta["step_index"] = 0
        if self.snapshot_ring is not None:
            # A client-requested fresh window invalidates the durable
            # copy — restoring it after a reset would resurrect the
            # episode the client just abandoned.
            self.snapshot_ring.drop(session_id)
        return slot

    def release(self, session_id: str, keep_snapshot: bool = False) -> None:
        """Engine release + capture finalize. ``keep_snapshot`` is the
        migration-cleanup variant (the router freeing the source's stale
        copy after a successful import): the shared ring file now backs
        the importer's session, so it must survive this release — and
        the capture outcome is "migrated", not "released", because the
        episode continues elsewhere."""
        self.engine.release(session_id)
        if self.capture is not None:
            self.capture.finalize(
                session_id, "migrated" if keep_snapshot else "released"
            )
        with self._meta_lock:
            self._session_meta.pop(session_id, None)
        if self.snapshot_ring is not None and not keep_snapshot:
            self.snapshot_ring.drop(session_id)

    # ------------------------------------------------------------------
    # Durable sessions: export/import/restore (rt1_tpu/serve/migrate.py)
    # ------------------------------------------------------------------

    def _note_act(
        self,
        session_id: str,
        obs: Dict[str, Any],
        result: Dict[str, Any],
    ) -> None:
        """Post-step bookkeeping: advance the per-session step counter
        (it rides exported snapshots so an importer resumes counting, not
        restarts at 0) and, when the snapshot ring is on, write the
        periodic incremental checkpoint. Best-effort by construction — a
        full disk or a racing release must never fail the served step."""
        with self._meta_lock:
            meta = self._session_meta.setdefault(
                session_id, {"step_index": 0}
            )
            if result.get("session_started"):
                meta["step_index"] = 0
            meta["step_index"] = int(meta["step_index"]) + 1
            instruction = obs.get("instruction")
            if isinstance(instruction, str) and instruction:
                meta["instruction"] = instruction
            steps = meta["step_index"]
        if (
            self.snapshot_ring is not None
            and steps % self.snapshot_every == 0
        ):
            try:
                self.snapshot_ring.save(self._build_snapshot(session_id))
            except Exception:
                pass  # durability is advisory; the answer already shipped

    def _build_snapshot(self, session_id: str) -> Dict[str, Any]:
        """Wire-format session snapshot: the engine's rolling state (and
        KV cache leaves when cached inference is on) plus everything the
        importer needs to validate and resume — schema, step counter,
        checkpoint generation, window length, and the instruction (with
        its cached embedding, so the target's embed cache warms without a
        recompute)."""
        base = self.engine.export_session(session_id)
        with self._meta_lock:
            meta = dict(self._session_meta.get(session_id, {}))
        snapshot: Dict[str, Any] = {
            "version": migrate.SNAPSHOT_VERSION,
            "session_id": session_id,
            "step_index": int(meta.get("step_index", 0)),
            "checkpoint_generation": self.checkpoint_generation,
            "window": int(getattr(self.engine, "window", 0)),
            "cached_inference": bool(base.get("cached_inference", False)),
            "schema": [
                [name, list(shape), dtype]
                for name, shape, dtype in base["schema"]
            ],
            "state": migrate.encode_state(base["state"]),
        }
        instruction = meta.get("instruction")
        if instruction:
            snapshot["instruction"] = instruction
            cached = None
            try:
                cached = self.engine.cached_embedding(instruction)
            except Exception:
                cached = None
            if cached is not None:
                snapshot["embedding"] = [float(x) for x in cached]
        return snapshot

    def export_session(self, session_id: str) -> Dict[str, Any]:
        """POST /session/export body: snapshot this session for transport
        to another replica. Pure read — the session keeps serving here
        until the importer confirms and the router remaps affinity."""
        snapshot = self._build_snapshot(session_id)
        with self._meta_lock:
            self.migration_exports += 1
        return snapshot

    def import_session(
        self,
        snapshot: Dict[str, Any],
        session_id: Optional[str] = None,
        _count: bool = True,
    ) -> Dict[str, Any]:
        """POST /session/import body: validate a wire snapshot against
        this replica's generation/window/mode/schema, then scatter it
        into a slot. Refusals raise SnapshotCompatibilityError (HTTP 409)
        naming the mismatched field; the caller falls back to the legacy
        orphan/restart path. `_count=False` is the crash-restore path,
        which books migration_restores instead of migration_imports."""
        try:
            migrate.check_compatibility(
                snapshot,
                checkpoint_generation=self.checkpoint_generation,
                window=int(getattr(self.engine, "window", 0)),
                cached_inference=bool(
                    getattr(self.engine, "cached_inference", False)
                ),
                schema=self.engine.state_schema(),
            )
            state = migrate.decode_state(snapshot["state"])
            slot = self.engine.import_session(
                {
                    "session_id": snapshot["session_id"],
                    "state": state,
                },
                session_id=session_id,
            )
        except Exception:
            if _count:
                with self._meta_lock:
                    self.migration_import_failures += 1
            raise
        sid = session_id or str(snapshot["session_id"])
        instruction = snapshot.get("instruction")
        embedding = snapshot.get("embedding")
        if instruction and embedding is not None:
            try:
                self.engine.seed_embedding(instruction, embedding)
            except Exception:
                pass  # a cold embed cache is a recompute, not an error
        step_index = int(snapshot.get("step_index", 0))
        with self._meta_lock:
            meta = self._session_meta.setdefault(sid, {"step_index": 0})
            meta["step_index"] = step_index
            if instruction:
                meta["instruction"] = instruction
            if _count:
                self.migration_imports += 1
        return {
            "session_id": sid,
            "slot": int(slot),
            "step_index": step_index,
        }

    def maybe_restore_session(
        self, session_id: str
    ) -> Optional[Dict[str, Any]]:
        """Crash-durability hook on the /act path: if this session has no
        live window here but the snapshot ring holds one (we re-homed
        after a SIGKILL), restore it — staleness-bounded, best-effort.
        Returns the response fields to merge (`session_restored`,
        `snapshot_age_s`) or None for the legacy fresh-window path."""
        ring = self.snapshot_ring
        if ring is None:
            return None
        try:
            if session_id in self.engine.session_ids():
                return None
        except Exception:
            return None
        loaded = ring.load(session_id)
        if loaded is None:
            return None
        snapshot, age_s = loaded
        try:
            faults.maybe_fail("session_restore", what=session_id)
            if age_s is not None and age_s > self.snapshot_max_age_s:
                raise migrate.SnapshotCompatibilityError(
                    "session snapshot for %r is %.1fs old, past the "
                    "%.1fs staleness bound — starting a fresh window"
                    % (session_id, age_s, self.snapshot_max_age_s)
                )
            result = self.import_session(
                snapshot, session_id=session_id, _count=False
            )
        except Exception:
            with self._meta_lock:
                self.migration_restore_failures += 1
            # A snapshot that failed once will fail again — drop it so
            # the next /act takes the fresh-window path immediately.
            ring.drop(session_id)
            return None
        with self._meta_lock:
            self.migration_restores += 1
        out: Dict[str, Any] = {
            "session_restored": True,
            "step_index_restored": result["step_index"],
        }
        if age_s is not None:
            out["snapshot_age_s"] = round(float(age_s), 3)
        return out

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: reject new work, flush everything admitted.

        The `_admit_lock` handshake closes the drain/in-flight race: any
        act() that saw `draining == False` has already scheduled its submit
        coroutine, and the loop runs callbacks FIFO — `batcher.drain()` is
        scheduled after it, so the batcher only starts refusing once every
        admitted request sits in its pending queue, where drain flushes it.
        """
        with self._admit_lock:
            self.draining = True
            self.ready = False  # /readyz flips 503 as draining starts
        if self._loop_thread.is_alive():
            asyncio.run_coroutine_threadsafe(
                self.batcher.drain(), self._loop
            ).result(timeout=timeout)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=timeout)
        if self.capture is not None:
            # Sessions cut off by shutdown are still served data — write
            # them (outcome "drain") before the process exits.
            self.capture.drain()
        if self.exemplar_path and len(self.exemplars):
            try:
                self.exemplars.dump(self.exemplar_path, reason="drain")
            except OSError:
                pass  # exit path: a full disk must not block the drain

    def reload(self, step: Optional[int] = None) -> Dict[str, Any]:
        """Zero-downtime checkpoint hot-swap: restore into a standby host
        buffer via `reload_fn`, validate, atomically swap into the engine.

        Serving continues throughout — in-flight and concurrent requests
        run on the old params until the swap lands between two batches.
        `/readyz` reports 503 `reloading` for the duration so a router
        pauses NEW session placement (rolling-reload drain semantics)
        while existing sessions keep flowing. One reload at a time
        (`ReloadInProgressError` -> 409).
        """
        if self._reload_fn is None:
            raise RequestError(
                "this replica has no reload source: started without a "
                "checkpoint workdir (pass reload_fn= to ServeApp)"
            )
        if not self._reload_lock.acquire(blocking=False):
            raise ReloadInProgressError(
                "a checkpoint reload is already in progress"
            )
        try:
            self.reloading = True
            variables, restored_step = self._reload_fn(step)
            info = self.engine.swap_variables(variables)
            self.metrics.observe_reload()
            if restored_step is not None:
                # New weights, new snapshot generation: a rolling state
                # exported under the old checkpoint must not be stepped
                # by the new one (the compatibility check refuses it by
                # generation, so the caller falls back to a fresh window
                # instead of silently mixing weights).
                self.checkpoint_generation = int(restored_step)
            return {
                "ok": True,
                "checkpoint_step": restored_step,
                "reloads_total": self.engine.reloads,
                **info,
            }
        finally:
            self.reloading = False
            self._reload_lock.release()

    def healthz(self) -> Dict[str, Any]:
        return {
            "status": "draining" if self.draining else "ok",
            "replica_id": self.replica_id,
            "image_shape": list(self.image_shape),
            "embed_dim": self.embed_dim,
            "max_sessions": self.engine.max_sessions,
            "active_sessions": self.engine.active_sessions,
            "compile_count": self.engine.compile_count,
            "reloads": self.engine.reloads,
            "inference_dtype": getattr(
                self.engine, "inference_dtype", "f32"
            ),
            "cached_inference": bool(
                getattr(self.engine, "cached_inference", False)
            ),
            # Migration compatibility surface: a router compares these
            # before shipping a session snapshot here (a mismatched
            # generation/window/mode import would be refused anyway —
            # checking first keeps failure counters honest).
            "checkpoint_generation": self.checkpoint_generation,
            "window": int(getattr(self.engine, "window", 0)),
            "session_snapshots": self.snapshot_ring is not None,
            # The serve hot-path contract (ISSUE 12): which scheduler
            # forms batches and which AOT bucket sizes exist —
            # compile_count is pinned at len(buckets) after warm-up.
            "scheduler": self.scheduler,
            "buckets": [
                int(b)
                for b in getattr(
                    self.engine, "buckets", (self.engine.max_sessions,)
                )
            ],
        }

    def readyz(self) -> Tuple[int, Dict[str, Any]]:
        """(http_code, payload) for the readiness probe: 503 unless the
        first AOT compile finished AND no drain/reload is in progress.
        `reloading` is a soft not-ready: the replica still serves /act
        (existing sessions keep flowing through a session-affine router),
        but new placement should wait out the swap."""
        if self.draining:
            return 503, {"ready": False, "reason": "draining"}
        if self.reloading:
            return 503, {"ready": False, "reason": "reloading"}
        if not self.ready:
            return 503, {"ready": False, "reason": "warming"}
        return 200, {"ready": True}

    def _engine_gauges(self) -> Dict[str, Any]:
        return {
            "active_sessions": self.engine.active_sessions,
            "compile_count": self.engine.compile_count,
            # The compile-count invariant's denominator: compile_count
            # must equal bucket_count after warm-up and every reload.
            "bucket_count": len(
                getattr(self.engine, "buckets", (1,))
            ),
            "embed_cache_misses": self.engine.embed_calls,
            # Nonzero while serving steady traffic = more live sessions
            # than slots; their context windows are thrashing to zero.
            "session_evictions": self.engine.evictions,
            "slow_exemplars": len(self.exemplars),
            # 1 while the batcher drains after SIGTERM (scrapers see the
            # shutdown even if their LB already stopped routing /readyz).
            "draining": int(self.draining),
            "ready": int(self.ready),
            "reloading": int(self.reloading),
            "replica_id": self.replica_id,
            # Low-precision serving mode + the param-byte evidence behind
            # its memory claim (docs/serving.md "Low-precision serving").
            "inference_dtype": getattr(
                self.engine, "inference_dtype", "f32"
            ),
            "param_bytes_device": getattr(
                self.engine, "serving_param_bytes", 0
            ),
            "param_bytes_master": getattr(
                self.engine, "master_param_bytes", 0
            ),
            # Incremental-decode (KV cache) gauges: enabled flag always
            # present so dashboards can tell "off" from "zero"; the
            # invalidation counters split by cause (swap|reset|evict) —
            # a swap-heavy fleet rebuilds, a churn-heavy one resets.
            "cache_enabled": int(
                bool(getattr(self.engine, "cached_inference", False))
            ),
            "cache_bytes_per_slot": getattr(
                self.engine, "cache_bytes_per_slot", 0
            ),
            "cache_cached_steps_total": getattr(
                self.engine, "cache_cached_steps", 0
            ),
            "cache_rebuild_steps_total": getattr(
                self.engine, "cache_rebuild_steps", 0
            ),
            "cache_invalidations": dict(
                getattr(self.engine, "cache_invalidations", {})
                or {"swap": 0, "reset": 0, "evict": 0}
            ),
            # Durable-session counters (rt1_serve_migration_*): always
            # present so dashboards can tell "migration idle" from "not
            # deployed". exports/imports are the live-migration transport;
            # restores are the crash-durability ring path.
            "migration_exports_total": self.migration_exports,
            "migration_imports_total": self.migration_imports,
            "migration_import_failures_total": (
                self.migration_import_failures
            ),
            "migration_restores_total": self.migration_restores,
            "migration_restore_failures_total": (
                self.migration_restore_failures
            ),
            # Flywheel capture gauges (rt1_serve_capture_*): enabled flag
            # always present so dashboards can tell "off" from "zero".
            **(
                self.capture.stats()
                if self.capture is not None
                else {"capture_enabled": 0}
            ),
        }

    def metrics_snapshot(self) -> Dict[str, Any]:
        return self.metrics.snapshot(**self._engine_gauges())

    def metrics_prometheus(self) -> str:
        """The same numbers in exposition text (scraper-negotiated path)."""
        return self.metrics.prometheus_text(**self._engine_gauges())


class _Handler(BaseHTTPRequestHandler):
    # Accurate Content-Length is set on every response, so HTTP/1.1
    # keep-alive is safe and saves the load generator a handshake per step.
    protocol_version = "HTTP/1.1"
    app: ServeApp = None  # bound by make_server
    quiet: bool = True

    def log_message(self, fmt, *args):  # noqa: D102 - stdlib hook
        if not self.quiet:
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            raise RequestError("missing request body")
        try:
            payload = json.loads(self.rfile.read(length))
        except json.JSONDecodeError as exc:
            raise RequestError(f"invalid JSON body: {exc}") from exc
        if not isinstance(payload, dict):
            raise RequestError("request body must be a JSON object")
        return payload

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib casing
        if self.path == "/healthz":
            self._reply(200, self.app.healthz())
        elif self.path == "/readyz":
            code, payload = self.app.readyz()
            self._reply(code, payload)
        elif self.path == "/metrics":
            # Content negotiation: JSON stays the default (loadgen,
            # existing automation); a Prometheus scraper's Accept header
            # (`text/plain` / openmetrics) gets the exposition format.
            if obs_prometheus.accepts_text(self.headers.get("Accept")):
                self._reply_text(
                    200,
                    self.app.metrics_prometheus(),
                    obs_prometheus.CONTENT_TYPE,
                )
            else:
                self._reply(200, self.app.metrics_snapshot())
        elif self.path == "/slow_requests":
            # The live exemplar ring: slowest/most recent requests with
            # their phase breakdowns (the router fans this out fleet-wide
            # on /fleet/slow_requests).
            self._reply(
                200,
                {
                    **self.app.exemplars.stats(),
                    "slow_requests": self.app.exemplars.snapshot(),
                },
            )
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802 - stdlib casing
        try:
            payload = self._read_json()
        except RequestError as exc:
            self._reply(400, {"error": str(exc)})
            return
        if self.path == "/act":
            self._act(payload)
        elif self.path == "/reset":
            self._session_op(payload, self.app.reset, "slot",
                             count_reset=True)
        elif self.path == "/release":
            self._session_op(
                payload,
                lambda sid: self.app.release(
                    sid, keep_snapshot=bool(payload.get("keep_snapshot"))
                ),
                None,
            )
        elif self.path == "/reload":
            self._reload(payload)
        elif self.path == "/session/export":
            self._session_export(payload)
        elif self.path == "/session/import":
            self._session_import(payload)
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def _session_export(self, payload):
        try:
            snapshot = self.app.export_session(self._session_id(payload))
        except RequestError as exc:
            self._reply(400, {"error": str(exc)})
            return
        except SessionError as exc:
            self._reply(404, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 - export must not 500-loop
            self._reply(500, {"error": f"export failed: {exc}"})
            return
        self._reply(200, {"ok": True, "snapshot": snapshot})

    def _session_import(self, payload):
        snapshot = payload.get("snapshot")
        if not isinstance(snapshot, dict):
            self._reply(400, {"error": "'snapshot' must be a JSON object"})
            return
        session_id = payload.get("session_id")
        if session_id is not None and (
            not isinstance(session_id, str) or not session_id
        ):
            self._reply(400, {"error": "'session_id' must be a non-empty "
                                       "string when given"})
            return
        try:
            result = self.app.import_session(snapshot, session_id=session_id)
        except migrate.SnapshotCompatibilityError as exc:
            # Before ValueError: it IS a ValueError, but a refusal is a
            # conflict with this replica's generation/window/mode (409),
            # not a malformed request (400).
            self._reply(409, {"error": str(exc)})
            return
        except SlotContentionError as exc:
            self._reply(503, {"error": str(exc), "retry": True})
            return
        except (RequestError, SessionError, ValueError) as exc:
            self._reply(400, {"error": str(exc)})
            return
        except Exception as exc:  # noqa: BLE001 - import must not crash
            self._reply(500, {"error": f"import failed: {exc}"})
            return
        self._reply(200, {"ok": True, **result})

    def _reload(self, payload):
        step = payload.get("step")
        if step is not None and not isinstance(step, int):
            self._reply(400, {"error": "'step' must be an integer"})
            return
        try:
            self._reply(200, self.app.reload(step))
        except RequestError as exc:
            self._reply(400, {"error": str(exc)})
        except ReloadInProgressError as exc:
            self._reply(409, {"error": str(exc), "retry": True})
        except Exception as exc:  # noqa: BLE001 - restore/validate failure
            # Old params are still serving (swap_variables rejects without
            # touching the engine) — report, don't crash the replica.
            self._reply(500, {"error": f"reload failed: {exc}"})

    def _session_id(self, payload) -> str:
        session_id = payload.get("session_id")
        if not isinstance(session_id, str) or not session_id:
            raise RequestError("'session_id' must be a non-empty string")
        return session_id

    def _session_op(self, payload, op, result_key, count_reset=False):
        try:
            value = op(self._session_id(payload))
        except RequestError as exc:
            self._reply(400, {"error": str(exc)})
            return
        except SlotContentionError as exc:
            # Transient: every slot is riding an in-flight step (a /reset
            # claiming a fresh slot under double-buffered saturation) —
            # retryable 503, same as the /act path.
            self._reply(503, {"error": str(exc), "retry": True})
            return
        except SessionError as exc:
            self._reply(404, {"error": str(exc)})
            return
        out = {"ok": True}
        if result_key is not None:
            out[result_key] = value
        if count_reset:
            self.app.metrics.observe_reset()
        self._reply(200, out)

    def _fail_act(self, code, phases, session_id, t0, outcome, body):
        """One exit for every non-200 /act path: metrics, exemplar ring
        (failures are exactly the exemplars a post-mortem wants), and the
        request id echoed so the client can quote it."""
        if outcome == "failed":
            self.app.metrics.observe_request(
                time.perf_counter() - t0, ok=False
            )
        body["request_id"] = phases.request_id
        self.app.exemplars.offer(
            (obs_trace.now_us() - phases.t_admit) / 1e3,
            request_id=phases.request_id,
            session=session_id,
            outcome=outcome,
            error=body.get("error"),
            phases=phases.phases_ms(),
        )
        self._reply(code, body)

    def _act(self, payload):
        phases = reqtrace.RequestPhases(
            reqtrace.request_id_from(self.headers, payload)
        )
        t0 = time.perf_counter()
        if self.app.draining:
            # Same contract as every other /act exit: the id is echoed
            # and the shed request is an exemplar (a drain-window 503 is
            # post-mortem material like any other rejection).
            self._fail_act(
                503, phases, payload.get("session_id"), t0,
                "rejected", {"error": "draining"})
            return
        session_id = None
        with obs_trace.span(
            "replica_act",
            request_id=phases.request_id,
            replica=self.app.replica_id,
        ):
            try:
                session_id = self._session_id(payload)
                obs = parse_observation(
                    payload, self.app.image_shape, self.app.embed_dim
                )
                task = payload.get("task")
                result = self.app.act(
                    session_id, obs, phases,
                    task=task if isinstance(task, str) else None,
                )
            except RequestError as exc:
                self._fail_act(400, phases, session_id, t0,
                               "failed", {"error": str(exc)})
                return
            except (BusyError, SlotContentionError):
                # Queue at max_queue, or every slot riding this batch /
                # an in-flight step (double-buffered oversubscription) —
                # both transient by construction: shed retryably.
                self._fail_act(503, phases, session_id, t0,
                               "rejected",
                               {"error": "busy", "retry": True})
                return
            except DrainingError:
                self._fail_act(503, phases, session_id, t0,
                               "rejected", {"error": "draining"})
                return
            except concurrent.futures.TimeoutError:
                self._fail_act(
                    504, phases, session_id, t0, "failed",
                    {"error": "request timed out in the server"})
                return
            except (SessionError, ValueError, KeyError) as exc:
                # KeyError: a TableInstructionEmbedder miss. The engine
                # turned per-item failures into markers; app.act re-raised
                # this one — batchmates were unaffected.
                self._fail_act(400, phases, session_id, t0,
                               "failed", {"error": str(exc)})
                return
            except Exception as exc:  # noqa: BLE001 - last-resort HTTP 500
                self._fail_act(500, phases, session_id, t0,
                               "failed",
                               {"error": f"internal error: {exc}"})
                return
        self.app.metrics.observe_request(time.perf_counter() - t0)
        phases.t_done = obs_trace.now_us()
        phases.emit_trace(session_id)
        breakdown = phases.phases_ms()
        self.app.exemplars.offer(
            breakdown["total_ms"],
            request_id=phases.request_id,
            session=session_id,
            outcome="ok",
            phases=breakdown,
        )
        out = {
            "action": [float(x) for x in result["action"]],
            "action_tokens": [int(x) for x in result["action_tokens"]],
            # True when this step started a fresh (zeroed) window — a
            # client that did not /reset just lost its slot to LRU reclaim.
            "session_started": result.get("session_started", False),
            "request_id": phases.request_id,
        }
        if payload.get(reqtrace.DEBUG_KEY):
            out["phases"] = breakdown
        if "terminate_episode" in result:
            out["terminate_episode"] = result["terminate_episode"]
        if result.get("session_restored"):
            # Crash durability: this step resumed a ring-snapshotted
            # window instead of starting fresh — the router books the
            # outcome as `migrated`, not `restarted`.
            out["session_restored"] = True
            if "snapshot_age_s" in result:
                out["snapshot_age_s"] = result["snapshot_age_s"]
        self._reply(200, out)


def make_server(
    app: ServeApp, host: str = "127.0.0.1", port: int = 0, quiet: bool = True
) -> ThreadingHTTPServer:
    """Bind a ThreadingHTTPServer to `app` (port 0 = ephemeral)."""
    handler = type("BoundHandler", (_Handler,), {"app": app, "quiet": quiet})
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_threads = True
    return httpd


def install_signal_handlers(
    app: ServeApp, httpd: ThreadingHTTPServer
) -> None:
    """SIGTERM/SIGINT -> drain accepted requests, then stop the server."""

    def _shutdown(signum, frame):  # noqa: ARG001 - signal signature
        def _run():
            app.drain()
            httpd.shutdown()

        threading.Thread(target=_run, name="rt1-serve-drain").start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
