"""Fleet supervisor: spawn, watch, restart, and chaos-test serving replicas.

`python -m rt1_tpu.serve.fleet --replicas 3 --config ... --random_init`
brings up N replica processes (`python -m rt1_tpu.serve`, or the model-free
stub with `--stub`), fronts them with the session-affine `Router`
(`serve/router.py`), and runs a supervision loop:

* **Warm-up gating.** A spawned replica is routable only after it prints
  the ready-line (which carries its ephemeral port) AND its `/readyz`
  returns 200 — a replica still paying jax import or the AOT compile never
  sees traffic, on first boot and on every restart alike.
* **Death and hang detection.** Every poll cycle checks `proc.poll()`
  (crash/kill) and probes `/readyz`. A process that is alive to the OS but
  black-holing probes (`replica_hang` chaos = SIGSTOP, a wedged runtime in
  production) accumulates consecutive probe failures and is SIGKILLed and
  respawned — SIGKILL because a stopped process cannot run a SIGTERM
  handler. Either way the router orphans its sessions immediately; their
  next `/act` re-homes with ``"restarted": true``.
* **Deterministic chaos.** The supervisor consults the PR 4 fault registry
  (`rt1_tpu/resilience/faults.py`, sites `replica_kill` / `replica_hang` /
  `serve_reload`) once per **chaos tick** — one tick every
  `chaos_interval_s`, counted only after the fleet first reports
  all-ready, with the tick ordinal as the fault index. Same plan, same
  failure schedule, every run: `replica_kill@1,serve_reload@2` always
  kills at tick 1 and rolls a reload at tick 2. Victim selection is
  deterministic too (lowest-id ready replica).

The supervisor owns processes, the router owns routing state; they meet at
the shared `Replica` objects. `scripts/serve_loadgen.py --fleet N` drives
this module as a subprocess and turns the chaos run into
`BENCH_serve_fleet.json`.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from rt1_tpu.resilience import faults
from rt1_tpu.serve.router import (
    DEAD,
    NOTREADY,
    READY,
    STARTING,
    Replica,
    Router,
    get_json,
    make_router_server,
)


class FleetSupervisor:
    """Owns N replica subprocesses on behalf of a Router."""

    def __init__(
        self,
        router: Router,
        spawn_argv_fn: Callable[[int], List[str]],
        n_replicas: int,
        *,
        poll_interval_s: float = 0.25,
        chaos_interval_s: float = 2.0,
        warmup_timeout_s: float = 600.0,
        hang_probe_failures: int = 3,
        probe_timeout_s: float = 2.0,
        max_restarts: int = 50,
        log_dir: Optional[str] = None,
        extra_env: Optional[Dict[str, str]] = None,
        exemplar_scrape_interval_s: float = 2.0,
        capture_root: Optional[str] = None,
    ):
        self.router = router
        self._spawn_argv_fn = spawn_argv_fn
        self.n_replicas = n_replicas
        self.poll_interval_s = poll_interval_s
        self.chaos_interval_s = chaos_interval_s
        self.warmup_timeout_s = warmup_timeout_s
        self.hang_probe_failures = hang_probe_failures
        self.probe_timeout_s = probe_timeout_s
        self.max_restarts = max_restarts
        self.log_dir = log_dir
        self.extra_env = extra_env
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._scrape_thread: Optional[threading.Thread] = None
        # Chaos bookkeeping (summary + determinism evidence). Mutated only
        # on the single supervisor thread; readers (summary, tests)
        # tolerate a stale int — no lock needed or implied.
        self.chaos_tick = 0
        self._fleet_was_ready = False
        self.kills_injected = 0
        self.hangs_injected = 0
        self.reloads_injected = 0
        self.restarts_total = 0
        # Slow-request exemplars, scraped from each live replica's
        # GET /slow_requests on a slow cadence. A SIGKILLed replica never
        # runs its drain-time dump, so the supervisor's last scrape is
        # the only copy of "what the victim was serving when it died" —
        # the serve-side flight-recorder semantics the post-mortem needs.
        self.exemplar_scrape_interval_s = exemplar_scrape_interval_s
        # Written by the scrape thread, read by slow_request_evidence()
        # (fleet main's final status line, while the scraper still runs).
        self._exemplar_lock = threading.Lock()
        self.last_exemplars: Dict[int, Dict[str, Any]] = {}
        # Data flywheel: each replica captures episodes into
        # <capture_root>/replica_<id>; the scrape loop sweeps completed
        # files into <capture_root>/staging — ONE dir the packer appends
        # from (`scripts/pack_dataset.py --append`), fed by N replicas
        # that keep writing across kills and respawns.
        self.capture_root = capture_root
        self.captures_swept = 0

    # ------------------------------------------------------------ spawning

    def _spawn(self, replica: Replica) -> None:
        """(Re)launch one replica; its ready-line reader runs on a thread."""
        argv = self._spawn_argv_fn(replica.id)
        stderr = None
        if self.log_dir is not None:
            os.makedirs(self.log_dir, exist_ok=True)
            path = os.path.join(
                self.log_dir,
                f"replica{replica.id}.g{replica.restarts}.log",
            )
            stderr = open(path, "w")  # noqa: SIM115 - closed after Popen
        env = dict(os.environ)
        if self.extra_env:
            env.update(self.extra_env)
        replica.url = None
        replica.state = STARTING
        replica.consecutive_probe_failures = 0
        try:
            replica.proc = subprocess.Popen(
                argv,
                stdout=subprocess.PIPE,
                stderr=stderr,
                text=True,
                env=env,
            )
        finally:
            if stderr is not None:
                # Popen dup'd the fd into the child; keeping the parent's
                # copy open would leak one fd per (re)spawn.
                stderr.close()
        threading.Thread(
            target=self._read_ready_line,
            args=(replica, replica.proc),
            name=f"rt1-fleet-stdout-{replica.id}",
            daemon=True,
        ).start()

    def _read_ready_line(self, replica: Replica, proc) -> None:
        """Parse `{"status": "serving", "port": ...}` off the replica's
        stdout, then keep draining so the pipe never fills."""
        try:
            for line in proc.stdout:
                if replica.url is None:
                    try:
                        ready = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if ready.get("status") == "serving":
                        host = ready.get("host", "127.0.0.1")
                        replica.url = f"http://{host}:{ready['port']}"
        except (ValueError, OSError):
            pass  # closed pipe on kill/shutdown

    def start(self, wait_ready: bool = True) -> None:
        for i in range(self.n_replicas):
            self.router.add_replica(Replica(i))
        for replica in self.router.replicas():
            self._spawn(replica)
        if wait_ready:
            try:
                self.wait_all_ready()
            except BaseException:
                # A failed warm-up (one replica crashed, bad config, ...)
                # must not leak the siblings that DID spawn.
                self.stop()
                raise
        self._thread = threading.Thread(
            target=self._supervise, name="rt1-fleet-supervisor", daemon=True
        )
        self._thread.start()
        if self.exemplar_scrape_interval_s > 0:
            # Own thread: a hung replica makes each /slow_requests probe
            # eat its full timeout, which on the supervision thread would
            # delay the very death detection that makes the scrape matter.
            self._scrape_thread = threading.Thread(
                target=self._scrape_loop,
                name="rt1-fleet-exemplar-scrape",
                daemon=True,
            )
            self._scrape_thread.start()

    def wait_all_ready(self) -> None:
        """Block until every replica passes warm-up (ready-line + /readyz),
        raising if one dies or the warm-up budget expires."""
        deadline = time.monotonic() + self.warmup_timeout_s
        pending = {r.id for r in self.router.replicas()}
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replicas {sorted(pending)} not ready after "
                    f"{self.warmup_timeout_s:.0f}s"
                )
            for replica in self.router.replicas():
                if replica.id not in pending:
                    continue
                if replica.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {replica.id} exited rc="
                        f"{replica.proc.returncode} during warm-up"
                        + (
                            f" (see {self.log_dir})"
                            if self.log_dir
                            else ""
                        )
                    )
                if self._probe_ready(replica):
                    pending.discard(replica.id)
            time.sleep(0.05)

    def _probe_ready(self, replica: Replica) -> bool:
        if replica.url is None:
            return False
        status, _ = get_json(
            replica.url + "/readyz", timeout=self.probe_timeout_s
        )
        if status == 200:
            replica.consecutive_probe_failures = 0
            self.router.set_state(replica.id, READY)
            return True
        return False

    # --------------------------------------------------------- supervision

    def _supervise(self) -> None:
        last_chaos = time.monotonic()
        while not self._stop.is_set():
            for replica in self.router.replicas():
                try:
                    self._check_replica(replica)
                except Exception as exc:  # noqa: BLE001 - keep healing
                    # One bad cycle (full-disk log open, a wait()
                    # timeout) must not kill supervision for good — a
                    # dead supervisor means no respawns and a silently
                    # decaying fleet.
                    print(
                        json.dumps(
                            {
                                "status": "supervise_error",
                                "replica": replica.id,
                                "error": str(exc),
                            }
                        ),
                        file=sys.stderr,
                        flush=True,
                    )
            if not self._fleet_was_ready:
                # Chaos ticks start only once the fleet has been fully
                # ready once — fault indices then count ticks, making
                # the schedule independent of warm-up wall time.
                self._fleet_was_ready = self.router.ready_count() == (
                    self.n_replicas
                )
                last_chaos = time.monotonic()
            elif time.monotonic() - last_chaos >= self.chaos_interval_s:
                last_chaos = time.monotonic()
                self.chaos_tick += 1
                try:
                    self._inject_chaos(self.chaos_tick)
                except Exception as exc:  # noqa: BLE001 - see above
                    print(
                        json.dumps(
                            {
                                "status": "chaos_error",
                                "tick": self.chaos_tick,
                                "error": str(exc),
                            }
                        ),
                        file=sys.stderr,
                        flush=True,
                    )
            self._stop.wait(self.poll_interval_s)

    def _check_replica(self, replica: Replica) -> None:
        if replica.proc is None:
            return
        if replica.proc.poll() is not None:
            if replica.state != DEAD:
                self.router.mark_dead(replica, reason="process exited")
            self._respawn(replica)
            return
        if replica.url is None:
            return  # still booting, ready-line not printed yet
        status, _ = get_json(
            replica.url + "/readyz", timeout=self.probe_timeout_s
        )
        if status == 200:
            replica.consecutive_probe_failures = 0
            if replica.state != READY:
                self.router.set_state(replica.id, READY)
        elif status == 0:
            replica.consecutive_probe_failures += 1
            if replica.consecutive_probe_failures >= self.hang_probe_failures:
                # Alive to the OS, dead to HTTP: hung. SIGKILL (a stopped
                # process cannot run SIGTERM handlers) and respawn.
                self.router.mark_dead(replica, reason="hang detected")
                replica.proc.kill()
                replica.proc.wait(timeout=10)
                self._respawn(replica)
        else:  # a live 503: warming / draining / reloading
            replica.consecutive_probe_failures = 0
            if replica.state == READY:
                self.router.set_state(replica.id, NOTREADY)

    def _scrape_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._scrape_exemplars()
                self.sweep_captures()
            except Exception as exc:  # noqa: BLE001 - keep scraping
                print(
                    json.dumps(
                        {"status": "exemplar_scrape_error", "error": str(exc)}
                    ),
                    file=sys.stderr,
                    flush=True,
                )
            self._stop.wait(self.exemplar_scrape_interval_s)

    def _scrape_exemplars(self) -> None:
        """Pull each live replica's slow-request ring into supervisor
        memory, so the exemplars survive a SIGKILL/crash of the replica.
        Keyed by replica id; a respawned replica's fresh (empty) ring only
        replaces the dead generation's scrape once it has entries —
        "nothing recorded yet" must not erase the crash evidence."""
        for replica in self.router.replicas():
            if replica.url is None or replica.state == DEAD:
                continue
            status, body = get_json(
                replica.url + "/slow_requests", timeout=self.probe_timeout_s
            )
            if status != 200 or not isinstance(body, dict):
                continue
            with self._exemplar_lock:
                if (
                    body.get("retained")
                    or replica.id not in self.last_exemplars
                ):
                    body["scraped_at"] = time.time()
                    body["generation"] = replica.restarts
                    self.last_exemplars[replica.id] = body

    def replica_capture_dir(self, replica_id: int) -> Optional[str]:
        if self.capture_root is None:
            return None
        return os.path.join(self.capture_root, f"replica_{replica_id}")

    def capture_staging_dir(self) -> Optional[str]:
        if self.capture_root is None:
            return None
        return os.path.join(self.capture_root, "staging")

    def sweep_captures(self) -> int:
        """Move completed per-replica capture files into the staging dir
        (same-filesystem renames; a SIGKILLed replica's already-renamed
        episodes survive it, exactly like the exemplar scrape)."""
        if self.capture_root is None:
            return 0
        from rt1_tpu.flywheel.capture import sweep_captures

        moved = sweep_captures(
            [
                self.replica_capture_dir(r.id)
                for r in self.router.replicas()
            ],
            self.capture_staging_dir(),
        )
        self.captures_swept += moved
        return moved

    def _respawn(self, replica: Replica) -> None:
        if self.restarts_total >= self.max_restarts:
            return  # crash-looping fleet: stop burning the host
        self.restarts_total += 1
        replica.restarts += 1
        self._spawn(replica)

    # --------------------------------------------------------------- chaos

    def _inject_chaos(self, tick: int) -> None:
        plan = faults.active()
        if plan is None:
            return
        if plan.should_fire("replica_kill", index=tick):
            victim = self._victim()
            if victim is not None:
                self.kills_injected += 1
                self.router.mark_dead(victim, reason="chaos replica_kill")
                victim.proc.kill()
        if plan.should_fire("replica_hang", index=tick):
            victim = self._victim()
            if victim is not None:
                self.hangs_injected += 1
                victim.proc.send_signal(signal.SIGSTOP)
        if plan.should_fire("serve_reload", index=tick):
            self.reloads_injected += 1
            threading.Thread(
                target=self.router.rolling_reload,
                name="rt1-fleet-chaos-reload",
                daemon=True,
            ).start()

    def _victim(self) -> Optional[Replica]:
        ready = [
            r for r in self.router.replicas()
            if r.state == READY and r.proc is not None
        ]
        return min(ready, key=lambda r: r.id) if ready else None

    # ------------------------------------------------------------ shutdown

    def stop(self, timeout: float = 15.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=timeout)
        for replica in self.router.replicas():
            proc = replica.proc
            if proc is None or proc.poll() is not None:
                continue
            proc.send_signal(signal.SIGCONT)  # un-wedge a SIGSTOP victim
            proc.terminate()
        for replica in self.router.replicas():
            proc = replica.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)

    def summary(self) -> Dict[str, Any]:
        return {
            "chaos_ticks": self.chaos_tick,
            "kills_injected": self.kills_injected,
            "hangs_injected": self.hangs_injected,
            "reloads_injected": self.reloads_injected,
            "replica_restarts": self.restarts_total,
            "captures_swept": self.captures_swept,
            "faults_fired": (
                faults.active().fired_counts() if faults.active() else {}
            ),
        }

    def slow_request_evidence(
        self, per_replica: int = 8
    ) -> Dict[str, Any]:
        """The last-scraped exemplars, trimmed to the `per_replica` most
        recent records each — the fleet's crash-surviving slow-request
        evidence for the final status line / post-mortem."""
        out = {}
        with self._exemplar_lock:
            snapshot = sorted(self.last_exemplars.items())
        for rid, scrape in snapshot:
            records = scrape.get("slow_requests", [])
            out[str(rid)] = {
                **{k: v for k, v in scrape.items() if k != "slow_requests"},
                "slow_requests": records[-per_replica:],
            }
        return out


# -------------------------------------------------------------- entry point


# Mirrors models/quant.INFERENCE_DTYPES without importing flax into the
# supervisor/router process (which stays model-free).
VALID_REPLICA_DTYPES = ("f32", "bf16", "int8")


def replica_dtype_for(args, replica_id: int) -> str:
    """This replica's inference dtype: the per-replica `--replica_dtypes`
    list (a mixed-dtype fleet — cheap int8 replicas beside an f32
    reference) wins over the fleet-wide `--inference_dtype` default.

    Every list entry is validated here — unlike `--inference_dtype` there
    is no argparse `choices` guard, and an invalid entry would otherwise
    surface as a replica crash-loop at the CHILD's argparse instead of a
    message naming the typo.
    """
    per_replica = [
        d.strip()
        for d in getattr(args, "replica_dtypes", "").split(",")
        if d.strip()
    ]
    for dtype in per_replica:
        if dtype not in VALID_REPLICA_DTYPES:
            raise ValueError(
                f"--replica_dtypes entry {dtype!r} is not one of "
                f"{VALID_REPLICA_DTYPES}"
            )
    if per_replica:
        return per_replica[replica_id % len(per_replica)]
    return getattr(args, "inference_dtype", "f32")


def replica_argv_builder(args) -> Callable[[int], List[str]]:
    """argv factory for one replica — the stub or the real server."""
    slow_threshold = getattr(args, "slow_threshold_ms", 0.0)
    scheduler = getattr(args, "scheduler", "continuous")
    buckets = getattr(args, "buckets", "auto")
    if args.stub:
        def build(replica_id: int) -> List[str]:
            return [
                sys.executable, "-m", "rt1_tpu.serve.stub",
                "--port", "0",
                "--replica_id", str(replica_id),
                "--max_sessions", str(args.max_sessions),
                "--act_delay_s", str(args.stub_act_delay_s),
                "--slow_threshold_ms", str(slow_threshold),
                "--inference_dtype", replica_dtype_for(args, replica_id),
                "--scheduler", scheduler,
                # The stub has no compiler; it advertises the contract
                # field ("1" = one bucket) unless a ladder is forced.
                "--buckets", buckets if buckets != "auto" else "1",
            ]
        return build

    capture_root = getattr(args, "capture_dir", "")

    def build(replica_id: int) -> List[str]:
        argv = [
            sys.executable, "-m", "rt1_tpu.serve",
            "--config", args.config,
            "--port", "0",
            "--replica_id", str(replica_id),
            "--max_sessions", str(args.max_sessions),
            "--embedder", args.embedder,
            "--slow_threshold_ms", str(slow_threshold),
            "--inference_dtype", replica_dtype_for(args, replica_id),
            "--scheduler", scheduler,
            "--buckets", buckets,
        ]
        if capture_root:
            # Per-replica capture dir; the supervisor sweeps completed
            # files into <capture_dir>/staging for the packer.
            argv.extend([
                "--capture_dir",
                os.path.join(capture_root, f"replica_{replica_id}"),
            ])
        if args.random_init:
            argv.append("--random_init")
        else:
            argv.extend(["--workdir", args.workdir])
        return argv
    return build


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8400,
                        help="Router bind port (0 = ephemeral).")
    parser.add_argument("--config", default="",
                        help="Model/data config path, forwarded to replicas.")
    parser.add_argument("--workdir", default="",
                        help="Checkpoint dir, forwarded to replicas "
                             "(enables /reload from disk).")
    parser.add_argument("--random_init", action="store_true")
    parser.add_argument("--stub", action="store_true",
                        help="Spawn model-free stub replicas "
                             "(rt1_tpu.serve.stub) — protocol-true, no jax.")
    parser.add_argument("--max_sessions", type=int, default=8)
    parser.add_argument("--embedder", default="hash")
    parser.add_argument("--stub_act_delay_s", type=float, default=0.0)
    parser.add_argument(
        "--scheduler", default="continuous",
        choices=["continuous", "cycle"],
        help="Batch scheduler forwarded to every replica (ISSUE 12: "
             "'continuous' rolls requests into the next device step; "
             "'cycle' is the legacy deadline loop).")
    parser.add_argument(
        "--buckets", default="auto",
        help="AOT batch-size buckets forwarded to every replica "
             "('auto' = pow2 ladder; comma ints to pin).")
    parser.add_argument(
        "--inference_dtype", default="f32",
        choices=["f32", "bf16", "int8"],
        help="Low-precision serving mode forwarded to every replica "
             "(rt1_tpu/models/quant.py).")
    parser.add_argument(
        "--replica_dtypes", default="",
        help="Comma list assigning a dtype per replica id (cycled), e.g. "
             "'f32,int8,int8' — a mixed-dtype fleet; overrides "
             "--inference_dtype.")
    parser.add_argument(
        "--capture_dir", default="",
        help="Data flywheel: per-replica episode capture under "
             "<dir>/replica_<id>, swept into <dir>/staging by the "
             "supervisor (real replicas only; the model-free stub serves "
             "no observations worth capturing).")
    parser.add_argument(
        "--slow_threshold_ms", type=float, default=0.0,
        help="Replica exemplar-ring threshold, forwarded to every "
             "replica (0 keeps the most recent window of all requests).")
    parser.add_argument(
        "--slo_availability", type=float, default=0.99,
        help="Router SLO: fraction of requests that must be ok.")
    parser.add_argument(
        "--slo_p50_ms", type=float, default=250.0,
        help="Router SLO: answered-request p50 objective (ms).")
    parser.add_argument(
        "--slo_p99_ms", type=float, default=2500.0,
        help="Router SLO: answered-request p99 objective (ms).")
    parser.add_argument("--faults", default="",
                        help="Chaos plan, e.g. 'replica_kill@1,"
                             "serve_reload@2' (RT1_FAULTS appended).")
    parser.add_argument("--chaos_interval_s", type=float, default=2.0)
    parser.add_argument("--poll_interval_s", type=float, default=0.25)
    parser.add_argument("--replica_timeout_s", type=float, default=30.0)
    parser.add_argument("--max_failovers", type=int, default=2)
    parser.add_argument("--warmup_timeout_s", type=float, default=600.0)
    parser.add_argument("--log_dir", default="",
                        help="Per-replica stderr logs (default: inherit).")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    if not args.stub and not args.config:
        parser.error("--config is required unless --stub")
    try:
        replica_dtype_for(args, 0)  # validates every --replica_dtypes entry
    except ValueError as exc:
        parser.error(str(exc))
    if not args.stub and not args.random_init and not args.workdir:
        parser.error("pass --workdir (checkpoint) or --random_init")

    faults.install_from(args.faults)

    from rt1_tpu.obs.slo import SLOLedger, SLOObjectives

    router = Router(
        replica_timeout_s=args.replica_timeout_s,
        max_failovers=args.max_failovers,
        slo=SLOLedger(
            SLOObjectives(
                availability=args.slo_availability,
                latency_p50_ms=args.slo_p50_ms,
                latency_p99_ms=args.slo_p99_ms,
            )
        ),
    )
    supervisor = FleetSupervisor(
        router,
        replica_argv_builder(args),
        args.replicas,
        chaos_interval_s=args.chaos_interval_s,
        poll_interval_s=args.poll_interval_s,
        warmup_timeout_s=args.warmup_timeout_s,
        log_dir=args.log_dir or None,
        capture_root=(args.capture_dir or None) if not args.stub else None,
    )
    supervisor.start(wait_ready=True)
    httpd = make_router_server(
        router, host=args.host, port=args.port, quiet=not args.verbose
    )

    stop_once = threading.Event()

    def _shutdown(signum, frame):  # noqa: ARG001 - signal signature
        if stop_once.is_set():
            return
        stop_once.set()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)

    print(
        json.dumps(
            {
                "status": "serving",
                "role": "router",
                "host": httpd.server_address[0],
                "port": httpd.server_address[1],
                "replicas": args.replicas,
                "stub": bool(args.stub),
                "faults": args.faults or os.environ.get(faults.ENV_VAR, ""),
            }
        ),
        flush=True,
    )
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        router.draining = True
        final = {
            "status": "stopped",
            "fleet": router.fleet_status(probe_metrics=True),
            "chaos": supervisor.summary(),
            "router_metrics": router.metrics_snapshot(),
            # The fleet's own judgement + crash-surviving exemplars, so a
            # chaos driver (loadgen) can fold the server-side SLO story
            # into its BENCH record without re-deriving it client-side.
            "slo": router.slo.summary(),
            "slow_requests": supervisor.slow_request_evidence(),
        }
        supervisor.stop()
        # Replicas drained on SIGTERM (writing their in-flight capture
        # buffers); one last sweep moves those into staging.
        supervisor.sweep_captures()
        final["chaos"]["captures_swept"] = supervisor.captures_swept
        print(json.dumps(final), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
