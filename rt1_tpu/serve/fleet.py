"""Fleet supervisor: spawn, watch, restart, and chaos-test serving replicas.

`python -m rt1_tpu.serve.fleet --replicas 3 --config ... --random_init`
brings up N replica processes (`python -m rt1_tpu.serve`, or the model-free
stub with `--stub`), fronts them with the session-affine `Router`
(`serve/router.py`), and runs a supervision loop:

* **Warm-up gating.** A spawned replica is routable only after it prints
  the ready-line (which carries its ephemeral port) AND its `/readyz`
  returns 200 — a replica still paying jax import or the AOT compile never
  sees traffic, on first boot and on every restart alike.
* **Death and hang detection.** Every poll cycle checks `proc.poll()`
  (crash/kill) and probes `/readyz`. A process that is alive to the OS but
  black-holing probes (`replica_hang` chaos = SIGSTOP, a wedged runtime in
  production) accumulates consecutive probe failures and is SIGKILLed and
  respawned — SIGKILL because a stopped process cannot run a SIGTERM
  handler. Either way the router orphans its sessions immediately; their
  next `/act` re-homes with ``"restarted": true``.
* **Deterministic chaos.** The supervisor consults the PR 4 fault registry
  (`rt1_tpu/resilience/faults.py`, sites `replica_kill` / `replica_hang` /
  `serve_reload`) once per **chaos tick** — one tick every
  `chaos_interval_s`, counted only after the fleet first reports
  all-ready, with the tick ordinal as the fault index. Same plan, same
  failure schedule, every run: `replica_kill@1,serve_reload@2` always
  kills at tick 1 and rolls a reload at tick 2. Victim selection is
  deterministic too (lowest-id ready replica).

* **Elastic autoscaling** (`--min_replicas`/`--max_replicas`, ISSUE 15).
  Once per `--autoscale_interval_s` the supervisor feeds router-observed
  signals (windowed session occupancy, in-flight depth, admission sheds,
  SLO rolling burn) to the hysteretic `serve/autoscale.py` policy —
  scale up fast, down slow. Scale-up boots a **surge-tier** replica at
  `--surge_dtype` (int8 is ~3.71x cheaper in device param bytes,
  BENCH_serve_quant.json) on a never-reused id; scale-down picks the
  highest-id surge replica, de-places it (router stops placement and
  orphans its sessions so they re-home through the failover path),
  grants a grace window for in-flight acts, SIGTERMs (the replica's own
  drain: flush, exit 0), reaps, and purges the id from every routing and
  metrics map — no ghost replicas on later scrapes. Every replica
  lifetime accrues into a per-dtype replica-second ledger; weighted by
  `DTYPE_COST_WEIGHTS` it becomes the cost-per-request column of
  `BENCH_serve_elastic.json`.

* **Metrics plane** (`--collector`, ISSUE 18). An in-process collector
  scrapes the fleet's own `/metrics` fan-out (and `/deploy/status` when
  promotion is armed) into a bounded ring TSDB every
  `--collector_interval_s`, evaluates the default alert ruleset
  (multi-window SLO burn, replica loss, compile drift, flap/storm
  detectors) after each cycle, and lights up `/alerts`, `/history` and
  `/dashboard` on the router port. Firing alerts land in the same
  flight-recorder stream as the slow-request exemplars; on shutdown the
  TSDB snapshots into `<obs_dir>/tsdb_snapshot.jsonl` for the
  run-report post-mortem. Unarmed, every surface is byte-identical to
  the pre-collector fleet.

The supervisor owns processes, the router owns routing state; they meet at
the shared `Replica` objects. `scripts/serve_loadgen.py --fleet N` drives
this module as a subprocess and turns the chaos run into
`BENCH_serve_fleet.json`; `--traffic_schedule` runs the elastic-vs-fixed
A/B into `BENCH_serve_elastic.json`.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from rt1_tpu.resilience import faults
from rt1_tpu.serve.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    FleetSignals,
)
from rt1_tpu.serve.router import (
    DEAD,
    NOTREADY,
    READY,
    STARTING,
    TIER_BASE,
    TIER_SURGE,
    AdmissionController,
    Replica,
    Router,
    get_json,
    make_router_server,
)

#: Relative per-replica-second cost weight by inference dtype,
#: proportional to device-resident param bytes — the measured flagship
#: serving tree is 141.1 MB f32 vs 38.0 MB int8 (3.71x,
#: BENCH_serve_quant.json) and bf16 halves the f32 tree. Cost-per-request
#: in BENCH_serve_elastic.json is replica-seconds weighted by these: an
#: int8 surge replica-second costs ~27% of an f32 one.
DTYPE_COST_WEIGHTS = {"f32": 1.0, "bf16": 0.5, "int8": 1.0 / 3.71}


class FleetSupervisor:
    """Owns N replica subprocesses on behalf of a Router."""

    def __init__(
        self,
        router: Router,
        spawn_argv_fn: Callable[[int], List[str]],
        n_replicas: int,
        *,
        poll_interval_s: float = 0.25,
        chaos_interval_s: float = 2.0,
        warmup_timeout_s: float = 600.0,
        hang_probe_failures: int = 3,
        probe_timeout_s: float = 2.0,
        max_restarts: int = 50,
        log_dir: Optional[str] = None,
        extra_env: Optional[Dict[str, str]] = None,
        exemplar_scrape_interval_s: float = 2.0,
        capture_root: Optional[str] = None,
        autoscale: Optional[AutoscalePolicy] = None,
        autoscale_interval_s: float = 1.0,
        max_sessions: int = 8,
        surge_dtype: Optional[str] = None,
        base_dtype_fn: Optional[Callable[[int], str]] = None,
        reclaim_grace_s: float = 0.5,
        reclaim_timeout_s: float = 30.0,
    ):
        self.router = router
        self._spawn_argv_fn = spawn_argv_fn
        self.n_replicas = n_replicas
        self.poll_interval_s = poll_interval_s
        self.chaos_interval_s = chaos_interval_s
        self.warmup_timeout_s = warmup_timeout_s
        self.hang_probe_failures = hang_probe_failures
        self.probe_timeout_s = probe_timeout_s
        self.max_restarts = max_restarts
        self.log_dir = log_dir
        self.extra_env = extra_env
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._scrape_thread: Optional[threading.Thread] = None
        # Chaos bookkeeping (summary + determinism evidence). Mutated only
        # on the single supervisor thread; readers (summary, tests)
        # tolerate a stale int — no lock needed or implied.
        self.chaos_tick = 0
        self._fleet_was_ready = False
        self.kills_injected = 0
        self.hangs_injected = 0
        self.reloads_injected = 0
        self.restarts_total = 0
        # Slow-request exemplars, scraped from each live replica's
        # GET /slow_requests on a slow cadence. A SIGKILLed replica never
        # runs its drain-time dump, so the supervisor's last scrape is
        # the only copy of "what the victim was serving when it died" —
        # the serve-side flight-recorder semantics the post-mortem needs.
        self.exemplar_scrape_interval_s = exemplar_scrape_interval_s
        # Written by the scrape thread, read by slow_request_evidence()
        # (fleet main's final status line, while the scraper still runs).
        self._exemplar_lock = threading.Lock()
        self.last_exemplars: Dict[int, Dict[str, Any]] = {}
        # Firing/resolving alerts ride the same flight-recorder stream:
        # the AlertManager's callbacks land transitions here (collector
        # thread), so "what was alerting when the fleet died" survives
        # into the final status line even if /alerts was never scraped.
        # deque(maxlen) keeps appends atomic and the log bounded.
        self.alert_events: "collections.deque" = collections.deque(
            maxlen=256
        )
        # Data flywheel: each replica captures episodes into
        # <capture_root>/replica_<id>; the scrape loop sweeps completed
        # files into <capture_root>/staging — ONE dir the packer appends
        # from (`scripts/pack_dataset.py --append`), fed by N replicas
        # that keep writing across kills and respawns.
        self.capture_root = capture_root
        self.captures_swept = 0
        # Elastic fleet (ISSUE 15): the autoscaler decides, this
        # supervisor spawns/drains/reaps. `None` keeps the fixed-N
        # behavior byte-identical. Surge replicas (ids >= the initial
        # fleet) boot at `surge_dtype` in the "surge" tier; the initial
        # fleet is the pinned base tier. Every replica's lifetime is
        # accrued into replica-seconds per dtype — the cost side of the
        # elastic bench — whether or not autoscaling is on.
        self.autoscale_policy = autoscale
        self.autoscaler = Autoscaler(autoscale) if autoscale else None
        self.autoscale_interval_s = autoscale_interval_s
        self.max_sessions = max_sessions
        self.surge_dtype = surge_dtype
        self._base_dtype_fn = base_dtype_fn or (lambda _rid: "f32")
        self.reclaim_grace_s = reclaim_grace_s
        self.reclaim_timeout_s = reclaim_timeout_s
        self.scale_ups = 0
        self.scale_downs = 0
        self.scale_events: List[Dict[str, Any]] = []  # bounded (256)
        self._t0 = time.monotonic()
        self._next_replica_id = n_replicas
        self._last_shed_total = 0
        # Replicas mid-reclaim: the supervision loop must not "heal" a
        # deliberate drain (their process exit is expected, not a death).
        self._reclaiming: set = set()
        self._reclaim_threads: List[threading.Thread] = []
        self._accrual_lock = threading.Lock()
        self._replica_seconds: Dict[str, float] = {}

    # ------------------------------------------------------------ spawning

    def _argv_for(self, replica: Replica) -> List[str]:
        """Spawn argv, honoring a per-replica dtype override (surge tier)
        when the builder accepts one; single-arg builders (older tests,
        custom fns) keep working unchanged."""
        import inspect

        try:
            takes_dtype = (
                len(inspect.signature(self._spawn_argv_fn).parameters) >= 2
            )
        except (TypeError, ValueError):  # builtins/partials w/o signature
            takes_dtype = False
        if takes_dtype:
            return self._spawn_argv_fn(replica.id, replica.dtype)
        return self._spawn_argv_fn(replica.id)

    def _spawn(self, replica: Replica) -> None:
        """(Re)launch one replica; its ready-line reader runs on a thread."""
        argv = self._argv_for(replica)
        stderr = None
        if self.log_dir is not None:
            os.makedirs(self.log_dir, exist_ok=True)
            path = os.path.join(
                self.log_dir,
                f"replica{replica.id}.g{replica.restarts}.log",
            )
            stderr = open(path, "w")  # noqa: SIM115 - closed after Popen
        env = dict(os.environ)
        if self.extra_env:
            env.update(self.extra_env)
        replica.url = None
        replica.state = STARTING
        replica.consecutive_probe_failures = 0
        try:
            replica.proc = subprocess.Popen(
                argv,
                stdout=subprocess.PIPE,
                stderr=stderr,
                text=True,
                env=env,
            )
        finally:
            if stderr is not None:
                # Popen dup'd the fd into the child; keeping the parent's
                # copy open would leak one fd per (re)spawn.
                stderr.close()
        replica.spawned_at = time.monotonic()
        threading.Thread(
            target=self._read_ready_line,
            args=(replica, replica.proc),
            name=f"rt1-fleet-stdout-{replica.id}",
            daemon=True,
        ).start()

    def _read_ready_line(self, replica: Replica, proc) -> None:
        """Parse `{"status": "serving", "port": ...}` off the replica's
        stdout, then keep draining so the pipe never fills."""
        try:
            for line in proc.stdout:
                if replica.url is None:
                    try:
                        ready = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if ready.get("status") == "serving":
                        host = ready.get("host", "127.0.0.1")
                        replica.url = f"http://{host}:{ready['port']}"
        except (ValueError, OSError):
            pass  # closed pipe on kill/shutdown

    def start(self, wait_ready: bool = True) -> None:
        for i in range(self.n_replicas):
            replica = Replica(i)
            replica.tier = TIER_BASE  # the pinned full-precision tier
            replica.dtype = self._base_dtype_fn(i)
            self.router.add_replica(replica)
        for replica in self.router.replicas():
            self._spawn(replica)
        if wait_ready:
            try:
                self.wait_all_ready()
            except BaseException:
                # A failed warm-up (one replica crashed, bad config, ...)
                # must not leak the siblings that DID spawn.
                self.stop()
                raise
        self._thread = threading.Thread(
            target=self._supervise, name="rt1-fleet-supervisor", daemon=True
        )
        self._thread.start()
        if self.exemplar_scrape_interval_s > 0:
            # Own thread: a hung replica makes each /slow_requests probe
            # eat its full timeout, which on the supervision thread would
            # delay the very death detection that makes the scrape matter.
            self._scrape_thread = threading.Thread(
                target=self._scrape_loop,
                name="rt1-fleet-exemplar-scrape",
                daemon=True,
            )
            self._scrape_thread.start()

    def wait_all_ready(self) -> None:
        """Block until every replica passes warm-up (ready-line + /readyz),
        raising if one dies or the warm-up budget expires."""
        deadline = time.monotonic() + self.warmup_timeout_s
        pending = {r.id for r in self.router.replicas()}
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replicas {sorted(pending)} not ready after "
                    f"{self.warmup_timeout_s:.0f}s"
                )
            for replica in self.router.replicas():
                if replica.id not in pending:
                    continue
                if replica.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {replica.id} exited rc="
                        f"{replica.proc.returncode} during warm-up"
                        + (
                            f" (see {self.log_dir})"
                            if self.log_dir
                            else ""
                        )
                    )
                if self._probe_ready(replica):
                    pending.discard(replica.id)
            time.sleep(0.05)

    def _probe_ready(self, replica: Replica) -> bool:
        if replica.url is None:
            return False
        status, _ = get_json(
            replica.url + "/readyz", timeout=self.probe_timeout_s
        )
        if status == 200:
            replica.consecutive_probe_failures = 0
            self.router.set_state(replica.id, READY)
            return True
        return False

    # --------------------------------------------------------- supervision

    def _supervise(self) -> None:
        last_chaos = time.monotonic()
        last_autoscale = time.monotonic()
        while not self._stop.is_set():
            for replica in self.router.replicas():
                if replica.id in self._reclaiming:
                    continue  # deliberate drain: its exit is not a death
                try:
                    self._check_replica(replica)
                except Exception as exc:  # noqa: BLE001 - keep healing
                    # One bad cycle (full-disk log open, a wait()
                    # timeout) must not kill supervision for good — a
                    # dead supervisor means no respawns and a silently
                    # decaying fleet.
                    print(
                        json.dumps(
                            {
                                "status": "supervise_error",
                                "replica": replica.id,
                                "error": str(exc),
                            }
                        ),
                        file=sys.stderr,
                        flush=True,
                    )
            if not self._fleet_was_ready:
                # Chaos ticks start only once the fleet has been fully
                # ready once — fault indices then count ticks, making
                # the schedule independent of warm-up wall time.
                self._fleet_was_ready = self.router.ready_count() == (
                    self.n_replicas
                )
                last_chaos = time.monotonic()
            elif time.monotonic() - last_chaos >= self.chaos_interval_s:
                last_chaos = time.monotonic()
                self.chaos_tick += 1
                try:
                    self._inject_chaos(self.chaos_tick)
                except Exception as exc:  # noqa: BLE001 - see above
                    print(
                        json.dumps(
                            {
                                "status": "chaos_error",
                                "tick": self.chaos_tick,
                                "error": str(exc),
                            }
                        ),
                        file=sys.stderr,
                        flush=True,
                    )
            if (
                self.autoscaler is not None
                and self._fleet_was_ready
                and time.monotonic() - last_autoscale
                >= self.autoscale_interval_s
            ):
                last_autoscale = time.monotonic()
                try:
                    self._autoscale_tick()
                except Exception as exc:  # noqa: BLE001 - keep supervising
                    print(
                        json.dumps(
                            {"status": "autoscale_error", "error": str(exc)}
                        ),
                        file=sys.stderr,
                        flush=True,
                    )
            self._stop.wait(self.poll_interval_s)

    def _check_replica(self, replica: Replica) -> None:
        if replica.proc is None:
            return
        if replica.proc.poll() is not None:
            if replica.state != DEAD:
                self.router.mark_dead(replica, reason="process exited")
            self._respawn(replica)
            return
        if replica.url is None:
            return  # still booting, ready-line not printed yet
        status, _ = get_json(
            replica.url + "/readyz", timeout=self.probe_timeout_s
        )
        if status == 200:
            replica.consecutive_probe_failures = 0
            if replica.state != READY:
                self.router.set_state(replica.id, READY)
        elif status == 0:
            replica.consecutive_probe_failures += 1
            if replica.consecutive_probe_failures >= self.hang_probe_failures:
                # Alive to the OS, dead to HTTP: hung. SIGKILL (a stopped
                # process cannot run SIGTERM handlers) and respawn.
                self.router.mark_dead(replica, reason="hang detected")
                replica.proc.kill()
                replica.proc.wait(timeout=10)
                self._respawn(replica)
        else:  # a live 503: warming / draining / reloading
            replica.consecutive_probe_failures = 0
            if replica.state == READY:
                self.router.set_state(replica.id, NOTREADY)

    def _scrape_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._scrape_exemplars()
                self.sweep_captures()
            except Exception as exc:  # noqa: BLE001 - keep scraping
                print(
                    json.dumps(
                        {"status": "exemplar_scrape_error", "error": str(exc)}
                    ),
                    file=sys.stderr,
                    flush=True,
                )
            self._stop.wait(self.exemplar_scrape_interval_s)

    def _scrape_exemplars(self) -> None:
        """Pull each live replica's slow-request ring into supervisor
        memory, so the exemplars survive a SIGKILL/crash of the replica.
        Keyed by replica id; a respawned replica's fresh (empty) ring only
        replaces the dead generation's scrape once it has entries —
        "nothing recorded yet" must not erase the crash evidence."""
        for replica in self.router.replicas():
            if replica.url is None or replica.state == DEAD:
                continue
            status, body = get_json(
                replica.url + "/slow_requests", timeout=self.probe_timeout_s
            )
            if status != 200 or not isinstance(body, dict):
                continue
            with self._exemplar_lock:
                if (
                    body.get("retained")
                    or replica.id not in self.last_exemplars
                ):
                    body["scraped_at"] = time.time()
                    body["generation"] = replica.restarts
                    self.last_exemplars[replica.id] = body

    def note_alert(self, event: Dict[str, Any]) -> None:
        """AlertManager on_fire/on_resolve hook — alert transitions into
        the fleet's crash-surviving evidence stream."""
        self.alert_events.append(dict(event))

    def replica_capture_dir(self, replica_id: int) -> Optional[str]:
        if self.capture_root is None:
            return None
        return os.path.join(self.capture_root, f"replica_{replica_id}")

    def capture_staging_dir(self) -> Optional[str]:
        if self.capture_root is None:
            return None
        return os.path.join(self.capture_root, "staging")

    def sweep_captures(self) -> int:
        """Move completed per-replica capture files into the staging dir
        (same-filesystem renames; a SIGKILLed replica's already-renamed
        episodes survive it, exactly like the exemplar scrape)."""
        if self.capture_root is None:
            return 0
        from rt1_tpu.flywheel.capture import sweep_captures

        moved = sweep_captures(
            [
                self.replica_capture_dir(r.id)
                for r in self.router.replicas()
            ],
            self.capture_staging_dir(),
        )
        self.captures_swept += moved
        return moved

    def _respawn(self, replica: Replica) -> None:
        # Close the dead generation's cost window FIRST: a replica past
        # the restart budget stays DEAD forever, and an open window would
        # keep accruing replica-seconds for a process that isn't running.
        self._accrue(replica)
        if self.restarts_total >= self.max_restarts:
            return  # crash-looping fleet: stop burning the host
        self.restarts_total += 1
        replica.restarts += 1
        self._spawn(replica)

    # ---------------------------------------------------------- autoscaling

    def _live_replicas(self) -> List[Replica]:
        """Replicas that count as capacity for scaling decisions: not
        mid-reclaim and not DEAD. Excluding DEAD matters for liveness —
        a crash-looping slot that exhausted max_restarts stays DEAD
        forever, and counting it in replicas_total would wedge the
        total==ready decision gate permanently (no surge under overload,
        ever). A transiently-dead slot is respawned into STARTING within
        one poll cycle, so the warming gate still holds while it boots."""
        return [
            r
            for r in self.router.replicas()
            if r.id not in self._reclaiming and r.state != DEAD
        ]

    def _signals(self) -> FleetSignals:
        live = self._live_replicas()
        ready = sum(1 for r in live if r.state == READY)
        window = (
            self.autoscale_policy.active_window_s
            if self.autoscale_policy
            else 5.0
        )
        # Capacity pressure counts ONLY global-overload sheds: a
        # client_rate shed is the token bucket doing its job on one hot
        # client — more replicas cannot admit it, and counting it would
        # pin the fleet at max while idle (see ServeMetrics.shed_total).
        shed_total = self.router.metrics.shed_total("overload")
        shed_delta = shed_total - self._last_shed_total
        self._last_shed_total = shed_total
        return FleetSignals(
            replicas_total=len(live),
            replicas_ready=ready,
            active_sessions=self.router.active_session_count(window),
            session_slots=ready * self.max_sessions,
            inflight=self.router.inflight,
            shed_delta=shed_delta,
            # Time-windowed burn (ISSUE 18), not the request-indexed
            # rolling gauge: with no follow-on traffic the window ages
            # out and the burn decays to zero on the wall clock, so a
            # shed/restart burst can't pin scale-up pressure forever.
            rolling_burn=self.router.slo.windowed_burn(
                self.autoscale_policy.burn_window_s
                if self.autoscale_policy
                else 60.0
            ),
            replicas_booting=sum(1 for r in live if r.state == STARTING),
        )

    def _autoscale_tick(self) -> None:
        if self._reclaiming:
            # A drain is still in flight: it is invisible to the signal
            # computation (deliberately — a draining replica is not
            # capacity), so without this gate a scale-up during a slow
            # reclaim could run max_replicas+1 live processes. Checked
            # BEFORE _signals(): computing signals would advance the
            # overload-shed baseline and throw the delta away, erasing
            # exactly the pressure evidence a shed burst during the
            # drain window should carry into the next live tick.
            return
        signals = self._signals()
        # Fleet-shape gauges refresh every tick (rt1_serve_autoscale_*).
        tiers: Dict[str, int] = {}
        for replica in self._live_replicas():
            dtype = replica.dtype or "f32"
            tiers[dtype] = tiers.get(dtype, 0) + 1
        self.router.metrics.set_autoscale_state(
            replicas=signals.replicas_total, tier_replicas=tiers
        )
        decision = self.autoscaler.decide(signals)
        if decision is None:
            return
        if decision.direction == "up":
            self._scale_up(decision.reason)
        else:
            self._scale_down(decision.reason)

    def _record_scale_event(self, event: Dict[str, Any]) -> None:
        event["t_s"] = round(time.monotonic() - self._t0, 3)
        self.scale_events.append(event)
        del self.scale_events[:-256]  # bounded log
        self.router.metrics.observe_scale_event(event["direction"])

    def _scale_up(self, reason: str) -> None:
        """Boot one surge replica (fresh id — ids are never reused, so
        metrics labels stay unambiguous across the fleet's history)."""
        rid = self._next_replica_id
        self._next_replica_id += 1
        replica = Replica(rid)
        replica.tier = TIER_SURGE
        replica.dtype = self.surge_dtype or self._base_dtype_fn(rid)
        # Spawn BEFORE registering: a failed Popen (ENOMEM/EMFILE —
        # exactly when a surge fires) must not leave a proc-less ghost
        # in the routing table that the ready gate would wait on forever.
        self._spawn(replica)
        self.router.add_replica(replica)
        self.scale_ups += 1
        self._record_scale_event(
            {
                "direction": "up",
                "replica_id": rid,
                "tier": replica.tier,
                "dtype": replica.dtype,
                "reason": reason,
                "replicas_after": len(self._live_replicas()),
            }
        )

    def _scale_down(self, reason: str) -> None:
        """Drain and reap one replica: surge tier first (highest id), a
        base replica only when no surge remains — and never replica 0,
        the parity canary. The reclaim itself runs on its own thread (a
        graceful drain takes seconds; the supervision loop must keep
        probing the rest of the fleet)."""
        candidates = [
            r
            for r in self._live_replicas()
            if r.proc is not None and r.id != 0
        ]
        min_replicas = (
            self.autoscale_policy.min_replicas if self.autoscale_policy else 1
        )
        if len(self._live_replicas()) <= min_replicas or not candidates:
            return
        candidates.sort(key=lambda r: (r.tier != TIER_SURGE, -r.id))
        victim = candidates[0]
        self._reclaiming.add(victim.id)
        self.scale_downs += 1
        self._reclaim_threads = [
            t for t in self._reclaim_threads if t.is_alive()
        ]
        thread = threading.Thread(
            target=self._reclaim,
            args=(victim, reason),
            name=f"rt1-fleet-reclaim-{victim.id}",
            daemon=True,
        )
        self._reclaim_threads.append(thread)
        thread.start()

    def manual_scale_down(
        self, payload: Dict[str, Any]
    ) -> Dict[str, Any]:
        """Operator-driven elastic drain (router ``POST /scale_down``):
        reclaim one replica NOW through the same migrating drain the
        autoscaler uses — live-migrate its sessions, de-place, SIGTERM,
        reap. ``replica_id`` picks the victim explicitly; omitted, the
        autoscaler's preference applies (surge tier first, highest id,
        never replica 0). Raises KeyError/ValueError (router -> 400) on
        an unknown or unreclaimable victim."""
        replica_id = payload.get("replica_id")
        if replica_id is not None and not isinstance(replica_id, int):
            raise ValueError("'replica_id' must be an integer when given")
        candidates = [
            r
            for r in self._live_replicas()
            if r.proc is not None
            and r.id != 0
            and r.id not in self._reclaiming
        ]
        if replica_id is not None:
            victim = next(
                (r for r in candidates if r.id == replica_id), None
            )
            if victim is None:
                raise KeyError(
                    f"replica {replica_id} is not reclaimable (unknown, "
                    f"already draining, or the pinned replica 0)"
                )
        else:
            if not candidates:
                raise ValueError("no reclaimable replica")
            candidates.sort(key=lambda r: (r.tier != TIER_SURGE, -r.id))
            victim = candidates[0]
        self._reclaiming.add(victim.id)
        self.scale_downs += 1
        self._reclaim_threads = [
            t for t in self._reclaim_threads if t.is_alive()
        ]
        thread = threading.Thread(
            target=self._reclaim,
            args=(victim, "manual"),
            name=f"rt1-fleet-reclaim-{victim.id}",
            daemon=True,
        )
        self._reclaim_threads.append(thread)
        thread.start()
        return {"ok": True, "replica_id": victim.id, "draining": True}

    def _reclaim(self, victim: Replica, reason: str) -> None:
        """Graceful scale-down of one replica: live-migrate its sessions
        onto the least-loaded compatible survivor (their next act
        continues token-identically with ``migrated: true``), de-place
        (router stops routing to it; any session that could NOT migrate
        is orphaned so it re-homes through the legacy failover path with
        ``restarted: true``), give in-flight requests a grace window,
        snapshot the compile-count evidence, SIGTERM (the replica's own
        drain path: stop admitting, flush, exit 0), and only then reap
        the process and purge the id from the routing/metrics maps — no
        ghost replicas."""
        event: Dict[str, Any] = {
            "direction": "down",
            "replica_id": victim.id,
            "tier": victim.tier,
            "dtype": victim.dtype,
            "reason": reason,
        }
        try:
            try:
                migration = self.router.migrate_sessions_from(
                    victim.id, reason=f"scale_down:{reason}"
                )
                if migration.get("attempted") or migration.get("failed"):
                    event["sessions_migrated"] = migration["migrated"]
                    event["migration_failed"] = migration["failed"]
            except Exception as exc:  # noqa: BLE001 - drain must proceed
                # Migration is best-effort sugar on top of the drain:
                # any failure here degrades to the legacy orphan path
                # below, never wedges the reclaim thread.
                event["migration_error"] = str(exc)
            self.router.deplace(victim.id)
            time.sleep(self.reclaim_grace_s)
            if victim.url is not None:
                status, body = get_json(
                    victim.url + "/metrics", timeout=self.probe_timeout_s
                )
                if status == 200 and isinstance(body, dict):
                    # The reclaim survivor's pinned-compile evidence,
                    # recorded BEFORE the process dies — the elastic
                    # bench asserts compile_count == bucket_count on
                    # every replica lifetime, reaped ones included.
                    event["compile_count"] = body.get("compile_count")
                    event["bucket_count"] = body.get("bucket_count")
                    event["requests_total"] = body.get("requests_total")
            proc = victim.proc
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGCONT)  # un-wedge SIGSTOP chaos
                proc.terminate()
                try:
                    proc.wait(timeout=self.reclaim_timeout_s)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5)
            event["exit_code"] = (
                victim.proc.returncode if victim.proc is not None else None
            )
        except Exception as exc:  # noqa: BLE001 - reclaim must not wedge
            event["error"] = str(exc)
            if victim.proc is not None and victim.proc.poll() is None:
                victim.proc.kill()
                try:
                    # Reap the corpse: an unwaited kill leaves a zombie
                    # per failed reclaim for the supervisor's lifetime.
                    victim.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
            if victim.proc is not None:
                event["exit_code"] = victim.proc.returncode
        finally:
            self._accrue(victim)
            self.router.remove_replica(victim.id)
            event["replicas_after"] = len(self.router.replicas())
            self._record_scale_event(event)
            self._reclaiming.discard(victim.id)

    # ----------------------------------------------------- cost accounting

    def _accrue(self, replica: Replica) -> None:
        """Close the replica's current lifetime into the per-dtype
        replica-second ledger (idempotent: spawned_at is consumed)."""
        if replica.spawned_at is None:
            return
        seconds = max(time.monotonic() - replica.spawned_at, 0.0)
        replica.spawned_at = None
        dtype = replica.dtype or "f32"
        with self._accrual_lock:
            self._replica_seconds[dtype] = (
                self._replica_seconds.get(dtype, 0.0) + seconds
            )

    def replica_seconds_by_dtype(self) -> Dict[str, float]:
        """Accrued + live replica-seconds per dtype (non-mutating, so the
        fleet's final status line can be built before stop())."""
        now = time.monotonic()
        with self._accrual_lock:
            out = dict(self._replica_seconds)
        for replica in self.router.replicas():
            if replica.spawned_at is not None:
                dtype = replica.dtype or "f32"
                out[dtype] = out.get(dtype, 0.0) + (
                    now - replica.spawned_at
                )
        return {k: round(v, 3) for k, v in sorted(out.items())}

    def autoscale_summary(self) -> Dict[str, Any]:
        """The elastic-fleet evidence for the final status line / BENCH
        record: scale-event log, replica-seconds per dtype tier, and the
        byte-weighted cost units behind cost-per-request."""
        seconds = self.replica_seconds_by_dtype()
        cost_units = sum(
            s * DTYPE_COST_WEIGHTS.get(dtype, 1.0)
            for dtype, s in seconds.items()
        )
        policy = self.autoscale_policy
        return {
            "enabled": policy is not None,
            "min_replicas": policy.min_replicas if policy else None,
            "max_replicas": policy.max_replicas if policy else None,
            "surge_dtype": self.surge_dtype,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "events": list(self.scale_events),
            "replica_seconds_by_dtype": seconds,
            "cost_units": round(cost_units, 3),
            "cost_weights": DTYPE_COST_WEIGHTS,
            "replicas_final": len(self._live_replicas()),
        }

    # --------------------------------------------------------------- chaos

    def _inject_chaos(self, tick: int) -> None:
        plan = faults.active()
        if plan is None:
            return
        if plan.should_fire("replica_kill", index=tick):
            victim = self._victim()
            if victim is not None:
                self.kills_injected += 1
                self.router.mark_dead(victim, reason="chaos replica_kill")
                victim.proc.kill()
        if plan.should_fire("replica_hang", index=tick):
            victim = self._victim()
            if victim is not None:
                self.hangs_injected += 1
                victim.proc.send_signal(signal.SIGSTOP)
        if plan.should_fire("serve_reload", index=tick):
            self.reloads_injected += 1
            threading.Thread(
                target=self.router.rolling_reload,
                name="rt1-fleet-chaos-reload",
                daemon=True,
            ).start()

    def _victim(self) -> Optional[Replica]:
        ready = [
            r for r in self.router.replicas()
            if r.state == READY and r.proc is not None
        ]
        return min(ready, key=lambda r: r.id) if ready else None

    # ------------------------------------------------------------ shutdown

    def stop(self, timeout: float = 15.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=timeout)
        for thread in self._reclaim_threads:
            thread.join(timeout=self.reclaim_timeout_s + timeout)
        for replica in self.router.replicas():
            proc = replica.proc
            if proc is None or proc.poll() is not None:
                continue
            proc.send_signal(signal.SIGCONT)  # un-wedge a SIGSTOP victim
            proc.terminate()
        for replica in self.router.replicas():
            proc = replica.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
            self._accrue(replica)  # close every cost window on shutdown

    def summary(self) -> Dict[str, Any]:
        return {
            "chaos_ticks": self.chaos_tick,
            "kills_injected": self.kills_injected,
            "hangs_injected": self.hangs_injected,
            "reloads_injected": self.reloads_injected,
            "replica_restarts": self.restarts_total,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "captures_swept": self.captures_swept,
            "faults_fired": (
                faults.active().fired_counts() if faults.active() else {}
            ),
        }

    def slow_request_evidence(
        self, per_replica: int = 8
    ) -> Dict[str, Any]:
        """The last-scraped exemplars, trimmed to the `per_replica` most
        recent records each — the fleet's crash-surviving slow-request
        evidence for the final status line / post-mortem."""
        out = {}
        with self._exemplar_lock:
            snapshot = sorted(self.last_exemplars.items())
        for rid, scrape in snapshot:
            records = scrape.get("slow_requests", [])
            out[str(rid)] = {
                **{k: v for k, v in scrape.items() if k != "slow_requests"},
                "slow_requests": records[-per_replica:],
            }
        return out


# -------------------------------------------------------------- entry point


# Mirrors models/quant.INFERENCE_DTYPES without importing flax into the
# supervisor/router process (which stays model-free).
VALID_REPLICA_DTYPES = ("f32", "bf16", "int8")


def replica_dtype_for(args, replica_id: int) -> str:
    """This replica's inference dtype: the per-replica `--replica_dtypes`
    list (a mixed-dtype fleet — cheap int8 replicas beside an f32
    reference) wins over the fleet-wide `--inference_dtype` default.

    Every list entry is validated here — unlike `--inference_dtype` there
    is no argparse `choices` guard, and an invalid entry would otherwise
    surface as a replica crash-loop at the CHILD's argparse instead of a
    message naming the typo.
    """
    per_replica = [
        d.strip()
        for d in getattr(args, "replica_dtypes", "").split(",")
        if d.strip()
    ]
    for dtype in per_replica:
        if dtype not in VALID_REPLICA_DTYPES:
            raise ValueError(
                f"--replica_dtypes entry {dtype!r} is not one of "
                f"{VALID_REPLICA_DTYPES}"
            )
    if per_replica:
        return per_replica[replica_id % len(per_replica)]
    return getattr(args, "inference_dtype", "f32")


def replica_argv_builder(args) -> Callable[..., List[str]]:
    """argv factory for one replica — the stub or the real server.

    The returned builder takes ``(replica_id, dtype=None)``: the optional
    dtype override is how autoscaler-spawned surge replicas boot at
    ``--surge_dtype`` while the base tier keeps the
    ``--replica_dtypes``/``--inference_dtype`` assignment.
    """
    slow_threshold = getattr(args, "slow_threshold_ms", 0.0)
    scheduler = getattr(args, "scheduler", "continuous")
    buckets = getattr(args, "buckets", "auto")
    # Durable sessions: ONE shared snapshot directory for the whole fleet
    # (ring files are keyed per session, writes are atomic) — the replica
    # a SIGKILL'd session re-homes onto must be able to read the ring
    # entry its dead home wrote. Empty = off (no disk writes).
    snapshot_dir = getattr(args, "session_snapshot_dir", "")
    snapshot_max_age = getattr(args, "snapshot_max_age_s", 600.0)
    if args.stub:
        act_concurrency = getattr(args, "stub_act_concurrency", 0)

        def build(replica_id: int, dtype: Optional[str] = None) -> List[str]:
            argv = [
                sys.executable, "-m", "rt1_tpu.serve.stub",
                "--port", "0",
                "--replica_id", str(replica_id),
                "--max_sessions", str(args.max_sessions),
                "--act_delay_s", str(args.stub_act_delay_s),
                "--act_concurrency", str(act_concurrency),
                "--slow_threshold_ms", str(slow_threshold),
                "--inference_dtype",
                dtype or replica_dtype_for(args, replica_id),
                "--scheduler", scheduler,
                # The stub has no compiler; it advertises the contract
                # field ("1" = one bucket) unless a ladder is forced.
                "--buckets", buckets if buckets != "auto" else "1",
            ]
            if snapshot_dir:
                argv.extend([
                    "--session_snapshot_dir", snapshot_dir,
                    "--snapshot_max_age_s", str(snapshot_max_age),
                ])
            return argv
        return build

    capture_root = getattr(args, "capture_dir", "")

    def build(replica_id: int, dtype: Optional[str] = None) -> List[str]:
        argv = [
            sys.executable, "-m", "rt1_tpu.serve",
            "--config", args.config,
            "--port", "0",
            "--replica_id", str(replica_id),
            "--max_sessions", str(args.max_sessions),
            "--embedder", args.embedder,
            "--slow_threshold_ms", str(slow_threshold),
            "--inference_dtype",
            dtype or replica_dtype_for(args, replica_id),
            "--scheduler", scheduler,
            "--buckets", buckets,
        ]
        if capture_root:
            # Per-replica capture dir; the supervisor sweeps completed
            # files into <capture_dir>/staging for the packer.
            argv.extend([
                "--capture_dir",
                os.path.join(capture_root, f"replica_{replica_id}"),
            ])
        if snapshot_dir:
            argv.extend([
                "--session_snapshot_dir", snapshot_dir,
                "--snapshot_max_age_s", str(snapshot_max_age),
            ])
        if args.random_init:
            argv.append("--random_init")
        else:
            argv.extend(["--workdir", args.workdir])
        return argv
    return build


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8400,
                        help="Router bind port (0 = ephemeral).")
    parser.add_argument("--config", default="",
                        help="Model/data config path, forwarded to replicas.")
    parser.add_argument("--workdir", default="",
                        help="Checkpoint dir, forwarded to replicas "
                             "(enables /reload from disk).")
    parser.add_argument("--random_init", action="store_true")
    parser.add_argument("--stub", action="store_true",
                        help="Spawn model-free stub replicas "
                             "(rt1_tpu.serve.stub) — protocol-true, no jax.")
    parser.add_argument("--max_sessions", type=int, default=8)
    parser.add_argument("--embedder", default="hash")
    parser.add_argument("--stub_act_delay_s", type=float, default=0.0)
    parser.add_argument(
        "--stub_act_concurrency", type=int, default=0,
        help="Stub device-concurrency limit: >0 serializes that many "
             "simulated device steps per stub replica, so replica count "
             "actually moves latency in elastic rehearsals (0 = "
             "unlimited, the legacy behavior).")
    # Elastic fleet (ISSUE 15): --min_replicas > 0 arms the autoscaler.
    parser.add_argument(
        "--min_replicas", type=int, default=0,
        help="Arm the autoscaler with this floor (also overrides "
             "--replicas as the initial fleet size). 0 = fixed fleet.")
    parser.add_argument(
        "--max_replicas", type=int, default=0,
        help="Autoscaler ceiling (required when --min_replicas > 0).")
    parser.add_argument("--autoscale_interval_s", type=float, default=1.0)
    parser.add_argument(
        "--scale_up_occupancy", type=float, default=0.75,
        help="Active sessions per ready slot at/above which sustained "
             "pressure scales up.")
    parser.add_argument(
        "--scale_down_occupancy", type=float, default=0.30,
        help="Occupancy at/below which sustained idleness scales down.")
    parser.add_argument(
        "--scale_up_ticks", type=int, default=2,
        help="Consecutive pressure ticks before scaling up (fast).")
    parser.add_argument(
        "--scale_down_ticks", type=int, default=6,
        help="Consecutive idle ticks before scaling down (slow).")
    parser.add_argument(
        "--active_window_s", type=float, default=5.0,
        help="A session counts toward occupancy this long after its "
             "last answered act.")
    parser.add_argument(
        "--surge_dtype", default="",
        choices=["", "f32", "bf16", "int8"],
        help="Dtype for autoscaler-spawned surge replicas (int8 is "
             "~3.71x cheaper in device param bytes — "
             "BENCH_serve_quant.json); '' = same as the base tier.")
    parser.add_argument(
        "--reclaim_grace_s", type=float, default=0.5,
        help="Seconds between de-placement and SIGTERM on scale-down "
             "(in-flight acts finish inside this window).")
    parser.add_argument(
        "--session_snapshot_dir", default="",
        help="Durable sessions: shared on-disk session-snapshot ring, "
             "forwarded to every replica (rt1_tpu/serve/migrate.py). A "
             "SIGKILL'd replica's sessions restore mid-episode on the "
             "replica they re-home to (booked `migrated`, not "
             "`restarted`). '' = off.")
    parser.add_argument(
        "--snapshot_max_age_s", type=float, default=600.0,
        help="Crash-restore staleness bound forwarded to every replica "
             "(older ring snapshots start a fresh window instead).")
    # Router admission control: both knobs default off.
    parser.add_argument(
        "--admission_rate", type=float, default=0.0,
        help="Token-bucket refill per client id (requests/s); past the "
             "bucket the router sheds with a fast 429. 0 = off.")
    parser.add_argument(
        "--admission_burst", type=float, default=8.0,
        help="Token-bucket depth per client id.")
    parser.add_argument(
        "--max_inflight", type=int, default=0,
        help="Global shed threshold: 429 new /acts while more than this "
             "many are mid-route. 0 = off.")
    parser.add_argument(
        "--scheduler", default="continuous",
        choices=["continuous", "cycle"],
        help="Batch scheduler forwarded to every replica (ISSUE 12: "
             "'continuous' rolls requests into the next device step; "
             "'cycle' is the legacy deadline loop).")
    parser.add_argument(
        "--buckets", default="auto",
        help="AOT batch-size buckets forwarded to every replica "
             "('auto' = pow2 ladder; comma ints to pin).")
    parser.add_argument(
        "--inference_dtype", default="f32",
        choices=["f32", "bf16", "int8"],
        help="Low-precision serving mode forwarded to every replica "
             "(rt1_tpu/models/quant.py).")
    parser.add_argument(
        "--replica_dtypes", default="",
        help="Comma list assigning a dtype per replica id (cycled), e.g. "
             "'f32,int8,int8' — a mixed-dtype fleet; overrides "
             "--inference_dtype.")
    parser.add_argument(
        "--capture_dir", default="",
        help="Data flywheel: per-replica episode capture under "
             "<dir>/replica_<id>, swept into <dir>/staging by the "
             "supervisor (real replicas only; the model-free stub serves "
             "no observations worth capturing).")
    parser.add_argument(
        "--slow_threshold_ms", type=float, default=0.0,
        help="Replica exemplar-ring threshold, forwarded to every "
             "replica (0 keeps the most recent window of all requests).")
    parser.add_argument(
        "--slo_availability", type=float, default=0.99,
        help="Router SLO: fraction of requests that must be ok.")
    parser.add_argument(
        "--slo_p50_ms", type=float, default=250.0,
        help="Router SLO: answered-request p50 objective (ms).")
    parser.add_argument(
        "--slo_p99_ms", type=float, default=2500.0,
        help="Router SLO: answered-request p99 objective (ms).")
    # Metrics plane (ISSUE 18): default off keeps surfaces byte-identical.
    parser.add_argument(
        "--collector", action="store_true",
        help="Arm the metrics plane: an in-process collector scrapes "
             "this fleet's own /metrics (and /deploy/status when "
             "promotion is armed) into a ring TSDB, evaluates the "
             "default alert ruleset each cycle, and serves /alerts, "
             "/history and /dashboard on the router port.")
    parser.add_argument(
        "--collector_interval_s", type=float, default=2.0,
        help="Scrape cadence — which is also the alert-evaluation "
             "cadence, like a Prometheus rule group.")
    parser.add_argument(
        "--obs_dir", default="",
        help="Where the armed collector writes tsdb_snapshot.jsonl on "
             "shutdown for the run_report.py post-mortem (default: "
             "--workdir when set; neither set = no snapshot).")
    parser.add_argument(
        "--promote_from", default="",
        help="Continuous deployment (rt1_tpu/deploy): watch this train "
             "workdir for new checkpoints, gate them offline, canary "
             "onto one replica at --canary_weight, promote fleet-wide "
             "after a clean burn window, auto-rollback on breach. Stub "
             "fleets auto-pass the offline gate (the supervisor process "
             "stays jax-free); real fleets run the eval-matrix + parity "
             "gate against --config.")
    parser.add_argument(
        "--canary_weight", type=float, default=0.25,
        help="Fraction of FRESH sessions routed to the canary replica "
             "(existing sessions keep their affinity).")
    parser.add_argument(
        "--burn_threshold", type=float, default=2.0,
        help="Canary rolling error-budget burn rate that counts as a "
             "breach (must also strictly exceed the incumbent fleet's).")
    parser.add_argument(
        "--breach_ticks", type=int, default=2,
        help="Consecutive breach ticks before auto-rollback.")
    parser.add_argument(
        "--clean_window_ticks", type=int, default=5,
        help="Consecutive clean ticks before fleet-wide promotion.")
    parser.add_argument(
        "--min_canary_requests", type=int, default=8,
        help="Evidence floor: hold the canary verdict until it has "
             "served this many requests (breaches still fire).")
    parser.add_argument(
        "--deploy_poll_interval_s", type=float, default=1.0,
        help="Promotion-controller tick interval.")
    parser.add_argument(
        "--gate_episodes", type=int, default=2,
        help="Eval-matrix episodes per task cell in the promotion gate "
             "(real fleets only).")
    parser.add_argument(
        "--gate_tasks", default="",
        help="Comma list of reward-family tasks for the promotion gate "
             "(empty = every canonical family).")
    parser.add_argument(
        "--gate_max_steps", type=int, default=80,
        help="Max env steps per gate eval episode.")
    parser.add_argument(
        "--deploy_incumbent_step", type=int, default=-1,
        help="Checkpoint step the fleet is currently serving (the gate "
             "baseline and rollback target). -1 = auto: the newest step "
             "in --promote_from at arm time; only checkpoints appearing "
             "AFTER that are candidates.")
    parser.add_argument("--faults", default="",
                        help="Chaos plan, e.g. 'replica_kill@1,"
                             "serve_reload@2' (RT1_FAULTS appended).")
    parser.add_argument("--chaos_interval_s", type=float, default=2.0)
    parser.add_argument("--poll_interval_s", type=float, default=0.25)
    parser.add_argument("--replica_timeout_s", type=float, default=30.0)
    parser.add_argument("--max_failovers", type=int, default=2)
    parser.add_argument("--warmup_timeout_s", type=float, default=600.0)
    parser.add_argument("--log_dir", default="",
                        help="Per-replica stderr logs (default: inherit).")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    if not args.stub and not args.config:
        parser.error("--config is required unless --stub")
    try:
        replica_dtype_for(args, 0)  # validates every --replica_dtypes entry
    except ValueError as exc:
        parser.error(str(exc))
    if not args.stub and not args.random_init and not args.workdir:
        parser.error("pass --workdir (checkpoint) or --random_init")

    policy = None
    if args.min_replicas > 0:
        if args.max_replicas < args.min_replicas:
            parser.error(
                "--max_replicas must be >= --min_replicas when the "
                "autoscaler is armed"
            )
        try:
            policy = AutoscalePolicy(
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                scale_up_occupancy=args.scale_up_occupancy,
                scale_down_occupancy=args.scale_down_occupancy,
                up_sustain_ticks=args.scale_up_ticks,
                down_sustain_ticks=args.scale_down_ticks,
                active_window_s=args.active_window_s,
            )
        except ValueError as exc:
            parser.error(str(exc))
        # The autoscaler owns the fleet size: boot at the floor (the
        # pinned base tier) and let traffic earn the surge replicas.
        args.replicas = args.min_replicas

    admission = None
    if args.admission_rate > 0 or args.max_inflight > 0:
        admission = AdmissionController(
            rate_per_client=args.admission_rate,
            burst=args.admission_burst,
            max_inflight=args.max_inflight,
        )

    faults.install_from(args.faults)
    # Export the combined fault spec so SPAWNED replicas arm their own
    # sites too (session_restore fires inside the replica process; the
    # supervisor's in-process plan can't reach it). Popen inherits
    # os.environ, and replica mains call faults.install_from("").
    combined_faults = ",".join(
        s for s in (args.faults, os.environ.get(faults.ENV_VAR, "")) if s
    )
    if combined_faults:
        os.environ[faults.ENV_VAR] = combined_faults

    from rt1_tpu.obs.slo import SLOLedger, SLOObjectives

    router = Router(
        replica_timeout_s=args.replica_timeout_s,
        max_failovers=args.max_failovers,
        slo=SLOLedger(
            SLOObjectives(
                availability=args.slo_availability,
                latency_p50_ms=args.slo_p50_ms,
                latency_p99_ms=args.slo_p99_ms,
            )
        ),
        admission=admission,
    )
    supervisor = FleetSupervisor(
        router,
        replica_argv_builder(args),
        args.replicas,
        chaos_interval_s=args.chaos_interval_s,
        poll_interval_s=args.poll_interval_s,
        warmup_timeout_s=args.warmup_timeout_s,
        log_dir=args.log_dir or None,
        capture_root=(args.capture_dir or None) if not args.stub else None,
        autoscale=policy,
        autoscale_interval_s=args.autoscale_interval_s,
        max_sessions=args.max_sessions,
        surge_dtype=args.surge_dtype or None,
        base_dtype_fn=lambda rid: replica_dtype_for(args, rid),
        reclaim_grace_s=args.reclaim_grace_s,
    )
    # Elastic-drain seam: POST /scale_down on the router drives the
    # supervisor's migrating drain (sessions carried to survivors before
    # the victim is reaped).
    router.scale_down_fn = supervisor.manual_scale_down
    supervisor.start(wait_ready=True)

    controller = None
    if args.promote_from:
        from rt1_tpu.deploy.controller import PromotionController
        from rt1_tpu.deploy.decision import CanaryPolicy
        from rt1_tpu.deploy.watcher import latest_checkpoint_step

        if args.deploy_incumbent_step >= 0:
            incumbent = args.deploy_incumbent_step
        else:
            # Auto: whatever is newest at arm time is what the fleet is
            # (presumed) serving — only LATER checkpoints are candidates.
            incumbent = latest_checkpoint_step(
                os.path.join(args.promote_from, "checkpoints")
            )
        if args.stub:
            # The supervisor process stays jax-free with stub replicas:
            # the offline gate auto-passes (canary burn + rollback paths
            # are what a stub deploy cycle exercises).
            def gate_fn(candidate_step, incumbent_step):
                return {
                    "gate": "auto_pass_stub",
                    "passed": True,
                    "candidate_step": candidate_step,
                    "incumbent_step": incumbent_step,
                }
        else:
            from rt1_tpu.deploy.gate import build_gate_fn, load_config

            gate_tasks = [t for t in args.gate_tasks.split(",") if t]
            gate_fn = build_gate_fn(
                load_config(args.config),
                args.promote_from,
                tasks=gate_tasks or None,
                episodes_per_cell=args.gate_episodes,
                max_episode_steps=args.gate_max_steps,
                inference_dtype=args.inference_dtype,
            )
        try:
            canary_policy = CanaryPolicy(
                burn_threshold=args.burn_threshold,
                breach_ticks=args.breach_ticks,
                clean_window_ticks=args.clean_window_ticks,
                min_canary_requests=args.min_canary_requests,
                canary_weight=args.canary_weight,
            )
        except ValueError as exc:
            parser.error(str(exc))
        controller = PromotionController(
            router,
            args.promote_from,
            gate_fn=gate_fn,
            policy=canary_policy,
            incumbent_step=incumbent,
            poll_interval_s=args.deploy_poll_interval_s,
        )
        router.deploy_gauges_fn = controller.deploy_gauges
        router.deploy_status_fn = controller.summary
        controller.start()

    httpd = make_router_server(
        router, host=args.host, port=args.port, quiet=not args.verbose
    )

    tsdb = None
    alert_manager = None
    collector = None
    if args.collector:
        from rt1_tpu.obs.alerts import AlertManager, default_ruleset
        from rt1_tpu.obs.collector import Collector, Target
        from rt1_tpu.obs.dashboard import render_dashboard_html
        from rt1_tpu.obs.tsdb import SNAPSHOT_BASENAME, TSDB

        tsdb = TSDB()
        alert_manager = AlertManager(
            tsdb,
            default_ruleset(),
            on_fire=supervisor.note_alert,
            on_resolve=supervisor.note_alert,
        )
        # The collector scrapes the fleet's OWN router port — the same
        # exposition text any external Prometheus would see, so the
        # history it stores can never disagree with the live scrape.
        router_url = (
            f"http://{httpd.server_address[0]}:{httpd.server_address[1]}"
        )
        obs_targets = [Target("fleet", router_url + "/metrics")]
        if controller is not None:
            obs_targets.append(
                Target(
                    "deploy",
                    router_url + "/deploy/status",
                    kind="json",
                    prefix="rt1_deploy_status",
                )
            )
        collector = Collector(
            tsdb,
            obs_targets,
            interval_s=args.collector_interval_s,
            alert_manager=alert_manager,
        )

        def _history(params: Dict[str, str]) -> Dict[str, Any]:
            # /history: no family = the series listing; family= one
            # family's windowed points across every label instance.
            # KeyError/ValueError propagate into the router's 400.
            window_s = float(params.get("window_s", 900.0))
            family = params.get("family", "")
            if not family:
                return {
                    "window_s": window_s,
                    "series": tsdb.series_index(),
                    "stats": tsdb.stats(),
                }
            series = [
                {
                    "family": family,
                    "labels": labels,
                    "points": tsdb.points(
                        family, labels=labels or None, window_s=window_s
                    ),
                }
                for labels in tsdb.instances(family)
            ]
            if not series:
                raise KeyError(family)
            return {
                "window_s": window_s, "family": family, "series": series,
            }

        router.alerts_status_fn = alert_manager.status
        router.history_fn = _history
        router.obs_metrics_text_fn = lambda: (
            alert_manager.prometheus_text() + collector.prometheus_text()
        )
        router.dashboard_html_fn = lambda: render_dashboard_html(
            tsdb,
            alert_manager=alert_manager,
            collector=collector,
            fleet_status=router.fleet_status(probe_metrics=False),
            deploy_status=(
                controller.deploy_gauges()
                if controller is not None
                else None
            ),
        )
        collector.start()

    stop_once = threading.Event()

    def _shutdown(signum, frame):  # noqa: ARG001 - signal signature
        if stop_once.is_set():
            return
        stop_once.set()
        threading.Thread(target=httpd.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)

    print(
        json.dumps(
            {
                "status": "serving",
                "role": "router",
                "host": httpd.server_address[0],
                "port": httpd.server_address[1],
                "replicas": args.replicas,
                "stub": bool(args.stub),
                "autoscale": (
                    {
                        "min": args.min_replicas,
                        "max": args.max_replicas,
                        "surge_dtype": args.surge_dtype or None,
                    }
                    if policy is not None
                    else None
                ),
                "admission": admission is not None,
                "collector": bool(args.collector),
                "deploy": (
                    {
                        "promote_from": args.promote_from,
                        "incumbent_step": controller.incumbent_step,
                        "canary_weight": args.canary_weight,
                    }
                    if controller is not None
                    else None
                ),
                "faults": args.faults or os.environ.get(faults.ENV_VAR, ""),
            }
        ),
        flush=True,
    )
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        if controller is not None:
            # Stop deciding BEFORE the drain flips: a promote/rollback
            # racing the shutdown would reload replicas mid-teardown.
            controller.stop()
        if collector is not None:
            # Stop scraping before teardown: a cycle racing the drain
            # would count shutdown 503s as target failures, and the
            # snapshot should capture the incident, not the funeral.
            collector.stop()
            obs_dir = args.obs_dir or args.workdir
            if obs_dir:
                tsdb.write_snapshot(
                    os.path.join(obs_dir, SNAPSHOT_BASENAME)
                )
        router.draining = True
        final = {
            "status": "stopped",
            "fleet": router.fleet_status(probe_metrics=True),
            "chaos": supervisor.summary(),
            # Elastic evidence for the bench: scale events + the
            # per-dtype replica-second cost ledger (always present; a
            # fixed fleet reports enabled=false with its own cost).
            "autoscale": supervisor.autoscale_summary(),
            "router_metrics": router.metrics_snapshot(),
            # The fleet's own judgement + crash-surviving exemplars, so a
            # chaos driver (loadgen) can fold the server-side SLO story
            # into its BENCH record without re-deriving it client-side.
            "slo": router.slo.summary(),
            "slow_requests": supervisor.slow_request_evidence(),
            # Promotion evidence (None without --promote_from): the full
            # gate/canary/promote/rollback timeline the deploy bench and
            # run-report consume.
            "deploy": (
                controller.summary() if controller is not None else None
            ),
            # Metrics-plane evidence (None unless --collector): final
            # alert state + full transition history, per-target scrape
            # bookkeeping, and the TSDB's own bounds counters.
            "obs": (
                {
                    "alerts": alert_manager.status(),
                    "alert_events": list(supervisor.alert_events),
                    "collector": collector.stats(),
                    "tsdb": tsdb.stats(),
                }
                if collector is not None
                else None
            ),
        }
        supervisor.stop()
        # Replicas drained on SIGTERM (writing their in-flight capture
        # buffers); one last sweep moves those into staging.
        supervisor.sweep_captures()
        final["chaos"]["captures_swept"] = supervisor.captures_swept
        print(json.dumps(final), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
