"""Session-affine router for a fleet of serving replicas.

A single serving process (`python -m rt1_tpu.serve`) holds one AOT-compiled
device batch; production traffic needs N of them. The catch is that a
session is not stateless: its rolling `network_state` (context image
tokens, action tokens, seq_idx) lives in a device slot on exactly ONE
replica (`serve/engine.py`), so a round-robin balancer would scatter a
session's observations across engines and corrupt every window. This
router keeps the affinity map — session id -> replica — and layers the
fleet behaviors on top:

* **Health-aware placement.** New sessions land on the READY replica with
  the fewest live sessions. Readiness comes from each replica's `/readyz`
  (warming / draining / reloading all report 503): a replica still paying
  XLA startup or mid-hot-swap keeps serving its existing sessions but
  receives no new ones.
* **Bounded failover, surfaced honestly.** A transport-dead replica
  (connection refused/reset, timeout) fails the request over to a live
  one — the session's rolling window is gone with the dead engine, so the
  re-homed `/act` starts a fresh window (the engine zeroes the slot) and
  the response carries ``"restarted": true``. The client sees a context
  reset it can react to, never a 5xx. Every other session homed on the
  dead replica is marked orphaned and picks up the same flag on its next
  act. Failover is bounded (`max_failovers`); past it the router sheds
  with a retryable 503.
* **Durable sessions / live migration** (`serve/migrate.py`). Planned
  reclaims do NOT reset windows: the scale-down drain and the rolling
  reload export each victim session (replica `POST /session/export`)
  and import it onto the least-loaded compatible survivor BEFORE
  anything is orphaned — affinity remaps atomically and the client's
  next act continues token-identically, carrying ``"migrated": true``
  (an SLO-good outcome class) instead of ``"restarted": true``.
  `POST /rebalance` moves the N hottest sessions off an overloaded
  replica through the same path. A replica that restored a window from
  its crash-durability snapshot ring reports ``session_restored`` and
  is booked ``migrated`` too. A failed export/import (generation /
  window / engine-mode skew, injected fault) degrades to the legacy
  orphan/restart path — the flag flips back to ``restarted``, never a
  5xx.
* **Rolling checkpoint reload.** `POST /reload` walks the fleet one
  replica at a time: hot-swap (`serve/server.py` `/reload` — zero-downtime
  in-place), then wait for `/readyz` to report ready again before touching
  the next replica. At most one replica is ever in the not-ready drain
  state, so fleet capacity never dips by more than one engine.
* **Request tracing.** Every `/act` resolves one request id (client
  `X-RT1-Request-Id` header honored, else minted — `serve/reqtrace.py`),
  wraps the route in a `router_route` span carrying that id, and forwards
  the id to the replica in the same header, so the router span, the
  replica's `replica_act`/`batch_wait`/`device_step` spans, and the
  response's `request_id` all correlate in one Perfetto timeline.
* **SLO ledger.** Every routed request lands in one outcome class
  (ok / restarted / rejected / failed — `rt1_tpu/obs/slo.py`); the
  ledger's availability / error-budget-burn gauges ride `/metrics` as
  ``rt1_serve_slo_*`` and `GET /slo` returns the full judgement. Each
  outcome is ALSO attributed to the replica that answered (or died
  answering), so one replica's burn — the canary question — is
  distinguishable from the fleet's: per-replica ledgers ride
  `/fleet/status` (``slo`` sub-dict), the JSON `/metrics` fan-out
  (``replica_slo``), and Prometheus text
  (``rt1_serve_replica_outcome_total{replica_id=,outcome=}`` plus
  per-replica rolling availability/burn gauges). Outcomes no replica
  produced — admission sheds, no-capacity 503s, exhausted failover —
  stay fleet-wide only: blaming a replica for a request it never saw
  would poison a canary verdict.
* **Fleet metrics aggregation.** The router's `/metrics` fans out to
  every live replica's `/metrics` and merges the snapshots into ONE
  scrape target: JSON carries a ``replicas`` map keyed by replica id,
  Prometheus text renders each curated replica field as a labeled family
  (``rt1_serve_replica_*{replica_id="N"}``). `GET /fleet/slow_requests`
  fans out the slow-request exemplar rings the same way.
* **Admission control** (`AdmissionController`, opt-in). Per-client token
  buckets plus a global in-flight threshold shed overload as fast 429s
  in the ``rejected`` outcome class — priced honestly against the SLO
  ledger (latency objectives judge answered requests only; the per-class
  burn entries book every shed). Shed reasons ride
  ``rt1_serve_autoscale_shed_total{reason=}``.
* **Elastic-fleet hooks.** The autoscaling supervisor (`serve/fleet.py`)
  reads router-observed signals (`active_session_count` — sessions that
  acted inside the recency window, `inflight`, the SLO rolling burn) and
  drives scale-down through `deplace` (stop placement + orphan sessions
  so they re-home via the existing failover path) and `remove_replica`
  (purge the reaped id from every map, so `/metrics` and `/fleet/status`
  never report a ghost). Placement is tier-aware: load first, then the
  pinned base tier beats quantized surge replicas on ties.

The router carries no model code — stdlib HTTP + `ServeMetrics` only — so
it stays featherweight next to N jax-heavy replicas (pinned by
`tests/test_obs_imports.py`). Process supervision (spawn, restart,
chaos) lives in `serve/fleet.py`; the router only reads the replica table
the supervisor maintains.
"""

from __future__ import annotations

import collections
import json
import math
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from rt1_tpu.obs import prometheus as obs_prometheus
from rt1_tpu.obs import trace as obs_trace
from rt1_tpu.obs.slo import OUTCOMES, SLOLedger, SLOObjectives
from rt1_tpu.serve import migrate, reqtrace
from rt1_tpu.serve.metrics import ServeMetrics

# Replica lifecycle as the router sees it. STARTING covers spawn ->
# ready-line -> first /readyz 200 (warm-up gating: never placed on);
# NOTREADY is a live replica whose /readyz says 503 (draining/reloading);
# DEAD is transport-dead or process-exited, awaiting supervisor respawn.
STARTING = "starting"
READY = "ready"
NOTREADY = "notready"
DEAD = "dead"


def post_json(
    url: str,
    payload: Dict[str, Any],
    timeout: float,
    headers: Optional[Dict[str, str]] = None,
) -> Tuple[int, Dict[str, Any]]:
    """POST JSON -> (status, body); status 0 = transport failure (the
    failover trigger: refused, reset, timeout, or a non-JSON corpse).
    `headers` rides extra metadata (the request-id propagation hop)."""
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        try:
            return exc.code, json.loads(exc.read())
        except Exception:  # noqa: BLE001 - non-JSON error body
            return exc.code, {"error": str(exc)}
    except Exception as exc:  # noqa: BLE001 - URLError/OSError/timeout/JSON
        return 0, {"error": str(exc)}


def get_json(url: str, timeout: float) -> Tuple[int, Dict[str, Any]]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        try:
            return exc.code, json.loads(exc.read())
        except Exception:  # noqa: BLE001
            return exc.code, {"error": str(exc)}
    except Exception as exc:  # noqa: BLE001
        return 0, {"error": str(exc)}


#: Placement preference order for capacity tiers: on a load tie, a new
#: session lands on the pinned full-precision base tier before a quantized
#: surge replica — the base tier is the parity canary, surge absorbs
#: overflow (docs/serving.md "Elastic fleet").
TIER_BASE = "base"
TIER_SURGE = "surge"
_TIER_RANK = {TIER_BASE: 0, TIER_SURGE: 1}


class Replica:
    """One serving process as the router tracks it (supervisor-owned
    fields — proc, restarts, tier, dtype, spawned_at — are written by
    serve/fleet.py)."""

    def __init__(self, replica_id: int, url: Optional[str] = None, proc=None):
        self.id = replica_id
        self.url = url  # base http://host:port, known once the ready-line
        #                 is read from the replica's stdout
        self.proc = proc
        self.state = STARTING
        self.restarts = 0  # times the supervisor respawned this slot
        self.consecutive_probe_failures = 0
        # Elastic-fleet capacity tiering: the initial fleet is the pinned
        # "base" tier; autoscaler-spawned surge replicas are "surge"
        # (typically quantized — int8 replicas are ~3.71x cheaper in
        # device param bytes, BENCH_serve_quant.json). `dtype` and
        # `spawned_at` feed the replica-second cost accounting.
        self.tier = TIER_BASE
        self.dtype: Optional[str] = None
        self.spawned_at: Optional[float] = None

    def summary(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "url": self.url,
            "state": self.state,
            "restarts": self.restarts,
            "tier": self.tier,
            "dtype": self.dtype,
        }


class AdmissionController:
    """Router-side admission control: per-client token buckets + a global
    overload threshold, so overload produces fast ``rejected`` 429s
    instead of blown p99s.

    * **Token bucket per client id** (`client_id` payload field, else the
      session id): `rate_per_client` tokens/s refill up to `burst`; an
      /act with no token is shed with reason ``client_rate``. One hot
      client cannot starve the fleet.
    * **Global shed threshold**: when more than `max_inflight` requests
      are simultaneously mid-route through the router, new arrivals shed
      with reason ``overload`` — the fleet is saturated fleet-wide and a
      queued request would only blow the answered-request p99.

    Shedding is priced honestly: every 429 lands in the SLO ledger's
    ``rejected`` class (which burns error budget per-class) and the
    latency objectives are judged on answered requests only — a fleet
    cannot "fix" its p99 by shedding (`rt1_tpu/obs/slo.py`).

    Stdlib-only and clock-injectable (tests drive a fake monotonic
    clock). Zero `rate_per_client` disables the per-client bucket, zero
    `max_inflight` disables the global threshold — both default off, so
    a router without an admission config behaves exactly as before.
    """

    def __init__(
        self,
        rate_per_client: float = 0.0,
        burst: float = 8.0,
        max_inflight: int = 0,
        max_clients: int = 65536,
        clock=time.monotonic,
    ):
        if rate_per_client < 0 or burst < 1.0:
            # burst < 1 would mean no bucket ever reaches a whole token:
            # every client's every request shed, forever — a total
            # lockout, not a rate limit.
            raise ValueError(
                f"rate_per_client must be >= 0 and burst >= 1, got "
                f"{rate_per_client}/{burst}"
            )
        self.rate_per_client = rate_per_client
        self.burst = burst
        self.max_inflight = max_inflight
        self.max_clients = max_clients
        self._clock = clock
        self._lock = threading.Lock()
        # client id -> [tokens, last_refill]; LRU-bounded (a bucket is
        # two floats, so the 64k default costs ~6 MB worst case). A
        # client that went quiet long enough to be evicted re-enters
        # with a full bucket — exactly what its refill would have
        # reached. Limitation, stated honestly: with MORE simultaneously
        # active clients than max_clients, hot clients get continuously
        # evicted-and-refilled and the per-client rate stops binding;
        # size max_clients above the concurrent client population, and
        # rely on `max_inflight` as the id-cycling/overload backstop
        # (an adversary minting fresh client ids defeats any per-client
        # bucket by construction).
        self._buckets: collections.OrderedDict = collections.OrderedDict()

    def reject_reason(self, client_id: str, inflight: int) -> Optional[str]:
        """None = admitted; otherwise the shed-reason label. Checked (and
        the token spent) once per routed /act, before placement."""
        if self.max_inflight > 0 and inflight > self.max_inflight:
            return "overload"
        if self.rate_per_client <= 0:
            return None
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(client_id)
            if bucket is None:
                bucket = [self.burst, now]
                self._buckets[client_id] = bucket
                while len(self._buckets) > self.max_clients:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(client_id)
            tokens, last = bucket
            tokens = min(
                self.burst, tokens + (now - last) * self.rate_per_client
            )
            if tokens < 1.0:
                bucket[0] = tokens
                bucket[1] = now
                return "client_rate"
            bucket[0] = tokens - 1.0
            bucket[1] = now
            return None

    def gauges(self) -> Dict[str, float]:
        """Token-bucket gauges for the router's /metrics merge."""
        with self._lock:
            tracked = len(self._buckets)
        return {
            "admission_clients_tracked": float(tracked),
            "admission_rate_per_client": self.rate_per_client,
            "admission_burst": self.burst,
            "admission_max_inflight": float(self.max_inflight),
        }


class Router:
    """Session-affinity routing table + failover + rolling reload."""

    def __init__(
        self,
        *,
        replica_timeout_s: float = 30.0,
        max_failovers: int = 2,
        reload_timeout_s: float = 300.0,
        max_tracked_sessions: int = 8192,
        metrics: Optional[ServeMetrics] = None,
        slo: Optional[SLOLedger] = None,
        metrics_probe_timeout_s: float = 3.0,
        admission: Optional[AdmissionController] = None,
    ):
        self._lock = threading.RLock()
        self._replicas: Dict[int, Replica] = {}
        # session id -> replica id, LRU-ordered and bounded: replicas cap
        # their own live state at max_sessions slots, so a router tracking
        # every id ever seen would leak memory and count long-evicted
        # sessions into "least-loaded" placement. Oldest entries fall off
        # past `max_tracked_sessions` (an evicted session that returns is
        # simply re-placed, same as after a replica-side LRU reclaim).
        self._sessions: collections.OrderedDict = collections.OrderedDict()
        self.max_tracked_sessions = max_tracked_sessions
        # Sessions whose replica died: their next successful act carries
        # "restarted": true so the client learns its context was reset.
        # Dict-as-ordered-set (values unused): bound eviction must drop
        # the OLDEST orphan first — set.pop() removed an arbitrary one,
        # which could silently eat a fresh orphan's restarted flag while
        # keeping a stale one forever.
        self._orphaned: Dict[str, None] = {}
        # Sessions whose window was carried to another replica intact
        # (live migration or ring restore): their next successful act
        # carries "migrated": true — continuity, not a reset. Same
        # ordered-set idiom and bound as the orphan map.
        self._migrated: Dict[str, None] = {}
        self.replica_timeout_s = replica_timeout_s
        self.max_failovers = max_failovers
        self.reload_timeout_s = reload_timeout_s
        self.metrics = metrics if metrics is not None else ServeMetrics()
        # The fleet's judge: every routed /act lands in exactly one
        # outcome class; gauges ride /metrics, GET /slo has the verdict.
        self.slo = slo if slo is not None else SLOLedger(SLOObjectives())
        # Per-replica attribution of the same outcome stream: one ledger
        # per replica that has ever answered (or died answering) an /act,
        # created lazily with the fleet ledger's objectives. A removed
        # replica's ledger is dropped with it (`remove_replica` — same
        # dropped-not-zeroed contract as the metrics fan-out).
        self._replica_slo: Dict[int, SLOLedger] = {}
        self.metrics_probe_timeout_s = metrics_probe_timeout_s
        # Admission control (ISSUE 15): None keeps the pre-elastic router
        # byte-identical — every request is admitted.
        self.admission = admission
        # Elastic-fleet occupancy signal: session id -> monotonic time of
        # its last answered act, recency-ordered. The affinity map counts
        # every session the router ever placed; the autoscaler needs the
        # sessions that are actually TALKING — active_session_count()
        # walks this from most-recent until it falls out of the window.
        self._act_times: collections.OrderedDict = collections.OrderedDict()
        # Requests currently mid-route (the router-side queue-depth
        # analogue): an autoscale signal and the global-shed input.
        self._inflight = 0
        self.draining = False
        # Weighted canary placement (deploy subsystem): while set, a
        # configured fraction of FRESH session placements land on the
        # canary replica instead of the least-loaded pick. Existing
        # sessions keep their affinity — a canary experiments on new
        # traffic, it never steals live windows.
        self._canary_id: Optional[int] = None
        self._canary_weight = 0.0
        self._fresh_placements = 0  # Bresenham counter, reset per canary
        # Deployment seam (ISSUE 16): fleet main points these at the
        # PromotionController when --promote_from is armed. The router
        # itself stays deploy-agnostic — when unset, /metrics and the
        # status surface are byte-identical to a fleet without a
        # controller.
        self.deploy_gauges_fn: Optional[Callable[[], Dict[str, Any]]] = None
        self.deploy_status_fn: Optional[Callable[[], Dict[str, Any]]] = None
        # Observability seam (ISSUE 18): fleet main points these at the
        # collector/TSDB/AlertManager when --collector is armed. Same
        # contract as the deploy seam — all None keeps every surface
        # (/alerts, /history, /dashboard, the appended rt1_alert_* /
        # rt1_obs_collector_* scrape families) absent and the unarmed
        # router byte-identical.
        self.alerts_status_fn: Optional[Callable[[], Dict[str, Any]]] = None
        # Elastic-drain seam: fleet main points this at the supervisor's
        # manual scale-down so `POST /scale_down` drives the migrating
        # drain end to end. Unset = 404 (routers without a supervisor).
        self.scale_down_fn: Optional[
            Callable[[Dict[str, Any]], Dict[str, Any]]
        ] = None
        self.history_fn: Optional[
            Callable[[Dict[str, str]], Dict[str, Any]]
        ] = None
        self.dashboard_html_fn: Optional[Callable[[], str]] = None
        self.obs_metrics_text_fn: Optional[Callable[[], str]] = None

    # ------------------------------------------------------------ registry

    def add_replica(self, replica: Replica) -> Replica:
        with self._lock:
            self._replicas[replica.id] = replica
        return replica

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def set_state(self, replica_id: int, state: str) -> None:
        with self._lock:
            replica = self._replicas.get(replica_id)
            if replica is None:
                return
            replica.state = state
            if state == DEAD:
                self._orphan_sessions_locked(replica_id)

    def _orphan_sessions_locked(self, replica_id: int) -> None:
        lost = [s for s, r in self._sessions.items() if r == replica_id]
        for sid in lost:
            del self._sessions[sid]
            self._mark_orphaned_locked(sid)

    def _mark_orphaned_locked(self, session_id: str) -> None:
        """Insertion-ordered add + oldest-first bound eviction: a client
        that dies with its replica never comes back to consume its
        restarted flag, and repeated replica churn would otherwise grow
        this forever. Evicting oldest-first (not set.pop()'s arbitrary
        pick) guarantees a fresh orphan's flag survives eviction
        pressure."""
        self._orphaned.pop(session_id, None)  # re-orphan = newest again
        self._orphaned[session_id] = None
        while len(self._orphaned) > self.max_tracked_sessions:
            del self._orphaned[next(iter(self._orphaned))]

    def _mark_migrated_locked(self, session_id: str) -> None:
        """Same ordered-set discipline for the migrated-flag map."""
        self._migrated.pop(session_id, None)
        self._migrated[session_id] = None
        while len(self._migrated) > self.max_tracked_sessions:
            del self._migrated[next(iter(self._migrated))]

    def mark_dead(self, replica: Replica, reason: str = "") -> None:
        """Replica is gone: orphan its sessions so their next act re-homes
        (and reports restarted). Supervisor respawn flips it back later."""
        del reason  # kept for call-site readability / future logging
        self.set_state(replica.id, DEAD)

    def deplace(self, replica_id: int) -> None:
        """Scale-down drain, step one: stop placing on the replica
        (NOTREADY — its own /readyz will report 503 once it drains) and
        orphan its sessions NOW so their next act re-homes through the
        existing failover path with ``restarted: true``. The replica keeps
        answering whatever is already in flight; the supervisor reaps the
        process only after this and a drain grace."""
        with self._lock:
            replica = self._replicas.get(replica_id)
            if replica is None:
                return
            replica.state = NOTREADY
            self._orphan_sessions_locked(replica_id)

    def remove_replica(self, replica_id: int) -> Optional[Replica]:
        """Scale-down reclaim, final step: purge the reaped replica from
        the routing table entirely. Unlike a DEAD replica (which the
        supervisor will respawn into the same slot), a removed replica is
        GONE: `/fleet/status`, the `/metrics` fan-out, and the
        `rt1_serve_replica_*` labeled families stop reporting its id —
        dropped, not zeroed (a ghost `replica_up 0` forever would read as
        a permanently-failing probe, not a deliberate reclaim)."""
        with self._lock:
            replica = self._replicas.pop(replica_id, None)
            if replica is not None:
                self._orphan_sessions_locked(replica_id)
            self._replica_slo.pop(replica_id, None)
            return replica

    def _orphan_session(self, session_id: str, replica_id: int) -> None:
        """Re-home ONE session (replica slow or mid-respawn): unmap it and
        flag the restart, leaving its neighbors' state intact."""
        with self._lock:
            if self._sessions.get(session_id) == replica_id:
                del self._sessions[session_id]
            self._mark_orphaned_locked(session_id)

    # ----------------------------------------------------------- placement

    def session_count(self, replica_id: int) -> int:
        with self._lock:
            return sum(1 for r in self._sessions.values() if r == replica_id)

    def _place_locked(self, session_id: str) -> Optional[Replica]:
        ready = [r for r in self._replicas.values() if r.state == READY]
        if not ready:
            return None
        loads = {rid: 0 for rid in self._replicas}
        for rid in self._sessions.values():
            loads[rid] = loads.get(rid, 0) + 1

        def least_loaded(candidates):
            # Tier-aware least-loaded: load first (surge capacity absorbs
            # genuine overflow), then the pinned base tier on ties (the
            # full-precision parity canary keeps serving the steady
            # state).
            return min(
                candidates,
                key=lambda r: (
                    loads.get(r.id, 0),
                    _TIER_RANK.get(r.tier, 0),
                    r.id,
                ),
            )

        best = None
        canary = (
            self._replicas.get(self._canary_id)
            if self._canary_id is not None
            else None
        )
        if canary is not None and canary.state == READY:
            # Deterministic weighted split (Bresenham): the n-th fresh
            # placement goes to the canary iff the running floor of
            # n*weight ticks up — exactly weight of fresh sessions, no
            # RNG, replayable in tests. A not-READY canary (mid-reload)
            # simply drops out of the split until it recovers.
            n = self._fresh_placements
            self._fresh_placements = n + 1
            w = self._canary_weight
            if math.floor((n + 1) * w) > math.floor(n * w):
                best = canary
            else:
                rest = [r for r in ready if r.id != canary.id]
                if rest:
                    best = least_loaded(rest)
                # A fleet where the canary is the only ready replica
                # falls through: serving beats the split.
        if best is None:
            best = least_loaded(ready)
        self._sessions[session_id] = best.id
        self._sessions.move_to_end(session_id)
        while len(self._sessions) > self.max_tracked_sessions:
            stale, _ = self._sessions.popitem(last=False)
            self._orphaned.pop(stale, None)
            self._migrated.pop(stale, None)
        return best

    # -------------------------------------------------------------- canary

    def set_canary(self, replica_id: int, weight: float) -> None:
        """Start the weighted canary split: `weight` of FRESH session
        placements land on `replica_id` (its existing sessions and every
        other session's affinity are untouched). The Bresenham counter
        resets so each canary's split starts deterministically."""
        if not 0.0 < weight <= 1.0:
            raise ValueError(f"canary weight must be in (0, 1], got {weight}")
        with self._lock:
            if replica_id not in self._replicas:
                raise KeyError(f"unknown replica {replica_id}")
            self._canary_id = replica_id
            self._canary_weight = float(weight)
            self._fresh_placements = 0

    def clear_canary(self) -> Optional[int]:
        """End the split, keeping the canary's sessions where they are —
        the PROMOTE path (the canary's checkpoint just became the fleet's,
        so its sessions are already on the right params)."""
        with self._lock:
            rid = self._canary_id
            self._canary_id = None
            self._canary_weight = 0.0
            self._fresh_placements = 0
            return rid

    def demote_canary(self) -> Optional[int]:
        """End the split AND evict the canary's sessions — the ROLLBACK
        path: every session on the breaching candidate re-homes through
        the existing failover machinery (next act lands on an incumbent
        replica with ``restarted: true``, never a 5xx)."""
        with self._lock:
            rid = self._canary_id
            self._canary_id = None
            self._canary_weight = 0.0
            self._fresh_placements = 0
            if rid is not None:
                self._orphan_sessions_locked(rid)
            return rid

    def canary_status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replica_id": self._canary_id,
                "weight": self._canary_weight,
                "fresh_placements": self._fresh_placements,
            }

    def reload_one(
        self, replica_id: int, step: Optional[int] = None
    ) -> Dict[str, Any]:
        """Hot-swap ONE replica — the canary-load / canary-rollback
        primitive. Same entry shape as a `rolling_reload` element: POST
        `/reload`, then wait for `/readyz` to recover (``recovered``), so
        the caller knows the replica is serving the requested step before
        any traffic decision leans on it."""
        with self._lock:
            replica = self._replicas.get(replica_id)
        if replica is None:
            return {"replica": replica_id, "skipped": "unknown"}
        if replica.state == DEAD or replica.url is None:
            return {"replica": replica_id, "skipped": replica.state}
        payload = {} if step is None else {"step": step}
        status, body = post_json(
            replica.url + "/reload", payload, self.reload_timeout_s
        )
        entry = {"replica": replica_id, "status": status, **body}
        if status == 0:
            self.mark_dead(replica, reason=body.get("error", ""))
        elif status == 200:
            entry["recovered"] = self._await_ready(replica)
            if not entry["recovered"]:
                entry["ok"] = False
            self.metrics.observe_reload()
        return entry

    def _replica_for(self, session_id: str) -> Optional[Replica]:
        """Existing assignment if its replica is still routable, else a
        fresh placement on the least-loaded ready replica (None when the
        fleet has no ready replica)."""
        with self._lock:
            rid = self._sessions.get(session_id)
            if rid is not None:
                replica = self._replicas.get(rid)
                # Affinity overrides readiness for NOTREADY (draining/
                # reloading replicas keep serving existing sessions);
                # only DEAD forces a re-placement.
                if replica is not None and replica.state != DEAD:
                    self._sessions.move_to_end(session_id)  # LRU touch
                    return replica
                del self._sessions[session_id]
                self._mark_orphaned_locked(session_id)
            return self._place_locked(session_id)

    # ------------------------------------------------------------- routing

    def route_act(
        self,
        payload: Dict[str, Any],
        headers=None,
    ) -> Tuple[int, Dict[str, Any]]:
        """Forward one /act with affinity + bounded failover. A replica
        death mid-request becomes `restarted: true` on the retried 200,
        never a client-visible 5xx.

        One request id spans the whole route: resolved here (client header
        / payload / minted), carried by the `router_route` span, forwarded
        to the replica in the `X-RT1-Request-Id` header, and echoed in the
        response body — including error bodies, so a client can quote the
        id of the exact request that was shed. Every exit classifies into
        the SLO ledger with the router-side wall time.
        """
        request_id = reqtrace.request_id_from(headers, payload)
        t0 = time.perf_counter()
        with self._lock:
            self._inflight += 1
        try:
            with obs_trace.span(
                "router_route",
                request_id=request_id,
                session=payload.get("session_id"),
            ):
                status, body, served_by = self._route_act_inner(
                    payload, request_id
                )
        finally:
            with self._lock:
                self._inflight -= 1
        body.setdefault("request_id", request_id)
        elapsed = time.perf_counter() - t0
        if status == 200 and "error" not in body:
            if body.get("migrated"):
                outcome = "migrated"
            elif body.get("restarted"):
                outcome = "restarted"
            else:
                outcome = "ok"
            self._note_act(payload.get("session_id"))
            # Router-side per-task labels under the single-replica family
            # names (the PR 8 convention): fleet-wide task totals on the
            # router scrape, per-replica splits in the aggregated
            # rt1_serve_replica_task_* families.
            task = payload.get("task")
            self.metrics.observe_task_request(
                task if isinstance(task, str) else None,
                new_session=body.get("session_started", False),
            )
        elif status in (429, 503):
            # 429 = admission-control shed, 503 = backpressure/no-capacity
            # shed; both are the `rejected` outcome class, priced against
            # the error budget per-class by the SLO ledger.
            outcome = "rejected"
        else:
            outcome = "failed"
        self.slo.observe(outcome, elapsed)
        # Attribute the same outcome to the replica that produced it.
        # `served_by` is None for requests no replica answered (admission
        # shed, draining, no capacity, failover budget exhausted) — those
        # stay fleet-wide only.
        self._observe_replica(served_by, outcome, elapsed)
        return status, body

    def _observe_replica(
        self, replica_id: Optional[int], outcome: str, elapsed: float
    ) -> None:
        """Book one outcome on the serving replica's own ledger (lazily
        created with the fleet ledger's objectives). None = no replica
        produced this response; the fleet-wide ledger already has it."""
        if replica_id is None:
            return
        with self._lock:
            ledger = self._replica_slo.get(replica_id)
            if ledger is None:
                ledger = SLOLedger(self.slo.objectives)
                self._replica_slo[replica_id] = ledger
        ledger.observe(outcome, elapsed)

    def replica_slo_snapshot(self) -> Dict[int, Dict[str, Any]]:
        """Per-replica outcome attribution, keyed by replica id: the
        outcome-class counts plus the rolling availability / burn pair a
        canary judgement reads. Only replicas that ever answered appear;
        a removed replica's entry is dropped with it."""
        with self._lock:
            ledgers = sorted(self._replica_slo.items())
        out: Dict[int, Dict[str, Any]] = {}
        for rid, ledger in ledgers:
            gauges = ledger.gauges()
            out[rid] = {
                "outcomes": {
                    o: int(gauges[f"slo_requests_{o}"]) for o in OUTCOMES
                },
                "requests_total": int(gauges["slo_requests_total"]),
                "availability_rolling": gauges["slo_availability_rolling"],
                "error_budget_burn_rolling": gauges[
                    "slo_error_budget_burn_rolling"
                ],
            }
        return out

    def _note_act(self, session_id) -> None:
        """Record an answered act for the occupancy signal (recency
        order; bounded alongside the affinity map)."""
        if not isinstance(session_id, str):
            return
        with self._lock:
            self._act_times[session_id] = time.monotonic()
            self._act_times.move_to_end(session_id)
            while len(self._act_times) > self.max_tracked_sessions:
                self._act_times.popitem(last=False)

    def active_session_count(self, window_s: float) -> int:
        """Sessions that acted within the last `window_s` seconds — the
        autoscaler's occupancy numerator. A session that went quiet stops
        counting when the window passes it, even though its affinity-map
        entry (and its replica-side slot) still exists."""
        cutoff = time.monotonic() - window_s
        count = 0
        with self._lock:
            for _, t in reversed(self._act_times.items()):
                if t < cutoff:
                    break  # recency-ordered: everything older is stale too
                count += 1
        return count

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def _route_act_inner(
        self, payload: Dict[str, Any], request_id: str
    ) -> Tuple[int, Dict[str, Any], Optional[int]]:
        """Route one /act -> (status, body, served_by). ``served_by`` is
        the id of the replica whose answer (or terminal error) this is,
        None when no replica produced the response — the per-replica SLO
        attribution key."""
        session_id = payload.get("session_id")
        if not isinstance(session_id, str) or not session_id:
            return (
                400,
                {"error": "'session_id' must be a non-empty string"},
                None,
            )
        if self.draining:
            return 503, {"error": "draining"}, None
        if self.admission is not None:
            # Admission control BEFORE placement: a shed request must be
            # fast (no replica hop) and cheap (no affinity mutation). The
            # client id defaults to the session id; a client running many
            # sessions can declare `client_id` to share one bucket.
            client = payload.get("client_id")
            reason = self.admission.reject_reason(
                client if isinstance(client, str) and client else session_id,
                self.inflight,
            )
            if reason is not None:
                self.metrics.observe_shed(reason)
                return (
                    429,
                    {
                        "error": f"admission control shed this request "
                        f"({reason})",
                        "reason": reason,
                        # Explicitly NOT retry:true — the client should
                        # back off, not hammer the token bucket (contrast
                        # the transient 503 busy path).
                        "retry": False,
                    },
                    None,
                )
        fwd_headers = {reqtrace.REQUEST_ID_HEADER: request_id}
        last_error = "no ready replicas"
        for _ in range(self.max_failovers + 1):
            replica = self._replica_for(session_id)
            if replica is None:
                return (
                    503,
                    {"error": "no ready replicas", "retry": True},
                    None,
                )
            # Snapshot the url: the supervisor may respawn this replica
            # (resetting url to None) between our request and the probe.
            target_url = replica.url
            if target_url is None:
                self._orphan_session(session_id, replica.id)
                continue
            status, body = post_json(
                target_url + "/act",
                payload,
                self.replica_timeout_s,
                headers=fwd_headers,
            )
            if status == 0:
                # Transport failure. Dead and merely-slow look identical
                # from one request (a timeout is also status 0), but the
                # blast radius differs: probe /readyz once to tell them
                # apart before orphaning EVERY session homed there.
                last_error = body.get("error", "transport failure")
                probe, _ = get_json(target_url + "/readyz", timeout=2.0)
                if probe == 0:
                    # Probe dead too: the replica is gone (or wedged —
                    # the supervisor's hang detector will kill it).
                    self.mark_dead(replica, reason=last_error)
                else:
                    # Alive but slow for THIS request: re-home only this
                    # session (its window may have advanced server-side —
                    # honesty demands the restarted flag either way) and
                    # leave its neighbors' state intact.
                    self._orphan_session(session_id, replica.id)
                continue
            if status == 200:
                with self._lock:
                    if session_id in self._migrated:
                        # Live migration carried the window intact —
                        # continuity, not a reset. The migrated flag
                        # consumes any stale orphan mark from an earlier
                        # event on the same session.
                        self._migrated.pop(session_id, None)
                        self._orphaned.pop(session_id, None)
                        body["migrated"] = True
                        self.metrics.observe_session_migration()
                    elif session_id in self._orphaned:
                        self._orphaned.pop(session_id, None)
                        if body.get("session_restored"):
                            # The replica restored the orphan's window
                            # from its crash-durability snapshot ring —
                            # the event happened, but the window
                            # survived it.
                            body["migrated"] = True
                            self.metrics.observe_session_migration()
                        else:
                            body["restarted"] = True
                            self.metrics.observe_session_restart()
                    elif body.get("session_restored"):
                        # Restored without the router ever noticing the
                        # death (e.g. the supervisor respawned between
                        # acts): still preserved continuity.
                        body["migrated"] = True
                        self.metrics.observe_session_migration()
            return status, body, replica.id
        return (
            503,
            {
                "error": f"failover budget exhausted: {last_error}",
                "retry": True,
            },
            None,
        )

    def route_session_op(
        self, path: str, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        """/reset places (a reset starts a fresh window anywhere);
        /release forwards to the owner and always clears the local map."""
        session_id = payload.get("session_id")
        if not isinstance(session_id, str) or not session_id:
            return 400, {"error": "'session_id' must be a non-empty string"}
        if path == "/release":
            with self._lock:
                rid = self._sessions.pop(session_id, None)
                was_orphaned = session_id in self._orphaned
                self._orphaned.pop(session_id, None)
                self._migrated.pop(session_id, None)
                # A released session is done talking: drop it from the
                # occupancy signal NOW (an orphaned session stays counted
                # — its client is alive and about to re-home).
                self._act_times.pop(session_id, None)
                replica = self._replicas.get(rid) if rid is not None else None
            if replica is None or replica.state == DEAD:
                # Never-seen is a client error; a session whose replica
                # died (orphaned, or mapped to a dead/gone replica) has no
                # server-side slot left to free — that release is a
                # successful no-op, not a 404.
                if rid is None and not was_orphaned:
                    return 404, {"error": f"unknown session {session_id!r}"}
                return 200, {"ok": True, "note": "replica was dead"}
            return post_json(
                replica.url + path, payload, self.replica_timeout_s
            )
        replica = self._replica_for(session_id)
        if replica is None:
            return 503, {"error": "no ready replicas", "retry": True}
        status, body = post_json(
            replica.url + path, payload, self.replica_timeout_s
        )
        if status == 0:
            self.mark_dead(replica, reason=body.get("error", ""))
            return 503, {"error": "replica died during reset", "retry": True}
        if status == 200:
            with self._lock:
                self._orphaned.pop(session_id, None)  # an explicit reset
                #   is a client-acknowledged fresh window, not a restart
                self._migrated.pop(session_id, None)
        return status, body

    # ----------------------------------------------------- live migration

    def _compat_surface(self, url: str) -> Optional[Tuple[Any, Any, Any]]:
        """(checkpoint_generation, window, cached_inference) from a
        replica's /healthz, or None when the probe failed or the replica
        predates the migration contract (no generation key — nothing to
        compare, let the import itself decide)."""
        status, body = get_json(url + "/healthz", timeout=5.0)
        if status != 200 or "checkpoint_generation" not in body:
            return None
        return (
            body.get("checkpoint_generation"),
            body.get("window"),
            bool(body.get("cached_inference", False)),
        )

    def migrate_sessions_from(
        self,
        replica_id: int,
        reason: str = "",
        session_ids: Optional[List[str]] = None,
        orphan_on_failure: bool = False,
    ) -> Dict[str, Any]:
        """Carry sessions off `replica_id` onto the least-loaded READY
        compatible survivor, one export/import round-trip each
        (`serve/migrate.py`), remapping affinity atomically on success —
        the client's next act continues token-identically with
        ``migrated: true``.

        `session_ids` narrows the move (the /rebalance path); None moves
        everything homed there (the drain / rolling-reload paths). The
        pre-flight /healthz compatibility guard skips targets whose
        checkpoint generation / window / engine mode differ from the
        source — a doomed import would only burn failure counters (the
        import itself still refuses, 409, if skew appears between probe
        and import). Sessions that could not migrate stay mapped unless
        `orphan_on_failure` (the drain path orphans them NOW so the
        legacy restart path picks them up; the rolling-reload path leaves
        them in place — the in-place hot-swap preserves their windows).

        Never raises; the summary dict reports attempted / migrated /
        failed / skipped with per-session detail.
        """
        out: Dict[str, Any] = {
            "replica_id": replica_id,
            "reason": reason,
            "attempted": 0,
            "migrated": 0,
            "failed": 0,
            "sessions": [],
        }
        with self._lock:
            source = self._replicas.get(replica_id)
            homed = [
                s for s, r in self._sessions.items() if r == replica_id
            ]
        if source is None or source.url is None:
            out["skipped"] = "source unknown or urlless"
            return out
        if session_ids is not None:
            homed_set = set(homed)
            homed = [s for s in session_ids if s in homed_set]
        if not homed:
            out["skipped"] = "no sessions to migrate"
            return out
        source_surface = self._compat_surface(source.url)
        for sid in homed:
            target = self._pick_migration_target(
                replica_id, source_surface
            )
            if target is None:
                entry = {
                    "session_id": sid,
                    "ok": False,
                    "error": "no compatible ready survivor",
                }
                out["failed"] += 1
            else:
                out["attempted"] += 1
                result = migrate.migrate_session(
                    source.url,
                    target.url,
                    sid,
                    timeout_s=self.replica_timeout_s,
                )
                entry = {**result, "target_id": target.id}
                if result.get("ok"):
                    with self._lock:
                        # Atomic remap: the next act routes straight to
                        # the importer (no orphan window in between).
                        self._sessions[sid] = target.id
                        self._sessions.move_to_end(sid)
                        self._orphaned.pop(sid, None)
                        self._mark_migrated_locked(sid)
                    out["migrated"] += 1
                    # Free the source's now-stale copy (best-effort: a
                    # draining/dying source may not answer, and that's
                    # fine — it's about to take the slot with it). The
                    # slot must not leak on a live source (rebalance),
                    # and a later failover back must not find a stale
                    # window to silently continue. keep_snapshot: the
                    # shared ring file now backs the TARGET's session —
                    # the usual release-drops-snapshot rule would strand
                    # the importer's crash durability until its next act.
                    status, _body = post_json(
                        source.url.rstrip("/") + "/release",
                        {"session_id": sid, "keep_snapshot": True},
                        self.replica_timeout_s,
                    )
                    entry["source_released"] = status == 200
                else:
                    out["failed"] += 1
            if not entry.get("ok") and orphan_on_failure:
                self._orphan_session(sid, replica_id)
                entry["orphaned"] = True
            out["sessions"].append(entry)
        return out

    def _pick_migration_target(
        self,
        source_id: int,
        source_surface: Optional[Tuple[Any, Any, Any]],
    ) -> Optional[Replica]:
        """Least-loaded READY survivor whose compatibility surface
        matches the source's (tier-aware on ties, same rule as
        placement). Recomputed per session: each successful migration
        shifts the load it balances against."""
        with self._lock:
            candidates = [
                r
                for r in self._replicas.values()
                if r.id != source_id
                and r.state == READY
                and r.url is not None
            ]
            loads: Dict[int, int] = {}
            for rid in self._sessions.values():
                loads[rid] = loads.get(rid, 0) + 1
        if source_surface is not None:
            candidates = [
                r
                for r in candidates
                if self._compat_surface(r.url) == source_surface
            ]
        if not candidates:
            return None
        return min(
            candidates,
            key=lambda r: (
                loads.get(r.id, 0),
                _TIER_RANK.get(r.tier, 0),
                r.id,
            ),
        )

    def hottest_sessions(self, replica_id: int, count: int) -> List[str]:
        """The `count` most recently acting sessions homed on
        `replica_id` — the /rebalance victim pick (recency from the
        occupancy signal; a session that never acted can't be hot)."""
        with self._lock:
            homed = {
                s for s, r in self._sessions.items() if r == replica_id
            }
            out: List[str] = []
            for sid in reversed(self._act_times):
                if sid in homed:
                    out.append(sid)
                    if len(out) >= count:
                        break
            return out

    def rebalance(
        self, replica_id: int, count: int = 1
    ) -> Tuple[int, Dict[str, Any]]:
        """POST /rebalance: migrate the `count` hottest sessions off an
        overloaded replica through the same export/import path the drain
        uses. Failed migrations leave sessions where they are (the
        replica is overloaded, not dying — a forced restart would be
        strictly worse than staying hot)."""
        with self._lock:
            known = replica_id in self._replicas
        if not known:
            return 404, {"error": f"unknown replica {replica_id}"}
        victims = self.hottest_sessions(replica_id, count)
        result = self.migrate_sessions_from(
            replica_id, reason="rebalance", session_ids=victims
        )
        return 200, {"ok": result["failed"] == 0, **result}

    # ------------------------------------------------------------- reload

    def rolling_reload(
        self, step: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        """Hot-swap a checkpoint across the fleet one replica at a time.

        Each replica's own `/reload` is already zero-downtime; the rolling
        walk bounds fleet impact: wait for `/readyz` to recover before
        moving on, so at most one replica is in the reloading drain state
        at any moment. A replica that fails to reload is recorded and the
        roll continues — a bad checkpoint rejected by `swap_variables`
        leaves every replica serving the old params.
        """
        results = []
        for replica in sorted(self.replicas(), key=lambda r: r.id):
            if replica.state == DEAD or replica.url is None:
                results.append(
                    {"replica": replica.id, "skipped": replica.state}
                )
                continue
            # Durable sessions: carry this replica's windows to a
            # compatible survivor before it pays the swap, so no session
            # waits out the reload. NOT orphan-on-failure — the in-place
            # hot-swap preserves any session that could not move (late in
            # the roll every survivor is already on the new generation,
            # so the compatibility guard correctly keeps them home).
            migration = self.migrate_sessions_from(
                replica.id, reason="rolling_reload"
            )
            payload = {} if step is None else {"step": step}
            status, body = post_json(
                replica.url + "/reload", payload, self.reload_timeout_s
            )
            entry = {"replica": replica.id, "status": status, **body}
            if migration["attempted"] or migration["failed"]:
                entry["sessions_migrated"] = migration["migrated"]
                entry["migration_failed"] = migration["failed"]
            if status == 0:
                self.mark_dead(replica, reason=body.get("error", ""))
            elif status == 200:
                # A swap that lands but never returns to ready degraded
                # the fleet — surface it, don't report a clean roll.
                entry["recovered"] = self._await_ready(replica)
                if not entry["recovered"]:
                    entry["ok"] = False
            results.append(entry)
        if any(r.get("status") == 200 for r in results):
            self.metrics.observe_reload()  # one counted roll, however driven
        return results

    def _await_ready(self, replica: Replica, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status, _ = get_json(replica.url + "/readyz", timeout=5.0)
            if status == 200:
                self.set_state(replica.id, READY)
                return True
            time.sleep(0.05)
        return False

    # -------------------------------------------------------------- status

    def ready_count(self) -> int:
        with self._lock:
            return sum(
                1 for r in self._replicas.values() if r.state == READY
            )

    def _gauges(self) -> Dict[str, Any]:
        with self._lock:
            states: Dict[str, int] = {}
            for replica in self._replicas.values():
                states[replica.state] = states.get(replica.state, 0) + 1
            out = {
                "replicas_total": len(self._replicas),
                "replicas_ready": states.get(READY, 0),
                "replicas_dead": states.get(DEAD, 0),
                "sessions_total": len(self._sessions),
                "sessions_orphaned": len(self._orphaned),
                "replica_restarts_total": sum(
                    r.restarts for r in self._replicas.values()
                ),
                "draining": int(self.draining),
                "ready": int(states.get(READY, 0) > 0),
                "router_inflight": self._inflight,
                # Canary split state (-1 = no canary): dashboards correlate
                # a replica's burn series with the window it was canary.
                "canary_replica_id": (
                    -1 if self._canary_id is None else self._canary_id
                ),
                "canary_weight": self._canary_weight,
            }
        if self.admission is not None:
            out.update(self.admission.gauges())
        return out

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Router-own counters + fleet gauges + the SLO ledger's
        ``slo_*`` gauges (exposed as ``rt1_serve_slo_*`` in text)."""
        return self.metrics.snapshot(**self._gauges(), **self.slo.gauges())

    def metrics_prometheus(self) -> str:
        return self.metrics.prometheus_text(
            **self._gauges(), **self.slo.gauges()
        )

    # -------------------------------------------------- fleet aggregation

    def _fan_out_get(self, path: str) -> Dict[int, Optional[Dict[str, Any]]]:
        """Probe `path` on every live replica CONCURRENTLY (one thread
        each): the scrape path must cost ~one probe timeout total, not
        replicas x timeout — a hung replica during an incident is exactly
        when the aggregated view matters most. {replica_id: body | None};
        None (dead, booting, probe failed) is preserved: the aggregated
        view reports absence (``replica_up 0``) instead of silently
        narrowing the fleet."""
        replicas = sorted(self.replicas(), key=lambda r: r.id)
        out: Dict[int, Optional[Dict[str, Any]]] = {
            r.id: None for r in replicas
        }

        def probe(replica: Replica) -> None:
            status, body = get_json(
                replica.url + path, timeout=self.metrics_probe_timeout_s
            )
            if status == 200 and isinstance(body, dict):
                out[replica.id] = body  # distinct key per thread: no lock

        threads = [
            threading.Thread(target=probe, args=(r,), daemon=True)
            for r in replicas
            if r.url is not None and r.state != DEAD
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self.metrics_probe_timeout_s + 1.0
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))
        return out

    def probe_replica_metrics(self) -> Dict[int, Optional[Dict[str, Any]]]:
        """Fan out to every registered replica's `/metrics` (JSON)."""
        return self._fan_out_get("/metrics")

    def fleet_metrics_snapshot(self) -> Dict[str, Any]:
        """The aggregated JSON view: the router's own snapshot (incl. SLO
        gauges) plus every replica's full snapshot under ``replicas``."""
        replicas = self.probe_replica_metrics()
        out = {
            **self.metrics_snapshot(),
            "replicas": {str(rid): snap for rid, snap in replicas.items()},
            "replica_slo": {
                str(rid): entry
                for rid, entry in self.replica_slo_snapshot().items()
            },
        }
        if self.deploy_gauges_fn is not None:
            out["deploy"] = self.deploy_gauges_fn()
        return out

    def fleet_metrics_prometheus(self) -> str:
        """One exposition body for the whole fleet: router families at
        their usual names + ``rt1_serve_replica_*{replica_id="N"}`` —
        plus the ``rt1_deploy_*`` families when a promotion controller
        is attached (one scrape target tells the whole rollout story)."""
        text = obs_prometheus.render_fleet_snapshot(
            self.metrics_snapshot(),
            self.probe_replica_metrics(),
            replica_slo=self.replica_slo_snapshot(),
        )
        if self.deploy_gauges_fn is not None:
            text += obs_prometheus.render_deploy_snapshot(
                self.deploy_gauges_fn()
            )
        if self.obs_metrics_text_fn is not None:
            # rt1_alert_* + rt1_obs_collector_* families when the metrics
            # plane is armed: the ops scrape carries its own health.
            text += self.obs_metrics_text_fn()
        return text

    def fleet_slow_requests(self) -> Dict[str, Any]:
        """Fan out `/slow_requests`: every live replica's exemplar ring,
        keyed by replica id (None for a replica that could not answer)."""
        probed = self._fan_out_get("/slow_requests")
        return {"replicas": {str(rid): body for rid, body in probed.items()}}

    def fleet_status(self, probe_metrics: bool = True) -> Dict[str, Any]:
        """Per-replica table for /fleet/status; with `probe_metrics`, each
        live replica's own /metrics is sampled for the single-compile and
        reload evidence the chaos bench asserts on."""
        replicas = []
        replica_slo = self.replica_slo_snapshot()
        for replica in sorted(self.replicas(), key=lambda r: r.id):
            entry = replica.summary()
            entry["sessions"] = self.session_count(replica.id)
            slo = replica_slo.get(replica.id)
            if slo is not None:
                entry["slo"] = slo
            if probe_metrics and replica.url and replica.state != DEAD:
                status, body = get_json(replica.url + "/metrics", timeout=5.0)
                if status == 200:
                    entry["metrics"] = {
                        k: body.get(k)
                        for k in (
                            "compile_count",
                            "bucket_count",
                            "reloads_total",
                            "requests_total",
                            "active_sessions",
                            "uptime_s",
                            "inference_dtype",
                            "param_bytes_device",
                        )
                    }
            replicas.append(entry)
        return {"replicas": replicas, **self._gauges()}

    def healthz(self) -> Dict[str, Any]:
        """Router liveness + the serving contract proxied from a ready
        replica (clients read image_shape from here, same as single-node)."""
        out: Dict[str, Any] = {
            "status": "draining" if self.draining else "ok",
            "role": "router",
            **self._gauges(),
        }
        for replica in self.replicas():
            if replica.state == READY and replica.url:
                status, body = get_json(
                    replica.url + "/healthz", timeout=5.0
                )
                if status == 200:
                    for key in ("image_shape", "embed_dim", "max_sessions"):
                        if key in body:
                            out[key] = body[key]
                    break
        return out

    def readyz(self) -> Tuple[int, Dict[str, Any]]:
        if self.draining:
            return 503, {"ready": False, "reason": "draining"}
        ready = self.ready_count()
        if ready == 0:
            return 503, {"ready": False, "reason": "no ready replicas"}
        return 200, {"ready": True, "replicas_ready": ready}


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    router: Router = None  # bound by make_router_server
    quiet: bool = True

    def log_message(self, fmt, *args):  # noqa: D102 - stdlib hook
        if not self.quiet:
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib casing
        if self.path.startswith("/history"):
            # /history[?family=...&window_s=...] — TSDB read-out (armed
            # fleets only; the query string selects one series window).
            if self.router.history_fn is None:
                self._reply(404, {"error": "no metrics collector armed"})
                return
            from urllib.parse import parse_qs, urlparse

            query = parse_qs(urlparse(self.path).query)
            params = {k: v[-1] for k, v in query.items()}
            try:
                self._reply(200, self.router.history_fn(params))
            except (KeyError, ValueError) as exc:
                self._reply(400, {"error": str(exc)})
            return
        if self.path == "/healthz":
            self._reply(200, self.router.healthz())
        elif self.path == "/readyz":
            code, payload = self.router.readyz()
            self._reply(code, payload)
        elif self.path == "/fleet/status":
            self._reply(200, self.router.fleet_status())
        elif self.path == "/fleet/slow_requests":
            self._reply(200, self.router.fleet_slow_requests())
        elif self.path == "/slo":
            self._reply(200, self.router.slo.summary())
        elif self.path == "/deploy/status":
            if self.router.deploy_status_fn is None:
                self._reply(404, {"error": "no promotion controller armed"})
            else:
                self._reply(200, self.router.deploy_status_fn())
        elif self.path == "/alerts":
            if self.router.alerts_status_fn is None:
                self._reply(404, {"error": "no metrics collector armed"})
            else:
                self._reply(200, self.router.alerts_status_fn())
        elif self.path == "/dashboard":
            if self.router.dashboard_html_fn is None:
                self._reply(404, {"error": "no metrics collector armed"})
            else:
                self._reply_text(
                    200,
                    self.router.dashboard_html_fn(),
                    "text/html; charset=utf-8",
                )
        elif self.path == "/metrics":
            # ONE scrape target for the whole fleet: the router's own
            # families plus every replica's curated fields, fanned out on
            # each scrape (same content negotiation as a lone replica).
            if obs_prometheus.accepts_text(self.headers.get("Accept")):
                self._reply_text(
                    200,
                    self.router.fleet_metrics_prometheus(),
                    obs_prometheus.CONTENT_TYPE,
                )
            else:
                self._reply(200, self.router.fleet_metrics_snapshot())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802 - stdlib casing
        length = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(length)) if length else {}
        except json.JSONDecodeError as exc:
            self._reply(400, {"error": f"invalid JSON body: {exc}"})
            return
        if not isinstance(payload, dict):
            self._reply(400, {"error": "request body must be a JSON object"})
            return
        t0 = time.perf_counter()
        if self.path == "/act":
            status, body = self.router.route_act(payload, self.headers)
            if status in (429, 503):
                # Shed load (admission 429, no-ready-replicas / failover
                # 503) is the rejected counter, not errors_total — same
                # split the single-replica server makes for its busy 503s.
                self.router.metrics.observe_rejected()
            else:
                self.router.metrics.observe_request(
                    time.perf_counter() - t0, ok=status == 200
                )
            self._reply(status, body)
        elif self.path in ("/reset", "/release"):
            status, body = self.router.route_session_op(self.path, payload)
            if self.path == "/reset" and status == 200:
                self.router.metrics.observe_reset()
            self._reply(status, body)
        elif self.path == "/reload":
            results = self.router.rolling_reload(payload.get("step"))
            # A clean roll means every replica swapped AND recovered; a
            # skipped (dead/respawning) replica is a partial roll — the
            # fleet may be serving mixed checkpoint versions — and must
            # not be reported as ok.
            failed = [
                r
                for r in results
                if r.get("status") != 200 or r.get("recovered") is False
            ]
            self._reply(
                200 if not failed else 502,
                {"ok": not failed, "replicas": results},
            )
        elif self.path == "/rebalance":
            replica_id = payload.get("replica_id")
            count = payload.get("count", 1)
            if not isinstance(replica_id, int):
                self._reply(400, {"error": "'replica_id' must be an "
                                           "integer"})
                return
            if not isinstance(count, int) or count < 1:
                self._reply(400, {"error": "'count' must be a positive "
                                           "integer"})
                return
            status, body = self.router.rebalance(replica_id, count)
            self._reply(status, body)
        elif self.path == "/scale_down":
            # Elastic-drain entry point: wired to the fleet supervisor's
            # manual scale-down (migrating drain) by fleet main; 404 on a
            # router without a supervisor.
            if self.router.scale_down_fn is None:
                self._reply(404, {"error": "no fleet supervisor armed"})
                return
            try:
                self._reply(200, self.router.scale_down_fn(payload))
            except (KeyError, ValueError) as exc:
                self._reply(400, {"error": str(exc)})
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})


def make_router_server(
    router: Router, host: str = "127.0.0.1", port: int = 0, quiet: bool = True
) -> ThreadingHTTPServer:
    """Bind a ThreadingHTTPServer to `router` (port 0 = ephemeral)."""
    handler = type(
        "BoundRouterHandler", (_RouterHandler,),
        {"router": router, "quiet": quiet},
    )
    httpd = ThreadingHTTPServer((host, port), handler)
    httpd.daemon_threads = True
    return httpd
