"""Durable sessions: snapshot schema, on-disk ring, and live migration.

The engine grew ``export_session``/``import_session`` (PR 17) as the
migration seam; this module is the fleet-wide layer on top of it
(ROADMAP item 3). Three pieces:

* **Wire snapshot** — the versioned, self-describing JSON record a
  replica's ``POST /session/export`` returns and ``POST /session/import``
  accepts. ``check_compatibility`` is the gatekeeper: a snapshot exported
  under a different checkpoint generation, window length, or
  cached-vs-windowed engine mode is refused with a named
  ``SnapshotCompatibilityError`` *before* any device memory is touched —
  the caller then falls back to the legacy orphan+restart path instead of
  corrupting a slot.
* **SnapshotRing** — a bounded on-disk ring of per-session snapshots
  (one JSON file per session, atomic tmp+rename writes, oldest evicted
  past capacity). Replicas sharing one ring directory give the fleet
  crash durability: after a SIGKILL the re-home target finds the dead
  replica's last snapshot and restores the window instead of resetting
  it. Restore is best-effort and staleness-bounded — ``load`` surfaces
  the snapshot age so the importer can refuse stale state.
* **migrate_session** — the one-session live-migration primitive the
  router and the fleet's scale-down drain share: export from the victim,
  import into the survivor, never raise. Both legs consult the chaos
  registry (``migrate_export`` / ``migrate_import`` sites) so fault
  injection proves a failed migration degrades to orphan+restart, never
  a 5xx.

State arrays travel base64-encoded raw bytes with an explicit
shape/dtype header (``encode_state``/``decode_state``); numpy is imported
lazily inside those two functions only, so the module itself stays
stdlib-light — the import-blocker probe pins it (with the router and the
fleet) clu/TF/jax-free. A jax-free exporter (the stub replica) may ship
plain JSON lists under a ``"data"`` key instead; ``decode_state`` passes
those through untouched.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from rt1_tpu.resilience import faults

#: Bump on any incompatible change to the snapshot wire schema. Importers
#: refuse other versions by name — silent best-effort decoding of a
#: foreign schema is exactly the corruption this layer exists to prevent.
SNAPSHOT_VERSION = 1


class SnapshotCompatibilityError(ValueError):
    """Snapshot refused: exporter and importer disagree on a contract
    field (version, checkpoint generation, window length,
    cached-vs-windowed mode, or state schema)."""


# ---------------------------------------------------------------- encoding


def encode_state(state: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Host-side state pytree -> JSON-safe ``{leaf: {shape, dtype, b64}}``.

    Raw little-endian bytes under base64 — lossless for every dtype the
    engine slots hold (int32 token windows, float32/bfloat16-as-float32
    caches), unlike a float round-trip through JSON text.
    """
    import numpy as np

    encoded = {}
    for name, value in state.items():
        arr = np.asarray(value)
        encoded[name] = {
            "shape": [int(d) for d in arr.shape],
            "dtype": str(arr.dtype),
            "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
        }
    return encoded


def decode_state(encoded: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Inverse of ``encode_state``. Entries carrying plain ``"data"``
    lists (a jax-free exporter like the stub) pass through untouched."""
    decoded: Dict[str, Any] = {}
    for name, spec in encoded.items():
        if not isinstance(spec, dict):
            raise SnapshotCompatibilityError(
                f"state leaf {name!r} is not an encoded-array object"
            )
        if "data" in spec:
            decoded[name] = spec["data"]
            continue
        import numpy as np

        try:
            raw = base64.b64decode(spec["b64"])
            arr = np.frombuffer(raw, dtype=np.dtype(spec["dtype"]))
            decoded[name] = arr.reshape(
                [int(d) for d in spec["shape"]]
            ).copy()  # frombuffer views are read-only; importers write
        except (KeyError, ValueError, TypeError) as exc:
            raise SnapshotCompatibilityError(
                f"state leaf {name!r} failed to decode: {exc}"
            ) from exc
    return decoded


def _norm_schema(schema) -> List[List[Any]]:
    """Schema triples -> canonical JSON shape ``[[name, [dims], dtype]]``
    so in-memory tuples compare equal to their JSON round-trip."""
    return [
        [str(name), [int(d) for d in shape], str(dtype)]
        for name, shape, dtype in schema
    ]


def check_compatibility(
    snapshot: Dict[str, Any],
    *,
    checkpoint_generation: Optional[int] = None,
    window: Optional[int] = None,
    cached_inference: Optional[bool] = None,
    schema: Optional[List] = None,
) -> None:
    """Refuse a snapshot this importer must not load, naming the field.

    Every keyword left ``None`` is skipped (the importer does not care);
    every keyword given is compared against the snapshot's self-described
    value. Raises :class:`SnapshotCompatibilityError` on the first
    mismatch, returns ``None`` when the snapshot is loadable.
    """
    if not isinstance(snapshot, dict):
        raise SnapshotCompatibilityError(
            f"snapshot must be a JSON object, got {type(snapshot).__name__}"
        )
    version = snapshot.get("version")
    if version != SNAPSHOT_VERSION:
        raise SnapshotCompatibilityError(
            f"snapshot version {version!r} is not the supported version "
            f"{SNAPSHOT_VERSION} — refusing a foreign schema"
        )
    sid = snapshot.get("session_id")
    if not isinstance(sid, str) or not sid:
        raise SnapshotCompatibilityError(
            "snapshot carries no 'session_id'"
        )
    if not isinstance(snapshot.get("state"), dict):
        raise SnapshotCompatibilityError(
            "snapshot carries no 'state' pytree"
        )
    for field, expected in (
        ("checkpoint_generation", checkpoint_generation),
        ("window", window),
        ("cached_inference", cached_inference),
    ):
        if expected is None:
            continue
        got = snapshot.get(field)
        if got != expected:
            raise SnapshotCompatibilityError(
                f"snapshot {field}={got!r} does not match this importer's "
                f"{field}={expected!r} — refusing a cross-"
                + (
                    "generation"
                    if field == "checkpoint_generation"
                    else "mode" if field == "cached_inference" else "window"
                )
                + " session snapshot"
            )
    if schema is not None:
        got_schema = snapshot.get("schema")
        try:
            normalized = _norm_schema(got_schema)
        except (TypeError, ValueError) as exc:
            raise SnapshotCompatibilityError(
                f"snapshot schema is malformed: {exc}"
            ) from exc
        if normalized != _norm_schema(schema):
            raise SnapshotCompatibilityError(
                "snapshot state schema does not match this engine's "
                f"schema — snapshot {normalized} vs engine "
                f"{_norm_schema(schema)}"
            )


# ----------------------------------------------------------- durability


class SnapshotRing:
    """Bounded on-disk session-snapshot ring (one JSON file per session).

    Writes are atomic (tmp + ``os.replace``) so a SIGKILL mid-write never
    leaves a torn record; past ``capacity`` live files the oldest (by
    mtime) are evicted. A whole fleet may share one directory — filenames
    hash the session id, so two replicas snapshotting the same re-homed
    session converge on one file and the re-home target finds the dead
    replica's last write.
    """

    def __init__(self, directory: str, capacity: int = 64):
        self.directory = directory
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self.saves = 0
        self.evictions = 0
        os.makedirs(directory, exist_ok=True)

    def _path(self, session_id: str) -> str:
        digest = hashlib.sha1(
            session_id.encode("utf-8", "surrogatepass")
        ).hexdigest()[:20]
        return os.path.join(self.directory, f"session-{digest}.json")

    def save(self, snapshot: Dict[str, Any]) -> str:
        """Persist one snapshot (stamping ``saved_at`` if absent);
        returns the file path. Raises ``OSError`` on write failure —
        callers treat durability as best-effort and count, not crash."""
        sid = snapshot.get("session_id")
        if not isinstance(sid, str) or not sid:
            raise ValueError("snapshot carries no 'session_id'")
        record = dict(snapshot)
        record.setdefault("saved_at", time.time())
        path = self._path(sid)
        tmp = f"{path}.tmp.{os.getpid()}"
        with self._lock:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(record, fh)
            os.replace(tmp, path)
            self.saves += 1
            self._evict_locked(keep=path)
        return path

    def _evict_locked(self, keep: Optional[str] = None) -> None:
        try:
            files = [
                os.path.join(self.directory, name)
                for name in os.listdir(self.directory)
                if name.endswith(".json")
            ]
        except OSError:
            return
        if len(files) <= self.capacity:
            return
        files.sort(key=lambda p: (p == keep, _mtime(p)))
        for path in files[: len(files) - self.capacity]:
            try:
                os.remove(path)
                self.evictions += 1
            except OSError:
                pass

    def load(
        self, session_id: str
    ) -> Optional[Tuple[Dict[str, Any], Optional[float]]]:
        """``(snapshot, age_s)`` for a session, or ``None`` when the ring
        holds nothing usable. ``age_s`` is seconds since ``saved_at``
        (``None`` when the record carries no timestamp) — the staleness
        bound the importer enforces and surfaces."""
        try:
            with open(self._path(session_id), encoding="utf-8") as fh:
                record = json.load(fh)
        except (OSError, json.JSONDecodeError, ValueError):
            return None
        if not isinstance(record, dict):
            return None
        saved_at = record.get("saved_at")
        age_s = (
            max(0.0, time.time() - float(saved_at))
            if isinstance(saved_at, (int, float))
            else None
        )
        return record, age_s

    def drop(self, session_id: str) -> None:
        """Forget a session's snapshot (release path) — best-effort."""
        try:
            os.remove(self._path(session_id))
        except OSError:
            pass

    def __len__(self) -> int:
        try:
            return sum(
                1
                for name in os.listdir(self.directory)
                if name.endswith(".json")
            )
        except OSError:
            return 0


def _mtime(path: str) -> float:
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


# ------------------------------------------------------------- migration


def _post_json(
    url: str, payload: Dict[str, Any], timeout_s: float
) -> Tuple[int, Dict[str, Any]]:
    data = json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        url,
        data=data,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 - non-JSON error body
            body = {"error": f"HTTP {exc.code}"}
        return exc.code, body


def migrate_session(
    source_url: str,
    target_url: str,
    session_id: str,
    timeout_s: float = 10.0,
) -> Dict[str, Any]:
    """Live-migrate ONE session: export from ``source_url``, import into
    ``target_url``. Never raises — the result dict carries ``ok`` and,
    on failure, which ``stage`` broke (``export`` / ``import`` /
    ``transport``) plus the error string, so callers (scale-down drain,
    rolling reload, rebalance) log it and fall back to orphan+restart.

    Chaos sites: ``migrate_export`` fires before the export leg,
    ``migrate_import`` before the import leg — both degrade to the
    legacy restart path by construction.
    """
    try:
        faults.maybe_fail("migrate_export", what=session_id)
        status, body = _post_json(
            source_url.rstrip("/") + "/session/export",
            {"session_id": session_id},
            timeout_s,
        )
        if status != 200 or not body.get("ok"):
            return {
                "ok": False,
                "session_id": session_id,
                "stage": "export",
                "error": str(body.get("error") or f"HTTP {status}"),
            }
        snapshot = body.get("snapshot")
        faults.maybe_fail("migrate_import", what=session_id)
        status, body = _post_json(
            target_url.rstrip("/") + "/session/import",
            {"snapshot": snapshot},
            timeout_s,
        )
        if status != 200 or not body.get("ok"):
            return {
                "ok": False,
                "session_id": session_id,
                "stage": "import",
                "error": str(body.get("error") or f"HTTP {status}"),
            }
        return {
            "ok": True,
            "session_id": session_id,
            "step_index": body.get("step_index"),
        }
    except Exception as exc:  # noqa: BLE001 - migration must never raise
        return {
            "ok": False,
            "session_id": session_id,
            "stage": "transport",
            "error": str(exc),
        }
