"""Canned-episode parity gates for low-precision and KV-cached engines.

A quantization bug must never ship silently: before a bf16/int8 engine is
trusted, its action-token stream is compared against the f32 engine's on a
canned, deterministic episode set. Action tokens are the right unit — they
are what the robot executes AND what the rolling window stores, so token
agreement bounds the behavioral divergence of the whole closed loop.
Tier-1 enforces the gate on the tiny config
(tests/test_quant.py::test_int8_engine_parity_gate); the serving quant
bench (`scripts/serve_loadgen.py --quant_ab`) reports the same statistics
per dtype over HTTP in `BENCH_serve_quant.json`. `check_cached_parity`
applies the same machinery to KV-cached incremental decode
(`PolicyEngine(cached_inference=True)`): the window-fill regime is gated
at the same threshold (cached decode is exact there), while post-roll
steady-state agreement is reported as a measured statistic.

Episodes are synthetic (seeded uniform frames + one normal instruction
embedding per episode) — the gate measures precision loss, not policy
quality, so any deterministic input stream the two engines both consume is
valid evidence. Each engine steps its own session; only the observation
stream is shared, exactly as two replicas of a mixed-dtype fleet would see
the same traffic.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

# The tier-1-enforced floor for int8-vs-f32 token agreement: below this,
# quantization noise is flipping decoded actions and the engine must not
# serve (build_serve_engine callers and tests share one constant).
PARITY_THRESHOLD = 0.99


def canned_episodes(
    image_shape: Sequence[int],
    embed_dim: int = 512,
    episodes: int = 4,
    steps: int = 8,
    seed: int = 1234,
) -> List[List[Dict[str, np.ndarray]]]:
    """Deterministic synthetic episodes: `episodes` lists of `steps`
    observations, one fixed instruction embedding per episode (matching a
    real session's constant instruction across its rolling window)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(episodes):
        embedding = rng.standard_normal(embed_dim).astype(np.float32)
        out.append(
            [
                {
                    "image": rng.random(tuple(image_shape)).astype(
                        np.float32
                    ),
                    "natural_language_embedding": embedding,
                }
                for _ in range(steps)
            ]
        )
    return out


def action_token_agreement(
    engine_ref: Any,
    engine_test: Any,
    episodes: Sequence[Sequence[Dict[str, np.ndarray]]],
    skip_steps: int = 0,
) -> Dict[str, Any]:
    """Step both engines through the same observation streams and compare
    action tokens elementwise.

    Returns agreement statistics (``agreement`` in [0, 1], plus the max
    absolute de-normalized action delta — the physical-units view of the
    same divergence). Each engine advances its own rolling state from its
    own weights; tokens are compared per step, so a divergence that
    compounds through the window is charged to every later step it
    corrupts, not amortized away.

    ``skip_steps`` excludes each episode's first N steps from the
    statistics while still stepping both engines through them — used by
    the KV-cache gate to measure the post-roll-over steady state in
    isolation from the (exact) window-fill phase.
    """
    total = 0
    agree = 0
    steps = 0
    max_action_diff = 0.0
    for index, episode in enumerate(episodes):
        sid = f"parity-{index}"
        engine_ref.reset(sid)
        engine_test.reset(sid)
        for step_index, obs in enumerate(episode):
            ref = engine_ref.act(sid, dict(obs))
            test = engine_test.act(sid, dict(obs))
            if step_index < skip_steps:
                continue
            ref_tokens = np.asarray(ref["action_tokens"])
            test_tokens = np.asarray(test["action_tokens"])
            total += int(ref_tokens.size)
            agree += int((ref_tokens == test_tokens).sum())
            max_action_diff = max(
                max_action_diff,
                float(
                    np.max(np.abs(ref["action"] - test["action"]))
                ),
            )
            steps += 1
        engine_ref.release(sid)
        engine_test.release(sid)
    return {
        "episodes": len(episodes),
        "steps": steps,
        "tokens_total": total,
        "tokens_agree": agree,
        "agreement": (agree / total) if total else 1.0,
        "max_abs_action_diff": max_action_diff,
    }


def check_parity(
    engine_ref: Any,
    engine_test: Any,
    image_shape: Sequence[int],
    threshold: float = PARITY_THRESHOLD,
    **episode_kwargs: Any,
) -> Dict[str, Any]:
    """Run the gate; raise ValueError (with the stats in the message) when
    agreement lands below `threshold`. Returns the stats dict on pass."""
    stats = action_token_agreement(
        engine_ref, engine_test, canned_episodes(image_shape, **episode_kwargs)
    )
    stats["threshold"] = threshold
    stats["passed"] = stats["agreement"] >= threshold
    if not stats["passed"]:
        raise ValueError(
            f"low-precision parity gate FAILED: action-token agreement "
            f"{stats['agreement']:.4f} < {threshold} over "
            f"{stats['tokens_total']} tokens "
            f"(max action delta {stats['max_abs_action_diff']:.5f}) — "
            "refusing to trust this engine"
        )
    return stats


def check_cached_parity(
    engine_ref: Any,
    engine_cached: Any,
    image_shape: Sequence[int],
    threshold: float = PARITY_THRESHOLD,
    steady_steps: int = 5,
    **episode_kwargs: Any,
) -> Dict[str, Any]:
    """Gate a KV-cached engine against the windowed reference engine.

    The incremental-decode contract has two regimes and the gate measures
    both:

    * **Fill** (the enforced gate): while a session's window fills — and
      after any cache rebuild — cached decode attends the same keys at
      the same positions as the full-window pass, so tokens must agree
      at >= `threshold` (they are bit-exact in practice; causal attention
      means earlier tokens never depend on later ones). Below threshold
      the cache plumbing is wrong and this raises ValueError.
    * **Steady state** (the reported statistic): after roll-over, cache
      entries keep their insertion-time learned position embeddings and
      pre-roll context, so agreement with the windowed engine is
      approximate (staleness structurally bounded at window-1 rolls —
      entries leave the window after `time_sequence_length` rolls).
      Reported as ``steady_agreement`` for deployment A/Bs, not gated:
      it measures an accepted accuracy/latency trade, not a bug.

    Episodes for the fill gate are cut at the window length so no roll
    occurs; the steady-state measurement then runs `window + steady_steps`
    steps and skips the fill prefix.
    """
    window = int(engine_cached.model.time_sequence_length)
    fill_kwargs = dict(episode_kwargs)
    fill_kwargs["steps"] = window
    stats = action_token_agreement(
        engine_ref,
        engine_cached,
        canned_episodes(image_shape, **fill_kwargs),
    )
    stats["threshold"] = threshold
    stats["passed"] = stats["agreement"] >= threshold
    steady_kwargs = dict(episode_kwargs)
    steady_kwargs["steps"] = window + steady_steps
    steady = action_token_agreement(
        engine_ref,
        engine_cached,
        canned_episodes(image_shape, **steady_kwargs),
        skip_steps=window,
    )
    stats["steady_agreement"] = steady["agreement"]
    stats["steady_steps"] = steady["steps"]
    stats["steady_max_abs_action_diff"] = steady["max_abs_action_diff"]
    if not stats["passed"]:
        raise ValueError(
            f"cached-inference parity gate FAILED: fill-phase action-token "
            f"agreement {stats['agreement']:.4f} < {threshold} over "
            f"{stats['tokens_total']} tokens — cached decode must be exact "
            "while the window fills; refusing to trust this engine"
        )
    return stats
