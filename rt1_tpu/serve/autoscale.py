"""Elastic-fleet autoscaling policy: signals in, scale decisions out.

The FleetSupervisor (`serve/fleet.py`) runs a fixed N replicas; production
traffic is diurnal and bursty, so a fixed fleet either wastes
replica-seconds at trough or blows p99 at peak. This module is the control
brain the supervisor consults once per autoscale tick: a pure, clock-free
decision function over router-observed signals —

* **occupancy** — sessions active in the recent window over the ready
  fleet's session slots (the router tracks last-act times; a session that
  stopped talking stops counting, unlike the raw affinity-map size);
* **queue pressure** — requests currently in flight through the router
  per slot (the router-side analogue of replica queue depth);
* **shed pressure** — admission-control rejections since the last tick
  (a router that is 429ing is a router that wants more capacity);
* **SLO burn** — the ledger's TIME-windowed error-budget burn
  (`SLOLedger.windowed_burn`, `rt1_tpu/obs/slo.py`): availability
  degradation is a scale-up signal even before occupancy saturates. The
  window is `burn_window_s` of wall clock, not a request count, so a
  post-incident quiet period decays the signal by itself — the old
  request-indexed rolling burn froze at its peak with no follow-on
  traffic, which is why pressure used to be activity-gated.

Decisions are hysteretic and asymmetric by design: scale **up fast**
(`up_sustain_ticks` consecutive pressure ticks, short cooldown — a spike
costs p99 every second it is under-served) and **down slow**
(`down_sustain_ticks` consecutive idle ticks — reclaiming capacity
during a lull that turns out to be a breather between bursts is how
autoscalers oscillate). One boot at a time: while a spawned replica is
still warming (STARTING), neither direction acts, so a slow AOT compile
cannot cause a thundering herd of boots. The gate is deliberately keyed
on booting replicas only — a lingering NOTREADY replica (alive HTTP,
/readyz 503 forever) must not wedge the autoscaler, so decisions, both
directions, proceed around it.

The actual spawn/drain/reap mechanics stay in `serve/fleet.py`; this
module is deliberately mechanism-free (stdlib only, no HTTP, no
subprocess) so the decision logic is unit-testable with fabricated
signals and stays importable in the clu/TF-free router process
(`tests/test_obs_imports.py`).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The elastic-fleet contract: bounds, thresholds, hysteresis.

    ``min_replicas`` is the pinned base tier (never reclaimed — it serves
    as the full-precision parity canary in a dtype-tiered fleet);
    ``max_replicas`` caps surge capacity. Occupancy thresholds are in
    sessions-per-slot (1.0 = every ready slot holds an active session).
    Sustain tick counts implement the fast-up/slow-down asymmetry;
    cooldowns keep consecutive events apart so a boot (or a drain) can
    land before the next decision.
    """

    min_replicas: int = 1
    max_replicas: int = 1
    scale_up_occupancy: float = 0.75
    scale_down_occupancy: float = 0.30
    up_sustain_ticks: int = 2
    down_sustain_ticks: int = 6
    up_cooldown_ticks: int = 2
    down_cooldown_ticks: int = 4
    # Time-windowed error-budget burn at/above this is scale-up pressure
    # even at low occupancy (slow replicas, not just full ones). 0
    # disables.
    burn_pressure: float = 2.0
    # Wall-clock window (seconds) the burn signal is computed over — the
    # supervisor passes this to `SLOLedger.windowed_burn` each tick.
    burn_window_s: float = 60.0
    # Window (seconds) a session counts as active after its last act —
    # consumed by the router's occupancy signal, carried here so the
    # whole policy travels as one object.
    active_window_s: float = 5.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})"
            )
        if self.scale_down_occupancy >= self.scale_up_occupancy:
            raise ValueError(
                "scale_down_occupancy must be strictly below "
                f"scale_up_occupancy, got {self.scale_down_occupancy} >= "
                f"{self.scale_up_occupancy} (no hysteresis band)"
            )
        if self.up_sustain_ticks < 1 or self.down_sustain_ticks < 1:
            raise ValueError("sustain tick counts must be >= 1")


@dataclasses.dataclass(frozen=True)
class FleetSignals:
    """One tick's router-observed state (all counts, no clocks)."""

    replicas_total: int  # live replicas incl. still-warming boots
    replicas_ready: int  # replicas currently routable
    active_sessions: int  # sessions that acted inside the active window
    session_slots: int  # replicas_ready * per-replica max_sessions
    inflight: int = 0  # requests mid-route through the router right now
    shed_delta: int = 0  # OVERLOAD admission sheds since the previous tick
    # SLO error-budget burn over the policy's `burn_window_s` of wall
    # clock (`SLOLedger.windowed_burn`) — decays on its own when traffic
    # stops, unlike the request-indexed rolling gauge.
    rolling_burn: float = 0.0
    # Replicas spawned but never yet ready (state STARTING) — the
    # one-boot-at-a-time gate keys on THIS, not on total != ready: a
    # replica that is alive but persistently 503 (wedged warmup, failed
    # reload) is NOTREADY, and gating on it would disable autoscaling —
    # including scale-up under overload — for as long as it lingers.
    replicas_booting: int = 0

    @property
    def occupancy(self) -> float:
        """Active sessions per ready slot; saturated (inf) when traffic
        exists but no slot does — maximal pressure, not a crash."""
        if self.session_slots > 0:
            return self.active_sessions / self.session_slots
        return float("inf") if self.active_sessions > 0 else 0.0

    @property
    def inflight_per_slot(self) -> float:
        if self.session_slots > 0:
            return self.inflight / self.session_slots
        return float("inf") if self.inflight > 0 else 0.0


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    direction: str  # "up" | "down"
    reason: str  # human-readable, recorded in the scale-event log


class Autoscaler:
    """Hysteretic decision state over a stream of `FleetSignals`.

    ``decide(signals)`` once per tick; returns a `ScaleDecision` or None.
    The caller owns the mechanism (spawn / drain+reap) and the tick clock.
    """

    def __init__(self, policy: AutoscalePolicy):
        self.policy = policy
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0

    # ------------------------------------------------------------- signals

    def _pressure_reason(self, s: FleetSignals) -> Optional[str]:
        p = self.policy
        if s.occupancy >= p.scale_up_occupancy:
            return (
                f"occupancy {s.occupancy:.2f} >= {p.scale_up_occupancy:.2f}"
            )
        if s.inflight_per_slot >= p.scale_up_occupancy:
            return (
                f"inflight/slot {s.inflight_per_slot:.2f} >= "
                f"{p.scale_up_occupancy:.2f}"
            )
        if s.shed_delta > 0:
            return f"admission shed {s.shed_delta} request(s) last tick"
        if p.burn_pressure > 0 and s.rolling_burn >= p.burn_pressure:
            # No activity gate: the burn signal is time-windowed
            # (`SLOLedger.windowed_burn`), so a shed/restart burst with no
            # follow-on traffic ages out of the window by itself — the
            # frozen-at-peak pathology the old request-indexed gauge had
            # (and the `active_sessions > 0` guard existed to patch) is
            # gone at the source.
            return (
                f"windowed SLO burn {s.rolling_burn:.2f} >= "
                f"{p.burn_pressure:.2f}"
            )
        return None

    def _is_idle(self, s: FleetSignals) -> bool:
        # Deliberately NOT gated on rolling burn: burn is a trailing
        # window over past requests, and a spike's shed residue would
        # otherwise pin the fleet at peak long after traffic left.
        return (
            s.occupancy <= self.policy.scale_down_occupancy
            and s.shed_delta == 0
            and s.inflight_per_slot <= self.policy.scale_down_occupancy
        )

    # ------------------------------------------------------------ decision

    def decide(self, signals: FleetSignals) -> Optional[ScaleDecision]:
        """One tick: update streaks, emit at most one decision."""
        p = self.policy
        pressure = self._pressure_reason(signals)
        idle = self._is_idle(signals)
        if pressure is not None:
            self._up_streak += 1
            self._down_streak = 0
        elif idle:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # The hysteresis band between the thresholds: hold, and make
            # both sides re-earn their sustain window.
            self._up_streak = 0
            self._down_streak = 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        # One boot in flight at a time: while a spawned replica is still
        # warming, neither direction acts — pressure cannot stack spawns
        # faster than they become routable, and a lull cannot reclaim a
        # replica that never served. Keyed on STARTING boots only (not
        # total != ready), so a lingering NOTREADY replica — alive HTTP,
        # /readyz 503 forever — cannot wedge the autoscaler.
        if signals.replicas_booting > 0:
            return None
        if (
            pressure is not None
            and self._up_streak >= p.up_sustain_ticks
            and signals.replicas_total < p.max_replicas
        ):
            self._up_streak = 0
            self._cooldown = p.up_cooldown_ticks
            return ScaleDecision("up", pressure)
        if (
            idle
            and self._down_streak >= p.down_sustain_ticks
            and signals.replicas_total > p.min_replicas
        ):
            self._down_streak = 0
            self._cooldown = p.down_cooldown_ticks
            return ScaleDecision(
                "down",
                f"occupancy {signals.occupancy:.2f} <= "
                f"{p.scale_down_occupancy:.2f} for "
                f"{p.down_sustain_ticks} ticks",
            )
        return None
