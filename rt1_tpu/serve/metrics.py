"""Serving-side metrics: latency histograms, batch occupancy, throughput.

Follows the `rt1_tpu/trainer/metrics.py` conventions — plain-Python
accumulators on the host, scalars published through the same clu
`metric_writers` interface (`create_writer` / `write_scalars`) when a
metrics workdir is configured, and a JSON `snapshot()` for the HTTP
`/metrics` endpoint and `scripts/serve_loadgen.py`.

Counters are lock-guarded: requests land from many HTTP handler threads
while batches complete on the batcher's executor thread.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, Optional

from rt1_tpu.obs.quantiles import bucket_quantile

#: Task label for served requests whose client declared no `task` tag —
#: keeps the per-task request counters summing to the served total.
TASK_UNLABELED = "unlabeled"

# Geometric-ish bucket upper bounds in seconds, 0.1 ms .. 30 s. Wide enough
# for a tiny-CPU smoke model (sub-ms) and a cold remote-TPU dispatch alike.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class LatencyHistogram:
    """Fixed-bucket histogram with conservative (upper-bound) quantiles."""

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1: overflow bucket
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, seconds)] += 1
        self.count += 1
        self.total += seconds
        if seconds > self.max:
            self.max = seconds

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket containing the q-quantile (0 if empty).
        The overflow bucket reports the observed max. Shared estimator:
        `rt1_tpu/obs/quantiles.py` (loadgen and the SLO ledger use the
        exact-sample twin from the same module)."""
        return bucket_quantile(
            self.buckets, self.counts, self.count, self.max, q
        )

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def cumulative_counts(self):
        """Prometheus-style cumulative buckets: ascending (upper_bound,
        cumulative_count) pairs ending with (inf, count). The per-bucket
        `counts` stay as-is; this is the exposition view of them."""
        out = []
        cumulative = 0
        for upper, c in zip(self.buckets, self.counts):
            cumulative += c
            out.append((upper, cumulative))
        out.append((float("inf"), self.count))
        return out


class ServeMetrics:
    """Aggregates the serving process's request/batch/session counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._started = time.monotonic()
        self.requests_total = 0
        self.errors_total = 0
        self.rejected_total = 0
        self.resets_total = 0
        self.reloads_total = 0            # checkpoint hot-swaps served
        self.sessions_restarted_total = 0  # sessions re-homed after a
        #                                    replica death (router-side)
        self.sessions_migrated_total = 0   # sessions whose window was
        #                                    live-migrated intact (drain,
        #                                    rolling reload, rebalance,
        #                                    snapshot restore; router-side)
        self.batches_total = 0
        self.occupancy_sum = 0
        self.occupancy_max = 0
        self.queue_depth = 0
        # Continuous-batching / double-buffer path (ISSUE 12): batches
        # currently dispatched-but-uncollected, requests that joined a
        # batch formed while another was already in flight, and the
        # per-bucket occupancy histogram (bucket size -> batch count +
        # summed occupancy, so mean fill per bucket is derivable).
        self.batches_in_flight = 0
        self.max_batches_in_flight = 0
        self.joined_mid_cycle_total = 0
        self.bucket_batches: Dict[int, int] = {}
        self.bucket_occupancy_sum: Dict[int, int] = {}
        # Per-task quality-observability labels (ISSUE 13): served /act
        # requests and new sessions bucketed by the client-declared `task`
        # tag (the same tag the flywheel capture stamps into episodes).
        # Requests without one land in TASK_UNLABELED so the per-task
        # counters always sum to the served-request total.
        self.task_requests_total: Dict[str, int] = {}
        self.task_sessions_total: Dict[str, int] = {}
        # Elastic fleet (ISSUE 15): scale events by direction, admission
        # sheds by reason, live replicas per dtype capacity tier, and the
        # current fleet size. Router-level state — replicas never set
        # these, so their snapshots (and every pre-elastic dashboard)
        # are byte-identical. `autoscale_replicas` None = autoscaler off,
        # the key is absent from the snapshot.
        self.autoscale_scale_events: Dict[str, int] = {}
        self.autoscale_shed: Dict[str, int] = {}
        self.autoscale_tier_replicas: Dict[str, int] = {}
        self.autoscale_replicas: Optional[int] = None
        self.latency = LatencyHistogram()      # full request wall time
        self.step_latency = LatencyHistogram()  # batched device step only

    # ------------------------------------------------------------ recording

    def observe_request(self, seconds: float, ok: bool = True) -> None:
        with self._lock:
            self.requests_total += 1
            if not ok:
                self.errors_total += 1
            self.latency.observe(seconds)

    def observe_rejected(self) -> None:
        with self._lock:
            self.rejected_total += 1

    def observe_reset(self) -> None:
        with self._lock:
            self.resets_total += 1

    def observe_reload(self) -> None:
        """One successful zero-downtime checkpoint hot-swap."""
        with self._lock:
            self.reloads_total += 1

    def observe_session_restart(self) -> None:
        """One session re-homed (and reset) after its replica died."""
        with self._lock:
            self.sessions_restarted_total += 1

    def observe_session_migration(self) -> None:
        """One session's window carried intact to another replica (live
        migration or snapshot-ring restore) — continuity, not a reset."""
        with self._lock:
            self.sessions_migrated_total += 1

    def observe_batch(
        self,
        size: int,
        queued: int = 0,
        in_flight: int = 0,
        joined_mid_cycle: int = 0,
    ) -> None:
        with self._lock:
            self.batches_total += 1
            self.occupancy_sum += size
            self.occupancy_max = max(self.occupancy_max, size)
            self.queue_depth = queued
            self.batches_in_flight = in_flight
            self.max_batches_in_flight = max(
                self.max_batches_in_flight, in_flight
            )
            self.joined_mid_cycle_total += joined_mid_cycle

    def observe_inflight(self, in_flight: int) -> None:
        """A batch completed (or launched outside observe_batch): refresh
        the in-flight gauge."""
        with self._lock:
            self.batches_in_flight = in_flight
            self.max_batches_in_flight = max(
                self.max_batches_in_flight, in_flight
            )

    def observe_task_request(
        self, task: Optional[str], new_session: bool = False
    ) -> None:
        """One successfully served /act under workload tag `task` (None ->
        TASK_UNLABELED); `new_session` marks the step that started a fresh
        session window, so `task_sessions_total` counts sessions, not
        steps. Rendered as the labeled `rt1_serve_task_*{task=...}`
        families and aggregated fleet-wide as
        `rt1_serve_replica_task_*{replica_id=,task=}`."""
        key = task if isinstance(task, str) and task else TASK_UNLABELED
        with self._lock:
            self.task_requests_total[key] = (
                self.task_requests_total.get(key, 0) + 1
            )
            if new_session:
                self.task_sessions_total[key] = (
                    self.task_sessions_total.get(key, 0) + 1
                )

    def observe_scale_event(self, direction: str) -> None:
        """One fleet scale event ('up' | 'down'), rendered as the labeled
        `rt1_serve_autoscale_scale_events_total{direction=}` family."""
        with self._lock:
            self.autoscale_scale_events[direction] = (
                self.autoscale_scale_events.get(direction, 0) + 1
            )

    def observe_shed(self, reason: str) -> None:
        """One request shed by router admission control ('client_rate' |
        'overload'), rendered as `rt1_serve_autoscale_shed_total{reason=}`.
        Counted in addition to `rejected_total` (the outcome class): the
        reason label tells WHY load was dropped, the class tells the SLO
        ledger it was."""
        with self._lock:
            self.autoscale_shed[reason] = (
                self.autoscale_shed.get(reason, 0) + 1
            )

    def shed_total(self, reason: Optional[str] = None) -> int:
        """Total admission sheds, optionally for one reason. The
        autoscaler reads `shed_total("overload")` only: per-client
        token-bucket sheds ('client_rate') are a policy verdict on one
        client, not a capacity shortfall — extra replicas cannot fix a
        rate limit, and counting them as pressure would let a single hot
        client pin the fleet at max."""
        with self._lock:
            if reason is not None:
                return self.autoscale_shed.get(reason, 0)
            return sum(self.autoscale_shed.values())

    def set_autoscale_state(
        self,
        replicas: Optional[int] = None,
        tier_replicas: Optional[Dict[str, int]] = None,
    ) -> None:
        """Refresh the autoscaler's fleet-shape gauges (set wholesale each
        tick: `rt1_serve_autoscale_replicas` and the per-dtype
        `rt1_serve_autoscale_tier_replicas{dtype=}` family)."""
        with self._lock:
            if replicas is not None:
                self.autoscale_replicas = int(replicas)
            if tier_replicas is not None:
                self.autoscale_tier_replicas = {
                    str(k): int(v) for k, v in tier_replicas.items()
                }

    def observe_bucket(self, bucket: int, occupancy: int) -> None:
        """One batch rode the AOT bucket of size `bucket` carrying
        `occupancy` active requests (the per-bucket occupancy histogram)."""
        with self._lock:
            self.bucket_batches[int(bucket)] = (
                self.bucket_batches.get(int(bucket), 0) + 1
            )
            self.bucket_occupancy_sum[int(bucket)] = (
                self.bucket_occupancy_sum.get(int(bucket), 0)
                + int(occupancy)
            )

    def observe_step(self, seconds: float) -> None:
        with self._lock:
            self.step_latency.observe(seconds)

    # ------------------------------------------------------------ reporting

    # Snapshot keys allowed to carry a string instead of a number — the
    # engine's dtype mode rides the snapshot verbatim so the Prometheus
    # renderer can emit it as an info-style labeled family
    # (`rt1_serve_inference_dtype{dtype="int8"} 1`). Everything else
    # stays strictly numeric (typo'd gauges must fail loudly, not vanish).
    TEXT_GAUGES = frozenset({"inference_dtype"})
    # Snapshot keys allowed to carry a {label: count} dict — the engine's
    # KV-cache invalidation counters ride the snapshot as a table so the
    # Prometheus renderer can emit one labeled family
    # (`rt1_serve_cache_invalidations_total{reason="swap"}`), matching the
    # internal labeled families (bucket_batches, task_requests_total).
    DICT_GAUGES = frozenset({"cache_invalidations"})

    @classmethod
    def _coerce_gauge(cls, name: str, value: Any):
        """Validate a caller-supplied gauge: numeric (including numpy/jax
        scalars) coerces to float; anything else raises, naming the gauge —
        a typo'd gauge must fail the caller, not vanish from /metrics."""
        if name in cls.TEXT_GAUGES and isinstance(value, str):
            return value
        if name in cls.DICT_GAUGES and isinstance(value, dict):
            return {str(k): float(v) for k, v in value.items()}
        if isinstance(value, bool):
            return float(value)
        try:
            out = float(value)
        except (TypeError, ValueError) as exc:
            raise ValueError(
                f"gauge {name!r} is not numeric: {value!r} "
                f"({type(value).__name__})"
            ) from exc
        return out

    @staticmethod
    def _bucket_json(hist: LatencyHistogram):
        """JSON encoding of `cumulative_counts`: inf -> '+Inf' (strict JSON
        has no Infinity literal; the Prometheus renderer understands both)."""
        return [
            ["+Inf" if le == float("inf") else le, c]
            for le, c in hist.cumulative_counts()
        ]

    def snapshot(self, **gauges: Any) -> Dict[str, Any]:
        """One flat JSON-serializable dict; extra `gauges` (active_sessions,
        compile_count, ...) are merged in by the caller that owns them —
        validated/coerced and merged under the lock, so a snapshot is one
        consistent cut even while handler threads record.

        Includes the cumulative histogram bucket counts
        (`latency_buckets`/`step_buckets` + `*_count`/`*_sum_s`), so the
        JSON view and the Prometheus exposition — which renders FROM this
        snapshot — cannot disagree.
        """
        coerced = {k: self._coerce_gauge(k, v) for k, v in gauges.items()}
        with self._lock:
            uptime = time.monotonic() - self._started
            out = {
                "uptime_s": uptime,
                "requests_total": self.requests_total,
                "errors_total": self.errors_total,
                "rejected_total": self.rejected_total,
                "resets_total": self.resets_total,
                "reloads_total": self.reloads_total,
                "sessions_restarted_total": self.sessions_restarted_total,
                "sessions_migrated_total": self.sessions_migrated_total,
                "requests_per_sec": (
                    self.requests_total / uptime if uptime > 0 else 0.0
                ),
                "latency_p50_ms": self.latency.quantile(0.5) * 1e3,
                "latency_p99_ms": self.latency.quantile(0.99) * 1e3,
                "latency_mean_ms": self.latency.mean() * 1e3,
                "latency_max_ms": self.latency.max * 1e3,
                "latency_buckets": self._bucket_json(self.latency),
                "latency_count": self.latency.count,
                "latency_sum_s": self.latency.total,
                "step_p50_ms": self.step_latency.quantile(0.5) * 1e3,
                "step_p99_ms": self.step_latency.quantile(0.99) * 1e3,
                "step_buckets": self._bucket_json(self.step_latency),
                "step_count": self.step_latency.count,
                "step_sum_s": self.step_latency.total,
                "batches_total": self.batches_total,
                "mean_batch_occupancy": (
                    self.occupancy_sum / self.batches_total
                    if self.batches_total
                    else 0.0
                ),
                "max_batch_occupancy": self.occupancy_max,
                "queue_depth": self.queue_depth,
                "batches_in_flight": self.batches_in_flight,
                "max_batches_in_flight": self.max_batches_in_flight,
                "joined_mid_cycle_total": self.joined_mid_cycle_total,
                # Per-bucket occupancy histogram, string-keyed for JSON;
                # the Prometheus renderer turns these into labeled
                # `rt1_serve_bucket_*{bucket="N"}` families.
                "bucket_batches": {
                    str(k): v for k, v in sorted(self.bucket_batches.items())
                },
                "bucket_occupancy_sum": {
                    str(k): v
                    for k, v in sorted(self.bucket_occupancy_sum.items())
                },
                # Per-task serve labels, string-keyed for JSON; the
                # Prometheus renderer emits them as labeled
                # `rt1_serve_task_*{task="..."}` families.
                "task_requests_total": dict(
                    sorted(self.task_requests_total.items())
                ),
                "task_sessions_total": dict(
                    sorted(self.task_sessions_total.items())
                ),
            }
            # Elastic-fleet families (router-level): present only once the
            # autoscaler / admission controller has touched them, so a
            # plain replica snapshot stays byte-identical to pre-elastic.
            if self.autoscale_replicas is not None:
                out["autoscale_replicas"] = self.autoscale_replicas
            if self.autoscale_scale_events:
                out["autoscale_scale_events_total"] = dict(
                    sorted(self.autoscale_scale_events.items())
                )
            if self.autoscale_shed:
                out["autoscale_shed_total"] = dict(
                    sorted(self.autoscale_shed.items())
                )
            if self.autoscale_tier_replicas:
                out["autoscale_tier_replicas"] = dict(
                    sorted(self.autoscale_tier_replicas.items())
                )
            out.update(coerced)
        return out

    def prometheus_text(self, **gauges: Any) -> str:
        """The snapshot in Prometheus exposition format (content-negotiated
        `/metrics` path; see rt1_tpu/obs/prometheus.py)."""
        from rt1_tpu.obs.prometheus import render_serve_snapshot

        return render_serve_snapshot(self.snapshot(**gauges))

    def write_to(self, writer, step: int, **gauges: Any) -> None:
        """Publish the snapshot through a clu metric writer (the
        `trainer/metrics.py:create_writer` object), `serve/`-prefixed.

        Gauges are validated by `snapshot` (non-numeric raises there); the
        only keys excluded here are the structural bucket arrays, which
        have no scalar representation.
        """
        scalars = {
            f"serve/{k}": float(v)
            for k, v in self.snapshot(**gauges).items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        writer.write_scalars(step, scalars)
