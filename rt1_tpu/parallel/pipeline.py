"""Pipeline parallelism: GPipe-style microbatch rotation over a ``stage`` axis.

Beyond reference parity (SURVEY.md §2.6: "Pipeline parallelism: No") — the
reference never shards layers. Here the decoder's layer stack can be
partitioned over the mesh's ``stage`` axis, with microbatches flowing
stage-to-stage over ICI via `jax.lax.ppermute` inside a `shard_map`:

  tick t:  stage 0 ingests microbatch t;  stage s computes the microbatch it
           received from stage s-1 last tick;  after M + S - 1 ticks every
           microbatch has crossed all S stages.

This is the collective-pipelining recipe (one `lax.scan` over ticks, a rotate
per tick) rather than a hand-scheduled 1F1B: autodiff through the scan +
ppermute gives the backward pipeline for free, and XLA overlaps the
(tiny, point-to-point) rotate with each stage's compute. Bubble fraction is
the GPipe (S-1)/(M+S-1); pick ``num_microbatches`` ≥ 4·S to amortize.

The unit here is a *stage function* ``stage_fn(stage_params, x) -> y`` with
``y.shape == x.shape`` (true for transformer blocks: (b, s, d_model) in/out).
``stacked_params`` holds every stage's parameters stacked on a leading axis
of size S·(layers-per-stage); `shard_map` splits that axis across stages, and
each stage folds its own chunk with an inner `lax.scan` (layers are
sequential within a stage).

`pp_causal_transformer_apply` applies a full `CausalTransformer`
(models/transformer.py) this way from its standard Flax params — embedding
and head are computed replicated (they are <2% of FLOPs); only the layer
stack is pipelined. Exactness vs the sequential module is pinned by
tests/test_pipeline.py.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def stack_layer_params(params: Any, num_layers: int, prefix: str = "layer_") -> Any:
    """Stack `CausalTransformer` per-layer param subtrees on a leading axis.

    Takes the module's standard params dict ({'layer_0': {...}, ...}) and
    returns a single pytree whose leaves have a leading ``num_layers`` axis —
    the layout `pipeline_apply` shards over ``stage``.
    """
    layers = [params[f"{prefix}{i}"] for i in range(num_layers)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layer_params(stacked: Any, prefix: str = "layer_") -> dict:
    """Inverse of `stack_layer_params` (for porting params back)."""
    num_layers = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return {
        f"{prefix}{i}": jax.tree.map(lambda x, i=i: x[i], stacked)
        for i in range(num_layers)
    }


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: int,
    stage_axis: str = "stage",
    data_axis: Optional[str] = "data",
) -> jnp.ndarray:
    """Run ``x`` through S pipelined stages; returns the final activations.

    * ``stacked_params`` leaves: (L, ...) with L divisible by S; stage s owns
      the [s·L/S, (s+1)·L/S) slice and scans `stage_fn` over it.
    * ``x``: (b, ...) activations. With a >1 ``data`` axis the batch dim is
      sharded over it (each data row runs an independent pipeline down its
      own stage column). The per-shard batch must divide `num_microbatches`.
    * Output == sequentially applying all L layers (exact; no renorm).

    Differentiable: the backward pass pipelines in reverse through the same
    scan/ppermute structure via autodiff.
    """
    S = mesh.shape[stage_axis]
    if S == 1:  # degenerate: plain scan over the stack, no collectives
        def fold(x, p):
            return stage_fn(p, x), None

        out, _ = jax.lax.scan(fold, x, stacked_params)
        return out

    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if L % S != 0:
        raise ValueError(f"{L} stacked layers not divisible by {S} stages")
    M = num_microbatches
    batch_spec = (
        P(data_axis)
        if data_axis and mesh.shape.get(data_axis, 1) > 1
        else P()
    )

    def local(params_chunk, x_local):
        # params_chunk leaves: (L/S, ...) — this stage's layers.
        b_local = x_local.shape[0]
        if b_local % M != 0:
            raise ValueError(
                f"per-shard batch {b_local} not divisible by "
                f"num_microbatches={M}"
            )
        mb = b_local // M
        s_idx = jax.lax.axis_index(stage_axis)
        feed = x_local.reshape((M, mb) + x_local.shape[1:])
        # Ticks M..M+S-2 feed no new microbatch; zeros keep shapes static.
        pad = jnp.zeros((S - 1,) + feed.shape[1:], feed.dtype)
        feed = jnp.concatenate([feed, pad], axis=0)  # (T, mb, ...)

        def run_stage(x_in):
            def fold(x, p):
                return stage_fn(p, x), None

            out, _ = jax.lax.scan(fold, x_in, params_chunk)
            return out

        rotate = [(i, (i + 1) % S) for i in range(S)]

        def tick(prev_y, x_t):
            incoming = jax.lax.ppermute(prev_y, stage_axis, rotate)
            x_in = jnp.where(s_idx == 0, x_t, incoming)
            y = run_stage(x_in)
            return y, y

        y0 = jnp.zeros(feed.shape[1:], feed.dtype)
        _, ys = jax.lax.scan(tick, y0, feed)  # (T, mb, ...)
        # Microbatch m exits the last stage at tick S-1+m. Replicate the
        # last stage's results to every stage with a masked psum so the
        # caller sees identical activations on all shards.
        out = ys[S - 1:]                      # (M, mb, ...)
        out = out * (s_idx == S - 1).astype(out.dtype)
        out = jax.lax.psum(out, stage_axis)
        return out.reshape((b_local,) + x_local.shape[1:])

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(stage_axis), batch_spec),
        out_specs=batch_spec,
        check_rep=False,
    )(stacked_params, x)


def pp_causal_transformer_apply(
    transformer: Any,
    params: Any,
    inputs: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: int,
    attention_mask: Optional[jnp.ndarray] = None,
    stage_axis: str = "stage",
) -> jnp.ndarray:
    """`CausalTransformer.__call__` with the layer stack pipelined.

    ``transformer`` is the `CausalTransformer` module instance (for its
    hyperparameters), ``params`` its standard Flax params. Embedding, the
    positional table, and the vocab head run replicated; the N pre-norm
    blocks run under `pipeline_apply`. Deterministic (train=False) — dropout
    inside a pipelined stage would need per-stage rng plumbing; training
    with PP uses the same structure with `rngs` folded into the stage id,
    which is left to the trainer integration.

    MoE caveat (``ffn_impl="moe"``): expert capacity is computed over the
    tokens of each *forward call*, so under PP it binds per microbatch
    (b/M·s tokens) rather than per batch — the standard per-device-batch
    semantics of MoE systems. Outputs match the sequential module exactly
    whenever no expert overflows its capacity (e.g. capacity_factor ≥
    num_experts guarantees it for top-1 routing); when drops do occur, the
    two schedules may drop different tokens.
    """
    from rt1_tpu.models.transformer import TransformerLayer

    b, s, _ = inputs.shape
    p = params["params"] if "params" in params else params
    x = inputs @ p["token_emb"]["kernel"] + p["token_emb"]["bias"]
    x = x + p["position_emb"]["embedding"][None, :s, :]

    if transformer.attention_impl != "dense":
        # Ring/pallas attention inside a pipelined stage would nest their
        # own collectives/kernels under this shard_map; unsupported.
        raise ValueError(
            "pipeline parallelism supports attention_impl='dense' only, "
            f"got {transformer.attention_impl!r}"
        )
    layer = TransformerLayer(
        key_dim=transformer.key_dim,
        num_heads=transformer.num_heads,
        d_model=transformer.d_model,
        dropout_rate=transformer.dropout_rate,
        dtype=transformer.dtype,
        ffn_impl=transformer.ffn_impl,
        num_experts=transformer.num_experts,
        moe_capacity_factor=transformer.moe_capacity_factor,
        moe_ff_dim=transformer.moe_ff_dim,
    )

    def stage_fn(layer_params, h):
        out, _ = layer.apply(
            {"params": layer_params}, h, mask=attention_mask, train=False
        )
        return out

    stacked = stack_layer_params(p, transformer.num_layers)
    x = pipeline_apply(
        stage_fn,
        stacked,
        x,
        mesh=mesh,
        num_microbatches=num_microbatches,
        stage_axis=stage_axis,
    )
    return x @ p["output_tokens"]["kernel"] + p["output_tokens"]["bias"]
