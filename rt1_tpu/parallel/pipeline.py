"""Pipeline parallelism: GPipe-style microbatch rotation over a ``stage`` axis.

Beyond reference parity (SURVEY.md §2.6: "Pipeline parallelism: No") — the
reference never shards layers. Here the decoder's layer stack can be
partitioned over the mesh's ``stage`` axis, with microbatches flowing
stage-to-stage over ICI via `jax.lax.ppermute` inside a `shard_map`:

  tick t:  stage 0 ingests microbatch t;  stage s computes the microbatch it
           received from stage s-1 last tick;  after M + S - 1 ticks every
           microbatch has crossed all S stages.

This is the collective-pipelining recipe (one `lax.scan` over ticks, a rotate
per tick) rather than a hand-scheduled 1F1B: autodiff through the scan +
ppermute gives the backward pipeline for free, and XLA overlaps the
(tiny, point-to-point) rotate with each stage's compute. Bubble fraction is
the GPipe (S-1)/(M+S-1); pick ``num_microbatches`` ≥ 4·S to amortize.

The unit here is a *stage function* ``stage_fn(stage_params, x) -> y`` with
``y.shape == x.shape`` (true for transformer blocks: (b, s, d_model) in/out).
``stacked_params`` holds every stage's parameters stacked on a leading axis
of size S·(layers-per-stage); `shard_map` splits that axis across stages, and
each stage folds its own chunk with an inner `lax.scan` (layers are
sequential within a stage).

`pp_causal_transformer_apply` applies a full `CausalTransformer`
(models/transformer.py) this way from its standard Flax params — embedding
and head are computed replicated (they are <2% of FLOPs); only the layer
stack is pipelined. Exactness vs the sequential module is pinned by
tests/test_pipeline.py.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
try:  # jax >= 0.6 promotes shard_map to the top-level namespace
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_layer_params(params: Any, num_layers: int, prefix: str = "layer_") -> Any:
    """Stack `CausalTransformer` per-layer param subtrees on a leading axis.

    Takes the module's standard params dict ({'layer_0': {...}, ...}) and
    returns a single pytree whose leaves have a leading ``num_layers`` axis —
    the layout `pipeline_apply` shards over ``stage``.
    """
    layers = [params[f"{prefix}{i}"] for i in range(num_layers)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layer_params(stacked: Any, prefix: str = "layer_") -> dict:
    """Inverse of `stack_layer_params` (for porting params back)."""
    num_layers = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return {
        f"{prefix}{i}": jax.tree.map(lambda x, i=i: x[i], stacked)
        for i in range(num_layers)
    }


def pipeline_apply(
    stage_fn: Callable[..., jnp.ndarray],
    stacked_params: Any,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: int,
    stage_axis: str = "stage",
    data_axis: Optional[str] = "data",
    pass_context: bool = False,
) -> jnp.ndarray:
    """Run ``x`` through S pipelined stages; returns the final activations.

    * ``stacked_params`` leaves: (L, ...) with L divisible by S; stage s owns
      the [s·L/S, (s+1)·L/S) slice and scans `stage_fn` over it.
    * ``x``: (b, ...) activations. With a >1 ``data`` axis the batch dim is
      sharded over it (each data row runs an independent pipeline down its
      own stage column). The per-shard batch must divide `num_microbatches`.
    * Output == sequentially applying all L layers (exact; no renorm).
    * ``pass_context``: call ``stage_fn(p, x, layer_idx, microbatch_idx)``
      instead of ``stage_fn(p, x)`` — the hook that lets training fold a
      dropout rng per (layer, microbatch). Both indices are traced int32
      scalars (global layer index; microbatch index clamped to [0, M) on
      bubble ticks, whose outputs are discarded).

    Differentiable: the backward pass pipelines in reverse through the same
    scan/ppermute structure via autodiff.
    """
    M = num_microbatches
    S = mesh.shape[stage_axis]
    if S == 1:  # degenerate: plain scan over the stack, no collectives
        L1 = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]

        def fold(x, p_i):
            p, i = p_i
            y = stage_fn(p, x, i, jnp.zeros((), jnp.int32)) if pass_context \
                else stage_fn(p, x)
            return y, None

        out, _ = jax.lax.scan(
            fold, x, (stacked_params, jnp.arange(L1, dtype=jnp.int32))
        )
        return out

    L = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if L % S != 0:
        raise ValueError(f"{L} stacked layers not divisible by {S} stages")
    batch_spec = (
        P(data_axis)
        if data_axis and mesh.shape.get(data_axis, 1) > 1
        else P()
    )

    def local(params_chunk, x_local):
        # params_chunk leaves: (L/S, ...) — this stage's layers.
        b_local = x_local.shape[0]
        if b_local % M != 0:
            raise ValueError(
                f"per-shard batch {b_local} not divisible by "
                f"num_microbatches={M}"
            )
        mb = b_local // M
        s_idx = jax.lax.axis_index(stage_axis)
        feed = x_local.reshape((M, mb) + x_local.shape[1:])
        # Ticks M..M+S-2 feed no new microbatch; zeros keep shapes static.
        pad = jnp.zeros((S - 1,) + feed.shape[1:], feed.dtype)
        feed = jnp.concatenate([feed, pad], axis=0)  # (T, mb, ...)

        layers_per_stage = L // S

        def run_stage(x_in, m_idx):
            def fold(x, p_l):
                p, l = p_l
                if pass_context:
                    y = stage_fn(p, x, s_idx * layers_per_stage + l, m_idx)
                else:
                    y = stage_fn(p, x)
                return y, None

            out, _ = jax.lax.scan(
                fold, x_in,
                (params_chunk, jnp.arange(layers_per_stage, dtype=jnp.int32)),
            )
            return out

        rotate = [(i, (i + 1) % S) for i in range(S)]

        def tick(prev_y, x_t_and_t):
            x_t, t = x_t_and_t
            incoming = jax.lax.ppermute(prev_y, stage_axis, rotate)
            x_in = jnp.where(s_idx == 0, x_t, incoming)
            # Stage s processes microbatch t - s at tick t (clamped on the
            # warm-up/drain bubbles, whose outputs never leave the mask).
            m_idx = jnp.clip(t - s_idx, 0, M - 1).astype(jnp.int32)
            y = run_stage(x_in, m_idx)
            return y, y

        y0 = jnp.zeros(feed.shape[1:], feed.dtype)
        ticks = jnp.arange(feed.shape[0], dtype=jnp.int32)
        _, ys = jax.lax.scan(tick, y0, (feed, ticks))  # (T, mb, ...)
        # Microbatch m exits the last stage at tick S-1+m. Replicate the
        # last stage's results to every stage with a masked psum so the
        # caller sees identical activations on all shards.
        out = ys[S - 1:]                      # (M, mb, ...)
        out = out * (s_idx == S - 1).astype(out.dtype)
        out = jax.lax.psum(out, stage_axis)
        return out.reshape((b_local,) + x_local.shape[1:])

    # Pre-reshard placement comes from the plan (plan.PIPELINE_STACK_RULES),
    # not inline special-casing: the stacked tree is pinned replicated before
    # the P(stage) reshard — the XLA:CPU miscompile guard documented there,
    # pinned by tests/test_pipeline.py::test_pp_train_step_equals_dense.
    from rt1_tpu.parallel import plan as planlib

    stacked_params = planlib.pipeline_stack_placement(stacked_params, mesh)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(stage_axis), batch_spec),
        out_specs=batch_spec,
        check_rep=False,
    )(stacked_params, x)


def pp_causal_transformer_apply(
    transformer: Any,
    params: Any,
    inputs: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: int,
    attention_mask: Optional[jnp.ndarray] = None,
    stage_axis: str = "stage",
    train: bool = False,
    dropout_rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """`CausalTransformer.__call__` with the layer stack pipelined.

    ``transformer`` is the `CausalTransformer` module instance (for its
    hyperparameters), ``params`` its standard Flax params. Embedding, the
    positional table, and the vocab head run replicated; the N pre-norm
    blocks run under `pipeline_apply` (the sequential module has no dropout
    outside the blocks, so this split is train-exact too).

    Training: pass ``train=True`` and a ``dropout_rng``; each (layer,
    microbatch) folds its indices into the rng, so masks are independent
    across layers and microbatches. This matches the sequential module's
    dropout *distribution* (every activation element keeps an independent
    Bernoulli mask) but not its bitstream — with `dropout_rate > 0` the
    pipelined and sequential losses are equal in expectation, not bitwise;
    exactness tests must set `dropout_rate = 0`.

    MoE caveat (``ffn_impl="moe"``): expert capacity is computed over the
    tokens of each *forward call*, so under PP it binds per microbatch
    (b/M·s tokens) rather than per batch — the standard per-device-batch
    semantics of MoE systems. Outputs match the sequential module exactly
    whenever no expert overflows its capacity (e.g. capacity_factor ≥
    num_experts guarantees it for top-1 routing); when drops do occur, the
    two schedules may drop different tokens. *Training* under PP+MoE is
    rejected: the Switch load-balancing aux loss is sown via `self.sow`,
    which an unmutable `layer.apply` inside the stage silently discards —
    training would lose the regularizer and invite router collapse.
    """
    from rt1_tpu.models.transformer import TransformerLayer

    b, s, _ = inputs.shape
    p = params["params"] if "params" in params else params
    x = inputs @ p["token_emb"]["kernel"] + p["token_emb"]["bias"]
    x = x + p["position_emb"]["embedding"][None, :s, :]

    if transformer.attention_impl != "dense":
        # Ring/pallas attention inside a pipelined stage would nest their
        # own collectives/kernels under this shard_map; unsupported.
        raise ValueError(
            "pipeline parallelism supports attention_impl='dense' only, "
            f"got {transformer.attention_impl!r}"
        )
    if train and transformer.ffn_impl == "moe":
        raise ValueError(
            "training with pipeline parallelism + MoE FFN is unsupported: "
            "the Switch aux loss sown inside the stage would be discarded "
            "(no mutable collections cross the shard_map); use ffn_impl="
            "'dense' under PP or train MoE on a stage=1 mesh"
        )
    use_dropout = train and transformer.dropout_rate > 0
    if use_dropout and dropout_rng is None:
        raise ValueError(
            "train=True with dropout_rate > 0 requires dropout_rng"
        )
    from flax import linen as _nn

    # Honor the module's remat flag on the pipelined path too (otherwise
    # remat=True + stage>1 would silently skip decoder rematerialization).
    # static_argnums counts self as 0: (self, x, mask, train) → train=3.
    layer_cls = (
        _nn.remat(TransformerLayer, static_argnums=(3,))
        if getattr(transformer, "remat", False)
        else TransformerLayer
    )
    layer = layer_cls(
        key_dim=transformer.key_dim,
        num_heads=transformer.num_heads,
        d_model=transformer.d_model,
        dropout_rate=transformer.dropout_rate,
        dtype=transformer.dtype,
        ffn_impl=transformer.ffn_impl,
        num_experts=transformer.num_experts,
        moe_capacity_factor=transformer.moe_capacity_factor,
        moe_ff_dim=transformer.moe_ff_dim,
        # Detach from any enclosing module context: this is a stateless
        # stage template applied with explicit params, not a submodule
        # (RT1Policy calls this helper from inside its own apply).
        parent=None,
    )

    # Inside the shard_map each data row is a different slice of the batch,
    # so the mask must differ per data shard too (folding only layer/micro
    # would reuse one mask across all data rows, shrinking effective dropout
    # noise as DP grows). axis_index is only bindable under the shard_map,
    # i.e. on the S > 1 path; the degenerate S == 1 path runs unsharded.
    fold_data = (
        mesh.shape[stage_axis] > 1 and mesh.shape.get("data", 1) > 1
    )

    def stage_fn(layer_params, h, layer_idx, mb_idx):
        rngs = None
        if use_dropout:
            r = jax.random.fold_in(dropout_rng, layer_idx)
            r = jax.random.fold_in(r, mb_idx)
            if fold_data:
                r = jax.random.fold_in(r, jax.lax.axis_index("data"))
            rngs = {"dropout": r}
        # Positional (x, mask, train): static_argnums on the remat wrap
        # refers to positional indices.
        out, _ = layer.apply(
            {"params": layer_params}, h, attention_mask, train, rngs=rngs
        )
        return out

    stacked = stack_layer_params(p, transformer.num_layers)
    x = pipeline_apply(
        stage_fn,
        stacked,
        x,
        mesh=mesh,
        num_microbatches=num_microbatches,
        stage_axis=stage_axis,
        pass_context=True,
    )
    return x @ p["output_tokens"]["kernel"] + p["output_tokens"]["bias"]
