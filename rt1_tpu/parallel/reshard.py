"""Checkpoint plan migration: restore a checkpoint saved under plan A onto
mesh/plan B.

Before this module a checkpoint's layout was a lucky coincidence: Orbax
restores into whatever template it is handed, and every caller handed it
the *current process's* concrete arrays — so train-on-a-big-mesh →
serve-on-small-replicas only worked when the layouts happened to line up.
Here migration is first-class and both ends resolve from the SAME rule
list (`parallel/plan.py`): the source plan decided where shards were
written; the target plan decides where they land. Because Orbax stores
GLOBAL logical arrays (per-host shard files + layout metadata), a restore
that presents target shardings is the entire migration — dense→fsdp,
4→8 devices, train-mesh→1-device serve replica — with no gather program
of our own on the happy path.

Two paths, consumed by `trainer/checkpoints.py` (``restore(plan=...)`` /
``restore_or_initialize(plan=...)``) and `eval/restore.py`:

* **Sharded restore** (`abstract_target`): the restore template is a
  pytree of `jax.ShapeDtypeStruct`s carrying the TARGET plan's
  `NamedSharding` per leaf — Orbax lays each global array out directly on
  the target mesh, reading only the bytes each host needs.
* **Host fallback** (`place_on_plan`): restore into plain host arrays,
  then gather→slice — `np.asarray` materializes each full leaf on host
  and one `jax.device_put` against the target shardings slices it onto
  the mesh. Single-process only (a host cannot materialize another
  host's shards); it exists for serve replicas on small hosts and for
  Orbax versions that reject abstract templates.

Round-trip contract (tests/test_reshard.py): save under the dense plan on
a 4-device mesh, restore under fsdp on an 8-device mesh (and back) with
bit-identical gathered params; `eval/restore.py` loads the same
checkpoint into a 1-device serve engine.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from rt1_tpu.parallel.plan import ShardingPlan


def target_shardings(tree: Any, plan: ShardingPlan) -> Any:
    """Per-leaf TARGET `NamedSharding`s for `tree` under `plan` — the same
    rule resolution the train step and serve placement use
    (`ShardingPlan.tree_shardings`), so a checkpoint migrates onto exactly
    the layout the consumer will compute with. Coverage is NOT re-checked
    here: the plan's consumer already ran `check_coverage` at build time,
    and a restore must not warn twice for the same decision."""
    return plan.tree_shardings(tree, check=False)


def abstract_target(tree: Any, plan: ShardingPlan) -> Any:
    """Restore template for a sharded (resharding) Orbax restore: each
    array leaf of `tree` becomes a `jax.ShapeDtypeStruct` with the target
    plan's sharding attached; non-array leaves pass through untouched.

    Shapes/dtypes come from `tree` (the freshly initialized state — the
    structural contract), placement from `plan` — which is how the SAME
    template restores a dense-saved checkpoint onto an fsdp mesh: the
    saved layout is metadata Orbax already has, only the target layout is
    ours to declare."""
    shardings = target_shardings(tree, plan)

    def one(leaf, sh):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)
        return leaf

    return jax.tree.map(one, tree, shardings)


def gather_to_host(tree: Any) -> Any:
    """Full host (numpy) copies of every array leaf — the "gather" half of
    the fallback path. Raises on non-addressable leaves: in a multi-process
    run a host only holds its own shards, and silently padding the rest
    with garbage would be far worse than failing."""

    def one(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            raise ValueError(
                "reshard.gather_to_host: leaf is not fully addressable from "
                "this process — the host-fallback path is single-process "
                "only; use the sharded restore (abstract_target) on "
                "multi-host meshes"
            )
        return np.asarray(leaf) if hasattr(leaf, "shape") else leaf

    return jax.tree.map(one, tree)


def place_on_plan(tree: Any, plan: ShardingPlan) -> Any:
    """The "slice" half of the fallback: lay host (or differently-laid-out
    device) arrays onto the target plan in one `device_put` — each device
    receives only its rule-decided shard. Non-array leaves pass through."""
    host = gather_to_host(tree)
    shardings = target_shardings(host, plan)

    def one(leaf, sh):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.device_put(leaf, sh)
        return leaf

    return jax.tree.map(one, host, shardings)


def gathered_equal(a: Any, b: Any) -> bool:
    """Bit-identity of two (possibly differently sharded) pytrees after
    gathering to host — the round-trip assertion: a checkpoint migrated
    A→B→A must hand back the exact bytes it started from."""
    ha, hb = gather_to_host(a), gather_to_host(b)
    leaves_a, treedef_a = jax.tree.flatten(ha)
    leaves_b, treedef_b = jax.tree.flatten(hb)
    if treedef_a != treedef_b:
        return False
    for la, lb in zip(leaves_a, leaves_b):
        xa, xb = np.asarray(la), np.asarray(lb)
        if xa.dtype != xb.dtype or xa.shape != xb.shape:
            return False
        # Byte comparison, not value comparison: NaNs must round-trip too,
        # and -0.0 vs 0.0 is a migration bug worth catching.
        if xa.tobytes() != xb.tobytes():
            return False
    return True
