"""One declarative sharding plan: name-pattern → PartitionSpec for every
RT-1 parameter group, resolved once and consumed identically by train
(`trainer/train.py`), eval restore (`eval/restore.py`), and serve
(`serve/engine.py`).

Before this module, parallelism was piecemeal: two hand-written rule lists in
`parallel/sharding.py` consumed only by the trainer, an inline XLA:CPU
replication workaround in `parallel/pipeline.py`, and ad-hoc `device_put`s on
the eval/serve path. Here the whole layout is ONE ordered list of
``(path-regex, PartitionSpec)`` rules in the GSPMD annotation-driven style
(Xu et al., 2021): annotate where each weight lives, let the partitioner
propagate everything else. The axes the specs name are the
``('data', 'stage', 'fsdp', 'seq', 'model')`` mesh of `parallel/mesh.py`:

* ``fsdp`` — ZeRO-3 weight sharding. The batch is sharded over it together
  with ``data``; weight matrices shard one dimension over it, so GSPMD emits
  per-layer all-gathers at use sites and reduce-scatters for gradients.
* ``model`` — tensor parallelism (attention heads / FFN columns, MoE experts).

Every spec is written against all axes; size-1 axes are free, so the same plan
degenerates to pure DP on a `dp=N` mesh at zero cost. Kernel layouts are Flax
Dense ``(in, out)``, which mirrors SNIPPETS.md [3]'s torch ``(out, in)``
``('tp','fsdp')`` map transposed: column-parallel kernels are
``P('fsdp', 'model')``, row-parallel are ``P('model', 'fsdp')``.

Coverage is checked, not assumed: `sharding_for_path`'s silent replicate-on-
no-match stays as the *mechanism*, but the plan refuses to let a weight matrix
fall through silently — `ShardingPlan.coverage` lists every rank≥2 leaf no
rule matched, `tree_shardings(check=True)` warns loudly (or raises in strict
mode) so a renamed module can't quietly replicate a gigabyte of experts.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rt1_tpu.parallel.mesh import MeshConfig, make_mesh

Rule = Tuple[str, P]

# Mesh-shape selection by device count when `config.parallel.auto` is set:
# n_devices -> (dp, fsdp, tp). The table follows SNIPPETS.md [1]'s shape
# ladder (small slices mix dp×fsdp, 8 adds tp, 16 goes fsdp×tp-heavy); the
# fallback for unlisted counts is pure fsdp — the memory-optimal default for
# a model that fits compute-bound on every chip.
#
# Keys are GLOBAL device counts (`jax.devices()`, host-major on multi-host
# slices — never `jax.local_devices()`): the 32/64 rows are pod-slice
# topologies where `dp` is the axis that crosses hosts. Because the mesh
# reshape is host-major with `dp` outermost (mesh.py), keeping fsdp×tp at or
# below the per-host device count keeps the bandwidth-hungry weight
# all-gathers on intra-host ICI while the (once-per-step, overlappable)
# gradient psum takes the DCN hops — `auto_mesh_shape` rebalances fsdp→dp
# when a row's model axes would spill across hosts.
AUTO_MESH_SHAPES = {
    1: (1, 1, 1),
    2: (2, 1, 1),
    4: (2, 2, 1),
    8: (2, 2, 2),
    16: (1, 4, 4),
    32: (4, 4, 2),
    64: (8, 4, 2),
}


def auto_mesh_shape(
    n_devices: int, local_device_count: Optional[int] = None
) -> Tuple[int, int, int]:
    """(dp, fsdp, tp) for `n_devices` GLOBAL devices, per AUTO_MESH_SHAPES.

    ``local_device_count`` (multi-host runs: `jax.local_device_count()`)
    keeps the table's rows host-contiguous: when a row's fsdp×tp product
    exceeds one host's devices, factors of 2 move from ``fsdp`` to ``dp``
    until the model axes fit inside a host — fsdp all-gathers stay on
    intra-host ICI and only the data-parallel gradient reduction crosses
    DCN. A single-host call (``local_device_count`` None or >= n_devices)
    returns the table row unchanged.
    """
    dp, fsdp, tp = AUTO_MESH_SHAPES.get(n_devices, (1, n_devices, 1))
    if local_device_count is not None and 0 < local_device_count < n_devices:
        while fsdp > 1 and fsdp % 2 == 0 and fsdp * tp > local_device_count:
            fsdp //= 2
            dp *= 2
    return dp, fsdp, tp


def rt1_sharding_plan() -> List[Rule]:
    """THE plan: ordered (path-regex, PartitionSpec) over every RT-1 param
    group. First match wins; paths are '/'-joined flax param paths.

    Folds the former `rt1_parameter_rules` + `moe_parameter_rules` (which
    covered only the decoder) and extends them to the FiLM-EfficientNet
    tokenizer, TokenLearner, embeddings, and the action head, so the
    coverage check can demand an explicit decision for every weight matrix.
    Norms/biases/BN stats are explicitly replicated — listed, not fallen
    through, so `coverage` distinguishes "decided small" from "forgotten".
    """
    return [
        # --- transformer decoder: attention ---------------------------------
        # qkv: (d_model, heads*key_dim) — columns over tp, rows over fsdp.
        (r"transformer/layer_\d+/attn/(query|key|value)/kernel$",
         P("fsdp", "model")),
        (r"transformer/layer_\d+/attn/(query|key|value)/bias$", P("model")),
        # out: (heads*key_dim, d_model) — row-parallel; GSPMD emits the psum
        # from the contraction.
        (r"transformer/layer_\d+/attn/out/kernel$", P("model", "fsdp")),
        (r"transformer/layer_\d+/attn/out/bias$", P()),
        # --- transformer decoder: FFN (single square Dense, transformer.py) -
        (r"transformer/layer_\d+/ff/kernel$", P("fsdp", "model")),
        (r"transformer/layer_\d+/ff/bias$", P("model")),
        (r"transformer/layer_\d+/norm_\d+/(scale|bias)$", P()),
        # --- Switch MoE FFN (models/moe.py) ---------------------------------
        # fp32 router replicated so every shard routes identically.
        (r"moe/gate/kernel$", P()),
        # Stacked experts (E, d, ff)/(E, ff, d): experts over `model` (the
        # dispatch/combine einsums lower to all-to-alls over ICI), the
        # non-contracting weight dim over `fsdp`.
        (r"moe/wi$", P("model", "fsdp", None)),
        (r"moe/wo$", P("model", None, "fsdp")),
        # --- embeddings + action head (the vocab head IS the action head:
        # action tokens decode from its logits) ------------------------------
        (r"transformer/token_emb/kernel$", P("fsdp", "model")),
        (r"transformer/token_emb/bias$", P("model")),
        (r"transformer/position_emb/embedding$", P(None, "fsdp")),
        (r"transformer/output_tokens/kernel$", P("fsdp", "model")),
        (r"transformer/output_tokens/bias$", P("model")),
        # --- FiLM-EfficientNet tokenizer ------------------------------------
        # FiLM projections: (512, channels) — shard the (large, always
        # divisible) embedding dim over fsdp; channels can be as small as 8.
        (r"projection_(add|mult)/kernel$", P("fsdp", None)),
        (r"projection_(add|mult)/bias$", P()),
        # Conv kernels, (kh, kw, cin, cout): output channels over fsdp.
        # Matches the EfficientNet stem/top/expand/project/depthwise convs,
        # the SE fc1/fc2 1x1 convs, the encoder conv1x1, the TokenLearner
        # conv1/conv2, and the tiny tokenizer's stem conv.
        (r"(conv|conv1|conv2|conv1x1|fc1|fc2)/kernel$",
         P(None, None, None, "fsdp")),
        (r"(conv|conv1|conv2|conv1x1|fc1|fc2)/bias$", P()),
        (r"bn/(scale|bias|mean|var)$", P()),
        (r"token_learner/norm/(scale|bias)$", P()),
        # Vision-pretrain classifier head (train/pretrain_vision.py grafts
        # drop it before policy training, but the encoder trains with it).
        (r"classifier/kernel$", P(None, "fsdp")),
        (r"classifier/bias$", P()),
        # --- tiny tokenizer (configs/tiny.py) -------------------------------
        (r"image_tokenizer_def/ctx_proj/kernel$", P("fsdp", None)),
        (r"image_tokenizer_def/ctx_proj/bias$", P()),
        (r"image_tokenizer_def/tok/kernel$", P(None, "fsdp")),
        (r"image_tokenizer_def/tok/bias$", P()),
    ]


# --------------------------------------------------------------- quant plan
#
# Quantization groups for the low-precision serving engine
# (rt1_tpu/models/quant.py): the SAME path-regex machinery as the sharding
# rules above, so "what gets int8" is declared next to "how it shards"
# (SNIPPETS.md [3]'s sharding map carries torch.int8 dtypes per entry for
# exactly this reason). First match wins; an unmatched path serves at the
# master dtype. Groups:
QUANT_INT8 = "int8"   # per-output-channel int8 weights + f32 scale sidecar
QUANT_F32 = "f32"     # never quantized (master/compute dtype)


def rt1_quant_rules() -> List[Tuple[str, str]]:
    """THE quant plan: ordered (path-regex, group) over every RT-1 param
    group. int8 covers the matmul/conv weights whose bytes dominate the
    serving tree — transformer qkv/out/FFN, MoE experts, FiLM projections,
    every EfficientNet/SE/TokenLearner/encoder conv, and the tiny
    tokenizer's projections. Embeddings, the action head (`output_tokens`
    IS the action decode), norms, biases, BN statistics, and the fp32 MoE
    router are listed f32 EXPLICITLY — `quant_coverage` distinguishes
    "decided full-precision" from "forgotten", same philosophy as the
    sharding plan's coverage check.
    """
    return [
        # --- explicit full-precision: embeddings + the action head -------
        (r"transformer/(token_emb|position_emb|output_tokens)/", QUANT_F32),
        # fp32 router: routing decisions must not flip under quant noise.
        (r"moe/gate/kernel$", QUANT_F32),
        # Vision-pretrain classifier head (dropped before policy serving,
        # but the rule set must decide every path it can meet).
        (r"classifier/", QUANT_F32),
        # Norm/BN leaves are rank<2 (never quantizable) — listed anyway so
        # the decision is readable here, not implied by rank.
        (r"(norm_\d+|norm|bn)/(scale|bias|mean|var)$", QUANT_F32),
        # --- int8: transformer decoder matmuls ---------------------------
        (r"transformer/layer_\d+/attn/(query|key|value|out)/kernel$",
         QUANT_INT8),
        (r"transformer/layer_\d+/ff/kernel$", QUANT_INT8),
        # Stacked Switch-MoE experts (E, d, ff)/(E, ff, d): per-channel on
        # the output dim, scales shared across experts (conservative).
        (r"moe/(wi|wo)$", QUANT_INT8),
        # --- int8: FiLM-EfficientNet tokenizer ---------------------------
        (r"projection_(add|mult)/kernel$", QUANT_INT8),
        # Conv kernels (stem/top/expand/project/depthwise, SE fc1/fc2,
        # encoder conv1x1, TokenLearner conv1/conv2, tiny stem conv).
        (r"(conv|conv1|conv2|conv1x1|fc1|fc2)/kernel$", QUANT_INT8),
        # --- int8: tiny tokenizer projections ----------------------------
        (r"image_tokenizer_def/(ctx_proj|tok)/kernel$", QUANT_INT8),
    ]


def quant_group_for_path(
    path_str: str, rules: Optional[Sequence[Tuple[str, str]]] = None
) -> str:
    """First matching quant rule's group; unmatched paths serve at the
    master dtype (QUANT_F32)."""
    if rules is None:
        rules = rt1_quant_rules()
    for pattern, group in rules:
        if re.search(pattern, path_str):
            return group
    return QUANT_F32


def quant_coverage(
    tree: Any, rules: Optional[Sequence[Tuple[str, str]]] = None
) -> List[str]:
    """Paths of rank>=2 leaves no quant rule decided (fell through to the
    master-dtype default). Mirrors `ShardingPlan.coverage`: a weight
    matrix nobody DECIDED about is how a renamed module quietly loses its
    3x memory win — tier-1 pins this empty for the shipped configs."""
    from rt1_tpu.parallel import sharding as shardlib

    if rules is None:
        rules = rt1_quant_rules()
    undecided = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if getattr(leaf, "ndim", 0) < 2:
            continue
        s = shardlib._path_str(path)
        if not any(re.search(pattern, s) for pattern, _ in rules):
            undecided.append(s)
    return undecided


# Plan-level placement for the stacked per-layer tree pipeline_apply shards
# over `stage`. The explicit replicated pin is load-bearing on XLA:CPU
# (jax 0.4.x): a stack/concatenate of per-layer params resharded straight
# into P(stage) on a mesh with another >1 axis SUMS the other axis' replicas
# into each stage shard. Pinning the stacked tree to a replicated layout
# first forces the partitioner to materialize the value before the stage
# reshard, which compiles correctly (the failure it masks is pinned in
# tests/test_pipeline.py::test_pp_train_step_equals_dense). Expressed as a
# rule list so the workaround lives in the plan, not inline in pipeline.py.
PIPELINE_STACK_RULES: List[Rule] = [
    (r".*", P()),
]


def pipeline_stack_placement(stacked_params: Any, mesh: Mesh) -> Any:
    """Apply the plan's pre-reshard placement to a stacked layer tree."""
    from rt1_tpu.parallel import sharding as shardlib

    return jax.tree_util.tree_map_with_path(
        lambda path, x: jax.lax.with_sharding_constraint(
            x, shardlib.sharding_for_path(path, mesh, PIPELINE_STACK_RULES)
        ),
        stacked_params,
    )


class PlanCoverageError(ValueError):
    """Strict mode: a weight matrix matched no plan rule."""


def strip_fsdp_axis(spec: P) -> P:
    """`spec` with the ``fsdp`` axis removed from every dim (the in-step
    gathered layout: tp sharding kept, weight shards whole again).

    The train step applies this as a `with_sharding_constraint` on the
    params at step entry: weights are STORED fsdp-sharded between steps
    (masters + optimizer moments — the ZeRO memory win) and gathered ONCE
    per step for fwd/bwd, with the gradient/update resharded back by the
    state's out_shardings (a reduce-scatter at the step boundary). One
    clean all-gather per step instead of per-use resharding also sidesteps
    the jax 0.4.x XLA:CPU partitioner's "involuntary full
    rematerialization" paths, which miscompute on dp>1 × fsdp>1 meshes
    when weights stay sharded through the fwd/bwd (pinned by
    tests/test_plan.py::test_dense_fsdp_tp_pp_equivalence_on_4_devices —
    the same bug family as PIPELINE_STACK_RULES' pin).
    """
    dims = []
    for d in spec:
        if d == "fsdp":
            dims.append(None)
        elif isinstance(d, (tuple, list)):
            kept = tuple(a for a in d if a != "fsdp")
            dims.append(kept if kept else None)
        else:
            dims.append(d)
    return P(*dims)


@dataclasses.dataclass
class ShardingPlan:
    """A resolved plan: mesh + rules + the batch layout, with coverage
    checking. Built once (`from_config`) and handed to every consumer.
    """

    mesh: Mesh
    rules: Sequence[Rule] = dataclasses.field(
        default_factory=rt1_sharding_plan
    )
    strict: bool = False

    # ------------------------------------------------------------ specs
    @property
    def batch_axes(self) -> Tuple[str, ...]:
        """Mesh axes the leading batch dim shards over. FSDP is data
        parallelism for activations, so the batch covers both axes."""
        return ("data", "fsdp")

    @property
    def data_parallel_size(self) -> int:
        """Total batch-sharding ways (per_host_batch_size must divide it)."""
        size = 1
        for a in self.batch_axes:
            size *= self.mesh.shape.get(a, 1)
        return size

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.batch_axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # ------------------------------------------------------------ matching
    def quant_group(self, path_str: str) -> str:
        """The quantization group for a param path (module-level quant
        rules; on the plan so layout consumers read shard + quant
        decisions from one object)."""
        return quant_group_for_path(path_str)

    def spec_for(self, path_str: str) -> Optional[P]:
        """First matching rule's spec, or None (≠ P()!) when unmatched."""
        for pattern, spec in self.rules:
            if re.search(pattern, path_str):
                return spec
        return None

    def coverage(self, tree: Any) -> List[str]:
        """Paths of rank≥2 leaves (weight matrices) no rule matched.

        Rank<2 leaves (biases, norms, BN stats, scalars) may fall through
        to replication freely — they are too small to matter; a silently
        replicated *matrix* is the bug this check exists for.
        """
        from rt1_tpu.parallel import sharding as shardlib

        unmatched = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if getattr(leaf, "ndim", 0) < 2:
                continue
            s = shardlib._path_str(path)
            if self.spec_for(s) is None:
                unmatched.append(s)
        return unmatched

    def check_coverage(self, tree: Any, what: str = "params") -> List[str]:
        """Loud-warn (or strict-raise) on unmatched weight matrices."""
        unmatched = self.coverage(tree)
        if unmatched:
            msg = (
                f"sharding plan: {len(unmatched)} {what} weight matrices "
                f"matched NO rule and would silently replicate: "
                f"{unmatched[:8]}{'...' if len(unmatched) > 8 else ''} — "
                f"add rules to rt1_tpu/parallel/plan.py"
            )
            if self.strict:
                raise PlanCoverageError(msg)
            import logging

            logging.getLogger("rt1_tpu.parallel.plan").warning(msg)
        return unmatched

    # ------------------------------------------------------------ placement
    def tree_shardings(self, tree: Any, check: bool = False) -> Any:
        """Pytree of NamedShardings matching `tree` per the rules; unmatched
        leaves replicate (after `check_coverage` when `check`)."""
        from rt1_tpu.parallel import sharding as shardlib

        if check:
            self.check_coverage(tree)
        return shardlib.shard_pytree(tree, self.mesh, self.rules)

    def place_variables(self, variables: Any, check: bool = True) -> Any:
        """device_put a restored `{'params': ..., 'batch_stats': ...}` tree
        through the plan — the eval/serve placement path."""
        return jax.device_put(
            variables, self.tree_shardings(variables, check=check)
        )

    def gather_shardings(self, tree: Any) -> Any:
        """Per-leaf NamedShardings for the IN-STEP layout: plan specs with
        the fsdp axis stripped (see `strip_fsdp_axis`). Applied by the
        train step as a with_sharding_constraint at step entry."""
        from rt1_tpu.parallel import sharding as shardlib

        def one(path, leaf):
            spec = self.spec_for(shardlib._path_str(path))
            spec = strip_fsdp_axis(spec if spec is not None else P())
            shape = getattr(leaf, "shape", None)
            if shape is not None:
                spec = shardlib.spec_for_shape(spec, shape, self.mesh)
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map_with_path(one, tree)

    # ------------------------------------------------------------ factory
    @classmethod
    def from_config(
        cls,
        config: Any = None,
        devices: Optional[Sequence[jax.Device]] = None,
        n_devices: Optional[int] = None,
        collapse_data: bool = False,
    ) -> "ShardingPlan":
        """Resolve the plan ONCE from `config.parallel` (dp/fsdp/tp/pp/sp
        sizes, `auto` mesh-shape selection by device count, `strict`
        coverage), falling back to the legacy `config.mesh` block
        (data/model/seq/stage) for configs that predate `config.parallel`,
        and to pure DP when neither block exists (pinned proof configs).

        ``collapse_data=True`` is the serving resolution (eval/restore.py
        `serving_plan`): there is no batch axis to shard (sessions are
        slots, not data shards), so `dp` collapses to 1 and the mesh covers
        exactly the fsdp × tp × pp × sp devices model parallelism needs —
        raising when the host has fewer. One resolver for train AND serve,
        so the ladder/axes can never drift between them.
        """
        dp, fsdp, tp, pp, sp = -1, 1, 1, 1, 1
        strict = False
        par = _get(config, "parallel")
        if par is not None:
            if _get(par, "auto", False):
                # Resolution is against the GLOBAL device set (`jax.
                # devices()`, host-major on a multi-process slice) — the
                # mesh spans every process's devices; `jax.local_devices()`
                # would build N disjoint single-host meshes instead of one
                # slice-wide program.
                local = None
                if n_devices is None and devices is None:
                    pool = jax.devices()
                    n = len(pool)
                    if jax.process_count() > 1:
                        local = jax.local_device_count()
                else:
                    n = n_devices if n_devices is not None else len(devices)
                pp = int(_get(par, "pp", 1))
                sp = int(_get(par, "sp", 1))
                # pp/sp are honored as configured: the auto table splits
                # only the devices left after the stage/seq axes take
                # theirs, so auto composes with pp>1 or sp>1 instead of
                # over-subscribing the mesh.
                dp, fsdp, tp = auto_mesh_shape(
                    max(n // max(pp * sp, 1), 1), local
                )
            else:
                dp = int(_get(par, "dp", -1))
                fsdp = int(_get(par, "fsdp", 1))
                tp = int(_get(par, "tp", 1))
                pp = int(_get(par, "pp", 1))
                sp = int(_get(par, "sp", 1))
            strict = bool(_get(par, "strict", False))
        else:
            legacy = _get(config, "mesh")
            if legacy is not None:
                dp = int(_get(legacy, "data", -1))
                tp = int(_get(legacy, "model", 1))
                sp = int(_get(legacy, "seq", 1))
                pp = int(_get(legacy, "stage", 1))
        if collapse_data:
            dp = 1
            n = fsdp * tp * pp * sp
            pool = list(devices) if devices is not None else jax.devices()
            if len(pool) < n:
                raise ValueError(
                    f"config.parallel asks for fsdp*tp*pp*sp={n} devices "
                    f"but this serving host has {len(pool)}"
                )
            devices = pool[:n]
        mesh = make_mesh(
            MeshConfig(data=dp, fsdp=fsdp, model=tp, seq=sp, stage=pp),
            devices=devices,
        )
        return cls(mesh=mesh, strict=strict)


def _get(obj: Any, key: str, default: Any = None) -> Any:
    """config attribute/key lookup tolerating ml_collections, dicts, None."""
    if obj is None:
        return default
    if hasattr(obj, "get"):
        try:
            v = obj.get(key, default)
            return default if v is None else v
        except TypeError:
            pass
    v = getattr(obj, key, default)
    return default if v is None else v


def mixed_precision_from_config(config: Any) -> bool:
    """The `config.parallel.mixed_precision` switch (False when absent)."""
    return bool(_get(_get(config, "parallel"), "mixed_precision", False))
