"""Device-mesh construction.

The reference's device topology is implicit (one process per GPU, NCCL ring under
Lightning DDP, `distribute_train.py:194,235`). On TPU the topology is explicit: a
`jax.sharding.Mesh` over the slice, with named axes that sharding specs refer to.

Axis conventions used throughout rt1_tpu:

* ``data``  — data parallelism (batch axis). Gradient reduction becomes an XLA
  psum over ICI, replacing DDP's NCCL bucket allreduce.
* ``fsdp``  — fully-sharded data parallelism (ZeRO-3): the batch is sharded over
  it like ``data``, but parameters/optimizer state are *also* sharded over it
  (per the plan in rt1_tpu/parallel/plan.py), so GSPMD emits all-gathers for
  weights at use sites and reduce-scatters for gradients.
* ``model`` — tensor parallelism (attention heads / FFN columns).
* ``seq``   — sequence/context parallelism (ring attention); unused for the 66-token
  RT-1 window (SURVEY.md §5 "long-context: absent") but first-class in the API so
  long-horizon variants can turn it on.
* ``stage`` — pipeline parallelism (GPipe-style microbatch rotation over layer
  stages, rt1_tpu/parallel/pipeline.py). Like ``seq``, beyond reference parity.

All axes are optional; size-1 axes are free (no collectives are emitted for them).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. -1 for `data` means "all remaining devices"."""

    data: int = -1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    stage: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        fixed = self.fsdp * self.model * self.seq * self.stage
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by "
                f"fsdp*model*seq*stage={fixed}"
            )
        data = self.data if self.data != -1 else n_devices // fixed
        if data * fixed != n_devices:
            raise ValueError(
                f"mesh {data}x{self.stage}x{self.fsdp}x{self.seq}x"
                f"{self.model} != {n_devices} devices"
            )
        return MeshConfig(
            data=data, fsdp=self.fsdp, model=self.model, seq=self.seq,
            stage=self.stage,
        )


def make_mesh(
    config: MeshConfig = MeshConfig(),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ('data', 'stage', 'fsdp', 'seq', 'model') mesh over `devices`
    (default: all).

    Axis order puts ``model`` innermost so tensor-parallel collectives ride the
    fastest ICI links (nearest-neighbor on a TPU slice), ``data`` outermost so DP
    psum tolerates the slower hops (and DCN across hosts on multi-host slices,
    where `jax.devices()` is already ordered host-major). ``fsdp`` sits between:
    its per-layer weight all-gathers are bandwidth-hungry like TP but overlap
    with compute, so it takes the middle hops. ``stage`` sits next to ``data``:
    pipeline ppermutes are point-to-point once per microbatch tick — far less
    bandwidth-hungry than TP/SP collectives — so they get the longer hops.
    """
    devices = list(devices if devices is not None else jax.devices())
    cfg = config.resolve(len(devices))
    arr = np.asarray(devices).reshape(
        cfg.data, cfg.stage, cfg.fsdp, cfg.seq, cfg.model
    )
    return Mesh(arr, axis_names=("data", "stage", "fsdp", "seq", "model"))
