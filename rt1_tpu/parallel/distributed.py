"""Multi-process (multi-host) runtime initialization.

Single-host rt1_tpu needs none of this: `jax.devices()` is the local chip
set and every collective stays on ICI. A pod slice is N cooperating
processes (one per host), and before any of them touches a device the JAX
runtime must rendezvous — `jax.distributed.initialize` with a coordinator
address plus this process's id — so `jax.devices()` becomes the host-major
GLOBAL device list the sharding plan resolves against
(`ShardingPlan.from_config`), cross-host collectives lower to DCN, and
Orbax checkpointing coordinates its per-host shard writes.

Config surface (`config.parallel.distributed`, docs/parallelism.md
"Multi-host"):

* ``enabled``             — off (default) keeps the exact single-process path.
* ``coordinator_address`` — "host:port" of process 0.
* ``process_id`` / ``num_processes`` — this process's rank and the world
  size; ``-1`` defers to environment fallbacks.

Environment fallbacks (checked in order) let one config file serve every
host of a slice — the per-host identity rides the launcher's environment:

* ``RT1_COORDINATOR`` / ``RT1_PROCESS_ID`` / ``RT1_NUM_PROCESSES`` — ours.
* ``JAX_COORDINATOR_ADDRESS`` / ``JAX_PROCESS_ID`` / ``JAX_NUM_PROCESSES``
  — the names `jax.distributed` itself honors.
* On TPU pods all three may be absent: `jax.distributed.initialize()` with
  no arguments reads the TPU metadata server (the "enabled with nothing
  else set" path).

`initialize_from_config` is idempotent (a second call is a no-op, loudly)
and must run before the first device access — the train entry calls it
ahead of plan resolution (`train/train.py train_and_evaluate`).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

#: Module-level latch: `jax.distributed.initialize` may run once per
#: process; a second train_and_evaluate in the same process (tests, sweeps)
#: must not crash on re-init.
_INITIALIZED = False


@dataclasses.dataclass(frozen=True)
class DistributedOptions:
    """Resolved `config.parallel.distributed` block (env fallbacks applied)."""

    enabled: bool = False
    coordinator_address: Optional[str] = None
    process_id: Optional[int] = None
    num_processes: Optional[int] = None

    @classmethod
    def from_config(cls, config: Any) -> "DistributedOptions":
        from rt1_tpu.parallel.plan import _get

        block = _get(_get(config, "parallel"), "distributed")
        if block is None:
            return cls()
        enabled = bool(_get(block, "enabled", False))
        addr = _get(block, "coordinator_address") or _env_str(
            "RT1_COORDINATOR", "JAX_COORDINATOR_ADDRESS"
        )
        pid = _int_or_none(_get(block, "process_id", -1))
        if pid is None:
            pid = _env_int("RT1_PROCESS_ID", "JAX_PROCESS_ID")
        count = _int_or_none(_get(block, "num_processes", -1))
        if count is None:
            count = _env_int("RT1_NUM_PROCESSES", "JAX_NUM_PROCESSES")
        return cls(
            enabled=enabled,
            coordinator_address=addr,
            process_id=pid,
            num_processes=count,
        )

    def validate(self) -> None:
        """Fail at the config seam: a half-specified rendezvous hangs in
        the coordinator handshake instead of erroring, so partial explicit
        settings are rejected here with the missing field named."""
        if not self.enabled:
            return
        explicit = [
            self.coordinator_address is not None,
            self.process_id is not None,
            self.num_processes is not None,
        ]
        if any(explicit) and not all(explicit):
            missing = [
                name
                for name, have in zip(
                    ("coordinator_address", "process_id", "num_processes"),
                    explicit,
                )
                if not have
            ]
            raise ValueError(
                f"parallel.distributed: {', '.join(missing)} unset while "
                f"other rendezvous fields are explicit — set them in the "
                f"config block or via RT1_COORDINATOR / RT1_PROCESS_ID / "
                f"RT1_NUM_PROCESSES (all three, or none for TPU-metadata "
                f"auto-discovery)"
            )
        if self.num_processes is not None and self.num_processes < 1:
            raise ValueError(
                f"parallel.distributed.num_processes={self.num_processes} "
                f"must be >= 1"
            )
        if (
            self.process_id is not None
            and self.num_processes is not None
            and not 0 <= self.process_id < self.num_processes
        ):
            raise ValueError(
                f"parallel.distributed.process_id={self.process_id} out of "
                f"range [0, {self.num_processes})"
            )


def _env_str(*names: str) -> Optional[str]:
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return None


def _env_int(*names: str) -> Optional[int]:
    v = _env_str(*names)
    return int(v) if v is not None else None


def _int_or_none(v: Any) -> Optional[int]:
    """Config ints where -1/None mean "defer to the environment"."""
    if v is None:
        return None
    v = int(v)
    return None if v < 0 else v


def initialize_from_config(config: Any) -> bool:
    """`jax.distributed.initialize` per `config.parallel.distributed`.

    Returns True when this call performed the initialization, False when
    the block is absent/disabled or the process was already initialized
    (idempotent — a second train run in one process logs and moves on).
    Must run before the first device access; the train entry calls it
    before resolving the sharding plan.
    """
    global _INITIALIZED

    opts = DistributedOptions.from_config(config)
    if not opts.enabled:
        return False
    opts.validate()
    from absl import logging

    if _INITIALIZED:
        logging.warning(
            "parallel.distributed: already initialized in this process — "
            "skipping re-initialization"
        )
        return False
    import jax

    kwargs = {}
    if opts.coordinator_address is not None:
        kwargs = dict(
            coordinator_address=opts.coordinator_address,
            process_id=opts.process_id,
            num_processes=opts.num_processes,
        )
    jax.distributed.initialize(**kwargs)
    _INITIALIZED = True
    logging.info(
        "parallel.distributed: process %d/%d up (%d local / %d global "
        "devices, coordinator %s)",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
        opts.coordinator_address or "<tpu metadata>",
    )
    return True


def force_cpu_multiprocess_runtime(
    devices_per_process: int, gloo: bool = True
) -> None:
    """Pin THIS process to a forced-CPU multi-device platform with a real
    cross-process collectives backend — the bootstrap every CPU-mesh
    scale-out rehearsal needs (tests/multiprocess_worker.py,
    tests/distributed_worker.py, scripts/bench_multihost.py), kept in ONE
    place so a collectives tweak cannot drift between suites.

    Gloo matters: XLA:CPU's default collectives ("none") cannot dispatch
    a computation spanning processes ("Multiprocess computations aren't
    implemented on the CPU backend"). Both the env var AND the live
    config are set because environments whose sitecustomize imports jax
    at interpreter start capture the config before any caller runs (the
    tests/conftest.py pattern). Must run before the first device access;
    never call it in a process that should keep its own backend (a parent
    test session importing a worker module, e.g.).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices_per_process}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if gloo:
        os.environ["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"
        jax.config.update("jax_cpu_collectives_implementation", "gloo")


def free_local_port() -> int:
    """An OS-assigned free loopback port — coordinator-address plumbing
    for the multi-process rehearsals (tests/bench spawn groups that need
    a rendezvous port before any process exists). One copy here so a
    port-allocation fix (e.g. reuse-race mitigation) lands everywhere."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def is_primary() -> bool:
    """True on the process that owns single-writer side effects (manifests,
    markers, reports) — process 0, or any process of a single-process run."""
    import jax

    return jax.process_index() == 0
