"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference has no long-context support — its window is a fixed 66 tokens
(SURVEY.md §5 "long-context: absent"). This module makes sequence/context
parallelism first-class for long-horizon variants: Q/K/V live sharded over
the mesh's ``seq`` axis, and K/V blocks rotate around the ring via
`jax.lax.ppermute` while each device folds one block per hop into a running
flash-attention-style (online softmax) accumulator. Attention is EXACT — the
rotation only changes where each block is multiplied, not the math — and
peak memory per device is O(T/S · T/S) per hop instead of O(T · T).

Design refs (public): Liu et al., "Ring Attention with Blockwise
Transformers" (2023); the `jax.lax.ppermute` collective rides ICI
neighbor-to-neighbor on a TPU slice, overlapping with the per-hop matmuls.

Masks use the framework convention (nonzero = attend, 0 = blocked,
`rt1_tpu/models/transformer.py:56-62`); the full (T, T) mask is replicated
and each hop slices the (q_chunk, k_chunk) block it needs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 promotes shard_map to the top-level namespace
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

NEG_INF = -1e9


def _ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    axis_name: str,
    scale: float,
):
    """Per-shard body (inside shard_map). q/k/v: (b, t_local, h, d)."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape

    qf = q.astype(jnp.float32) * scale

    def fold_block(s, o, l, m, k_blk, v_blk):
        """Online-softmax update with the block currently held (origin
        device my_idx + s: ppermute sends block i -> i-1 each hop)."""
        src = jax.lax.rem(my_idx + s, axis_size)
        logits = jnp.einsum(
            "bshd,bthd->bhst", qf, k_blk.astype(jnp.float32)
        )  # (b, h, t_local, t_local)
        if mask is not None:
            blk = jax.lax.dynamic_slice(
                mask,
                (my_idx * t_local, src * t_local),
                (t_local, t_local),
            )
            logits = jnp.where(blk[None, None].astype(bool), logits, NEG_INF)

        m_blk = jnp.max(logits, axis=-1)  # (b, h, t_local)
        m_new = jnp.maximum(m, m_blk)
        # Rescale the running accumulator to the new max, fold in this block.
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhst,bthd->bshd", p, v_blk.astype(jnp.float32)
        ).transpose(0, 2, 1, 3)  # -> (b, h, t_local, d)
        return o_new, l_new, m_new

    def hop(s, carry):
        o, l, m, k_blk, v_blk = carry
        o, l, m = fold_block(s, o, l, m, k_blk, v_blk)
        # Rotate K/V one hop around the ring (receive from the next device).
        perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, l, m, k_nxt, v_nxt

    o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    m0 = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    # Rotate on hops 0..S-2 only; the final block folds without the two
    # wasted ppermutes a full S-iteration loop would issue.
    o, l, m, k_last, v_last = jax.lax.fori_loop(
        0, axis_size - 1, hop, (o0, l0, m0, k, v)
    )
    o, l, m = fold_block(axis_size - 1, o, l, m, k_last, v_last)

    # Fully-masked rows (l == 0) produce 0 output rather than NaN.
    out = jnp.where(
        l[..., None] > 0, o / jnp.maximum(l, 1e-30)[..., None], 0.0
    )
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (b, t_local, h, d)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    mask: Optional[jnp.ndarray] = None,
    seq_axis: str = "seq",
    batch_axis: Optional[str] = "data",
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Exact multi-head attention with sequence sharded over `seq_axis`.

    Args:
      q, k, v: (b, t, h, d) global arrays; t must divide by the seq-axis size.
      mesh: the device mesh.
      mask: optional (t, t) mask, nonzero = attend (replicated).
      seq_axis: mesh axis to ring over.
      batch_axis: mesh axis the batch is sharded over (None = replicated).
      scale: logit scale; default 1/sqrt(d).
    Returns:
      (b, t, h, d) attention output, sharded like q.
    """
    t = q.shape[1]
    s = mesh.shape[seq_axis]
    if t % s != 0:
        raise ValueError(f"seq len {t} not divisible by {seq_axis}={s}")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)

    qkv_spec = P(batch_axis, seq_axis, None, None)
    mask_spec = P(None, None)
    body = functools.partial(
        _ring_attention_local, axis_name=seq_axis, scale=scale
    )
    kwargs = dict(
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec if mask is not None else None),
        out_specs=qkv_spec,
    )
    try:
        # jax >= 0.6 renamed the replication check flag check_rep -> check_vma.
        mapped = _shard_map(body, check_vma=False, **kwargs)
    except TypeError:
        mapped = _shard_map(body, check_rep=False, **kwargs)
    return mapped(q, k, v, mask)


def dense_attention_reference(q, k, v, mask=None, scale=None):
    """Single-device reference for testing parity."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum(
        "bshd,bthd->bhst", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if mask is not None:
        logits = jnp.where(mask[None, None].astype(bool), logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
