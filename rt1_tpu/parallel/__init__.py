"""SPMD parallelism: device meshes, sharding rules, and distributed helpers.

This package is the TPU-native replacement for the reference's two comm backends
(SURVEY.md §2.6): PyTorch-Lightning `DDPStrategy` over NCCL
(`distribute_train.py:235`) and `jax.pmap`/`lax.pmean` with axis name "batch"
(`language_table/train/train.py:143-151`). Instead of explicit allreduce calls,
we lay out a single `jax.sharding.Mesh` over the slice and let GSPMD insert XLA
collectives (psum / all-gather / reduce-scatter) over ICI.

Layout policy lives in `plan.py`: one declarative (name-pattern →
PartitionSpec) plan over the ``('data', 'stage', 'fsdp', 'seq', 'model')``
mesh, resolved once from `config.parallel` and consumed identically by train,
eval, and serve — dense/fsdp/tp/pp are config switches, not code paths.
"""

from rt1_tpu.parallel.distributed import (
    DistributedOptions,
    initialize_from_config,
    is_primary,
)
from rt1_tpu.parallel.mesh import MeshConfig, make_mesh
from rt1_tpu.parallel.pipeline import (
    pipeline_apply,
    pp_causal_transformer_apply,
    stack_layer_params,
    unstack_layer_params,
)
from rt1_tpu.parallel.plan import (
    AUTO_MESH_SHAPES,
    PlanCoverageError,
    ShardingPlan,
    auto_mesh_shape,
    mixed_precision_from_config,
    rt1_sharding_plan,
)
from rt1_tpu.parallel.sharding import (
    batch_sharding,
    moe_parameter_rules,
    replicated,
    rt1_parameter_rules,
    shard_pytree,
    sharding_for_path,
)

__all__ = [
    "AUTO_MESH_SHAPES",
    "DistributedOptions",
    "MeshConfig",
    "PlanCoverageError",
    "ShardingPlan",
    "auto_mesh_shape",
    "initialize_from_config",
    "is_primary",
    "make_mesh",
    "batch_sharding",
    "mixed_precision_from_config",
    "moe_parameter_rules",
    "pipeline_apply",
    "pp_causal_transformer_apply",
    "replicated",
    "rt1_parameter_rules",
    "rt1_sharding_plan",
    "shard_pytree",
    "sharding_for_path",
    "stack_layer_params",
    "unstack_layer_params",
]
