"""Sharding rules: map parameter paths / batch pytrees to `NamedSharding`s.

Replaces the reference's implicit "replicate everything" layout (DDP keeps a full
model copy per GPU, `distribute_train.py:235`; `flax_utils.replicate` in Stack B,
`language_table/train/train.py:140`). Here layout is explicit and rule-driven: a
list of (path-regex, PartitionSpec) pairs decides where each parameter lives, and
GSPMD propagates everything else.

Default RT-1 rules implement **tensor parallelism over the `model` axis** for the
transformer (qkv projections column-sharded on heads, output row-sharded, FFN
column-sharded) and replication for everything small (FiLM, norms, embeddings).
With a size-1 `model` axis these all degenerate to pure data parallelism at zero
cost, which is the reference-parity configuration.
"""

from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rule = Tuple[str, P]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over `axis`, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def rt1_parameter_rules() -> List[Rule]:
    """Path-regex → PartitionSpec for RT1Policy parameters.

    Paths are '/'-joined flax param paths, e.g.
    ``transformer/layer_0/attn/query/kernel``. First match wins; no match →
    replicated. Kernel layouts: Dense kernels are (in, out).
    """
    return [
        # Attention qkv: (d_model, heads*key_dim) — shard the head dim (columns).
        (r"transformer/layer_\d+/attn/(query|key|value)/kernel$", P(None, "model")),
        (r"transformer/layer_\d+/attn/(query|key|value)/bias$", P("model")),
        # Attention out: (heads*key_dim, d_model) — shard rows; output needs psum,
        # which GSPMD emits from the contraction.
        (r"transformer/layer_\d+/attn/out/kernel$", P("model", None)),
        # The reference's "FFN" is a single square Dense (transformer.py quirk);
        # column-shard it — the residual add forces a gather which GSPMD places.
        (r"transformer/layer_\d+/ff/kernel$", P(None, "model")),
        (r"transformer/layer_\d+/ff/bias$", P("model")),
        # Vocab head: (d_model, vocab) — column-shard.
        (r"transformer/output_tokens/kernel$", P(None, "model")),
        (r"transformer/output_tokens/bias$", P("model")),
    ] + moe_parameter_rules()


def moe_parameter_rules() -> List[Rule]:
    """Expert parallelism: stacked expert weights (E, d, ff) sharded over
    ``model`` on the expert axis. GSPMD lowers the dispatch/combine einsums
    (models/moe.py) to all-to-alls over ICI; the fp32 router stays
    replicated so every shard routes identically.
    """
    return [
        (r"moe/(wi|wo)$", P("model", None, None)),
    ]


def _path_str(path: Tuple[Any, ...]) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):       # GetAttrKey (dataclass fields, e.g. TrainState)
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def sharding_for_path(
    path: Tuple[Any, ...], mesh: Mesh, rules: Sequence[Rule]
) -> NamedSharding:
    s = _path_str(path)
    for pattern, spec in rules:
        if re.search(pattern, s):
            return NamedSharding(mesh, spec)
    return NamedSharding(mesh, P())


def shard_pytree(tree: Any, mesh: Mesh, rules: Sequence[Rule]) -> Any:
    """A pytree of NamedShardings matching `tree`'s structure, per the rules."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: sharding_for_path(path, mesh, rules), tree
    )
