"""Sharding mechanics: map parameter paths / batch pytrees to `NamedSharding`s.

Replaces the reference's implicit "replicate everything" layout (DDP keeps a full
model copy per GPU, `distribute_train.py:235`; `flax_utils.replicate` in Stack B,
`language_table/train/train.py:140`). Here layout is explicit and rule-driven: a
list of (path-regex, PartitionSpec) pairs decides where each parameter lives, and
GSPMD propagates everything else.

The rules themselves live in ONE place — `rt1_tpu/parallel/plan.py`'s
declarative plan, which covers every RT-1 param group over the
``('data', 'stage', 'fsdp', 'seq', 'model')`` mesh and carries the coverage
check that keeps a renamed module from silently replicating. The historical
entry points below (`rt1_parameter_rules`, `moe_parameter_rules`) are thin
views into that plan; this module keeps the pure mechanics: path
stringification, first-match-wins resolution, pytree mapping.

With every plan axis at size 1 the specs all degenerate to pure data
parallelism at zero cost, which is the reference-parity configuration.
"""

from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rule = Tuple[str, P]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over `axis`, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def rt1_parameter_rules() -> List[Rule]:
    """Path-regex → PartitionSpec for RT1Policy parameters: the full
    declarative plan (plan.py), one rule list for every param group.

    Paths are '/'-joined flax param paths, e.g.
    ``transformer/layer_0/attn/query/kernel``. First match wins; no match →
    replicated (but see `plan.ShardingPlan.coverage` — weight matrices are
    not allowed to fall through silently). Kernel layouts: Dense kernels
    are (in, out).
    """
    from rt1_tpu.parallel import plan as planlib

    return planlib.rt1_sharding_plan()


def moe_parameter_rules() -> List[Rule]:
    """Expert-parallel subset of the plan (stacked expert weights sharded
    over ``model`` on the expert axis; the fp32 router stays replicated so
    every shard routes identically). Kept for callers that shard a bare
    MoE tree; `rt1_parameter_rules` already includes these.
    """
    return [r for r in rt1_parameter_rules() if "moe/" in r[0]]


def _path_str(path: Tuple[Any, ...]) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):       # GetAttrKey (dataclass fields, e.g. TrainState)
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def sharding_for_path(
    path: Tuple[Any, ...], mesh: Mesh, rules: Sequence[Rule]
) -> NamedSharding:
    s = _path_str(path)
    for pattern, spec in rules:
        if re.search(pattern, s):
            return NamedSharding(mesh, spec)
    return NamedSharding(mesh, P())


def spec_for_shape(spec: P, shape: Sequence[int], mesh: Mesh) -> P:
    """`spec` with any axis entry dropped (that dim replicated) when the
    mesh-axes product does not divide the dim.

    The plan's rules are written for the large-config shapes; small
    instantiations hit indivisible dims (EfficientNet SE bottlenecks have
    cout as small as 6, FiLM channels as small as 8) which XLA refuses to
    shard. Replicating such a dim is the intended degradation — the
    tensors for which divisibility fails are precisely the ones too small
    for sharding to matter — and keeps dense/fsdp/tp config switches from
    crashing at placement on any model size.
    """
    if not spec:
        return spec
    dims = []
    changed = False
    for i, entry in enumerate(spec):
        if entry is None or i >= len(shape):
            dims.append(entry)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        ways = 1
        for a in axes:
            ways *= mesh.shape.get(a, 1)
        if ways > 1 and shape[i] % ways != 0:
            dims.append(None)
            changed = True
        else:
            dims.append(entry)
    if not changed:
        return spec
    while dims and dims[-1] is None:  # P(None, ..., None) ≡ P()
        dims.pop()
    return P(*dims)


def shard_pytree(tree: Any, mesh: Mesh, rules: Sequence[Rule]) -> Any:
    """A pytree of NamedShardings matching `tree`'s structure, per the rules
    (indivisible dims fall back per `spec_for_shape`)."""

    def one(path, leaf):
        sh = sharding_for_path(path, mesh, rules)
        shape = getattr(leaf, "shape", None)
        if shape is None:
            return sh
        safe = spec_for_shape(sh.spec, shape, mesh)
        return sh if safe is sh.spec else NamedSharding(mesh, safe)

    return jax.tree_util.tree_map_with_path(one, tree)
