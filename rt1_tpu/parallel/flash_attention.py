"""Fused masked attention as a Pallas TPU kernel.

RT-1's attention is small (66 tokens/window) but latency-critical at
inference: the 10 Hz control loop runs `tokens_per_action`-free single-pass
decoding (`rt1_tpu/models/rt1.py::infer_step`), and at these sizes the
HBM round-trips between the QK^T, mask/softmax, and PV stages dominate over
FLOPs. This kernel keeps the whole (s, s) score matrix in VMEM for one
(batch, head) program: logits, masking, fp32 softmax, and the value matmul
all fuse with zero HBM intermediates.

Scope (documented): forward-only — used for inference; training uses the
XLA dense path (which autodiffs). Whole-sequence blocks are used rather
than a flash-style K/V loop because s^2 fp32 fits VMEM comfortably up to
s ~ 1024 (4 MB); long-context sharding is ring attention's job
(`rt1_tpu/parallel/ring_attention.py`), and this kernel can serve as its
per-shard block compute.

Set `interpret=True` to run on CPU (tests do this; on TPU it lowers to
Mosaic).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e9


def _attention_kernel(q_ref, k_ref, v_ref, mask_ref, out_ref, *, scale):
    """One (batch, head) program: full fused attention in VMEM.

    q_ref/k_ref/v_ref: (1, s, d) blocks; mask_ref: (s, s) int32 or None;
    out_ref: (1, s, d).
    """
    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    logits = jax.lax.dot_general(
        q,
        k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (s, s)
    if mask_ref is not None:
        logits = jnp.where(mask_ref[:] != 0, logits, NEG_INF)
    # Numerically-stable softmax in fp32 on the VPU.
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.exp(logits - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    probs = p / l
    out = jax.lax.dot_general(
        probs,
        v_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    out_ref[0] = out.astype(out_ref.dtype)


def fused_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused multi-head attention. q/k/v: (b, s, h, d); mask: (s, s) 0/1.

    Returns (b, s, h, d), matching
    `rt1_tpu/parallel/ring_attention.py::dense_attention_reference`.
    """
    b, s_in, h, d_in = q.shape
    if scale is None:
        scale = 1.0 / (d_in**0.5)

    # Mosaic tiles fp32 as (8, 128) and bf16 as (16, 128): pad sequence to a
    # multiple of 16 (covers both) and head_dim to a multiple of 128 so the
    # kernel lowers on real TPUs (RT-1's s=66, d=64 is unaligned). Padding
    # changes no real output: padded K/V columns are masked out of every
    # real row, padded Q rows attend only to themselves (keeps their softmax
    # finite) and are sliced away.
    s = -(-s_in // 16) * 16
    d = -(-d_in // 128) * 128
    pad_sd = [(0, 0), (0, s - s_in), (0, 0), (0, d - d_in)]
    if s != s_in or d != d_in:
        q = jnp.pad(q, pad_sd)
        k = jnp.pad(k, pad_sd)
        v = jnp.pad(v, pad_sd)
    if s != s_in:
        # Zero-padded d columns need no masking (they add zeros to the
        # logits); padded sequence positions do.
        if mask is None:
            mask = jnp.ones((s_in, s_in), jnp.int32)
        mask = jnp.pad(mask.astype(jnp.int32), [(0, s - s_in), (0, s - s_in)])
        mask = mask.at[jnp.arange(s_in, s), jnp.arange(s_in, s)].set(1)

    # One grid program per (batch, head): layout as (b*h, s, d).
    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    qbh, kbh, vbh = to_bh(q), to_bh(k), to_bh(v)

    qkv_spec = pl.BlockSpec((1, s, d), lambda i: (i, 0, 0))
    kernel = functools.partial(_attention_kernel, scale=scale)

    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    args = [qbh, kbh, vbh]
    if mask is not None:
        # Mask replicated across programs.
        in_specs.append(pl.BlockSpec((s, s), lambda i: (0, 0)))
        args.append(mask.astype(jnp.int32))
        wrapped = kernel
    else:
        wrapped = lambda q_ref, k_ref, v_ref, out_ref: kernel(
            q_ref, k_ref, v_ref, None, out_ref
        )

    out = pl.pallas_call(
        wrapped,
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        grid=(b * h,),
        in_specs=in_specs,
        out_specs=qkv_spec,
        interpret=interpret,
    )(*args)
    out = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return out[:, :s_in, :, :d_in]
