"""On-device image preprocessing ops.

TPU-native replacement for `pytorch_robotics_transformer/film_efficientnet/
preprocessors.py:37-56` (`convert_dtype_and_crop_images`): uint8→[0,1] conversion and
the pad-±ratio / random-shift-crop-back augmentation. The reference builds a meshgrid
and fancy-indexes on GPU; here the crop is a single `lax.dynamic_slice` on the padded
image — static output shape, fuses cleanly under jit, and vmaps over the batch.

Layout note: all rt1_tpu image ops are NHWC (TPU-preferred), vs the reference's NCHW.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def convert_dtype(images: jnp.ndarray) -> jnp.ndarray:
    """uint8 [0,255] → float32 [0,1]; float inputs pass through as float32."""
    if images.dtype == jnp.uint8:
        images = images.astype(jnp.float32) / 255.0
    return images.astype(jnp.float32)


def random_shift_crop(
    images: jnp.ndarray,
    rng: jax.Array,
    ratio: float = 0.07,
) -> jnp.ndarray:
    """Pad H/W by `int(dim * ratio)` each side, crop back at a random offset.

    Matches preprocessors.py:42-54: one shift is drawn per *batch* (the reference
    draws a single (shif_h, shif_w) for the whole batch), offsets uniform over
    [0, 2*pad] inclusive. Input/output: (..., H, W, C), any leading batch dims.
    """
    h, w = images.shape[-3], images.shape[-2]
    ud_pad = int(h * ratio)
    lr_pad = int(w * ratio)
    pad_cfg = [(0, 0)] * (images.ndim - 3) + [(ud_pad, ud_pad), (lr_pad, lr_pad), (0, 0)]
    padded = jnp.pad(images, pad_cfg)
    rng_h, rng_w = jax.random.split(rng)
    shift_h = jax.random.randint(rng_h, (), 0, 2 * ud_pad + 1)
    shift_w = jax.random.randint(rng_w, (), 0, 2 * lr_pad + 1)
    starts = [jnp.zeros((), jnp.int32)] * (images.ndim - 3) + [shift_h, shift_w, jnp.zeros((), jnp.int32)]
    return lax.dynamic_slice(padded, starts, images.shape)


def convert_dtype_and_crop_images(
    images: jnp.ndarray,
    rng: jax.Array | None = None,
    ratio: float = 0.07,
    train: bool = True,
) -> jnp.ndarray:
    """dtype conversion + (train only) random shift crop, as one fused op."""
    images = convert_dtype(images)
    if train and rng is not None and ratio > 0:
        images = random_shift_crop(images, rng, ratio)
    return images


def resize_bilinear(images: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """Bilinear resize to (height, width); NHWC, any leading dims."""
    shape = images.shape[:-3] + (height, width, images.shape[-1])
    return jax.image.resize(images, shape, method="bilinear")


def central_crop_and_resize(
    images: jnp.ndarray, crop_factor: float, height: int, width: int
) -> jnp.ndarray:
    """Deterministic center crop by `crop_factor` then resize.

    Eval-side equivalent of the train random crop — mirrors
    `language_table/eval/wrappers.py:99-123` (`CentralCropImageWrapper`).
    """
    h, w = images.shape[-3], images.shape[-2]
    ch = int(h * crop_factor)
    cw = int(w * crop_factor)
    top = (h - ch) // 2
    left = (w - cw) // 2
    cropped = images[..., top : top + ch, left : left + cw, :]
    return resize_bilinear(cropped, height, width)
