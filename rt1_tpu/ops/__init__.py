"""Low-level ops: image preprocessing, attention primitives, Pallas kernels."""
