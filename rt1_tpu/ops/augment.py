"""Photometric distortion augmentations (jnp, jit-friendly).

Parity source: reference `language_table/train/input_pipeline_rlds.py:
391-457` (`PhotometricDistortions`): per-video uniform brightness,
saturation, hue, and contrast jitter, applied in that order with TF image
semantics. Implemented in pure jnp (RGB<->HSV round trip included) so the
augmentation can run on-device fused into the input pipeline instead of on
host CPU.

All functions take images in [0, 1] float, shape (..., H, W, 3).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


def rgb_to_hsv(rgb: jnp.ndarray) -> jnp.ndarray:
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = jnp.max(rgb, axis=-1)
    minc = jnp.min(rgb, axis=-1)
    v = maxc
    delta = maxc - minc
    safe_delta = jnp.where(delta == 0, 1.0, delta)
    s = jnp.where(maxc == 0, 0.0, delta / jnp.where(maxc == 0, 1.0, maxc))
    rc = (maxc - r) / safe_delta
    gc = (maxc - g) / safe_delta
    bc = (maxc - b) / safe_delta
    h = jnp.where(
        maxc == r,
        bc - gc,
        jnp.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc),
    )
    h = jnp.where(delta == 0, 0.0, (h / 6.0) % 1.0)
    return jnp.stack([h, s, v], axis=-1)


def hsv_to_rgb(hsv: jnp.ndarray) -> jnp.ndarray:
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [v, q, p, p, t, v])
    g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [t, v, v, q, p, p])
    b = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [p, p, t, v, v, q])
    return jnp.stack([r, g, b], axis=-1)


def adjust_brightness(images: jnp.ndarray, delta) -> jnp.ndarray:
    return jnp.clip(images + delta, 0.0, 1.0)


def adjust_contrast(images: jnp.ndarray, factor) -> jnp.ndarray:
    """TF semantics: interpolate toward the per-channel spatial mean."""
    mean = jnp.mean(images, axis=(-3, -2), keepdims=True)
    return jnp.clip((images - mean) * factor + mean, 0.0, 1.0)


def adjust_saturation(images: jnp.ndarray, factor) -> jnp.ndarray:
    hsv = rgb_to_hsv(images)
    hsv = hsv.at[..., 1].set(jnp.clip(hsv[..., 1] * factor, 0.0, 1.0))
    return hsv_to_rgb(hsv)


def adjust_hue(images: jnp.ndarray, delta) -> jnp.ndarray:
    hsv = rgb_to_hsv(images)
    hsv = hsv.at[..., 0].set((hsv[..., 0] + delta) % 1.0)
    return hsv_to_rgb(hsv)


@dataclasses.dataclass(frozen=True)
class PhotometricConfig:
    brightness_max_delta: float = 0.1
    contrast_lower: float = 0.8
    contrast_upper: float = 1.2
    hue_max_delta: float = 0.03
    saturation_lower: float = 0.8
    saturation_upper: float = 1.2


def photometric_distortions(
    images: jnp.ndarray,
    rng: jax.Array,
    config: Optional[PhotometricConfig] = None,
) -> jnp.ndarray:
    """One uniform distortion level per call (per video), reference order:
    brightness -> saturation -> hue -> contrast."""
    config = config or PhotometricConfig()
    r0, r1, r2, r3 = jax.random.split(rng, 4)
    if config.brightness_max_delta:
        delta = jax.random.uniform(
            r0,
            minval=-config.brightness_max_delta,
            maxval=config.brightness_max_delta,
        )
        images = adjust_brightness(images, delta)
    if config.saturation_lower != 1.0 or config.saturation_upper != 1.0:
        factor = jax.random.uniform(
            r1, minval=config.saturation_lower, maxval=config.saturation_upper
        )
        images = adjust_saturation(images, factor)
    if config.hue_max_delta:
        delta = jax.random.uniform(
            r2, minval=-config.hue_max_delta, maxval=config.hue_max_delta
        )
        images = adjust_hue(images, delta)
    if config.contrast_lower != 1.0 or config.contrast_upper != 1.0:
        factor = jax.random.uniform(
            r3, minval=config.contrast_lower, maxval=config.contrast_upper
        )
        images = adjust_contrast(images, factor)
    return images
