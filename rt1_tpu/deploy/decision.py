"""Canary judgement: per-replica burn signals in, hold/promote/rollback out.

The deploy controller must answer one question every watch tick: is the
canary replica burning error budget faster than the incumbent fleet, or
has it served cleanly long enough to trust fleet-wide? This module is
that answer as a pure, clock-free decision function — the same shape as
`serve/autoscale.py`'s policy brain, for the same reason: the mechanics
(reload, demote, re-home) live in the controller, the *judgement* is
unit-testable with fabricated signals and stays importable in the
clu/TF-free supervisor process (`tests/test_obs_imports.py`).

Hysteresis, both directions:

* **rollback** needs `breach_ticks` CONSECUTIVE breach ticks — one bad
  scrape (a p99 blip, a single failed request in a tiny window) must
  not demote a healthy candidate.
* **promote** needs `clean_window_ticks` CONSECUTIVE clean ticks with
  real evidence (`min_canary_requests` served) — a canary that nobody
  talked to has proven nothing, so low-traffic ticks hold without
  advancing the clean streak.

A breach is *relative*: the canary's rolling burn must clear the
absolute threshold AND strictly exceed the incumbent fleet's — a
fleet-wide incident (dependency down, host thrash) burns every replica
alike and must not scapegoat the candidate that happened to be canary.
A canary that stops being routable (died, wedged) is a breach outright:
whatever killed it, the candidate failed to serve.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class CanaryPolicy:
    """The burn-window contract a canary is judged against.

    ``burn_threshold`` is in rolling error-budget-burn units (1.0 =
    spending budget exactly at the objective rate; the autoscaler's
    pressure default is 2.0). ``breach_ticks`` / ``clean_window_ticks``
    are consecutive watch ticks (hysteresis); ``min_canary_requests`` is
    the evidence floor below which a clean tick proves nothing.
    """

    burn_threshold: float = 2.0
    breach_ticks: int = 2
    clean_window_ticks: int = 5
    min_canary_requests: int = 8
    canary_weight: float = 0.25

    def __post_init__(self):
        if self.burn_threshold <= 0:
            raise ValueError(
                f"burn_threshold must be > 0, got {self.burn_threshold}"
            )
        if self.breach_ticks < 1 or self.clean_window_ticks < 1:
            raise ValueError(
                f"breach_ticks/clean_window_ticks must be >= 1, got "
                f"{self.breach_ticks}/{self.clean_window_ticks}"
            )
        if self.min_canary_requests < 0:
            raise ValueError(
                f"min_canary_requests must be >= 0, got "
                f"{self.min_canary_requests}"
            )
        if not 0.0 < self.canary_weight <= 1.0:
            raise ValueError(
                f"canary_weight must be in (0, 1], got {self.canary_weight}"
            )

    def as_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class CanarySignals:
    """One watch tick's router-observed canary state (no clocks).

    ``canary_burn`` / ``fleet_burn`` are rolling error-budget burns from
    the router's per-replica SLO attribution: the canary's own ledger vs.
    the worst incumbent replica's (the relative-breach reference).
    ``canary_requests`` is the canary ledger's total since it was loaded;
    ``canary_ready`` is the router's view of the replica state.
    """

    canary_requests: int
    canary_burn: float
    fleet_burn: float = 0.0
    canary_ready: bool = True


class CanaryJudge:
    """Streak accumulator over per-tick signals -> hold/promote/rollback.

    One judge per canary episode: the controller constructs a fresh one
    (or calls `reset()`) when a candidate lands on the canary replica,
    then feeds it every watch tick. Decisions are sticky only through
    the streak counters — the judge never remembers a verdict."""

    def __init__(self, policy: Optional[CanaryPolicy] = None):
        self.policy = policy or CanaryPolicy()
        self.breach_streak = 0
        self.clean_streak = 0

    def reset(self) -> None:
        self.breach_streak = 0
        self.clean_streak = 0

    def is_breach(self, signals: CanarySignals) -> bool:
        """One tick's breach predicate: canary unroutable, or its burn
        clears the threshold while STRICTLY exceeding the incumbent
        fleet's (a fleet-wide incident never scapegoats the canary)."""
        if not signals.canary_ready:
            return True
        return (
            signals.canary_burn >= self.policy.burn_threshold
            and signals.canary_burn > signals.fleet_burn
        )

    def decide(self, signals: CanarySignals) -> str:
        """Advance the streaks with one tick's signals and judge.

        Returns ``"rollback"`` | ``"promote"`` | ``"hold"``. Breach is
        checked before the evidence floor — a canary that is already
        burning needs no more requests to be condemned — while a clean
        low-traffic tick holds WITHOUT advancing either streak (no
        evidence, no verdict movement)."""
        if self.is_breach(signals):
            self.breach_streak += 1
            self.clean_streak = 0
            if self.breach_streak >= self.policy.breach_ticks:
                return "rollback"
            return "hold"
        if signals.canary_requests < self.policy.min_canary_requests:
            return "hold"
        self.clean_streak += 1
        self.breach_streak = 0
        if self.clean_streak >= self.policy.clean_window_ticks:
            return "promote"
        return "hold"
