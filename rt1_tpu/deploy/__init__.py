"""Continuous deployment: eval-gated promotion, weighted canary,
SLO-burn auto-rollback.

The repo's last human-in-the-loop step: training writes checkpoints,
serving hot-swaps them, the eval matrix judges them — but a person still
glues those together. This package closes the collect -> train ->
**deploy** -> serve loop:

* `watcher`   — torn-write-tolerant checkpoint discovery on a train
                workdir (the candidate source).
* `decision`  — the pure burn-window/hysteresis judge: canary signals
                in, hold | promote | rollback out.
* `verdict`   — signed promotion-verdict artifacts (HMAC over canonical
                JSON) so "who approved this checkpoint" is evidence,
                not a log line.
* `gate`      — the offline promotion gate: eval-matrix cells vs. the
                incumbent + the serve parity check (jax-heavy, imported
                lazily).
* `controller`— the PromotionController state machine driving the fleet
                router: gate -> canary one replica at a weighted
                fraction of fresh sessions -> watch per-replica burn ->
                promote fleet-wide (rolling reload) or auto-roll-back.

Everything except `gate` is import-light (stdlib only — pinned by
`tests/test_obs_imports.py`): the controller runs inside the fleet
supervisor process, which must never pay jax/TF import cost.
"""

from rt1_tpu.deploy.decision import (  # noqa: F401
    CanaryJudge,
    CanaryPolicy,
    CanarySignals,
)
from rt1_tpu.deploy.watcher import (  # noqa: F401
    CheckpointWatcher,
    latest_checkpoint_step,
)
