"""PromotionController: the deploy state machine driving the fleet router.

The closed loop the ROADMAP names, one tick at a time:

    IDLE  --new checkpoint--> gate (inline, synchronous)
          --gate passed----> canary load (reload_one) + weighted split
    CANARY --clean window--> promote: rolling reload fleet-wide, clear
                             the split (canary sessions stay — they are
                             already on the promoted params)
           --burn breach---> rollback: demote the canary (sessions
                             re-home via failover, ``restarted: true``),
                             hot-swap the incumbent back onto the
                             canary replica; the incumbent fleet is
                             never touched

The controller owns no mechanism: checkpoint discovery is the torn-dir
tolerant `watcher`, the verdict is the injected ``gate_fn`` (auto-pass
for stub fleets, `deploy/gate.build_gate_fn` for real ones — signed to
disk either way via `verdict`), the traffic split and per-replica burn
attribution live in `serve/router.py`, and the promote/rollback
judgement is the pure `decision.CanaryJudge`. What remains here is the
state machine, its evidence (timeline events, ``rt1_deploy_*`` gauges,
the run-report summary), and the two chaos sites:

* ``promote@N`` — the N-th fleet-wide promote attempt raises before the
  roll starts; the controller must roll the canary back and leave the
  incumbent serving.
* ``canary_slo_breach@N`` — forces the observed canary burn over the
  threshold starting at canary-watch tick N (synthetic breach: client
  traffic stays clean; what's under test is the rollback path).

Import-light (stdlib + router/decision/watcher/verdict/faults — pinned
by `tests/test_obs_imports.py`): the controller thread lives inside the
fleet supervisor process, which never pays jax/TF import cost.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from rt1_tpu.deploy import verdict as verdict_lib
from rt1_tpu.deploy.decision import CanaryJudge, CanaryPolicy, CanarySignals
from rt1_tpu.deploy.watcher import CheckpointWatcher
from rt1_tpu.resilience import faults
from rt1_tpu.serve.router import READY, Router

IDLE = "idle"
CANARY = "canary"

#: Watch-log ring bound: per-tick canary signals kept for the post-mortem
#: (the timeline keeps only state TRANSITIONS, so a long clean canary
#: doesn't bloat the summary).
WATCH_LOG_LIMIT = 256


class PromotionController:
    """Eval-gated promotion with router-weighted canary + auto-rollback.

    ``gate_fn(candidate_step, incumbent_step) -> verdict dict`` (must
    carry ``passed``); everything else is knobs. Drive it with
    :meth:`tick` (tests, and the E2E driver's deterministic loop) or
    :meth:`start` (a daemon thread ticking every ``poll_interval_s``,
    the `--promote_from` supervisor arm).
    """

    def __init__(
        self,
        router: Router,
        workdir: str,
        *,
        gate_fn: Callable[[int, Optional[int]], Dict[str, Any]],
        policy: Optional[CanaryPolicy] = None,
        incumbent_step: Optional[int] = None,
        poll_interval_s: float = 1.0,
        verdict_dir: Optional[str] = None,
        signing_key: Optional[str] = None,
        min_incumbent_replicas: int = 1,
    ):
        self.router = router
        self.workdir = workdir
        self.gate_fn = gate_fn
        self.policy = policy or CanaryPolicy()
        self.poll_interval_s = poll_interval_s
        # The watcher's high-water mark starts at the incumbent: the
        # checkpoint the fleet booted from is not a candidate.
        self.watcher = CheckpointWatcher(workdir, seen_through=incumbent_step)
        self.incumbent_step = incumbent_step
        self.verdict_dir = verdict_dir or os.path.join(workdir, "deploy")
        self.signing_key = signing_key or verdict_lib.signing_key(
            self.verdict_dir
        )
        # A canary needs an incumbent fleet to compare against (and to
        # keep serving if it breaches): never canary below this many
        # OTHER ready replicas.
        self.min_incumbent_replicas = min_incumbent_replicas

        self.state = IDLE
        self.ticks = 0
        self.canary_tick = 0  # monotonic across episodes: the chaos index
        self.candidates_seen = 0
        self.gates_passed = 0
        self.gates_failed = 0
        self.promotions = 0
        self.rollbacks = 0
        self.promote_attempts = 0
        self.errors = 0
        self.timeline: List[Dict[str, Any]] = []
        self.watch_log: List[Dict[str, Any]] = []
        self.verdict_paths: List[str] = []

        self._judge = CanaryJudge(self.policy)
        self._candidate: Optional[int] = None
        self._canary_rid: Optional[int] = None
        self._canary_baseline = 0
        self._synthetic_breach = False  # latched for the canary episode
        # Last judged canary/fleet burn pair, held for the scrape between
        # ticks (0.0 while IDLE) — the `rt1_deploy_canary_burn` family
        # the CanarySLOBreach alert watches. Includes a synthetic breach's
        # forced burn: the alert plane must see exactly what the judge
        # saw, or a chaos-proved rollback would be alert-invisible.
        self._last_canary_burn = 0.0
        self._last_fleet_burn = 0.0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="rt1-deploy-controller", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must survive
                self.errors += 1
                self._event("error", error=traceback.format_exc(limit=5))
            self._stop.wait(self.poll_interval_s)

    # ----------------------------------------------------------- the ticks

    def tick(self) -> None:
        """One controller step: candidate discovery + gate while IDLE,
        one burn-window judgement while CANARY."""
        with self._lock:
            self.ticks += 1
            state = self.state
        # Only this thread mutates controller state, so dispatching on a
        # snapshot is safe — and _tick_idle must run the (minutes-long,
        # jax-heavy) gate WITHOUT the lock or every scrape of
        # /deploy/status and the rt1_deploy_* families would block on it.
        if state == IDLE:
            self._tick_idle()
        elif state == CANARY:
            with self._lock:
                self._tick_canary()

    def _event(self, event: str, **fields: Any) -> Dict[str, Any]:
        entry = {
            "tick": self.ticks,
            "unix_time": round(time.time(), 3),
            "event": event,
            **fields,
        }
        self.timeline.append(entry)
        return entry

    def _tick_idle(self) -> None:
        with self._lock:
            step = self.watcher.poll()
            if step is None:
                return
            self.candidates_seen += 1
            incumbent = self.incumbent_step
            self._event("candidate", step=step, incumbent=incumbent)
        # The gate runs unlocked: scrapes stay live while it evals.
        try:
            verdict = self.gate_fn(step, incumbent)
        except Exception as exc:  # noqa: BLE001 - a crashed gate rejects
            verdict = {"passed": False, "error": str(exc)}
        with self._lock:
            verdict = dict(verdict)
            verdict.setdefault("candidate_step", step)
            verdict.setdefault("incumbent_step", incumbent)
            path = os.path.join(self.verdict_dir, f"verdict_{step}.json")
            verdict_lib.write_verdict(path, verdict, self.signing_key)
            self.verdict_paths.append(path)
            if not verdict.get("passed"):
                self.gates_failed += 1
                self._event("gate_rejected", step=step, verdict_path=path)
                return
            self.gates_passed += 1
            self._event("gate_passed", step=step, verdict_path=path)
            self._start_canary(step)

    def _pick_canary(self) -> Optional[int]:
        """Highest-id READY replica, and only when enough OTHER ready
        replicas remain to hold the incumbent fleet. Highest id = the
        newest slot — base-tier low ids keep serving the steady state,
        mirroring the placement tiebreak."""
        ready = sorted(
            r.id for r in self.router.replicas() if r.state == READY
        )
        if len(ready) < self.min_incumbent_replicas + 1:
            return None
        return ready[-1]

    def _start_canary(self, step: int) -> None:
        rid = self._pick_canary()
        if rid is None:
            # No capacity to canary: the candidate stays gated-approved
            # but undeployed; surface it and retry on a later checkpoint
            # (the fleet is degraded — deploying into it would be worse).
            self._event("canary_unplaceable", step=step)
            return
        entry = self.router.reload_one(rid, step)
        if entry.get("status") != 200 or entry.get("recovered") is False:
            self._event("canary_load_failed", step=step, reload=entry)
            # Best effort: put the incumbent back on the replica.
            if self.incumbent_step is not None:
                self.router.reload_one(rid, self.incumbent_step)
            return
        snap = self.router.replica_slo_snapshot().get(rid, {})
        self._canary_baseline = int(snap.get("requests_total", 0))
        self._candidate = step
        self._canary_rid = rid
        self._judge.reset()
        self.router.set_canary(rid, self.policy.canary_weight)
        self.state = CANARY
        self._event(
            "canary_started",
            step=step,
            replica=rid,
            weight=self.policy.canary_weight,
        )

    def _tick_canary(self) -> None:
        self.canary_tick += 1
        rid = self._canary_rid
        snap = self.router.replica_slo_snapshot()
        entry = snap.get(rid, {})
        requests = int(entry.get("requests_total", 0)) - self._canary_baseline
        burn = float(entry.get("error_budget_burn_rolling", 0.0))
        fleet_burn = max(
            (
                float(e.get("error_budget_burn_rolling", 0.0))
                for r, e in snap.items()
                if r != rid
            ),
            default=0.0,
        )
        ready = any(
            r.id == rid and r.state == READY for r in self.router.replicas()
        )
        plan = faults.active()
        if (
            plan is not None
            and plan.should_fire("canary_slo_breach", index=self.canary_tick)
        ):
            # Latched for the rest of the episode: a real burn breach is
            # persistent too (the rolling window keeps reporting it), and
            # the rollback needs `breach_ticks` CONSECUTIVE breach ticks —
            # a one-tick blip is exactly what the hysteresis ignores.
            self._synthetic_breach = True
        synthetic = self._synthetic_breach
        if synthetic:
            # Synthetic breach: the observed burn is forced over both the
            # absolute threshold and the relative (strictly-above-fleet)
            # bar. Client traffic stays clean — the rollback PATH is what
            # the chaos run proves.
            burn = max(burn, self.policy.burn_threshold + fleet_burn)
        self._last_canary_burn = burn
        self._last_fleet_burn = fleet_burn
        signals = CanarySignals(
            canary_requests=max(requests, 0),
            canary_burn=burn,
            fleet_burn=fleet_burn,
            canary_ready=ready,
        )
        decision = self._judge.decide(signals)
        self.watch_log.append(
            {
                "canary_tick": self.canary_tick,
                "requests": signals.canary_requests,
                "burn": round(burn, 4),
                "fleet_burn": round(fleet_burn, 4),
                "ready": ready,
                "synthetic_breach": synthetic,
                "breach_streak": self._judge.breach_streak,
                "clean_streak": self._judge.clean_streak,
                "decision": decision,
            }
        )
        del self.watch_log[:-WATCH_LOG_LIMIT]
        if decision == "rollback":
            reason = "canary_died" if not ready else "slo_breach"
            if synthetic:
                reason = "slo_breach_injected"
            self._rollback(reason=reason, fleet_wide=False)
        elif decision == "promote":
            self._promote()

    def _promote(self) -> None:
        step = self._candidate
        self.promote_attempts += 1
        try:
            faults.maybe_fail(
                "promote", index=self.promote_attempts,
                what=f"fleet-wide promote of step {step}",
            )
            results = self.router.rolling_reload(step)
            failed = [
                r
                for r in results
                if r.get("status") != 200 or r.get("recovered") is False
            ]
            if failed:
                raise OSError(f"rolling reload failed: {failed}")
        except OSError as exc:
            self._event("promote_failed", step=step, error=str(exc))
            # A partial roll may have landed the candidate on some
            # replicas: the rollback is fleet-wide (idempotent for the
            # untouched ones).
            self._rollback(reason=f"promote_failed: {exc}", fleet_wide=True)
            return
        self.router.clear_canary()
        self.promotions += 1
        self._event(
            "promoted",
            step=step,
            previous_incumbent=self.incumbent_step,
            replicas=len(results),
        )
        self.incumbent_step = step
        self._end_canary()

    def _rollback(self, reason: str, fleet_wide: bool) -> None:
        step = self._candidate
        rid = self.router.demote_canary()
        restored: Any = None
        if self.incumbent_step is not None:
            if fleet_wide:
                restored = self.router.rolling_reload(self.incumbent_step)
            elif rid is not None:
                restored = self.router.reload_one(rid, self.incumbent_step)
        self.rollbacks += 1
        self._event(
            "rolled_back",
            step=step,
            replica=rid,
            reason=reason,
            incumbent=self.incumbent_step,
            restore=restored,
        )
        self._end_canary()

    def _end_canary(self) -> None:
        self._candidate = None
        self._canary_rid = None
        self._canary_baseline = 0
        self._synthetic_breach = False
        self._last_canary_burn = 0.0
        self._last_fleet_burn = 0.0
        self._judge.reset()
        self.state = IDLE

    # ------------------------------------------------------------ reporting

    def deploy_gauges(self) -> Dict[str, Any]:
        """Flat ``rt1_deploy_*`` scrape payload (strings render as
        info-style families, ``*_total`` as counters, the rest gauges —
        `obs/prometheus.render_deploy_snapshot`)."""
        with self._lock:
            return {
                "state": self.state,
                "ticks_total": self.ticks,
                "canary_ticks_total": self.canary_tick,
                "candidates_seen_total": self.candidates_seen,
                "gates_passed_total": self.gates_passed,
                "gates_failed_total": self.gates_failed,
                "promotions_total": self.promotions,
                "rollbacks_total": self.rollbacks,
                "promote_attempts_total": self.promote_attempts,
                "controller_errors_total": self.errors,
                "incumbent_step": (
                    -1 if self.incumbent_step is None else self.incumbent_step
                ),
                "candidate_step": (
                    -1 if self._candidate is None else self._candidate
                ),
                "canary_replica_id": (
                    -1 if self._canary_rid is None else self._canary_rid
                ),
                "canary_weight": self.policy.canary_weight,
                "canary_burn": self._last_canary_burn,
                "fleet_burn": self._last_fleet_burn,
                "breach_streak": self._judge.breach_streak,
                "clean_streak": self._judge.clean_streak,
            }

    def summary(self) -> Dict[str, Any]:
        """The post-mortem payload: gauges + policy + the full promotion
        timeline + the canary watch-log tail + verdict artifact paths."""
        with self._lock:
            return {
                **self.deploy_gauges(),
                "policy": self.policy.as_dict(),
                "workdir": self.workdir,
                "verdicts": list(self.verdict_paths),
                "timeline": list(self.timeline),
                "watch_log": list(self.watch_log),
            }
