"""Signed promotion-verdict artifacts: who approved this checkpoint.

A promotion decision outlives the process that made it — an incident
review three days later needs to know WHICH gate run (cells, episodes,
parity stats) approved the checkpoint now serving, and that the artifact
on disk is the one the controller wrote, not a hand-edited JSON. The
verdict is therefore signed: HMAC-SHA256 over the canonical JSON
encoding (sorted keys, fixed separators — byte-stable across Python
runs), keyed by a deployment secret.

Key resolution (``signing_key``): the ``RT1_DEPLOY_KEY`` env var when
set (fleet operators inject one key across controller + verifiers),
else a per-workdir key file generated once (`deploy_key`, mode 0600) —
so a single-host loop is signed out of the box without key management.

This is tamper-EVIDENCE, not secrecy: the payload stays readable JSON,
and anyone holding the key can re-sign. Stdlib only (hashlib/hmac/json)
— the controller process must stay import-light
(`tests/test_obs_imports.py`).
"""

from __future__ import annotations

import hmac
import hashlib
import json
import os
from typing import Any, Dict, Optional, Tuple

ENV_KEY = "RT1_DEPLOY_KEY"
KEY_BASENAME = "deploy_key"
SIGNATURE_FIELD = "signature"


def canonical_bytes(payload: Dict[str, Any]) -> bytes:
    """Byte-stable encoding the signature covers (sorted keys, no
    whitespace variance). The signature field itself is excluded."""
    clean = {k: v for k, v in payload.items() if k != SIGNATURE_FIELD}
    return json.dumps(
        clean, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")


def signing_key(workdir: str) -> str:
    """Resolve the deployment signing key: env var, else a generated
    per-workdir key file (created once, 0600)."""
    env = os.environ.get(ENV_KEY)
    if env:
        return env
    path = os.path.join(workdir, KEY_BASENAME)
    if os.path.exists(path):
        with open(path) as f:
            return f.read().strip()
    os.makedirs(workdir, exist_ok=True)
    key = os.urandom(32).hex()
    tmp = path + ".tmp"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(key)
    os.replace(tmp, path)
    return key


def sign_payload(payload: Dict[str, Any], key: str) -> str:
    return hmac.new(
        key.encode("utf-8"), canonical_bytes(payload), hashlib.sha256
    ).hexdigest()


def write_verdict(
    path: str, payload: Dict[str, Any], key: str
) -> Dict[str, Any]:
    """Sign `payload` and write it atomically (tmp + rename, the repo's
    artifact convention). Returns the signed payload."""
    signed = {k: v for k, v in payload.items() if k != SIGNATURE_FIELD}
    signed[SIGNATURE_FIELD] = sign_payload(signed, key)
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(signed, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return signed


def verify_verdict(
    path: str, key: str
) -> Tuple[Optional[Dict[str, Any]], bool]:
    """Read a verdict artifact -> (payload, signature_ok). A missing or
    torn file is (None, False) — absence is a verification failure, not
    an exception (the run-report renders what it can prove)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None, False
    if not isinstance(payload, dict):
        return None, False
    recorded = payload.get(SIGNATURE_FIELD)
    if not isinstance(recorded, str):
        return payload, False
    expected = sign_payload(payload, key)
    return payload, hmac.compare_digest(recorded, expected)
