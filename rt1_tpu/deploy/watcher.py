"""Checkpoint discovery on a live train workdir, torn-write tolerant.

The deploy controller watches the trainer's output directory for new
candidate steps. The scan mirrors `trainer/checkpoints.latest_step`
EXACTLY — Orbax step dirs are plain integer-named directories, in-flight
writes are `<step>.orbax-checkpoint-tmp-<ts>` dirs that fail the digit
check, and an empty integer dir (mkdir landed, contents didn't) is not a
checkpoint — but lives here as a local replica because importing
`trainer.checkpoints` drags the full orbax/flax context into the
supervisor process, which must stay jax-free
(`tests/test_obs_imports.py`). `tests/test_deploy.py` pins the two
implementations to identical answers on the same directory.
"""

from __future__ import annotations

import os
from typing import List, Optional


def latest_checkpoint_step(ckpt_dir: str) -> Optional[int]:
    """Newest complete checkpoint step under `ckpt_dir`, or None.

    Import-light twin of `rt1_tpu.trainer.checkpoints.latest_step` (same
    tmp-dir and torn-write tolerance, zero orbax imports)."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for d in os.listdir(ckpt_dir):
        if not d.isdigit():
            continue  # Orbax tmp dirs and sidecar files
        full = os.path.join(ckpt_dir, d)
        try:
            if not os.path.isdir(full) or not os.listdir(full):
                continue
        except OSError:
            continue
        steps.append(int(d))
    return max(steps) if steps else None


class CheckpointWatcher:
    """Poll a train workdir for steps newer than any already seen.

    ``poll()`` returns a NEW candidate step exactly once (then remembers
    it), so the controller's tick loop can call it unconditionally. Steps
    at or below the high-water mark — including the incumbent the fleet
    booted from, and candidates already gated-and-rejected — are never
    re-surfaced; `dismiss(step)` raises the mark explicitly when a
    candidate is disposed of out of band."""

    def __init__(self, workdir: str, *, seen_through: Optional[int] = None):
        self.workdir = workdir
        self.ckpt_dir = os.path.join(workdir, "checkpoints")
        # High-water mark: poll() only surfaces steps strictly above it.
        self.seen_through = -1 if seen_through is None else seen_through
        self.polls = 0

    def poll(self) -> Optional[int]:
        self.polls += 1
        step = latest_checkpoint_step(self.ckpt_dir)
        if step is None or step <= self.seen_through:
            return None
        self.seen_through = step
        return step

    def pending_steps(self) -> List[int]:
        """Every complete step currently on disk (ascending) — the
        run-report provenance view, not the dedup path."""
        if not os.path.isdir(self.ckpt_dir):
            return []
        steps = []
        for d in os.listdir(self.ckpt_dir):
            if not d.isdigit():
                continue
            full = os.path.join(self.ckpt_dir, d)
            try:
                if not os.path.isdir(full) or not os.listdir(full):
                    continue
            except OSError:
                continue
            steps.append(int(d))
        return sorted(steps)

    def dismiss(self, step: int) -> None:
        self.seen_through = max(self.seen_through, step)
