"""The real (jax-heavy) promotion gate: eval matrix + serve parity.

Composes the two offline judgements the ISSUE names into one verdict
payload for the controller:

* **eval-matrix gate** (`eval/matrix.run_gate`): closed-loop success of
  the candidate checkpoint vs. the incumbent on the same task grid,
  lazy per-column restore — one parameter set resident at a time.
* **parity gate** (`serve/parity.check_parity`): the candidate restored
  into a serving engine at the fleet's inference dtype must agree with
  its own f32 reference on ≥99% of action tokens over the canned
  episode set — the same bar a quantized replica must clear before it
  serves (`tests/test_quant.py`). Catches a checkpoint that evals fine
  but quantizes badly BEFORE it touches a live replica.

Everything heavy imports lazily inside the functions: the module itself
must stay importable in the blocker-pinned controller process
(`tests/test_obs_imports.py`); only *calling* the gate pays the jax
context. The stub fleet path injects an auto-pass `gate_fn` instead and
never imports this module's internals.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence


def load_config(path: str):
    """Execute a train config file (`rt1_tpu/train/configs/*.py`) and
    return its ``get_config()``. The fleet supervisor is argparse-based
    (no absl/config_flags in that process); this is the minimal loader
    so ``--promote_from`` can bind the real gate to the same config file
    the replicas were launched with."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("rt1_deploy_gate_cfg", path)
    if spec is None or spec.loader is None:
        raise ValueError(f"cannot load config file: {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.get_config()


def run_parity_gate(
    config,
    workdir: str,
    step: int,
    *,
    inference_dtype: str = "f32",
    threshold: Optional[float] = None,
    episodes: int = 2,
    steps: int = 4,
) -> Dict[str, Any]:
    """Restore `step` twice — f32 reference + serving dtype — and run the
    action-token parity check. Returns the stats dict; a failed gate
    returns ``passed: False`` (the ValueError is caught and folded in)
    so the controller records a rejection instead of crashing the loop."""
    from rt1_tpu.eval.restore import build_serve_engine
    from rt1_tpu.serve import parity

    engine_ref, _ = build_serve_engine(
        config, workdir=workdir, step=step, inference_dtype="f32"
    )
    engine_test, _ = build_serve_engine(
        config, workdir=workdir, step=step, inference_dtype=inference_dtype
    )
    shape = (config.data.height, config.data.width, 3)
    kwargs: Dict[str, Any] = {"episodes": episodes, "steps": steps}
    if threshold is not None:
        kwargs["threshold"] = threshold
    try:
        stats = parity.check_parity(
            engine_ref, engine_test, shape, **kwargs
        )
    except ValueError as exc:
        return {
            "passed": False,
            "inference_dtype": inference_dtype,
            "error": str(exc),
        }
    stats["inference_dtype"] = inference_dtype
    return stats


def build_gate_fn(
    config,
    workdir: str,
    *,
    tasks: Optional[Sequence[str]] = None,
    episodes_per_cell: int = 2,
    max_episode_steps: int = 80,
    block_mode: str = "BLOCK_8",
    seed: int = 0,
    embedder: str = "hash",
    env_kwargs: Optional[Dict[str, Any]] = None,
    margin: float = 0.0,
    inference_dtype: str = "f32",
    parity_episodes: int = 2,
    parity_steps: int = 4,
    progress: Optional[Callable[[str, str, Dict[str, Any]], None]] = None,
) -> Callable[[int, Optional[int]], Dict[str, Any]]:
    """Bind config + gate knobs into the ``gate_fn(candidate_step,
    incumbent_step) -> verdict`` the PromotionController consumes.

    The verdict passes only when BOTH judgements pass; the eval matrix
    runs first (cheaper rejection: a regressed policy never pays the
    double engine build the parity check needs)."""
    from rt1_tpu.eval import matrix as matrix_lib

    def gate_fn(
        candidate_step: int, incumbent_step: Optional[int]
    ) -> Dict[str, Any]:
        verdict = matrix_lib.run_gate(
            config,
            workdir,
            candidate_step,
            incumbent_step,
            tasks=tasks,
            episodes_per_cell=episodes_per_cell,
            max_episode_steps=max_episode_steps,
            block_mode=block_mode,
            seed=seed,
            embedder=embedder,
            env_kwargs=env_kwargs,
            margin=margin,
            progress=progress,
        )
        eval_passed = bool(verdict["passed"])
        if eval_passed:
            parity = run_parity_gate(
                config,
                workdir,
                candidate_step,
                inference_dtype=inference_dtype,
                episodes=parity_episodes,
                steps=parity_steps,
            )
            verdict["parity"] = parity
            verdict["passed"] = bool(parity.get("passed"))
        else:
            verdict["parity"] = {"skipped": "eval gate failed"}
        verdict["eval_passed"] = eval_passed
        return verdict

    return gate_fn
