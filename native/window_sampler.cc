// Native window assembler: multi-threaded uint8 crop + bilinear resize.
//
// The per-sample hot path of the training input pipeline (the role the
// reference fills with DataLoader(num_workers=15) forking Python workers,
// `distribute_train.py:200` + `load_np_dataset.py:8-39`): for each frame of
// a window, crop a box and bilinear-resize it to the model resolution. Done
// here in C++ with a thread pool over frames, it runs GIL-free and
// allocation-free per frame, so one host process can assemble batches for a
// TPU chip without Python worker processes.
//
// Resize convention matches cv2.INTER_LINEAR / TF half-pixel centers:
//   src = (dst + 0.5) * (in/out) - 0.5, edge-clamped,
// so the native path is a drop-in for the cv2 implementation in
// rt1_tpu/data/pipeline.py::_cv2_crop_resize (equivalence tested to
// +/-1 LSB in tests/test_native_reader.py).
//
// Build: g++ -O2 -shared -fPIC -std=c++17 window_sampler.cc -lpthread
//          -o libwindow_sampler.so

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Fixed-point bilinear (11-bit weights) with precomputed per-column
// coefficients: one mul-add tree per channel, no float math in the loop.
constexpr int kShift = 11;
constexpr int kOne = 1 << kShift;

struct XCoef {
  int32_t x0, x1;
  int32_t w0, w1;  // sum to kOne
};

void compute_coefs(int src, int out, std::vector<XCoef>& coefs) {
  coefs.resize(out);
  const float scale = static_cast<float>(src) / out;
  for (int o = 0; o < out; ++o) {
    float f = (o + 0.5f) * scale - 0.5f;
    int i0 = static_cast<int>(std::floor(f));
    float w = f - i0;
    int i1 = std::min(i0 + 1, src - 1);
    i0 = std::max(i0, 0);
    int32_t w1 = static_cast<int32_t>(w * kOne + 0.5f);
    coefs[o] = {i0, i1, kOne - w1, w1};
  }
}

void crop_resize_one(const uint8_t* frame, int h, int w, int top, int left,
                     int crop_h, int crop_w, uint8_t* out, int out_h,
                     int out_w, const std::vector<XCoef>& xc,
                     const std::vector<XCoef>& yc) {
  const uint8_t* src = frame + (static_cast<int64_t>(top) * w + left) * 3;
  const int src_stride = w * 3;
  // Row buffers: horizontal-pass results for the two source rows feeding
  // the current output row, as int32 fixed point (8-bit pixel x 11-bit
  // weight sum fits 19 bits); the vertical pass widens to int64 before the
  // 2*kShift rounding shift.
  std::vector<int32_t> row0(out_w * 3), row1(out_w * 3);
  int cached_y0 = -1, cached_y1 = -1;

  auto hpass = [&](const uint8_t* src_row, std::vector<int32_t>& dst) {
    for (int ox = 0; ox < out_w; ++ox) {
      const XCoef& c = xc[ox];
      const uint8_t* p0 = src_row + c.x0 * 3;
      const uint8_t* p1 = src_row + c.x1 * 3;
      int32_t* d = dst.data() + ox * 3;
      d[0] = c.w0 * p0[0] + c.w1 * p1[0];
      d[1] = c.w0 * p0[1] + c.w1 * p1[1];
      d[2] = c.w0 * p0[2] + c.w1 * p1[2];
    }
  };

  for (int oy = 0; oy < out_h; ++oy) {
    const XCoef& c = yc[oy];
    if (c.x0 != cached_y0) {
      if (c.x0 == cached_y1) {
        std::swap(row0, row1);
        cached_y0 = c.x0;
        cached_y1 = -1;
      } else {
        hpass(src + static_cast<int64_t>(c.x0) * src_stride, row0);
        cached_y0 = c.x0;
      }
    }
    if (c.x1 != cached_y1) {
      hpass(src + static_cast<int64_t>(c.x1) * src_stride, row1);
      cached_y1 = c.x1;
    }
    uint8_t* out_row = out + static_cast<int64_t>(oy) * out_w * 3;
    const int64_t round = 1LL << (2 * kShift - 1);
    for (int i = 0; i < out_w * 3; ++i) {
      int64_t v = static_cast<int64_t>(c.w0) * row0[i] +
                  static_cast<int64_t>(c.w1) * row1[i];
      int32_t q = static_cast<int32_t>((v + round) >> (2 * kShift));
      out_row[i] = static_cast<uint8_t>(std::min(255, std::max(0, q)));
    }
  }
}

}  // namespace

extern "C" {

// frames: n pointers to (h, w, 3) uint8 images (all the same h, w).
// boxes:  n * 4 int32 (top, left, crop_h, crop_w) per frame.
// out:    n * out_h * out_w * 3 uint8, written in frame order.
// threads: worker threads (<=1 runs inline).
void ws_crop_resize_batch(const uint8_t** frames, const int32_t* boxes,
                          int n, int h, int w, uint8_t* out, int out_h,
                          int out_w, int threads) {
  const int64_t out_sz = static_cast<int64_t>(out_h) * out_w * 3;
  auto work = [&](int i) {
    const int32_t* b = boxes + i * 4;
    // Coefficients depend only on (crop, out) sizes; crops share a size in
    // the common fixed-crop_factor case but boxes may differ, so compute
    // per frame (cheap: O(out) vs O(out^2) pixels).
    std::vector<XCoef> xc, yc;
    compute_coefs(b[3], out_w, xc);
    compute_coefs(b[2], out_h, yc);
    crop_resize_one(frames[i], h, w, b[0], b[1], b[2], b[3], out + i * out_sz,
                    out_h, out_w, xc, yc);
  };
  if (threads <= 1 || n <= 1) {
    for (int i = 0; i < n; ++i) work(i);
    return;
  }
  std::atomic<int> next{0};
  auto runner = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) work(i);
  };
  int n_threads = std::min(threads, n);
  std::vector<std::thread> pool;
  pool.reserve(n_threads - 1);
  for (int t = 1; t < n_threads; ++t) pool.emplace_back(runner);
  runner();
  for (auto& th : pool) th.join();
}

// Packed-format gather (rt1_tpu/data/pack.py): frames live as one
// contiguous (T, ph, pw, 3) uint8 block per episode (an mmap), and a
// training window is n crops addressed by frame index into that block.
// The packed geometry makes every crop exactly (out_h, out_w), so the hot
// path is a threaded strided row-memcpy straight out of the page cache —
// no decode, no resize, no Python per-frame pointer list. Crops that are
// NOT already at the output size (crop_factor=None packs, future headroom
// formats) fall through to the bilinear resample above.
//
// base:      start of the (T, ph, pw, 3) uint8 frame block.
// frame_idx: n int64 frame indices into the block.
// boxes:     n * 4 int32 (top, left, crop_h, crop_w) in PACKED coords.
// out:       n * out_h * out_w * 3 uint8.
void ws_packed_gather(const uint8_t* base, const int64_t* frame_idx,
                      const int32_t* boxes, int n, int ph, int pw,
                      uint8_t* out, int out_h, int out_w, int threads) {
  const int64_t frame_sz = static_cast<int64_t>(ph) * pw * 3;
  const int64_t out_sz = static_cast<int64_t>(out_h) * out_w * 3;
  auto work = [&](int i) {
    const uint8_t* frame = base + frame_idx[i] * frame_sz;
    const int32_t* b = boxes + i * 4;
    uint8_t* dst = out + i * out_sz;
    if (b[2] == out_h && b[3] == out_w) {
      const uint8_t* src = frame + (static_cast<int64_t>(b[0]) * pw + b[1]) * 3;
      const int64_t src_stride = static_cast<int64_t>(pw) * 3;
      const int64_t row_bytes = static_cast<int64_t>(out_w) * 3;
      for (int y = 0; y < out_h; ++y) {
        std::memcpy(dst + y * row_bytes, src + y * src_stride, row_bytes);
      }
      return;
    }
    std::vector<XCoef> xc, yc;
    compute_coefs(b[3], out_w, xc);
    compute_coefs(b[2], out_h, yc);
    crop_resize_one(frame, ph, pw, b[0], b[1], b[2], b[3], dst, out_h, out_w,
                    xc, yc);
  };
  if (threads <= 1 || n <= 1) {
    for (int i = 0; i < n; ++i) work(i);
    return;
  }
  std::atomic<int> next{0};
  auto runner = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) work(i);
  };
  int n_threads = std::min(threads, n);
  std::vector<std::thread> pool;
  pool.reserve(n_threads - 1);
  for (int t = 1; t < n_threads; ++t) pool.emplace_back(runner);
  runner();
  for (auto& th : pool) th.join();
}

}  // extern "C"
