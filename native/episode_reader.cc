// Native episode reader: mmap-backed .npy / .npz parsing with a C ABI.
//
// Replaces the reference data path's per-sample `np.load` of whole episode
// files (`load_np_dataset.py:79-83`, SURVEY.md §7 hard-part 7) at a lower
// level: one mmap per episode, zero-copy array views for uncompressed
// members, zlib inflate for deflated npz members. Exposed to Python via
// ctypes (rt1_tpu/data/native.py); the pipeline falls back to numpy when
// the shared library is unavailable.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 episode_reader.cc -lz \
//          -o libepisode_reader.so

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <zlib.h>

namespace {

constexpr int kMaxDims = 8;

struct Member {
  std::string name;
  std::string dtype;          // numpy descr, e.g. "<f4", "|u1"
  int ndim = 0;
  int64_t shape[kMaxDims] = {0};
  const uint8_t* data = nullptr;  // zero-copy view into the mmap, or...
  std::vector<uint8_t> owned;     // ...inflated buffer for deflated members
  int64_t nbytes = 0;
};

struct Reader {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_size = 0;
  std::vector<Member> members;
  std::string error;
};

// ---------------------------------------------------------------- npy header

bool parse_npy(const uint8_t* buf, size_t len, Member* m) {
  if (len < 10 || memcmp(buf, "\x93NUMPY", 6) != 0) return false;
  const uint8_t major = buf[6];
  size_t header_len, header_off;
  if (major == 1) {
    header_len = buf[8] | (buf[9] << 8);
    header_off = 10;
  } else {
    if (len < 12) return false;
    header_len = buf[8] | (buf[9] << 8) | (buf[10] << 16)
        | (static_cast<size_t>(buf[11]) << 24);
    header_off = 12;
  }
  if (header_off + header_len > len) return false;
  std::string header(reinterpret_cast<const char*>(buf + header_off),
                     header_len);

  // descr
  size_t dpos = header.find("'descr'");
  if (dpos == std::string::npos) return false;
  size_t q1 = header.find('\'', dpos + 7);
  size_t q2 = header.find('\'', q1 + 1);
  if (q1 == std::string::npos || q2 == std::string::npos) return false;
  m->dtype = header.substr(q1 + 1, q2 - q1 - 1);

  // fortran_order must be False (C layout only).
  size_t fpos = header.find("'fortran_order'");
  if (fpos != std::string::npos &&
      header.find("True", fpos) != std::string::npos &&
      header.find("True", fpos) < header.find(',', fpos)) {
    return false;
  }

  // shape tuple
  size_t spos = header.find("'shape'");
  if (spos == std::string::npos) return false;
  size_t p1 = header.find('(', spos);
  size_t p2 = header.find(')', p1);
  if (p1 == std::string::npos || p2 == std::string::npos) return false;
  std::string shape_str = header.substr(p1 + 1, p2 - p1 - 1);
  m->ndim = 0;
  const char* s = shape_str.c_str();
  while (*s) {
    while (*s == ' ' || *s == ',') s++;
    if (!*s) break;
    char* end;
    long long v = strtoll(s, &end, 10);
    if (end == s) break;
    if (m->ndim >= kMaxDims) return false;  // refuse, don't truncate
    m->shape[m->ndim++] = v;
    s = end;
  }

  // element size from descr: trailing integer is the byte width.
  int itemsize = atoi(m->dtype.c_str() + 2);
  if (itemsize <= 0) itemsize = 1;
  int64_t count = 1;
  for (int i = 0; i < m->ndim; i++) count *= m->shape[i];
  m->nbytes = count * itemsize;

  m->data = buf + header_off + header_len;
  if (header_off + header_len + m->nbytes > len) return false;
  return true;
}

// ------------------------------------------------------------------- zip/npz

uint16_t rd16(const uint8_t* p) { return p[0] | (p[1] << 8); }
uint32_t rd32(const uint8_t* p) {
  return p[0] | (p[1] << 8) | (p[2] << 16)
      | (static_cast<uint32_t>(p[3]) << 24);
}

bool parse_npz(Reader* r) {
  const uint8_t* buf = r->map;
  size_t len = r->map_size;
  // Find End Of Central Directory (scan back past an optional comment).
  if (len < 22) return false;
  size_t eocd = std::string::npos;
  size_t scan_limit = len >= 22 + 65535 ? len - 22 - 65535 : 0;
  for (size_t i = len - 22; ; i--) {
    if (rd32(buf + i) == 0x06054b50) { eocd = i; break; }
    if (i == scan_limit) break;
  }
  if (eocd == std::string::npos) return false;
  uint16_t n_entries = rd16(buf + eocd + 10);
  uint32_t cd_offset = rd32(buf + eocd + 16);

  size_t pos = cd_offset;
  for (int e = 0; e < n_entries; e++) {
    if (pos + 46 > len || rd32(buf + pos) != 0x02014b50) return false;
    uint16_t method = rd16(buf + pos + 10);
    uint32_t comp_size = rd32(buf + pos + 20);
    uint32_t raw_size = rd32(buf + pos + 24);
    uint16_t name_len = rd16(buf + pos + 28);
    uint16_t extra_len = rd16(buf + pos + 30);
    uint16_t comment_len = rd16(buf + pos + 32);
    uint32_t local_off = rd32(buf + pos + 42);
    if (pos + 46 + name_len > len) return false;
    std::string name(reinterpret_cast<const char*>(buf + pos + 46), name_len);
    pos += 46 + static_cast<size_t>(name_len) + extra_len + comment_len;
    if (pos > len) return false;

    // Local header gives the true data offset. Every offset/length from the
    // file is untrusted: bounds-check before dereferencing, so corrupt files
    // fail cleanly (Python then falls back to numpy) instead of faulting.
    if (local_off > len || local_off + 30 > len ||
        rd32(buf + local_off) != 0x04034b50)
      return false;
    uint16_t lname = rd16(buf + local_off + 26);
    uint16_t lextra = rd16(buf + local_off + 28);
    size_t payload_off =
        static_cast<size_t>(local_off) + 30 + lname + lextra;
    if (payload_off > len || payload_off + comp_size > len) return false;
    const uint8_t* payload = buf + payload_off;

    Member m;
    if (name.size() > 4 && name.substr(name.size() - 4) == ".npy")
      m.name = name.substr(0, name.size() - 4);
    else
      m.name = name;

    if (method == 0) {  // stored: zero-copy
      if (!parse_npy(payload, comp_size, &m)) {
        r->error = "bad npy member (stored): " + name;
        return false;
      }
    } else if (method == 8) {  // deflated: inflate to owned buffer
      m.owned.resize(raw_size);
      z_stream zs;
      memset(&zs, 0, sizeof(zs));
      if (inflateInit2(&zs, -MAX_WBITS) != Z_OK) return false;
      zs.next_in = const_cast<uint8_t*>(payload);
      zs.avail_in = comp_size;
      zs.next_out = m.owned.data();
      zs.avail_out = raw_size;
      int rc = inflate(&zs, Z_FINISH);
      inflateEnd(&zs);
      if (rc != Z_STREAM_END) {
        r->error = "inflate failed: " + name;
        return false;
      }
      if (!parse_npy(m.owned.data(), raw_size, &m)) {
        r->error = "bad npy member (deflated): " + name;
        return false;
      }
    } else {
      r->error = "unsupported zip method for: " + name;
      return false;
    }
    r->members.push_back(std::move(m));
  }
  return true;
}

}  // namespace

extern "C" {

void* er_open(const char* path) {
  Reader* r = new Reader();
  r->fd = open(path, O_RDONLY);
  if (r->fd < 0) { delete r; return nullptr; }
  struct stat st;
  if (fstat(r->fd, &st) != 0) { close(r->fd); delete r; return nullptr; }
  r->map_size = st.st_size;
  r->map = static_cast<const uint8_t*>(
      mmap(nullptr, r->map_size, PROT_READ, MAP_PRIVATE, r->fd, 0));
  if (r->map == MAP_FAILED) { close(r->fd); delete r; return nullptr; }
  madvise(const_cast<uint8_t*>(r->map), r->map_size, MADV_SEQUENTIAL);

  bool ok;
  if (r->map_size >= 6 && memcmp(r->map, "\x93NUMPY", 6) == 0) {
    Member m;
    m.name = "data";
    ok = parse_npy(r->map, r->map_size, &m);
    if (ok) r->members.push_back(std::move(m));
  } else {
    ok = parse_npz(r);
  }
  if (!ok) {
    munmap(const_cast<uint8_t*>(r->map), r->map_size);
    close(r->fd);
    delete r;
    return nullptr;
  }
  return r;
}

int er_num_members(void* handle) {
  return static_cast<Reader*>(handle)->members.size();
}

const char* er_member_name(void* handle, int i) {
  return static_cast<Reader*>(handle)->members[i].name.c_str();
}

const char* er_member_dtype(void* handle, int i) {
  return static_cast<Reader*>(handle)->members[i].dtype.c_str();
}

int er_member_ndim(void* handle, int i) {
  return static_cast<Reader*>(handle)->members[i].ndim;
}

void er_member_shape(void* handle, int i, int64_t* out) {
  const Member& m = static_cast<Reader*>(handle)->members[i];
  memcpy(out, m.shape, m.ndim * sizeof(int64_t));
}

const void* er_member_data(void* handle, int i) {
  return static_cast<Reader*>(handle)->members[i].data;
}

int64_t er_member_nbytes(void* handle, int i) {
  return static_cast<Reader*>(handle)->members[i].nbytes;
}

void er_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (r->map) munmap(const_cast<uint8_t*>(r->map), r->map_size);
  if (r->fd >= 0) close(r->fd);
  delete r;
}

}  // extern "C"
