"""Data pipeline tests: storage round-trip, reference-format compat, window/pad
semantics (`load_np_dataset.py:49-116` parity), loaders, device feeding."""

import numpy as np
import pytest

from rt1_tpu.data import (
    WindowedEpisodeDataset,
    device_feeder,
    generate_synthetic_episode,
    load_episode,
    read_reference_episode,
    save_episode,
)

W = 6


@pytest.fixture
def episode_dir(tmp_path, np_rng):
    lens = [8, 12, 7]
    paths = []
    for i, t in enumerate(lens):
        ep = generate_synthetic_episode(np_rng, num_steps=t, height=36, width=64)
        p = str(tmp_path / f"episode_{i}.npz")
        save_episode(p, ep)
        paths.append(p)
    return paths, lens


def test_save_load_roundtrip(tmp_path, np_rng):
    ep = generate_synthetic_episode(np_rng, num_steps=5)
    p = str(tmp_path / "e.npz")
    save_episode(p, ep)
    back = load_episode(p)
    for k in ep:
        np.testing.assert_array_equal(ep[k], back[k])


def test_reference_format_compat(tmp_path, np_rng):
    """Our reader consumes the reference's pickled list-of-step-dicts .npy."""
    ep = generate_synthetic_episode(np_rng, num_steps=4, height=16, width=16)
    steps = [
        {
            "rgb": ep["rgb"][i],
            "action": ep["action"][i],
            "is_first": bool(ep["is_first"][i]),
            "is_terminal": bool(ep["is_terminal"][i]),
            "instruction": ep["instruction"][i],
        }
        for i in range(4)
    ]
    p = str(tmp_path / "episode_0.npy")
    np.save(p, np.array(steps, dtype=object), allow_pickle=True)
    back = read_reference_episode(p)
    np.testing.assert_array_equal(back["rgb"], ep["rgb"])
    np.testing.assert_allclose(back["action"], ep["action"])
    np.testing.assert_array_equal(back["is_terminal"], [False, False, False, True])


def test_window_count_matches_reference(episode_dir):
    """Padded length T+W-1 → exactly T windows per episode (load_np_dataset.py:65-74)."""
    paths, lens = episode_dir
    ds = WindowedEpisodeDataset(paths, window=W, height=24, width=40)
    assert len(ds) == sum(lens)


def test_first_window_is_all_first_frame(episode_dir, np_rng):
    """Window 0 of an episode sees the first step repeated W times, and only the
    real first step keeps is_first semantics (pad copies get is_first=False,
    load_np_dataset.py:49-63) — observable via identical frames/labels."""
    paths, _ = episode_dir
    ds = WindowedEpisodeDataset(paths, window=W, crop_factor=None, height=36, width=64)
    s = ds.get_window(0, np_rng)
    img = s["observations"]["image"]
    for j in range(1, W):
        np.testing.assert_array_equal(img[0], img[j])
    # Action labels all equal the first step's action.
    act = s["actions"]["action"]
    for j in range(1, W):
        np.testing.assert_array_equal(act[0], act[j])


def test_last_window_hits_terminal(episode_dir, np_rng):
    paths, lens = episode_dir
    ds = WindowedEpisodeDataset(paths, window=W, crop_factor=None, height=36, width=64)
    # Last window of episode 0 is index lens[0]-1; its final label is terminal.
    s = ds.get_window(lens[0] - 1, np_rng)
    term = s["actions"]["terminate_episode"]
    assert term[-1] == 1
    assert term[:-1].sum() == 0


def test_crop_resize_shapes_and_range(episode_dir, np_rng):
    paths, _ = episode_dir
    # Default ships uint8 (4x fewer H2D bytes; device converts to [0,1]).
    ds = WindowedEpisodeDataset(paths, window=W, crop_factor=0.95, height=24, width=40)
    s = ds.get_window(3, np_rng)
    img = s["observations"]["image"]
    assert img.shape == (W, 24, 40, 3)
    assert img.dtype == np.uint8

    # float32 option preserves the legacy [0,1] host representation, and the
    # two representations agree to quantization error.
    ds_f = WindowedEpisodeDataset(
        paths, window=W, crop_factor=0.95, height=24, width=40,
        image_dtype="float32",
    )
    rng_a, rng_b = (np.random.default_rng(7), np.random.default_rng(7))
    img_u = ds.get_window(3, rng_a)["observations"]["image"]
    img_f = ds_f.get_window(3, rng_b)["observations"]["image"]
    assert img_f.dtype == np.float32
    assert 0.0 <= img_f.min() and img_f.max() <= 1.0
    np.testing.assert_allclose(
        img_u.astype(np.float32) / 255.0, img_f, atol=1 / 255
    )


def test_numpy_batches_shapes(episode_dir):
    paths, lens = episode_dir
    ds = WindowedEpisodeDataset(paths, window=W, height=24, width=40)
    it = ds.numpy_batches(batch_size=4, num_epochs=1, seed=1)
    batch = next(it)
    assert batch["observations"]["image"].shape == (4, W, 24, 40, 3)
    assert batch["observations"]["natural_language_embedding"].shape == (4, W, 512)
    assert batch["actions"]["terminate_episode"].shape == (4, W)
    assert batch["actions"]["action"].shape == (4, W, 2)
    # One epoch covers all windows (minus the dropped remainder).
    count = 1 + sum(1 for _ in it)
    assert count == sum(lens) // 4


def test_process_sharding_partitions_windows(episode_dir):
    paths, lens = episode_dir
    ds = WindowedEpisodeDataset(paths, window=W, height=24, width=40)
    total = sum(lens)
    seen = 0
    for pi in range(2):
        it = ds.numpy_batches(
            batch_size=1, num_epochs=1, shuffle=False, process_index=pi, process_count=2
        )
        seen += sum(1 for _ in it)
    assert seen == total


def test_tf_dataset_pipeline(episode_dir):
    tf = pytest.importorskip("tensorflow")
    paths, _ = episode_dir
    ds = WindowedEpisodeDataset(paths, window=W, height=24, width=40)
    tfds = ds.as_tf_dataset(batch_size=4, repeat=True, num_parallel_calls=2)
    batch = next(iter(tfds))
    assert batch["observations"]["image"].shape == (4, W, 24, 40, 3)
    assert batch["actions"]["action"].shape == (4, W, 2)


def test_device_feeder_shards_batch(episode_dir):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from rt1_tpu.parallel import MeshConfig, make_mesh

    paths, _ = episode_dir
    mesh = make_mesh(MeshConfig())
    sh = NamedSharding(mesh, P("data"))
    ds = WindowedEpisodeDataset(paths, window=W, height=24, width=40)
    feeder = device_feeder(ds.numpy_batches(batch_size=8, num_epochs=1), sh)
    obs, actions = next(feeder)
    assert obs["image"].sharding == sh
    assert actions["action"].shape == (8, W, 2)


def test_prefetch_to_device_order_and_drain(episode_dir):
    """Double-buffered device feed preserves order and yields every batch."""
    import jax

    from rt1_tpu.data.pipeline import prefetch_to_device

    batches = [{"x": np.full((2, 2), i, np.float32)} for i in range(5)]
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    out = list(prefetch_to_device(iter(batches), sharding, depth=2))
    assert len(out) == 5
    for i, b in enumerate(out):
        assert isinstance(b["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(b["x"]), batches[i]["x"])

    # depth larger than the stream still drains completely.
    out = list(prefetch_to_device(iter(batches[:2]), sharding, depth=8))
    assert len(out) == 2


def test_instruction_text_roundtrip(tmp_path, np_rng):
    from rt1_tpu.data.episodes import (
        decode_instruction_text,
        encode_instruction_text,
    )

    ep = generate_synthetic_episode(np_rng, num_steps=4, height=16, width=16)
    ep["instruction_text"] = encode_instruction_text("push the red moon")
    p = str(tmp_path / "e.npz")
    save_episode(p, ep)
    back = load_episode(p)  # native reader handles the uint8 bytes member
    assert decode_instruction_text(back["instruction_text"]) == "push the red moon"


def test_clip_tokenized_windows(tmp_path, np_rng):
    from rt1_tpu.data.episodes import encode_instruction_text
    from rt1_tpu.text.clip_bpe import default_tokenizer

    texts = ["push the red moon", "slide the blue cube left"]
    paths = []
    for i, text in enumerate(texts):
        ep = generate_synthetic_episode(np_rng, num_steps=4, height=16, width=24)
        ep["instruction_text"] = encode_instruction_text(text)
        p = str(tmp_path / f"episode_{i}.npz")
        save_episode(p, ep)
        paths.append(p)

    tok = default_tokenizer()
    ds = WindowedEpisodeDataset(
        paths, window=3, height=16, width=24, clip_tokenizer=tok
    )
    s = ds.get_window(0, np_rng)
    tokens = s["observations"]["instruction_tokenized_clip"]
    assert tokens.shape == (3, tok.context_length)
    assert tokens.dtype == np.int32
    # Constant along the window; equals direct tokenization.
    np.testing.assert_array_equal(tokens[0], tokens[1])
    np.testing.assert_array_equal(tokens[0], tok.tokenize_text(texts[0])[0])

    # tf loader carries the extra observation with a static shape.
    tf = pytest.importorskip("tensorflow")
    tfds = ds.as_tf_dataset(batch_size=2, num_parallel_calls=2)
    batch = next(iter(tfds))
    assert batch["observations"]["instruction_tokenized_clip"].shape == (
        2, 3, tok.context_length
    )

    # Pre-text episodes fail loudly, not silently.
    ep = generate_synthetic_episode(np_rng, num_steps=4, height=16, width=24)
    p_old = str(tmp_path / "episode_old.npz")
    save_episode(p_old, ep)
    ds_old = WindowedEpisodeDataset(
        [p_old], window=3, height=16, width=24, clip_tokenizer=tok
    )
    with pytest.raises(KeyError, match="instruction_text"):
        ds_old.get_window(0, np_rng)
