"""obs/trace.py: thread-safe Chrome-trace recording + disabled fast path."""

import json
import threading

import pytest

from rt1_tpu.obs import trace


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    """The module-level recorder is process-wide state; isolate every test."""
    trace._tracer = None
    yield
    trace._tracer = None


def test_disabled_tracer_is_a_shared_noop():
    assert not trace.enabled()
    s = trace.span("anything", step=1)
    assert s is trace._NULL_SPAN
    with s:
        pass
    # Instant/counter/dump are no-ops, not errors.
    trace.instant("marker")
    trace.counter("depth", 3)
    assert trace.dump() is None

    # Nothing recorded once enabled afterwards: the disabled-period calls
    # left no buffered state behind.
    rec = trace.enable()
    assert rec.to_dict()["traceEvents"] == []


def test_spans_from_two_threads_serialize_to_valid_chrome_trace(tmp_path):
    path = str(tmp_path / "trace.json")
    trace.enable(path)

    def worker():
        for i in range(3):
            with trace.span("worker_assemble", ticket=i):
                pass

    t = threading.Thread(target=worker, name="rt1-test-worker")
    with trace.span("main_phase", step=0):
        t.start()
        t.join()
    trace.counter("queue_depth", 2)
    written = trace.dump()
    assert written == path

    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    tids = {e["tid"] for e in spans}
    assert len(tids) >= 2, "expected spans from the main + worker threads"
    for e in spans:
        assert {"name", "pid", "tid", "ts", "dur"} <= set(e)
        assert e["dur"] >= 0
    # Thread-name metadata present for both threads, with the worker's name.
    names = {
        e["tid"]: e["args"]["name"]
        for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert set(names) >= tids
    assert "rt1-test-worker" in names.values()
    # Counter event carries its series.
    counters = [e for e in events if e["ph"] == "C"]
    assert counters and counters[0]["args"] == {"value": 2}


def test_span_args_and_instant_events(tmp_path):
    rec = trace.enable()
    with trace.span("phase", step=7):
        trace.instant("inside", detail="x")
    events = rec.to_dict()["traceEvents"]
    by_ph = {e["ph"]: e for e in events}
    assert by_ph["X"]["args"] == {"step": 7}
    assert by_ph["i"]["name"] == "inside"
    # Instant falls inside the span on the same thread's clock.
    assert (
        by_ph["X"]["ts"]
        <= by_ph["i"]["ts"]
        <= by_ph["X"]["ts"] + by_ph["X"]["dur"]
    )


def test_ring_bounds_memory_and_reports_drops():
    rec = trace.enable(max_events=10)
    for i in range(25):
        with trace.span("s", i=i):
            pass
    doc = rec.to_dict()
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 10
    # Most recent survive.
    assert [e["args"]["i"] for e in spans] == list(range(15, 25))
    assert doc["otherData"]["dropped_events"] == 15


def test_enable_updates_existing_recorder(tmp_path):
    """A stale recorder (aborted prior run) must not hijack the new run's
    dump path or ring size — explicit enable() args win, events survive."""
    rec = trace.enable(str(tmp_path / "old.json"), max_events=100)
    with trace.span("kept"):
        pass
    same = trace.enable(str(tmp_path / "new.json"), max_events=5)
    assert same is rec
    assert rec.path == str(tmp_path / "new.json")
    assert rec._events.maxlen == 5
    assert [e["name"] for e in rec.to_dict()["traceEvents"] if e["ph"] == "X"] == ["kept"]
    # Omitted args keep the installed configuration.
    trace.enable()
    assert rec.path == str(tmp_path / "new.json")
    assert rec._events.maxlen == 5


def test_disable_dumps_when_path_configured(tmp_path):
    path = str(tmp_path / "out" / "trace.json")
    trace.enable(path)
    with trace.span("s"):
        pass
    trace.disable()
    assert not trace.enabled()
    with open(path) as f:
        doc = json.load(f)
    assert any(e["ph"] == "X" for e in doc["traceEvents"])
