"""Low-precision serving engine (tier-1): per-channel quant op error
bounds, the plan's quant rules deciding every leaf, quantize-at-restore
structure, Quant layer f32 bit-identity, the int8-vs-f32 engine parity
gate (the ship-blocking acceptance bar), bf16-restore ≡ bf16-compute, and
quantized hot-swap (standby = f32 masters, requantized, compile_count 1).

The engine fixtures go through `build_serve_engine(inference_dtype=)` on
the tiny config — the exact restore path `python -m rt1_tpu.serve
--inference_dtype` takes — so the gate here covers what production serves.
"""

import numpy as np
import pytest

from rt1_tpu.models import quant
from rt1_tpu.parallel.plan import (
    QUANT_F32,
    QUANT_INT8,
    quant_coverage,
    quant_group_for_path,
    rt1_quant_rules,
)

EPS = 1e-6


# ------------------------------------------------------------ the quant op


def test_per_channel_round_trip_error_bound():
    """Symmetric per-channel quantization: the round-trip error of every
    entry is at most half a quantization step of ITS channel, and the
    channel's max-abs entry uses the full ±127 range (scale = amax/127)."""
    rng = np.random.default_rng(0)
    w = (rng.standard_normal((48, 24)) * 0.05).astype(np.float32)
    q, scale = quant.quantize_per_channel(w)
    assert q.dtype == np.int8 and q.shape == w.shape
    assert scale.dtype == np.float32 and scale.shape == (24,)
    err = np.abs(quant.dequantize(q, scale) - w)
    assert np.all(err <= scale[None, :] * 0.5 + EPS)
    np.testing.assert_array_equal(np.abs(q).max(axis=0), 127)
    # Relative view: the worst error is ~0.4% of the channel amax.
    amax = np.abs(w).max(axis=0)
    assert np.all(err.max(axis=0) <= amax / (2 * quant.INT8_MAX) + EPS)


def test_per_channel_conv_kernels_and_edge_cases():
    rng = np.random.default_rng(1)
    # Conv layout (kh, kw, cin, cout): scale is per-cout over the whole
    # receptive field.
    k = rng.standard_normal((3, 3, 4, 8)).astype(np.float32)
    q, scale = quant.quantize_per_channel(k)
    assert scale.shape == (8,)
    err = np.abs(quant.dequantize(q, scale) - k)
    assert np.all(err <= scale * 0.5 + EPS)
    # An all-zero output channel (FiLM's zero-init projections) round-trips
    # exactly instead of dividing 0/0.
    z = np.zeros((6, 3), np.float32)
    z[:, 0] = rng.standard_normal(6)
    qz, sz = quant.quantize_per_channel(z)
    assert sz[1] == 1.0 and sz[2] == 1.0
    np.testing.assert_array_equal(quant.dequantize(qz, sz)[:, 1:], 0.0)
    # Rank-1 leaves have no output channel to scale by.
    with pytest.raises(ValueError, match="rank"):
        quant.quantize_per_channel(np.zeros(5, np.float32))


# ------------------------------------------------------- plan quant rules


def test_quant_rules_groups_for_key_paths():
    """The declared split: matmul/conv weights int8; embeddings, the
    action head, and the fp32 MoE router explicitly full-precision."""
    int8_paths = [
        "params/transformer/layer_0/attn/query/kernel",
        "params/transformer/layer_0/attn/out/kernel",
        "params/transformer/layer_3/ff/kernel",
        "params/transformer/layer_1/moe/wi",
        "params/transformer/layer_1/moe/wo",
        "params/image_tokenizer_def/blocks_3/film/projection_add/kernel",
        "params/image_tokenizer_def/net/stem/conv/kernel",
        "params/image_tokenizer_def/token_learner/conv1/kernel",
        "params/image_tokenizer_def/conv1x1/kernel",
        "params/image_tokenizer_def/tok/kernel",
    ]
    f32_paths = [
        "params/transformer/token_emb/embedding",
        "params/transformer/position_emb/embedding",
        "params/transformer/output_tokens/kernel",  # IS the action decode
        "params/transformer/layer_1/moe/gate/kernel",  # fp32 router
    ]
    for path in int8_paths:
        assert quant_group_for_path(path) == QUANT_INT8, path
    for path in f32_paths:
        assert quant_group_for_path(path) == QUANT_F32, path
    # Unmatched paths fall through to the master dtype, never to int8.
    assert quant_group_for_path("params/some/new/module/w") == QUANT_F32


def test_quant_rules_decide_every_leaf_of_shipped_configs():
    """`quant_coverage` analogue of the sharding plan's coverage check: on
    the tiny AND flagship serving trees, every rank≥2 leaf is decided by
    an explicit rule — a renamed module cannot silently lose (or gain) the
    int8 memory win."""
    from rt1_tpu.train.configs import language_table, tiny

    for get_config in (tiny.get_config, language_table.get_config):
        shapes = quant.abstract_serving_variables(get_config())
        assert quant_coverage(shapes) == []
        assert quant.quantized_paths(shapes)  # the int8 group is non-empty


def test_flagship_byte_report_meets_3x_reduction():
    """The acceptance headline, from abstract shapes (no init cost): the
    flagship serving tree shrinks ≥3× under int8 and exactly 2× under
    bf16 (BENCH_serve_quant.json records the same accounting)."""
    from rt1_tpu.train.configs import language_table

    report = quant.quant_byte_report(language_table.get_config())
    assert report["int8_reduction"] >= 3.0
    assert report["bf16_reduction"] == 2.0
    assert report["quantized_leaves"] > 100
    assert report["int8_bytes"] < report["bf16_bytes"] < report["f32_bytes"]


# ------------------------------------------------ quantize-at-restore tree


@pytest.fixture(scope="module")
def tiny_model_vars():
    import jax

    from rt1_tpu.specs import language_table_action_space, sample_space
    from tests.test_rt1 import tiny_policy

    model = tiny_policy(time_sequence_length=3)
    rng = jax.random.PRNGKey(0)
    obs = {
        "image": np.zeros((1, 3, 32, 56, 3), np.float32),
        "natural_language_embedding": np.zeros((1, 3, 512), np.float32),
    }
    actions = sample_space(
        language_table_action_space(), jax.random.fold_in(rng, 1), (1, 3)
    )
    variables = model.init(
        {"params": rng, "crop": rng}, obs, actions, train=False
    )
    import jax as _jax

    host = _jax.tree.map(lambda x: np.asarray(x), variables)
    return model, host


def _get_path(tree, path):
    node = tree
    for key in path.split("/"):
        node = node[key]
    return node


def test_quantize_tree_structure_and_scale_sidecar(tiny_model_vars):
    _, variables = tiny_model_vars
    served = quant.quantize_tree(variables)
    paths = quant.quantized_paths(variables)
    assert paths
    for path in paths:
        leaf = _get_path(served, path)
        master = _get_path(variables, path)
        assert leaf.dtype == np.int8, path
        # The sidecar scale mirrors the module path with a `_scale` suffix
        # (exactly where QuantDense/QuantConv look it up) and inverts to
        # within half a step per channel.
        scale_path = path.replace("params/", "", 1) + "_scale"
        scale = _get_path(served[quant.QUANT_COLLECTION], scale_path)
        assert scale.shape == (master.shape[-1],)
        err = np.abs(quant.dequantize(leaf, scale) - master)
        assert np.all(err <= scale * 0.5 + EPS), path
    # Undeclared leaves (biases, norms, embeddings) ride through untouched.
    bias = _get_path(served, "params/transformer/layer_0/attn/query/bias")
    np.testing.assert_array_equal(
        bias, _get_path(variables, "params/transformer/layer_0/attn/query/bias")
    )
    assert bias.dtype == np.float32


def test_quantize_tree_error_cases(tiny_model_vars):
    _, variables = tiny_model_vars
    # An empty rule set would serve a byte-identical f32 tree while
    # reporting an int8 engine — refused loudly.
    with pytest.raises(ValueError, match="no leaf matched"):
        quant.quantize_tree(variables, rules=[])
    with pytest.raises(ValueError, match="'params'"):
        quant.quantize_tree({"batch_stats": {}})
    with pytest.raises(ValueError, match="inference_dtype"):
        quant.check_inference_dtype("fp8")
    # serving_preparer: identity for f32, transforms otherwise.
    assert quant.serving_preparer("f32") is None
    assert quant.serving_preparer("int8") is not None


# ------------------------------------------------------------ quant layers


def test_quant_layers_identical_to_stock_flax_on_f32_trees():
    """QuantDense/QuantConv override only param retrieval: on an f32 tree
    they are bit-identical to nn.Dense/nn.Conv (training and checkpoints
    never see the difference)."""
    import flax.linen as nn
    import jax

    x = np.linspace(-1.0, 1.0, 24, dtype=np.float32).reshape(2, 12)
    params = nn.Dense(6).init(jax.random.PRNGKey(0), x)
    np.testing.assert_array_equal(
        nn.Dense(6).apply(params, x), quant.QuantDense(6).apply(params, x)
    )
    img = np.linspace(0.0, 1.0, 2 * 8 * 8 * 3, dtype=np.float32).reshape(
        2, 8, 8, 3
    )
    cparams = nn.Conv(4, (3, 3)).init(jax.random.PRNGKey(1), img)
    np.testing.assert_array_equal(
        nn.Conv(4, (3, 3)).apply(cparams, img),
        quant.QuantConv(4, (3, 3)).apply(cparams, img),
    )


def test_quant_dense_dequantizes_int8_kernel():
    import jax

    rng = np.random.default_rng(2)
    x = rng.standard_normal((4, 12)).astype(np.float32)
    dense_params = quant.QuantDense(6).init(jax.random.PRNGKey(0), x)
    kernel = np.asarray(dense_params["params"]["kernel"])
    q, scale = quant.quantize_per_channel(kernel)
    out = quant.QuantDense(6).apply(
        {
            "params": {"kernel": q, "bias": dense_params["params"]["bias"]},
            quant.QUANT_COLLECTION: {"kernel_scale": scale},
        },
        x,
    )
    ref = quant.QuantDense(6).apply(dense_params, x)
    # Weight-only quantization error bound: |Δout| ≤ |x| @ (scale/2).
    bound = np.abs(x) @ np.full((12, 6), 1.0) * (scale * 0.5).max() + 1e-5
    assert np.all(np.abs(np.asarray(out) - np.asarray(ref)) <= bound)


def test_int8_kernel_without_scale_is_a_hard_error():
    """Serving raw int8 integers through a matmul would return garbage
    with 200 OK — an int8 leaf with no sidecar scale must refuse."""
    params = {
        "params": {
            "kernel": np.ones((12, 6), np.int8),
            "bias": np.zeros(6, np.float32),
        }
    }
    with pytest.raises(ValueError, match="quantize_tree"):
        quant.QuantDense(6).apply(params, np.ones((2, 12), np.float32))


# -------------------------------------------------------- engine-level gate


@pytest.fixture(scope="module")
def tiny_engines():
    """f32 + int8 engines through the REAL restore path (random init is
    deterministic, so both serve the same master weights)."""
    from rt1_tpu.eval.restore import build_serve_engine
    from rt1_tpu.train.configs import tiny

    config = tiny.get_config()
    engines = {}
    for dtype in ("f32", "int8"):
        engine, step = build_serve_engine(
            config, workdir=None, inference_dtype=dtype, max_sessions=4
        )
        assert step == -1
        engines[dtype] = engine
    return config, engines


def test_int8_engine_parity_gate(tiny_engines):
    """THE acceptance bar: ≥99% action-token agreement int8-vs-f32 on the
    canned episode set, with the single-compile invariant intact."""
    from rt1_tpu.serve.parity import PARITY_THRESHOLD, check_parity

    config, engines = tiny_engines
    shape = (config.data.height, config.data.width, 3)
    stats = check_parity(engines["f32"], engines["int8"], shape)
    assert stats["passed"] and stats["agreement"] >= PARITY_THRESHOLD
    assert stats["tokens_total"] > 0
    assert engines["f32"].compile_count == 1
    assert engines["int8"].compile_count == 1
    assert engines["int8"].inference_dtype == "int8"


def test_parity_gate_raises_below_threshold(tiny_engines):
    """The gate's failure mode is a refusal, not a warning."""
    from rt1_tpu.serve.parity import check_parity

    config, engines = tiny_engines
    shape = (config.data.height, config.data.width, 3)
    with pytest.raises(ValueError, match="parity gate FAILED"):
        check_parity(
            engines["f32"],
            engines["int8"],
            shape,
            threshold=1.01,  # unreachable: forces the refusal path
            episodes=1,
            steps=2,
        )


def test_int8_engine_byte_accounting(tiny_engines):
    """The memory win is real device bytes: the int8 serving tree is
    smaller than f32's, while both report the same f32 master bytes (the
    checkpoint contract reloads validate against)."""
    _, engines = tiny_engines
    f32, int8 = engines["f32"], engines["int8"]
    assert f32.serving_param_bytes == f32.master_param_bytes
    assert int8.master_param_bytes == f32.master_param_bytes
    assert int8.serving_param_bytes < f32.serving_param_bytes


def test_quantized_hot_swap_accepts_masters_rejects_precast(tiny_engines):
    """ISSUE satellite regression: in int8 mode the standby arrives as an
    f32 MASTER checkpoint — `swap_variables` validates it against the
    master spec, requantizes, and keeps compile_count 1; a tree pre-cast
    or pre-quantized to serving dtypes is rejected (it would recompile or
    serve garbage)."""
    import jax

    from rt1_tpu.eval.restore import load_standby_variables

    config, engines = tiny_engines
    engine = engines["int8"]
    rng = np.random.default_rng(11)
    emb = rng.standard_normal(512).astype(np.float32)
    stream = [
        {
            "image": rng.random(
                (config.data.height, config.data.width, 3), dtype=np.float32
            ),
            "natural_language_embedding": emb,
        }
        for _ in range(3)
    ]
    engine.reset("swap")
    before = [engine.act("swap", obs) for obs in stream]

    # The PR 6 contract: workdir=None rebuilds the same deterministic
    # random init, as f32 masters — the reload path of a quantized fleet.
    standby, step = load_standby_variables(config, workdir=None)
    assert step == -1
    info = engine.swap_variables(standby)
    assert info["inference_dtype"] == "int8"
    assert engine.reloads == 1
    assert engine.compile_count == 1

    # Identical masters → identical requantization → bit-identical tokens.
    engine.reset("swap")
    after = [engine.act("swap", obs) for obs in stream]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b["action_tokens"], a["action_tokens"])
        np.testing.assert_array_equal(b["action"], a["action"])

    # A pre-quantized serving tree has a different structure (the quant
    # collection) — rejected against the master spec.
    with pytest.raises(ValueError, match="master"):
        engine.swap_variables(quant.quantize_tree(standby))
    # A bf16 pre-cast matches the structure but not the master dtypes.
    with pytest.raises(ValueError, match="master spec"):
        engine.swap_variables(quant.cast_tree(standby))
    assert engine.reloads == 1  # both refusals left the engine untouched
    assert engine.compile_count == 1
    engine.release("swap")


def test_bf16_restore_bit_identical_to_bf16_compute():
    """bf16 mode's correctness story: casting every float leaf ONCE at
    restore (half the resident bytes) is bit-identical to flax's own
    compute-dtype cast at use sites — same model, same tokens, same
    actions."""
    from rt1_tpu.eval.restore import (
        _config_with_model_dtype,
        build_serve_engine,
    )
    from rt1_tpu.train.configs import tiny

    config = tiny.get_config()
    restore_engine, _ = build_serve_engine(
        config, workdir=None, inference_dtype="bf16", max_sessions=1
    )
    assert restore_engine.inference_dtype == "bf16"
    # Reference: f32 masters + a bf16-compute model (the cast happens at
    # every use site instead of once at restore).
    compute_engine, _ = build_serve_engine(
        _config_with_model_dtype(config, "bfloat16"),
        workdir=None,
        inference_dtype="f32",
        max_sessions=1,
    )
    rng = np.random.default_rng(21)
    emb = rng.standard_normal(512).astype(np.float32)
    for step in range(3):
        obs = {
            "image": rng.random(
                (config.data.height, config.data.width, 3), dtype=np.float32
            ),
            "natural_language_embedding": emb,
        }
        a = restore_engine.act("s", dict(obs))
        b = compute_engine.act("s", dict(obs))
        np.testing.assert_array_equal(a["action_tokens"], b["action_tokens"])
        np.testing.assert_array_equal(a["action"], b["action"])
    # bf16 at rest is half the f32 master bytes.
    assert (
        restore_engine.serving_param_bytes
        == restore_engine.master_param_bytes // 2
    )
