"""xArm6 FK/IK tests, mirroring reference `utils/xarm_sim_robot_test.py`
intent: FK determinism + plausibility, IK∘FK round-trip to tight tolerance,
and Pose3d algebra.
"""

import numpy as np
import pytest
from scipy.spatial import transform

from rt1_tpu.envs import constants
from rt1_tpu.envs.utils import Pose3d, XArmKinematics
from rt1_tpu.envs.utils.xarm import HOME_JOINT_POSITIONS


@pytest.fixture(scope="module")
def arm():
    return XArmKinematics()


def test_fk_home_pose_plausible(arm):
    pose = arm.forward(HOME_JOINT_POSITIONS)
    x, y, z = pose.translation
    # Home posture reaches forward over the table at a sane height.
    assert 0.1 < x < 0.7
    assert abs(y) < 0.3
    assert 0.0 < z < 0.6


def test_fk_deterministic(arm):
    q = np.array([0.3, -0.5, -0.7, 0.2, 0.9, -0.4])
    p1, p2 = arm.forward(q), arm.forward(q)
    np.testing.assert_array_equal(p1.translation, p2.translation)
    np.testing.assert_array_equal(
        p1.rotation.as_quat(), p2.rotation.as_quat()
    )


def test_fk_reference_initial_joints_parity(arm):
    """The strongest parity check available without the URDF: the reference
    documents that INITIAL_JOINT_POSITIONS corresponds to translation
    (0.3, -0.2, 0.145) with rotation rotvec [0, pi, 0]
    (`environments/constants.py:59-65`). Our DH model reproduces it to
    sub-millimeter accuracy."""
    init = np.array(
        [
            -0.5875016909413221,
            0.15985553866983415,
            -0.4992862770497537,
            0.0017427885915130214,
            0.33927183830553914,
            -3.7249551487437524,
        ]
    )
    pose = arm.forward(init)
    np.testing.assert_allclose(
        pose.translation, [0.3, -0.2, 0.145], atol=1e-3
    )
    np.testing.assert_allclose(
        pose.rotation.as_rotvec(), [0.0, np.pi, 0.0], atol=1e-2
    )


def test_fk_zero_config(arm):
    # xArm6 zero posture folds forward: flange near (0.207, 0, 0.112).
    pose = arm.forward(np.zeros(6))
    np.testing.assert_allclose(
        pose.translation, [0.207, 0.0, 0.112], atol=5e-3
    )


def test_ik_fk_roundtrip(arm):
    # Reference asserts IK∘FK to 2 decimals (`xarm_sim_robot_test.py:41-78`);
    # our DLS converges much tighter.
    rng = np.random.RandomState(0)
    for _ in range(5):
        q = HOME_JOINT_POSITIONS + rng.uniform(-0.3, 0.3, 6)
        target = arm.forward(q)
        q_sol = arm.inverse(target, initial_joints=HOME_JOINT_POSITIONS)
        assert q_sol is not None
        reached = arm.forward(q_sol)
        np.testing.assert_allclose(
            reached.translation, target.translation, atol=1e-3
        )


def test_ik_workspace_target(arm):
    # The Language-Table effector pose: down-pointing at EFFECTOR_HEIGHT.
    target = Pose3d(
        rotation=transform.Rotation.from_rotvec(
            constants.EFFECTOR_DOWN_ROTVEC
        ),
        translation=np.array(
            [constants.CENTER_X, constants.CENTER_Y, constants.EFFECTOR_HEIGHT]
        ),
    )
    q = arm.inverse(target)
    assert q is not None
    reached = arm.forward(q)
    np.testing.assert_allclose(
        reached.translation, target.translation, atol=2e-3
    )


def test_ik_unreachable_returns_none(arm):
    target = Pose3d(
        rotation=transform.Rotation.identity(),
        translation=np.array([5.0, 5.0, 5.0]),  # far outside reach
    )
    assert arm.inverse(target, max_iters=50) is None


def test_pose3d_algebra():
    a = Pose3d(
        rotation=transform.Rotation.from_euler("z", 0.5),
        translation=np.array([1.0, 2.0, 3.0]),
    )
    identity = a.multiply(a.inverse())
    np.testing.assert_allclose(identity.translation, 0.0, atol=1e-12)
    np.testing.assert_allclose(
        identity.rotation.as_matrix(), np.eye(3), atol=1e-12
    )
    # serialize round trip (float-list conversion renormalizes the quat, so
    # compare numerically; __eq__ is intentionally exact like the reference).
    b = Pose3d.deserialize(a.serialize())
    np.testing.assert_allclose(
        b.rotation.as_quat(), a.rotation.as_quat(), atol=1e-15
    )
    np.testing.assert_array_equal(b.translation, a.translation)
    assert a == a
    assert a.vec7.shape == (7,)
