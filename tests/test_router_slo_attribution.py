"""Per-replica SLO attribution (ISSUE 16 satellite): the router books
every routed outcome against the replica that answered it, so one
replica's burn — the canary question — is distinguishable from the
fleet's. In-process stub replicas + a Router instance, no subprocesses:
tier-1 fast."""

import pytest

from rt1_tpu.obs import prometheus as prom
from rt1_tpu.serve.router import READY, Replica, Router
from rt1_tpu.serve.stub import StubReplicaApp, make_stub_server


@pytest.fixture()
def fleet():
    apps, servers, threads = [], [], []
    router = Router(replica_timeout_s=5.0)
    import threading

    for rid in range(2):
        app = StubReplicaApp(replica_id=rid)
        httpd = make_stub_server(app)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        host, port = httpd.server_address[:2]
        replica = router.add_replica(
            Replica(rid, url=f"http://{host}:{port}")
        )
        replica.state = READY
        apps.append(app)
        servers.append(httpd)
        threads.append(thread)
    yield router, servers
    for httpd in servers:
        try:
            httpd.shutdown()
            httpd.server_close()
        except OSError:
            pass


def _act(router, session_id):
    return router.route_act(
        {"session_id": session_id, "image_b64": "AAAA"}
    )


def test_outcomes_attributed_to_serving_replica(fleet):
    router, _ = fleet
    # Least-loaded placement with a lower-id tiebreak: "a" lands on
    # replica 0, "b" on replica 1 — a deterministic 2-way split.
    for _ in range(3):
        status, body = _act(router, "a")
        assert status == 200 and body["replica_id"] == 0
    for _ in range(2):
        status, body = _act(router, "b")
        assert status == 200 and body["replica_id"] == 1

    snap = router.replica_slo_snapshot()
    assert set(snap) == {0, 1}
    assert snap[0]["outcomes"]["ok"] == 3
    assert snap[1]["outcomes"]["ok"] == 2
    for entry in snap.values():
        assert entry["requests_total"] == sum(entry["outcomes"].values())
        assert entry["availability_rolling"] == 1.0
        assert entry["error_budget_burn_rolling"] == 0.0
    # Per-replica counts sum to the fleet ledger's — same outcome stream,
    # two attributions.
    fleet_gauges = router.slo.gauges()
    assert fleet_gauges["slo_requests_ok"] == 5

    # The attribution rides /fleet/status...
    status_view = router.fleet_status(probe_metrics=False)
    by_id = {e["id"]: e for e in status_view["replicas"]}
    assert by_id[0]["slo"]["outcomes"]["ok"] == 3
    assert by_id[1]["slo"]["outcomes"]["ok"] == 2
    # ...the JSON fan-out...
    json_view = router.fleet_metrics_snapshot()
    assert json_view["replica_slo"]["0"]["outcomes"]["ok"] == 3
    # ...and the Prometheus exposition.
    text = router.fleet_metrics_prometheus()
    assert (
        'rt1_serve_replica_outcome_total{replica_id="0",outcome="ok"} 3'
        in text
    )
    assert (
        'rt1_serve_replica_slo_error_budget_burn_rolling{replica_id="1"} 0'
        in text
    )


def test_sheds_without_a_replica_stay_fleet_wide(fleet):
    router, _ = fleet
    status, body = _act(router, "a")
    assert status == 200
    router.draining = True
    status, _ = _act(router, "a")
    assert status == 503
    router.draining = False
    # The shed burned fleet-wide budget but no replica produced it:
    # blaming one would poison a canary verdict.
    assert router.slo.gauges()["slo_requests_rejected"] == 1
    snap = router.replica_slo_snapshot()
    assert sum(e["outcomes"]["rejected"] for e in snap.values()) == 0
    assert sum(e["requests_total"] for e in snap.values()) == 1


def test_replica_death_attributes_final_outcome_to_survivor(fleet):
    router, servers = fleet
    status, body = _act(router, "a")  # -> replica 0
    assert status == 200 and body["replica_id"] == 0
    status, body = _act(router, "b")  # -> replica 1
    assert status == 200 and body["replica_id"] == 1
    # Kill replica 1's server: session "b"'s next act fails over to
    # replica 0 and surfaces restarted:true. The final outcome class
    # (restarted) is attributed to the replica that ANSWERED — the dead
    # one reports nothing (its absence shows up as replica_up 0).
    servers[1].shutdown()
    servers[1].server_close()
    status, body = _act(router, "b")
    assert status == 200
    assert body["restarted"] is True
    assert body["replica_id"] == 0
    snap = router.replica_slo_snapshot()
    assert snap[0]["outcomes"]["restarted"] == 1
    assert snap[1]["outcomes"] == {
        "ok": 1, "migrated": 0, "restarted": 0, "rejected": 0, "failed": 0
    }


def test_remove_replica_drops_its_ledger(fleet):
    router, _ = fleet
    _act(router, "a")
    _act(router, "b")
    assert set(router.replica_slo_snapshot()) == {0, 1}
    router.remove_replica(1)
    # Dropped, not zeroed — same ghost-purge contract as the metrics
    # fan-out: a reclaimed replica's series vanish from every view.
    snap = router.replica_slo_snapshot()
    assert set(snap) == {0}
    text = router.fleet_metrics_prometheus()
    assert 'rt1_serve_replica_outcome_total{replica_id="1"' not in text
    assert 'rt1_serve_replica_outcome_total{replica_id="0"' in text
    status_view = router.fleet_status(probe_metrics=False)
    assert [e["id"] for e in status_view["replicas"]] == [0]
