"""Golden validation of the torch->flax EfficientNet weight porter.

The reference proves its blind ordered-zip load with a real-image golden test
('tabby', `film_efficientnet/film_efficientnet_encoder_test.py:54-80`) — the
pretrained blobs aren't in this image, so the equivalent proof here is
*functional*: build a torch EfficientNet-B3 whose module registration order
matches torchvision's state-dict layout (driven by the SAME
`EfficientNet.block_configs()` the flax model uses), randomize every weight
AND BatchNorm running stat, port the state dict, and require the flax model
to reproduce the torch activations on a fixed input. Any drift in the
ordered-zip alignment — one module swapped, a BN stat crossed, a conv layout
transposed — changes the output and fails the allclose.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-size compiles / heavy module fixture

torch = pytest.importorskip("torch")

from rt1_tpu.models.efficientnet import EfficientNetB3, round_filters
from rt1_tpu.models.load_pretrained import port_torch_efficientnet


class TorchSE(torch.nn.Module):
    """torchvision SqueezeExcitation layout: fc1/fc2 1x1 convs."""

    def __init__(self, expand_size, block_in_size, se_ratio=0.25):
        super().__init__()
        se_size = max(1, int(block_in_size * se_ratio))
        self.fc1 = torch.nn.Conv2d(expand_size, se_size, 1)
        self.fc2 = torch.nn.Conv2d(se_size, expand_size, 1)

    def forward(self, x):
        s = x.mean((2, 3), keepdim=True)
        s = torch.nn.functional.silu(self.fc1(s))
        return x * torch.sigmoid(self.fc2(s))


class TorchConvBnAct(torch.nn.Module):
    def __init__(self, cin, cout, k, stride=1, groups=1, act=True):
        super().__init__()
        self.conv = torch.nn.Conv2d(
            cin, cout, k, stride=stride, padding=(k - 1) // 2,
            groups=groups, bias=False,
        )
        self.bn = torch.nn.BatchNorm2d(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return torch.nn.functional.silu(x) if self.act else x


class TorchMBConv(torch.nn.Module):
    def __init__(self, cfg):
        super().__init__()
        cin, cout = cfg["in_size"], cfg["out_size"]
        expand = cin * cfg["expand_ratio"]
        self.use_skip = cfg["strides"] == 1 and cin == cout
        if cfg["expand_ratio"] != 1:
            self.expand = TorchConvBnAct(cin, expand, 1)
        self.depthwise = TorchConvBnAct(
            expand, expand, cfg["kernel_size"], stride=cfg["strides"],
            groups=expand,
        )
        self.se = TorchSE(expand, cin, cfg["se_ratio"])
        self.project = TorchConvBnAct(expand, cout, 1, act=False)

    def forward(self, x):
        inputs = x
        if hasattr(self, "expand"):
            x = self.expand(x)
        x = self.project(self.se(self.depthwise(x)))
        return inputs + x if self.use_skip else x


class TorchEffNetB3(torch.nn.Module):
    """Same construction order as the flax model (and torchvision's layout):
    stem, blocks (expand/depthwise/se/project), top, classifier."""

    def __init__(self, flax_model, classes=10):
        super().__init__()
        div, wc = flax_model.depth_divisor, flax_model.width_coefficient
        stem_ch = round_filters(32, div, wc)
        self.stem = TorchConvBnAct(3, stem_ch, 3, stride=2)
        self.blocks = torch.nn.ModuleList(
            [TorchMBConv(cfg) for cfg in flax_model.block_configs()]
        )
        top_ch = round_filters(1280, div, wc)
        last = flax_model.block_configs()[-1]["out_size"]
        self.top = TorchConvBnAct(last, top_ch, 1)
        self.classifier = torch.nn.Linear(top_ch, classes)

    def forward(self, x):
        x = self.stem(x)
        for b in self.blocks:
            x = b(x)
        x = self.top(x)
        x = x.mean((2, 3))
        return self.classifier(x)


def _randomize(model, seed=0):
    """Random weights + non-trivial BN running stats (catches stat swaps)."""
    g = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, (torch.nn.Conv2d, torch.nn.Linear)):
                m.weight.normal_(0, 0.05, generator=g)
                if m.bias is not None:
                    m.bias.normal_(0, 0.05, generator=g)
            elif isinstance(m, torch.nn.BatchNorm2d):
                m.weight.uniform_(0.8, 1.2, generator=g)
                m.bias.normal_(0, 0.05, generator=g)
                m.running_mean.normal_(0, 0.05, generator=g)
                m.running_var.uniform_(0.8, 1.2, generator=g)


@pytest.fixture(scope="module")
def golden():
    import jax

    flax_model = EfficientNetB3(include_top=True, classes=10)
    tmodel = TorchEffNetB3(flax_model, classes=10)
    _randomize(tmodel)
    tmodel.eval()

    x = np.random.default_rng(1).uniform(0, 1, (2, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        y_torch = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()

    variables = flax_model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 64, 64, 3), np.float32)
    )
    return flax_model, tmodel.state_dict(), x, y_torch, variables


def test_ported_b3_reproduces_torch_activations(golden):
    """The golden check: flax(port(torch weights)) == torch forward."""
    flax_model, state_dict, x, y_torch, variables = golden
    ported = port_torch_efficientnet(state_dict, variables)
    y_flax = np.asarray(
        flax_model.apply(
            {"params": ported["params"], "batch_stats": ported["batch_stats"]},
            x,
            train=False,
        )
    )
    np.testing.assert_allclose(y_flax, y_torch, rtol=1e-3, atol=1e-4)


def test_one_module_drift_fails(golden):
    """Deleting one mid-net block module breaks the count check — the
    ordered zip cannot silently misalign."""
    flax_model, state_dict, x, y_torch, variables = golden
    broken = {
        k: v for k, v in state_dict.items() if "blocks.7.se.fc1" not in k
    }
    with pytest.raises(ValueError):
        port_torch_efficientnet(broken, variables)


def test_film_variant_preserves_ported_behavior(golden):
    """Porting into the FiLM model leaves zero-init FiLM layers untouched, so
    the conditioned-net output with any context equals the plain net
    (reference `film_efficientnet_encoder.py:400-407` behavior)."""
    import jax

    flax_model, state_dict, x, y_torch, variables = golden
    film = EfficientNetB3(include_top=True, classes=10, include_film=True)
    film_vars = film.init(
        {"params": jax.random.PRNGKey(0)},
        np.zeros((1, 64, 64, 3), np.float32),
        np.zeros((1, 8), np.float32),
    )
    ported = port_torch_efficientnet(state_dict, film_vars)
    ctx = np.random.default_rng(2).normal(size=(2, 8)).astype(np.float32)
    y_film = np.asarray(
        film.apply(
            {"params": ported["params"], "batch_stats": ported["batch_stats"]},
            x,
            ctx,
            train=False,
        )
    )
    np.testing.assert_allclose(y_film, y_torch, rtol=1e-3, atol=1e-4)
