"""RT-1 network integration tests.

Mirrors the reference's `transformer_network_test.py`: train-mode loss shapes
(`:50-69`), inference with rolling state (`:75-93`), and the **causality test**
(`:99-157`) — the semantic spec of the custom mask. Adds the single-pass ≡
autoregressive equivalence proof that justifies our 1-pass inference design.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rt1_tpu.models.rt1 import RT1Policy
from rt1_tpu.specs import language_table_action_space, sample_space

T = 3          # time_sequence_length (tiny for CPU)
I_TOK = 2      # image tokens per frame
A_TOK = 3      # action tokens (language-table space)
EMB = 16
VOCAB = 32
H = W = 16


from rt1_tpu.models.tiny_tokenizer import TinyImageTokenizer  # noqa: E402


def tiny_policy(**kw):
    cfg = dict(
        action_space=language_table_action_space(),
        vocab_size=VOCAB,
        token_embedding_size=EMB,
        num_layers=2,
        layer_size=8,
        num_heads=2,
        feed_forward_size=16,
        dropout_rate=0.0,
        time_sequence_length=T,
        num_image_tokens=I_TOK,
        image_tokenizer_def=TinyImageTokenizer(num_tokens=I_TOK, emb=EMB),
    )
    cfg.update(kw)
    return RT1Policy(**cfg)


def make_batch(rng, b=2):
    obs = {
        "image": jax.random.uniform(rng, (b, T, H, W, 3)),
        "natural_language_embedding": jax.random.normal(jax.random.fold_in(rng, 1), (b, T, 8)),
    }
    actions = sample_space(language_table_action_space(), jax.random.fold_in(rng, 2), (b, T))
    return obs, actions


@pytest.fixture(scope="module")
def policy_and_params():
    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng)
    params = model.init({"params": rng, "crop": rng}, obs, actions, train=False)
    return model, params


def test_train_forward_shapes(policy_and_params, rng):
    model, params = policy_and_params
    obs, actions = make_batch(rng, b=2)
    out = model.apply(params, obs, actions, train=True, rngs={"crop": rng})
    assert out["loss"].shape == ()
    assert out["action_loss"].shape == (2, T)        # (b, t) like reference :317-322
    assert out["action_predictions"].shape == (2, T, A_TOK)
    assert out["action_labels"].shape == (2, T, A_TOK)
    assert out["action_logits"].shape == (2, T, A_TOK, VOCAB)
    assert np.isfinite(float(out["loss"]))


def test_reference_loss_scaling(policy_and_params, rng):
    """loss_scale='reference' divides per-(b,t) CE mean by b·t·(I+A) (:314-320)."""
    model, params = policy_and_params
    obs, actions = make_batch(rng, b=2)
    out_ref = model.apply(params, obs, actions, train=False)
    model_mean = tiny_policy(loss_scale="mean")
    out_mean = model_mean.apply(params, obs, actions, train=False)
    num_items = 2 * T * (I_TOK + A_TOK)
    np.testing.assert_allclose(
        np.asarray(out_ref["action_loss"]) * num_items,
        np.asarray(out_mean["action_loss"]),
        rtol=1e-5,
    )


def test_focal_gamma(policy_and_params, rng):
    """focal_gamma modulates the optimized loss by (1-p)^gamma while the
    "cross_entropy" aux output stays raw CE; gamma=0 equals hand-computed
    softmax CE; the modulated loss stays a valid differentiable objective."""
    model, params = policy_and_params
    obs, actions = make_batch(rng, b=2)
    out0 = model.apply(params, obs, actions, train=False)

    # gamma=0 parity against CE computed by hand from the emitted logits —
    # catches a broken gate (e.g. `>= 0` routing through the floor branch).
    logits = np.asarray(out0["action_logits"], np.float64)
    labels = np.asarray(out0["action_labels"])
    logz = np.log(np.exp(logits).sum(-1))
    label_logit = np.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    np.testing.assert_allclose(
        np.asarray(out0["cross_entropy"]), logz - label_logit, rtol=1e-5
    )
    num_items = 2 * T * (I_TOK + A_TOK)
    np.testing.assert_allclose(
        np.asarray(out0["action_loss"]),
        (logz - label_logit).mean(-1) / num_items,
        rtol=1e-5,
    )

    model_f = tiny_policy(focal_gamma=2.0)
    out_f = model_f.apply(params, obs, actions, train=False)
    # Aux CE is unmodulated; the optimized loss is shrunk ((1-p)^2 <= 1).
    np.testing.assert_allclose(
        np.asarray(out_f["cross_entropy"]), np.asarray(out0["cross_entropy"]),
        rtol=1e-6,
    )
    assert np.all(
        np.asarray(out_f["action_loss"]) <= np.asarray(out0["action_loss"]) + 1e-9
    )
    assert float(out_f["loss"]) < float(out0["loss"])

    def loss_fn(p):
        return model_f.apply(p, obs, actions, train=False)["loss"]

    grads = jax.grad(loss_fn)(params)
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
    assert any(float(np.max(np.abs(np.asarray(g)))) > 0 for g in flat)


def test_aux_mse_soft_argmax(policy_and_params, rng):
    """aux_mse_weight adds a parameter-free soft-argmax regression term:
    E[a] under the token softmax vs the clipped continuous label. Bin math
    must agree with the detokenizer, the aux must appear in the output, and
    gradients must flow."""
    from rt1_tpu.models import action_tokenizer
    from rt1_tpu.specs import language_table_action_space

    space = language_table_action_space()
    bins, mask = action_tokenizer.box_bin_values(space, VOCAB)
    assert bins.shape == (A_TOK, VOCAB) and mask.tolist() == [0.0, 1.0, 1.0]
    # A one-hot distribution's expectation == the detokenized bin value.
    tok = jnp.full((1, A_TOK), 7, jnp.int32)
    det = action_tokenizer.detokenize(space, tok, VOCAB)["action"]
    np.testing.assert_allclose(np.asarray(bins[1:, 7]), np.asarray(det[0]), rtol=1e-6)

    model, params = policy_and_params
    obs, actions = make_batch(rng, b=2)
    out0 = model.apply(params, obs, actions, train=False)
    model_a = tiny_policy(aux_mse_weight=10.0)
    out_a = model_a.apply(params, obs, actions, train=False)
    assert "aux_mse" in out_a and float(out_a["aux_mse"]) > 0
    # Under 'reference' scaling the aux term shares the CE normalizer, so
    # accumulation exactness and CE/aux balance are batch-independent.
    num_items = 2 * T * (I_TOK + A_TOK)
    np.testing.assert_allclose(
        float(out_a["loss"]),
        float(out0["loss"]) + 10.0 * float(out_a["aux_mse"]) / num_items,
        rtol=1e-5,
    )
    grads = jax.grad(
        lambda p: model_a.apply(p, obs, actions, train=False)["loss"]
    )(params)
    assert all(
        np.all(np.isfinite(np.asarray(g)))
        for g in jax.tree_util.tree_leaves(grads)
    )


def test_expected_action_decode(policy_and_params, rng):
    """action_decode='expected' emits E[a] for Box dims: bounded by the
    action space, equal to argmax-decode in the sharp-logit limit, and
    identical Discrete handling; state semantics unchanged."""
    from rt1_tpu.models import action_tokenizer
    from rt1_tpu.specs import language_table_action_space

    space = language_table_action_space()
    # Sharp logits -> expected == detokenize(argmax).
    sharp = np.full((1, A_TOK, VOCAB), -30.0, np.float32)
    for k, tok in enumerate((1, 5, 9)):
        sharp[0, k, tok] = 30.0
    exp = action_tokenizer.detokenize_expected(space, jnp.asarray(sharp), VOCAB)
    hard = action_tokenizer.detokenize(
        space, jnp.asarray([[1, 5, 9]], jnp.int32), VOCAB
    )
    np.testing.assert_allclose(
        np.asarray(exp["action"]), np.asarray(hard["action"]), atol=1e-4
    )
    assert int(exp["terminate_episode"][0]) == int(hard["terminate_episode"][0])
    # OOV Discrete (tok > n, the reference quirk) decodes to 0 here too.
    oov = np.full((1, A_TOK, VOCAB), -30.0, np.float32)
    for k, tok in enumerate((5, 5, 9)):  # Discrete(2) slot gets tok 5 > n
        oov[0, k, tok] = 30.0
    exp_oov = action_tokenizer.detokenize_expected(space, jnp.asarray(oov), VOCAB)
    assert int(exp_oov["terminate_episode"][0]) == 0

    model, params = policy_and_params
    model_e = tiny_policy(action_decode="expected")
    state = model_e.initial_state(batch_size=1)
    frame = {
        "image": jax.random.uniform(rng, (1, H, W, 3)),
        "natural_language_embedding": jax.random.normal(rng, (1, 8)),
    }
    out_e, state_e = model_e.apply(params, frame, state, method=model_e.infer_step)
    out_h, state_h = model.apply(
        params, frame, model.initial_state(batch_size=1), method=model.infer_step
    )
    # E[a] stays inside the Box bounds and the rolling state (argmax tokens)
    # is identical between decode modes.
    assert np.all(np.abs(np.asarray(out_e["action"])) <= 0.1 + 1e-6)
    np.testing.assert_array_equal(
        np.asarray(state_e["action_tokens"]), np.asarray(state_h["action_tokens"])
    )


def test_expected_decode_rejects_all_discrete(rng):
    """'expected' with an all-Discrete action space is rejected at setup
    with the real reason (soft decode only differs from argmax for Box) —
    not at trace time by box_bin_values with an aux-MSE-flavored message."""
    from rt1_tpu.specs import DiscreteSpec

    model = tiny_policy(
        action_space={"terminate_episode": DiscreteSpec(2)},
        action_decode="expected",
    )
    frame = {
        "image": jax.random.uniform(rng, (1, H, W, 3)),
        "natural_language_embedding": jax.random.normal(rng, (1, 8)),
    }
    with pytest.raises(ValueError, match="all-Discrete"):
        model.init(
            rng, frame, model.initial_state(batch_size=1),
            method=model.infer_step,
        )


def test_remat_preserves_loss_and_grads(policy_and_params, rng):
    """remat=True is a memory/compute trade, NOT a semantic change: loss and
    gradients must match the stored-activation path. (The tiny tokenizer has
    no MBConv blocks, so this exercises the transformer-side nn.remat; the
    conv-side wrap is pinned by
    tests/test_vision.py::test_efficientnet_remat_grad_parity.)"""
    model, params = policy_and_params
    obs, actions = make_batch(rng, b=2)
    model_r = tiny_policy(remat=True)

    def loss(m, p):
        return m.apply(p, obs, actions, train=False)["loss"]

    l0, g0 = jax.value_and_grad(lambda p: loss(model, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(model_r, p))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5
        ),
        g0,
        g1,
    )


def test_inference_state_machine(policy_and_params, rng):
    """Rolling-window inference over > T steps keeps shapes static and state sane."""
    model, params = policy_and_params
    state = model.initial_state(batch_size=1)
    infer = jax.jit(lambda o, s: model.apply(params, o, s, method=model.infer_step))
    for step in range(T + 2):
        obs = {
            "image": jax.random.uniform(jax.random.fold_in(rng, step), (1, H, W, 3)),
            "natural_language_embedding": jnp.ones((1, 8)),
        }
        out, state = infer(obs, state)
        assert out["action_tokens"].shape == (1, A_TOK)
        assert out["action"].shape == (1, 2)
        assert int(state["seq_idx"]) == min(step + 1, T)
    # Detokenized Box action stays in bounds.
    assert float(jnp.abs(out["action"]).max()) <= 0.1 + 1e-6


def test_single_pass_equals_autoregressive(policy_and_params, rng):
    """Our 1-pass inference is bit-equal to the reference's A-pass loop (:246-268).

    Holds because action tokens are zeroed at input assembly (:383) and the mask
    blocks action→action attention, so the A passes see identical inputs.
    """
    model, params = policy_and_params
    state1 = model.initial_state(1)
    state2 = jax.tree_util.tree_map(jnp.copy, state1)
    for step in range(T + 1):
        obs = {
            "image": jax.random.uniform(jax.random.fold_in(rng, 100 + step), (1, H, W, 3)),
            "natural_language_embedding": jnp.ones((1, 8)),
        }
        out1, state1 = model.apply(params, obs, state1, method=model.infer_step)
        out2, state2 = model.apply(params, obs, state2, method=model.infer_step_autoregressive)
        np.testing.assert_array_equal(np.asarray(out1["action_tokens"]), np.asarray(out2["action_tokens"]))
        np.testing.assert_allclose(
            np.asarray(out1["action_logits"]), np.asarray(out2["action_logits"]), atol=1e-5
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5),
            state1, state2,
        )


def test_causality(policy_and_params, rng):
    """Port of the reference causality test (transformer_network_test.py:99-157).

    With the custom mask, the logits that produce timestep t's action depend only on
    observations ≤ t: feeding observations that differ only at times > t leaves the
    action logits at t unchanged.
    """
    model, params = policy_and_params
    obs, actions = make_batch(rng, b=1)

    out_full = model.apply(params, obs, actions, train=False)
    logits_full = np.asarray(out_full["action_logits"])  # (1, T, A, V)

    for t_cut in range(T):
        # Perturb every frame strictly after t_cut.
        obs_cut = {
            "image": obs["image"].at[:, t_cut + 1 :].set(0.123),
            "natural_language_embedding": obs["natural_language_embedding"],
        }
        out_cut = model.apply(params, obs_cut, actions, train=False)
        logits_cut = np.asarray(out_cut["action_logits"])
        np.testing.assert_allclose(
            logits_full[:, : t_cut + 1],
            logits_cut[:, : t_cut + 1],
            atol=1e-5,
            err_msg=f"future perturbation leaked into t<={t_cut}",
        )
        if t_cut < T - 1:
            assert not np.allclose(logits_full[:, t_cut + 1 :], logits_cut[:, t_cut + 1 :])


def test_inference_matches_training_logits(policy_and_params, rng):
    """Feeding the same T frames step-by-step reproduces the training-mode logits of
    the final step (the inference cache is exact, not approximate)."""
    model, params = policy_and_params
    obs, actions = make_batch(rng, b=1)
    out_train = model.apply(params, obs, actions, train=False)

    state = model.initial_state(1)
    for step in range(T):
        frame = {
            "image": obs["image"][:, step],
            "natural_language_embedding": obs["natural_language_embedding"][:, step],
        }
        out, state = model.apply(params, frame, state, method=model.infer_step)
    np.testing.assert_allclose(
        np.asarray(out["action_logits"]),
        np.asarray(out_train["action_logits"])[:, -1],
        atol=1e-5,
    )


def test_params_are_time_sequence_length_invariant(rng):
    """Pins the bench.py infer-mode init trick (`bench.py:120-124`): params
    initialized with a time_sequence_length=1 clone must be structurally and
    shape-wise identical to the full-T model's (the positional table floors
    at 256 rows, so no parameter depends on T). If a posemb change ever makes
    params T-dependent, this fails before the bench silently loads garbage."""
    model_t = tiny_policy()
    model_1 = model_t.clone(time_sequence_length=1)
    obs, actions = make_batch(rng, b=1)
    obs1 = jax.tree.map(lambda x: x[:, :1], obs)
    act1 = jax.tree.map(lambda x: x[:, :1], actions)
    p_t = model_t.init({"params": rng, "crop": rng}, obs, actions, train=False)
    p_1 = model_1.init({"params": rng, "crop": rng}, obs1, act1, train=False)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a.shape, b.shape),
        p_t["params"],
        p_1["params"],
    )
    # And the t=1 params actually run under the full-T model.
    out = model_t.apply(p_1, obs, actions, train=False)
    assert np.isfinite(float(out["loss"]))
