"""Vision stack tests: FiLM, EfficientNet-B3, encoder, TokenLearner, image tokenizer.

Mirrors reference coverage in `film_efficientnet_encoder_test.py`,
`pretrained_efficientnet_encoder_test.py:46-86`, `token_learner_test.py:28-39`,
`image_tokenizer_test.py:30-46` (shape + FiLM-zero-init behavioral checks; the
pretrained-'tabby' golden test needs ImageNet blobs absent from this image — the
zero-init invariance test below proves the same property structurally).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rt1_tpu.models.efficientnet import EfficientNet, EfficientNetB3, round_filters, round_repeats
from rt1_tpu.models.encoder import EfficientNetEncoder
from rt1_tpu.models.film import FilmConditioning
from rt1_tpu.models.image_tokenizer import RT1ImageTokenizer
from rt1_tpu.models.token_learner import TokenLearner

# A tiny EfficientNet (width/depth 0.1 → minimum channels, 7 blocks) for fast CPU tests.
TINY = dict(width_coefficient=0.1, depth_coefficient=0.1, dropout_rate=0.1)


def test_round_filters_b3():
    # B3 widths: stem 40, stage outs 24,32,48,96,136,232,384, top 1536.
    assert round_filters(32, 8, 1.2) == 40
    assert [round_filters(c, 8, 1.2) for c in (16, 24, 40, 80, 112, 192, 320)] == [
        24, 32, 48, 96, 136, 232, 384]
    assert round_filters(1280, 8, 1.2) == 1536


def test_round_repeats_b3_block_count():
    reps = [round_repeats(r, 1.4) for r in (1, 2, 2, 3, 3, 4, 1)]
    assert sum(reps) == 26  # 26 MBConv blocks in B3 (SURVEY §2.1)
    cfgs = EfficientNetB3().block_configs()
    assert len(cfgs) == 26
    # drop rate increases linearly from 0 (reference :303).
    assert cfgs[0]["drop_rate"] == 0.0
    assert cfgs[-1]["drop_rate"] == pytest.approx(0.2 * 25 / 26)


def test_film_zero_init_is_identity(rng):
    film = FilmConditioning(num_channels=8)
    x = jax.random.normal(rng, (2, 4, 4, 8))
    ctx = jax.random.normal(jax.random.fold_in(rng, 1), (2, 512))
    params = film.init(rng, x, ctx)
    out = film.apply(params, x, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)


def test_film_efficientnet_matches_plain_at_init(rng):
    """FiLM layers are zero-init ⇒ conditioned net ≡ unconditioned net at init.

    This is the structural content of the reference's pretrained-weights golden test
    (film_efficientnet_encoder_test.py:54-80): adding FiLM must not change function.
    """
    img = jax.random.uniform(rng, (1, 64, 64, 3))
    ctx = jax.random.normal(jax.random.fold_in(rng, 1), (1, 512))
    plain = EfficientNet(**TINY, include_top=True, classes=10, include_film=False)
    filmed = EfficientNet(**TINY, include_top=True, classes=10, include_film=True)
    p1 = plain.init(rng, img, train=False)
    p2 = filmed.init(rng, img, context=ctx, train=False)
    # Graft the plain params into the filmed net (FiLM params stay zero).
    merged = jax.tree_util.tree_map(lambda x: x, p2)
    flat1 = flax_flatten(p1)
    flat2 = flax_flatten(merged)
    for k, v in flat1.items():
        assert k in flat2, k
        flat2[k] = v
    merged = flax_unflatten(flat2)
    out_plain = plain.apply(p1, img, train=False)
    out_filmed = filmed.apply(merged, img, context=ctx, train=False)
    np.testing.assert_allclose(np.asarray(out_plain), np.asarray(out_filmed), atol=1e-5)


def flax_flatten(tree):
    from flax.traverse_util import flatten_dict

    return dict(flatten_dict(tree))


def flax_unflatten(flat):
    from flax.traverse_util import unflatten_dict

    return unflatten_dict(flat)


def test_efficientnet_feature_map_shape(rng):
    """No-top output is (B, ceil(H/32), ceil(W/32), top_ch)."""
    net = EfficientNet(**TINY, include_top=False)
    img = jnp.zeros((1, 64, 96, 3))
    params = net.init(rng, img, train=False)
    out = net.apply(params, img, train=False)
    assert out.shape == (1, 2, 3, round_filters(1280, 8, 0.1))


@pytest.mark.slow
def test_encoder_pooling_and_map(rng):
    enc = EfficientNetEncoder(token_embedding_size=32, pooling=False)
    img = jnp.zeros((1, 64, 64, 3))
    ctx = jnp.zeros((1, 512))
    variables = enc.init(rng, img, ctx, train=False)
    out = enc.apply(variables, img, ctx, train=False)
    assert out.shape == (1, 2, 2, 32)
    pooled = EfficientNetEncoder(token_embedding_size=32, pooling=True)
    out2 = pooled.apply(variables, img, ctx, train=False)
    assert out2.shape == (1, 32)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out).mean(axis=(1, 2)), rtol=1e-5)


def test_token_learner_shapes(rng):
    tl = TokenLearner(num_tokens=8)
    x = jax.random.normal(rng, (3, 10, 10, 16))
    params = tl.init(rng, x)
    out = tl.apply(params, x)
    assert out.shape == (3, 8, 16)


def test_token_learner_weights_sum_to_one(rng):
    """Constant feature maps must be preserved exactly (softmax weights sum to 1)."""
    tl = TokenLearner(num_tokens=4)
    x = jnp.full((2, 6, 6, 5), 3.5)
    params = tl.init(rng, x)
    out = tl.apply(params, x)
    np.testing.assert_allclose(np.asarray(out), 3.5, rtol=1e-5)


@pytest.mark.slow
def test_image_tokenizer_shapes_b3(rng):
    tok = RT1ImageTokenizer(embedding_output_dim=512, use_token_learner=True, num_tokens=8)
    img = jnp.zeros((1, 2, 64, 64, 3))
    ctx = jnp.zeros((1, 2, 512))
    variables = tok.init(rng, img, ctx, train=False)
    out = tok.apply(variables, img, ctx, train=False)
    assert out.shape == (1, 2, 8, 512)


@pytest.mark.slow
def test_image_tokenizer_no_token_learner(rng):
    tok = RT1ImageTokenizer(embedding_output_dim=64, use_token_learner=False)
    img = jnp.zeros((1, 1, 64, 96, 3))
    ctx = jnp.zeros((1, 1, 512))
    variables = tok.init(rng, img, ctx, train=False)
    out = tok.apply(variables, img, ctx, train=False)
    assert out.shape == (1, 1, 2 * 3, 64)  # h'·w' spatial tokens (reference :80-85)


@pytest.mark.slow
def test_efficientnet_remat_grad_parity():
    """remat=True on the conv trunk (MBConv blocks under jax.checkpoint,
    stochastic depth + FiLM interleaved) must reproduce the stored-
    activation path's loss and gradients."""
    x = jax.random.uniform(jax.random.PRNGKey(0), (2, 32, 32, 3))
    models = [
        EfficientNet(
            width_coefficient=0.35, depth_coefficient=0.35,
            include_top=False, include_film=True, remat=r,
        )
        for r in (False, True)
    ]
    ctx = jax.random.normal(jax.random.PRNGKey(2), (2, 512))
    variables = models[0].init(
        jax.random.PRNGKey(1), x, context=ctx, train=False
    )
    results = []
    for m in models:
        loss, grads = jax.value_and_grad(
            lambda p: jnp.sum(m.apply(p, x, context=ctx, train=False) ** 2)
        )(variables)
        results.append((float(loss), grads))
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        ),
        results[0][1],
        results[1][1],
    )
