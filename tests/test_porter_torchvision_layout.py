"""Porter validation against torchvision's REAL state-dict layout.

VERDICT r2 #3: the functional porter golden (tests/test_porter_golden.py)
builds its torch mirror from the same `block_configs()` the flax model uses,
so a *shared* misreading of torchvision's layout would pass both sides. This
module closes that gap without network access or torchvision itself: the
manifest below is generated from torchvision's own published builder
algorithm (`torchvision/models/efficientnet.py`: `_efficientnet_conf`
bneck_conf table, `_make_divisible` channel rounding, and the
`features.{stage}.{i}.block.{j}` / `Conv2dNormActivation` /
`SqueezeExcitation(fc1/fc2)` module naming), re-derived here independently
of the repo's `EfficientNet.block_configs()`.

Independent anchor: the manifest's learnable-parameter total must equal
**12,233,232** — torchvision's published `efficientnet_b3` parameter count
(torchvision model zoo, `EfficientNet_B3_Weights.IMAGENET1K_V1`). A
mis-remembered channel width, squeeze ratio, repeat count, or a missing
module cannot hit that number.

The tests then require the porter to consume a state dict with EXACTLY this
key order and these shapes — the layout contract the reference's blind
ordered-zip load (`film_efficientnet_encoder.py:411-425`) silently assumes.
Any divergence between the repo's architecture and torchvision's (one conv
swapped, a BN missing, a squeeze width off) breaks the per-kind counts or a
shape check and fails loudly.
"""

import math

import numpy as np
import pytest

TORCHVISION_B3_PARAMS = 12_233_232  # published efficientnet_b3 total


def _make_divisible(v, divisor=8):
    """torchvision.models._utils._make_divisible (min_value=None path)."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def torchvision_b3_manifest():
    """Ordered [(key, shape)] of torchvision efficientnet_b3.state_dict().

    Derived from torchvision's builder: width_mult 1.2 / depth_mult 1.4 over
    the B0 MBConv table, SE squeeze = max(1, block_input_channels // 4),
    head = 4 * last_channels, classifier Linear(head, 1000).
    """
    width, depth = 1.2, 1.4

    def ch(c):
        return _make_divisible(c * width)

    def rep(r):
        return int(math.ceil(r * depth))

    # (expand, kernel, stride, in_base, out_base, repeats_base)
    base = [
        (1, 3, 1, 32, 16, 1),
        (6, 3, 2, 16, 24, 2),
        (6, 5, 2, 24, 40, 2),
        (6, 3, 2, 40, 80, 3),
        (6, 5, 1, 80, 112, 3),
        (6, 5, 2, 112, 192, 4),
        (6, 3, 1, 192, 320, 1),
    ]

    keys = []

    def conv_norm_act(prefix, cin, cout, k, groups=1):
        keys.append((f"{prefix}.0.weight", (cout, cin // groups, k, k)))
        keys.append((f"{prefix}.1.weight", (cout,)))
        keys.append((f"{prefix}.1.bias", (cout,)))
        keys.append((f"{prefix}.1.running_mean", (cout,)))
        keys.append((f"{prefix}.1.running_var", (cout,)))
        keys.append((f"{prefix}.1.num_batches_tracked", ()))

    def squeeze_excite(prefix, exp, sq):
        keys.append((f"{prefix}.fc1.weight", (sq, exp, 1, 1)))
        keys.append((f"{prefix}.fc1.bias", (sq,)))
        keys.append((f"{prefix}.fc2.weight", (exp, sq, 1, 1)))
        keys.append((f"{prefix}.fc2.bias", (exp,)))

    stem = ch(32)
    conv_norm_act("features.0", 3, stem, 3)
    cin = stem
    for stage, (e, k, _st, _bi, bo, r) in enumerate(base, start=1):
        cout = ch(bo)
        for i in range(rep(r)):
            p = f"features.{stage}.{i}.block"
            block_in = cin if i == 0 else cout
            sq = max(1, block_in // 4)
            exp = block_in * e
            if e != 1:
                conv_norm_act(f"{p}.0", block_in, exp, 1)          # expand
                conv_norm_act(f"{p}.1", exp, exp, k, groups=exp)   # depthwise
                squeeze_excite(f"{p}.2", exp, sq)
                conv_norm_act(f"{p}.3", exp, cout, 1)              # project
            else:
                conv_norm_act(f"{p}.0", block_in, exp, k, groups=exp)
                squeeze_excite(f"{p}.1", exp, sq)
                conv_norm_act(f"{p}.2", exp, cout, 1)
        cin = cout

    head = 4 * ch(320)
    conv_norm_act("features.8", cin, head, 1)
    keys.append(("classifier.1.weight", (1000, head)))
    keys.append(("classifier.1.bias", (1000,)))
    return keys


def test_manifest_matches_published_param_count():
    """The independent anchor: learnable params == torchvision's 12,233,232."""
    manifest = torchvision_b3_manifest()
    learnable = sum(
        math.prod(shape)
        for key, shape in manifest
        if "running_" not in key and "num_batches" not in key
    )
    assert learnable == TORCHVISION_B3_PARAMS
    # Structure sanity pinned too: 26 MBConv blocks, stem 40, head 1536.
    assert sum(1 for k, _ in manifest if k.endswith(".block.0.0.weight")) == 26
    assert dict(manifest)["features.0.0.weight"] == (40, 3, 3, 3)
    assert dict(manifest)["features.8.0.weight"] == (1536, 384, 1, 1)


def _synthetic_state_dict(seed=0):
    rng = np.random.default_rng(seed)
    sd = {}
    for key, shape in torchvision_b3_manifest():
        if key.endswith("num_batches_tracked"):
            sd[key] = np.zeros(shape, np.int64)
        elif key.endswith("running_var"):
            sd[key] = rng.uniform(0.5, 1.5, shape).astype(np.float32)
        else:
            sd[key] = rng.standard_normal(shape).astype(np.float32) * 0.05
    return sd


@pytest.mark.slow
@pytest.mark.parametrize("include_film", [False, True])
def test_porter_consumes_real_torchvision_layout(include_film):
    """A state dict with torchvision's exact key order and shapes ports into
    the flax B3 (plain and FiLM variants) with every shape matching — the
    test that fails when OUR architecture diverges from torchvision's, not
    from its own mirror."""
    import jax
    import jax.numpy as jnp

    from rt1_tpu.models.efficientnet import EfficientNetB3
    from rt1_tpu.models.load_pretrained import port_torch_efficientnet

    model = EfficientNetB3(include_top=True, include_film=include_film)
    x = jnp.zeros((1, 64, 64, 3))
    kwargs = {"context": jnp.zeros((1, 512))} if include_film else {}
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), x, train=False, **kwargs)
    )
    # eval_shape gives ShapeDtypeStructs; materialize zeros cheaply (a full
    # real init of B3 on one CPU core is ~40 s and adds nothing here).
    variables = jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), variables)

    sd = _synthetic_state_dict()
    ported = port_torch_efficientnet(sd, variables)

    # Ordered-zip semantics: the FIRST torch conv (stem) must land in the
    # flax stem kernel, OIHW -> HWIO transposed.
    flat = {
        "/".join(k): v
        for k, v in __import__("flax").traverse_util.flatten_dict(
            ported["params"]
        ).items()
    }
    stem_key = next(k for k in flat if k.endswith("kernel") and flat[k].shape == (3, 3, 3, 40))
    np.testing.assert_array_equal(
        flat[stem_key],
        np.transpose(sd["features.0.0.weight"], (2, 3, 1, 0)),
    )
    # And the classifier Linear transposes (1000, 1536) -> (1536, 1000).
    cls_key = next(k for k in flat if flat[k].shape == (1536, 1000))
    np.testing.assert_array_equal(
        flat[cls_key], sd["classifier.1.weight"].T
    )
    # BN running stats route into batch_stats, not params.
    stats_flat = __import__("flax").traverse_util.flatten_dict(
        ported["batch_stats"]
    )
    means = [v for k, v in stats_flat.items() if k[-1] == "mean" and v.shape == (40,)]
    assert any(
        np.array_equal(m, sd["features.0.1.running_mean"]) for m in means
    )


@pytest.mark.slow
def test_porter_rejects_layout_drift():
    """Dropping one torchvision module breaks the per-kind count check —
    the porter can never silently mis-zip a divergent layout."""
    import jax
    import jax.numpy as jnp

    from rt1_tpu.models.efficientnet import EfficientNetB3
    from rt1_tpu.models.load_pretrained import port_torch_efficientnet

    model = EfficientNetB3(include_top=True)
    variables = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)), train=False
        )
    )
    variables = jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), variables)

    sd = _synthetic_state_dict()
    for key in list(sd):
        if key.startswith("features.3.1.block.2.fc1"):
            del sd[key]
    with pytest.raises(ValueError):
        port_torch_efficientnet(sd, variables)
