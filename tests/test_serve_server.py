"""HTTP serving stack end-to-end on CPU: ThreadingHTTPServer + asyncio
micro-batcher + batched engine, driven by the real load generator
(`scripts/serve_loadgen.py` imported from its file path).

Covers the ISSUE acceptance bar in-process: >= 8 concurrent synthetic
sessions against the tiny model, exactly one XLA compile of the batched
step, loadgen JSON valid with mean batch occupancy > 1, plus endpoint
semantics (healthz/metrics/reset/errors) and graceful drain.
"""

import importlib.util
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from rt1_tpu.eval.embedding import HashInstructionEmbedder
from rt1_tpu.serve import PolicyEngine, ServeApp, make_server

H, W, D = 32, 56, 512
T = 3


def _load_loadgen():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "serve_loadgen.py",
    )
    spec = importlib.util.spec_from_file_location("serve_loadgen", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def serving_stack():
    import jax

    from rt1_tpu.specs import language_table_action_space, sample_space
    from tests.test_rt1 import tiny_policy

    model = tiny_policy(time_sequence_length=T)
    rng = jax.random.PRNGKey(0)
    obs = {
        "image": np.zeros((1, T, H, W, 3), np.float32),
        "natural_language_embedding": np.zeros((1, T, D), np.float32),
    }
    actions = sample_space(
        language_table_action_space(), jax.random.fold_in(rng, 1), (1, T)
    )
    variables = model.init(
        {"params": rng, "crop": rng}, obs, actions, train=False
    )
    engine = PolicyEngine(
        model,
        variables,
        max_sessions=8,
        embedder=HashInstructionEmbedder(),
    )
    app = ServeApp(
        engine,
        image_shape=(H, W, 3),
        embed_dim=D,
        # A wider deadline than production's 10 ms keeps occupancy > 1
        # robust on a loaded CI box; the batch still flushes early at 8.
        max_delay_s=0.05,
        max_queue=64,
    )
    app.start(warmup=True)
    httpd = make_server(app, host="127.0.0.1", port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield app, engine, httpd, url
    if not app.draining:
        app.drain()
    httpd.shutdown()
    httpd.server_close()
    thread.join(timeout=5)


def _post(url, payload):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_healthz_reports_contract(serving_stack):
    app, engine, _, url = serving_stack
    status, body = _get(url + "/healthz")
    assert status == 200
    assert body["status"] == "ok"
    assert body["image_shape"] == [H, W, 3]
    assert body["max_sessions"] == 8
    assert body["compile_count"] == 1  # AOT warmup already done


def test_act_and_reset_roundtrip(serving_stack):
    _, engine, _, url = serving_stack
    status, body = _post(url + "/reset", {"session_id": "rt"})
    assert status == 200 and body["ok"]
    frame = np.zeros((H, W, 3), np.float32)
    status, body = _post(
        url + "/act",
        {
            "session_id": "rt",
            "image": frame.tolist(),
            "instruction": "push the red moon to the blue cube",
        },
    )
    assert status == 200
    action = np.asarray(body["action"], np.float32)
    assert action.shape == (2,)
    assert (np.abs(action) <= 0.03 + 1e-9).all()
    assert len(body["action_tokens"]) == 3  # terminate + 2 action dims
    assert int(engine.session_state("rt")["seq_idx"]) == 1
    _post(url + "/release", {"session_id": "rt"})


def test_act_task_labels_in_metrics(serving_stack):
    """ISSUE 13: the client-declared `task` tag lands in the per-task
    request/session counters (unlabeled traffic in 'unlabeled'), and the
    labeled families render on the Prometheus scrape."""
    _, _, _, url = serving_stack
    frame = np.zeros((H, W, 3), np.float32).tolist()
    for i in range(3):
        status, _ = _post(
            url + "/act",
            {
                "session_id": "task-sess",
                "image": frame,
                "instruction": "push the red moon to the blue cube",
                "task": "block2block",
            },
        )
        assert status == 200
    status, snap = _get(url + "/metrics")
    assert status == 200
    assert snap["task_requests_total"]["block2block"] == 3
    # One fresh session window under the tag, no matter how many steps.
    assert snap["task_sessions_total"]["block2block"] == 1
    req = urllib.request.Request(
        url + "/metrics", headers={"Accept": "text/plain"}
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        text = resp.read().decode("utf-8")
    assert 'rt1_serve_task_requests_total{task="block2block"} 3' in text
    assert 'rt1_serve_task_sessions_total{task="block2block"} 1' in text
    _post(url + "/release", {"session_id": "task-sess"})


def test_act_error_paths(serving_stack):
    _, _, _, url = serving_stack
    status, body = _post(url + "/act", {"session_id": "e"})
    assert status == 400 and "image" in body["error"]
    frame = np.zeros((H, W, 3), np.float32).tolist()
    status, body = _post(url + "/act", {"session_id": "e", "image": frame})
    assert status == 400 and "instruction" in body["error"]
    status, body = _post(
        url + "/act",
        {"session_id": "", "image": frame, "instruction": "x"},
    )
    assert status == 400
    status, body = _post(
        url + "/act",
        {
            "session_id": "e",
            "image_b64": "AAAA",  # wrong byte count for (H, W, 3)
            "instruction": "x",
        },
    )
    assert status == 400 and "decodes to" in body["error"]
    status, body = _post(
        url + "/act",
        {"session_id": "e", "image": frame, "embedding": [0.0] * 9},
    )
    assert status == 400 and "embedding shape" in body["error"]
    status, body = _post(url + "/release", {"session_id": "never-seen"})
    assert status == 404
    status, body = _get(url + "/nope")
    assert status == 404


def test_loadgen_eight_concurrent_sessions(serving_stack):
    """The acceptance criterion, in-process: 8 concurrent synthetic
    sessions, valid loadgen metrics JSON, mean batch occupancy > 1, and
    still exactly one compile of the batched step."""
    _, engine, _, url = serving_stack
    loadgen = _load_loadgen()
    result = loadgen.run_loadgen(url, sessions=8, steps=6, seed=3)
    assert json.loads(json.dumps(result)) == result  # JSON-serializable
    assert result["metric"] == "serve_requests_per_sec"
    assert result["unit"] == "req/s"
    assert result["requests_ok"] == 8 * 6
    assert result["requests_failed"] == 0
    assert result["value"] > 0
    assert result["latency_p99_ms"] >= result["latency_p50_ms"] > 0
    # Micro-batching actually batched: more than one session per step on
    # average, and at least one full-ish batch happened.
    assert result["mean_batch_occupancy"] > 1
    assert result["max_batch_occupancy"] >= 2
    # One XLA compile total, across warmup + all traffic.
    assert result["server_compile_count"] == 1
    assert engine.compile_count == 1


def test_metrics_endpoint_accumulates(serving_stack):
    _, _, _, url = serving_stack
    status, body = _get(url + "/metrics")
    assert status == 200
    assert body["requests_total"] > 0
    assert body["batches_total"] > 0
    assert body["mean_batch_occupancy"] > 0
    assert body["latency_p50_ms"] > 0
    assert body["compile_count"] == 1
    assert 0 <= body["active_sessions"] <= 8


def test_metrics_prometheus_content_negotiation(serving_stack):
    """`Accept: text/plain` flips /metrics to Prometheus exposition; the
    default stays JSON, and both report the same counters."""
    _, _, _, url = serving_stack
    _, body = _get(url + "/metrics")  # default: JSON, with bucket counts
    assert body["latency_buckets"][-1][0] == "+Inf"
    assert body["latency_buckets"][-1][1] == body["latency_count"]

    req = urllib.request.Request(
        url + "/metrics", headers={"Accept": "text/plain;version=0.0.4"}
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode("utf-8")
    assert "# TYPE rt1_serve_requests_total counter" in text
    assert "# TYPE rt1_serve_request_latency_seconds histogram" in text
    assert 'rt1_serve_request_latency_seconds_bucket{le="+Inf"} ' in text
    for line in text.splitlines():
        assert line == "" or line.startswith("#") or " " in line
    # Same numbers through both syntaxes.
    assert f"rt1_serve_requests_total {body['requests_total']}" in text


def test_readyz_is_200_while_serving(serving_stack):
    """Readiness (load-balancer routing) is separate from liveness: a
    started, non-draining replica is ready, and the metrics carry the
    ready/draining gauges."""
    _, _, _, url = serving_stack
    status, body = _get(url + "/readyz")
    assert status == 200 and body == {"ready": True}
    _, metrics = _get(url + "/metrics")
    assert metrics["ready"] == 1
    assert metrics["draining"] == 0


def test_readyz_warming_before_first_compile(serving_stack):
    """An app that has not finished start()/AOT warmup reports 503
    'warming' — the LB must not route to a replica still paying XLA
    latency — while its liveness payload is already healthy."""
    app, engine, _, _ = serving_stack
    from rt1_tpu.serve import ServeApp

    cold = ServeApp(engine, image_shape=(H, W, 3), embed_dim=D)
    try:
        code, body = cold.readyz()
        assert code == 503 and body["reason"] == "warming"
        assert cold.healthz()["status"] == "ok"  # alive, just not ready
    finally:
        cold._loop.close()


def test_metrics_fleet_gauges_present(serving_stack):
    """Satellite contract: replica_id, uptime_s, reloads_total, and
    sessions_restarted_total appear in both /metrics formats."""
    _, _, _, url = serving_stack
    _, body = _get(url + "/metrics")
    assert body["replica_id"] == 0
    assert body["uptime_s"] > 0
    assert body["reloads_total"] == 0
    assert body["sessions_restarted_total"] == 0
    assert body["reloading"] == 0
    req = urllib.request.Request(
        url + "/metrics", headers={"Accept": "text/plain"}
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        text = resp.read().decode("utf-8")
    assert "# TYPE rt1_serve_reloads_total counter" in text
    assert "# TYPE rt1_serve_sessions_restarted_total counter" in text
    assert "rt1_serve_replica_id 0" in text


def test_request_id_echo_and_debug_phases(serving_stack):
    """Request tracing on the REAL engine path: a client-supplied
    X-RT1-Request-Id round-trips in the response, and `debug: true`
    returns the per-phase breakdown carrying the same id with every
    pipeline phase actually stamped (admission through serialization)."""
    _, _, _, url = serving_stack
    frame = np.zeros((H, W, 3), np.float32).tolist()
    payload = {
        "session_id": "traced",
        "image": frame,
        "instruction": "push the red moon to the blue cube",
        "debug": True,
    }
    req = urllib.request.Request(
        url + "/act",
        data=json.dumps(payload).encode(),
        headers={
            "Content-Type": "application/json",
            "X-RT1-Request-Id": "client-chosen-id",
        },
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body["request_id"] == "client-chosen-id"
    phases = body["phases"]
    assert phases["request_id"] == "client-chosen-id"
    # Every boundary the request crossed is a real (>= 0) measurement,
    # and the parts are bounded by the whole.
    for key in (
        "admission_ms", "queue_wait_ms", "batch_form_ms",
        "device_ms", "serialize_ms", "total_ms",
    ):
        assert phases[key] is not None and phases[key] >= 0.0, key
    parts = (
        phases["admission_ms"] + phases["queue_wait_ms"]
        + phases["batch_form_ms"] + phases["device_ms"]
        + phases["serialize_ms"]
    )
    assert parts == pytest.approx(phases["total_ms"], abs=1.0)
    # Without the debug flag the breakdown stays server-side...
    del payload["debug"]
    status, body = _post(url + "/act", payload)
    assert status == 200
    assert "phases" not in body
    assert len(body["request_id"]) == 16  # minted when no client id
    _post(url + "/release", {"session_id": "traced"})


def test_slow_requests_exemplar_endpoint(serving_stack):
    """...but it lands in the exemplar ring regardless: GET
    /slow_requests names recent requests with their phase breakdowns,
    including failed ones (400s carry an outcome + error)."""
    app, _, _, url = serving_stack
    status, body = _get(url + "/slow_requests")
    assert status == 200
    assert body["capacity"] == 128
    recorded = {r["request_id"] for r in body["slow_requests"]}
    assert "client-chosen-id" in recorded
    by_id = {r["request_id"]: r for r in body["slow_requests"]}
    rec = by_id["client-chosen-id"]
    assert rec["outcome"] == "ok"
    assert rec["session"] == "traced"
    assert rec["phases"]["device_ms"] >= 0.0
    assert rec["total_ms"] >= rec["phases"]["device_ms"]
    # A 400 (no image) is an exemplar too — failures are exactly what a
    # post-mortem wants on file.
    status, body = _post(
        url + "/act", {"session_id": "exemplar-fail"}
    )
    assert status == 400
    failed_id = body["request_id"]
    _, body = _get(url + "/slow_requests")
    by_id = {r["request_id"]: r for r in body["slow_requests"]}
    assert by_id[failed_id]["outcome"] == "failed"
    assert "image" in by_id[failed_id]["error"]
    # Unreached phases are None in the failed exemplar, not zeros.
    assert by_id[failed_id]["phases"]["device_ms"] is None


class _InstantEngine:
    """Model-free engine double: the exact attribute/act_batch surface
    ServeApp touches, with zero-latency steps — lets a drain-path test
    run without a jax boot (the module fixture's app must stay alive for
    later tests, so it cannot be drained here)."""

    max_sessions = 8
    active_sessions = 0
    compile_count = 1
    reloads = 0
    embed_calls = 0
    evictions = 0

    def warmup(self, image_shape, embed_dim):
        pass

    def act_batch(self, items):
        return [
            {"action": [0.0, 0.0], "action_tokens": [0, 0, 0]}
            for _ in items
        ]


def test_exemplar_ring_dumped_on_drain(tmp_path):
    """The serve-side flight-recorder semantics: a replica that drains
    (the SIGTERM path) leaves its exemplar ring on disk for run_report,
    through ServeApp's own drain hook."""
    from rt1_tpu.obs.recorder import read_exemplars
    from rt1_tpu.serve import reqtrace
    from rt1_tpu.serve.server import ServeApp

    path = str(tmp_path / "slow_requests.jsonl")
    app = ServeApp(
        _InstantEngine(),
        image_shape=(H, W, 3),
        embed_dim=D,
        exemplar_path=path,
    )
    app.start(warmup=False)
    phases = reqtrace.RequestPhases("pre-drain")
    result = app.act("drain-sess", {"image": None}, phases)
    assert result["action"] == [0.0, 0.0]
    # The handler normally offers post-act; the drain dump only writes
    # what the ring holds, so record the finished request as _act does.
    app.exemplars.offer(
        phases.phases_ms()["total_ms"],
        request_id=phases.request_id,
        outcome="ok",
        phases=phases.phases_ms(),
    )
    app.drain(timeout=10.0)
    loaded = read_exemplars(path)
    assert loaded["header"]["reason"] == "drain"
    assert [r["request_id"] for r in loaded["records"]] == ["pre-drain"]
    # The batcher stamped the cross-thread boundaries on the way through.
    assert loaded["records"][0]["phases"]["queue_wait_ms"] is not None
    assert loaded["records"][0]["phases"]["device_ms"] is not None


def test_reload_endpoint_requires_a_source(serving_stack):
    """The module app has no reload_fn: POST /reload is a clean 400, not
    a crash."""
    _, _, _, url = serving_stack
    status, body = _post(url + "/reload", {})
    assert status == 400 and "no reload source" in body["error"]
    status, body = _post(url + "/reload", {"step": "seven"})
    assert status == 400 and "integer" in body["error"]


def test_reload_endpoint_hot_swaps_without_recompile(serving_stack):
    """POST /reload on an app with a reload source: params swap in with
    the same action stream (identical params), one compile, counters up,
    and in-flight traffic keeps flowing (the swap lands between batches)."""
    import jax

    from rt1_tpu.serve import ServeApp

    _, engine, _, _ = serving_stack
    reloads_before = engine.reloads
    host_vars = jax.tree.map(lambda x: np.asarray(x), engine._variables)
    app2 = ServeApp(
        engine,
        image_shape=(H, W, 3),
        embed_dim=D,
        replica_id=5,
        reload_fn=lambda step: (host_vars, step if step is not None else 42),
    )
    app2.start(warmup=True)  # engine already compiled: no second compile
    httpd = make_server(app2, host="127.0.0.1", port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    url2 = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        frame = np.zeros((H, W, 3), np.float32).tolist()
        act = {
            "session_id": "hs",
            "image": frame,
            "instruction": "push the red moon to the blue cube",
        }
        status, before = _post(url2 + "/act", act)
        assert status == 200

        status, body = _post(url2 + "/reload", {})
        assert status == 200, body
        assert body["ok"] is True
        assert body["checkpoint_step"] == 42
        assert body["params_swapped"] > 0

        status, body = _post(url2 + "/reload", {"step": 7})
        assert status == 200 and body["checkpoint_step"] == 7

        # Identical params: the continuing session's policy is unchanged;
        # the engine never recompiled; both reload counters advanced.
        status, after = _post(url2 + "/act", act)
        assert status == 200
        assert engine.compile_count == 1
        assert engine.reloads == reloads_before + 2
        _, metrics = _get(url2 + "/metrics")
        assert metrics["reloads_total"] == 2
        assert metrics["replica_id"] == 5
        assert metrics["compile_count"] == 1
        health = app2.healthz()
        assert health["replica_id"] == 5 and health["reloads"] >= 2
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)
        app2.drain()


def test_act_admitted_during_drain_race_is_flushed(serving_stack):
    """The drain/in-flight race regression (ISSUE 6 satellite): a request
    that passed admission just before SIGTERM's drain() flips `draining`
    must be flushed with a 200, never answered 503. The test freezes a
    request INSIDE the admission window (after the draining check, before
    its submit is scheduled) by shimming run_coroutine_threadsafe, then
    fires the drain path concurrently — exactly what the SIGTERM handler
    runs (install_signal_handlers -> app.drain)."""
    import asyncio as real_asyncio
    import time as _time

    from rt1_tpu.serve import DrainingError, ServeApp
    from rt1_tpu.serve import server as server_mod

    _, engine, _, _ = serving_stack
    app2 = ServeApp(engine, image_shape=(H, W, 3), embed_dim=D)
    app2.start(warmup=True)
    obs = {
        "image": np.zeros((H, W, 3), np.float32),
        "natural_language_embedding": np.zeros(D, np.float32),
    }

    in_window = threading.Event()
    release = threading.Event()

    class SlowSubmitAsyncio:
        """Delegates to asyncio, but parks submit-coroutine scheduling
        until released — holding the request in the race window."""

        def __getattr__(self, name):
            return getattr(real_asyncio, name)

        def run_coroutine_threadsafe(self, coro, loop):
            if getattr(coro, "__qualname__", "").endswith("submit"):
                in_window.set()
                release.wait(10)
            return real_asyncio.run_coroutine_threadsafe(coro, loop)

    orig = server_mod.asyncio
    server_mod.asyncio = SlowSubmitAsyncio()
    result = {}
    try:
        def racing_act():
            try:
                result["out"] = app2.act("race", obs)
            except Exception as exc:  # noqa: BLE001 - recorded for assert
                result["exc"] = exc

        actor = threading.Thread(target=racing_act)
        actor.start()
        assert in_window.wait(5)  # admitted, submit not yet scheduled

        drainer = threading.Thread(target=app2.drain)
        drainer.start()
        _time.sleep(0.3)
        # drain() must WAIT for the admitted request's handshake instead
        # of racing past it into the batcher shutdown.
        assert drainer.is_alive()

        release.set()
        actor.join(timeout=15)
        drainer.join(timeout=15)
        assert not actor.is_alive() and not drainer.is_alive()
    finally:
        release.set()
        server_mod.asyncio = orig
    # The admitted request was flushed, not 503'd...
    assert "exc" not in result, f"admitted act rejected: {result.get('exc')}"
    assert "action" in result["out"]
    # ...and post-drain admissions are refused.
    with pytest.raises(DrainingError):
        app2.act("late", obs)


def test_continuous_bucketed_stack_end_to_end(serving_stack):
    """ISSUE 12 stack: continuous scheduler + double-buffered engine
    pipeline + AOT bucket ladder, driven by the real load generator.
    Pins: compile_count == len(buckets) after warm-up, zero failed
    requests, the new metric families in JSON and Prometheus text, and
    the scheduling contract on /healthz."""
    from rt1_tpu.serve import PolicyEngine, ServeApp, make_server

    _, base_engine, _, _ = serving_stack
    engine = PolicyEngine(
        base_engine._model,
        base_engine._variables,
        max_sessions=4,
        buckets=[1, 2, 4],
        embedder=HashInstructionEmbedder(),
    )
    app = ServeApp(
        engine,
        image_shape=(H, W, 3),
        embed_dim=D,
        scheduler="continuous",
        pipeline_depth=2,
        max_queue=64,
    )
    app.start(warmup=True)
    assert engine.compile_count == 3  # every bucket precompiled
    httpd = make_server(app, host="127.0.0.1", port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        _, health = _get(url + "/healthz")
        assert health["scheduler"] == "continuous"
        assert health["buckets"] == [1, 2, 4]
        loadgen = _load_loadgen()
        result = loadgen.run_loadgen(url, sessions=4, steps=6, seed=7)
        assert result["requests_failed"] == 0
        assert result["requests_ok"] == 4 * 6
        assert result["server_compile_count"] == 3  # pinned: no compile
        #   was paid by any live request
        assert engine.compile_count == 3

        _, metrics = _get(url + "/metrics")
        assert metrics["bucket_count"] == 3
        assert metrics["compile_count"] == metrics["bucket_count"]
        # Every dispatched batch was booked into exactly one bucket.
        assert sum(metrics["bucket_batches"].values()) == (
            metrics["batches_total"]
        )
        assert set(metrics["bucket_batches"]) <= {"1", "2", "4"}
        assert metrics["joined_mid_cycle_total"] >= 0
        assert metrics["batches_in_flight"] == 0  # quiesced
        assert metrics["max_batches_in_flight"] >= 1

        req = urllib.request.Request(
            url + "/metrics", headers={"Accept": "text/plain"}
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            text = resp.read().decode("utf-8")
        assert "# TYPE rt1_serve_bucket_batches_total counter" in text
        assert 'rt1_serve_bucket_batches_total{bucket="' in text
        assert "# TYPE rt1_serve_joined_mid_cycle_total counter" in text
        assert "# TYPE rt1_serve_batches_in_flight gauge" in text
        assert "rt1_serve_bucket_count 3" in text

        # Drain with traffic racing in: every admitted request resolves
        # exactly once (200 result) or is cleanly refused (DrainingError)
        # — never lost, never answered twice, never 500.
        obs = {
            "image": np.zeros((H, W, 3), np.float32),
            "natural_language_embedding": np.zeros(D, np.float32),
        }
        outcomes = {}

        def burst(i):
            # i % 4 keeps the burst within the slot count: the race under
            # test is drain-vs-inflight, not slot oversubscription (that
            # path is covered by the engine contention test).
            try:
                outcomes[i] = ("ok", app.act(f"drain-{i % 4}", dict(obs)))
            except Exception as exc:  # noqa: BLE001 - recorded for assert
                outcomes[i] = ("exc", exc)

        threads = [
            threading.Thread(target=burst, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        app.drain(timeout=30.0)
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        assert len(outcomes) == 6
        from rt1_tpu.serve import DrainingError

        for kind, value in outcomes.values():
            if kind == "ok":
                assert "action" in value
            else:
                assert isinstance(value, DrainingError), value
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=5)
        if not app.draining:
            app.drain()


def test_session_export_import_token_identical(serving_stack):
    """Durable sessions on the real engine (ISSUE 19): export a live
    session's window, import it under a new id on the same stack, and
    the next act returns byte-identical action tokens — the continuation
    the user would have seen had nothing moved. The /act body carries no
    step index, so continuity is judged by the tokens themselves plus
    the export/import responses' step_index."""
    _, engine, _, url = serving_stack
    emb = [0.01 * (i % 50) for i in range(D)]

    def frame(k):
        return np.full((H, W, 3), k / 10.0, np.float32).tolist()

    for k in range(3):
        status, body = _post(
            url + "/act",
            {"session_id": "mig-src", "image": frame(k), "embedding": emb},
        )
        assert status == 200
    status, body = _post(url + "/session/export", {"session_id": "mig-src"})
    assert status == 200 and body["ok"] is True
    snapshot = body["snapshot"]
    assert snapshot["step_index"] == 3
    assert snapshot["window"] == T
    assert snapshot["version"] == 1
    # The reference continuation: step 4 served from the source window.
    status, ref = _post(
        url + "/act",
        {"session_id": "mig-src", "image": frame(3), "embedding": emb},
    )
    assert status == 200 and ref["session_started"] is False

    status, body = _post(
        url + "/session/import",
        {"snapshot": snapshot, "session_id": "mig-dst"},
    )
    assert status == 200 and body["ok"] is True
    assert body["step_index"] == 3
    status, cont = _post(
        url + "/act",
        {"session_id": "mig-dst", "image": frame(3), "embedding": emb},
    )
    assert status == 200
    assert cont["session_started"] is False  # the window moved, whole
    assert cont["action_tokens"] == ref["action_tokens"]
    assert cont["action"] == ref["action"]

    # Compatibility refusals are 409s that NAME the mismatched field.
    status, body = _post(
        url + "/session/import",
        {
            "snapshot": {**snapshot, "checkpoint_generation": 12345},
            "session_id": "mig-bad",
        },
    )
    assert status == 409 and "checkpoint_generation" in body["error"]
    # Exporting a session that was never opened is a 404, not a crash.
    status, body = _post(url + "/session/export", {"session_id": "ghost"})
    assert status == 404

    # Import scatters into the live batched step: no recompile.
    status, health = _get(url + "/healthz")
    assert health["compile_count"] == 1
    for sid in ("mig-src", "mig-dst"):
        _post(url + "/release", {"session_id": sid})


def test_drain_rejects_new_work(serving_stack):
    """Runs last (name-independent: fixtures are module-scoped, and this
    mutates app state — keep it after the traffic tests)."""
    app, _, _, url = serving_stack
    app.drain()
    status, body = _get(url + "/healthz")
    assert status == 200  # liveness stays 200: draining != dead
    assert body["status"] == "draining"
    # Readiness flips 503 so load balancers stop routing BEFORE shutdown.
    status, body = _get(url + "/readyz")
    assert status == 503 and body["reason"] == "draining"
    _, metrics = _get(url + "/metrics")
    assert metrics["draining"] == 1 and metrics["ready"] == 0
    frame = np.zeros((H, W, 3), np.float32).tolist()
    status, body = _post(
        url + "/act",
        {"session_id": "z", "image": frame, "instruction": "x"},
    )
    assert status == 503 and body["error"] == "draining"
