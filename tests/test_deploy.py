"""Continuous deployment subsystem (ISSUE 16): eval-gated promotion,
router-weighted canary, SLO-burn auto-rollback.

Three layers, mirroring the subsystem's own split:

* pure units — the `CanaryJudge` burn-window decision fn, the
  torn-dir-tolerant checkpoint watcher (pinned to the trainer's
  `latest_step` on the same canned directory), signed verdict artifacts;
* router mechanism — deterministic Bresenham weighted placement and the
  demote/re-home path, on in-process stub replicas;
* the full stub-fleet deploy cycle — a good candidate canaried then
  promoted fleet-wide, a bad candidate (chaos ``canary_slo_breach``)
  auto-rolled-back with zero failed requests, and a failed fleet-wide
  promote (chaos ``promote``) rolled back with the incumbent untouched.
"""

import json
import os
import threading

import pytest

from rt1_tpu.deploy.controller import PromotionController
from rt1_tpu.deploy.decision import CanaryJudge, CanaryPolicy, CanarySignals
from rt1_tpu.deploy.watcher import CheckpointWatcher, latest_checkpoint_step
from rt1_tpu.deploy import verdict as verdict_lib
from rt1_tpu.resilience import faults
from rt1_tpu.serve.router import NOTREADY, READY, Replica, Router
from rt1_tpu.serve.stub import StubReplicaApp, make_stub_server


# ------------------------------------------------------------------ decision


def test_policy_validation():
    with pytest.raises(ValueError):
        CanaryPolicy(burn_threshold=0.0)
    with pytest.raises(ValueError):
        CanaryPolicy(breach_ticks=0)
    with pytest.raises(ValueError):
        CanaryPolicy(clean_window_ticks=0)
    with pytest.raises(ValueError):
        CanaryPolicy(min_canary_requests=-1)
    with pytest.raises(ValueError):
        CanaryPolicy(canary_weight=0.0)
    with pytest.raises(ValueError):
        CanaryPolicy(canary_weight=1.5)


def _signals(requests=100, burn=0.0, fleet=0.0, ready=True):
    return CanarySignals(
        canary_requests=requests,
        canary_burn=burn,
        fleet_burn=fleet,
        canary_ready=ready,
    )


def test_judge_promotes_after_clean_window():
    judge = CanaryJudge(CanaryPolicy(clean_window_ticks=3))
    assert judge.decide(_signals()) == "hold"
    assert judge.decide(_signals()) == "hold"
    assert judge.decide(_signals()) == "promote"
    assert judge.clean_streak == 3


def test_judge_rolls_back_after_consecutive_breaches():
    judge = CanaryJudge(CanaryPolicy(breach_ticks=2, burn_threshold=2.0))
    assert judge.decide(_signals(burn=5.0)) == "hold"
    assert judge.decide(_signals(burn=5.0)) == "rollback"


def test_judge_breach_streak_resets_on_clean_tick():
    judge = CanaryJudge(CanaryPolicy(breach_ticks=2, clean_window_ticks=99))
    assert judge.decide(_signals(burn=5.0)) == "hold"
    assert judge.decide(_signals(burn=0.0)) == "hold"  # blip forgiven
    assert judge.breach_streak == 0
    assert judge.decide(_signals(burn=5.0)) == "hold"  # streak restarts


def test_judge_evidence_floor_holds_without_advancing_streaks():
    judge = CanaryJudge(
        CanaryPolicy(clean_window_ticks=1, min_canary_requests=8)
    )
    for _ in range(5):
        assert judge.decide(_signals(requests=3)) == "hold"
    assert judge.clean_streak == 0
    # ...but a breach needs no more evidence to be condemned.
    judge2 = CanaryJudge(
        CanaryPolicy(breach_ticks=1, min_canary_requests=8)
    )
    assert judge2.decide(_signals(requests=0, burn=9.0)) == "rollback"


def test_judge_fleet_wide_incident_never_scapegoats_canary():
    judge = CanaryJudge(CanaryPolicy(breach_ticks=1, burn_threshold=2.0))
    # Canary over threshold but NOT strictly above the fleet: not a breach.
    assert judge.decide(_signals(burn=5.0, fleet=5.0)) == "hold"
    assert judge.breach_streak == 0
    # Strictly above the fleet: breach.
    assert judge.decide(_signals(burn=5.0, fleet=4.0)) == "rollback"


def test_judge_unroutable_canary_is_a_breach():
    judge = CanaryJudge(CanaryPolicy(breach_ticks=1))
    assert judge.decide(_signals(ready=False)) == "rollback"


# ------------------------------------------------------------------- watcher


def _make_ckpt(root, step, complete=True):
    d = os.path.join(root, str(step))
    os.makedirs(d, exist_ok=True)
    if complete:
        with open(os.path.join(d, "checkpoint"), "w") as f:
            f.write("x")
    return d


def test_latest_checkpoint_step_matches_trainer_latest_step(tmp_path):
    """The deploy watcher is an import-light twin of
    `trainer.checkpoints.latest_step`; this pins the two implementations
    to identical answers on the same adversarial directory."""
    from rt1_tpu.trainer.checkpoints import latest_step as trainer_latest

    root = str(tmp_path / "checkpoints")
    cases = []
    cases.append(("missing dir", root))
    os.makedirs(root)
    cases.append(("empty dir", root))
    _make_ckpt(root, 2)
    cases.append(("one step", root))
    _make_ckpt(root, 10)
    _make_ckpt(root, 5)
    cases.append(("several steps", root))
    # Orbax in-flight tmp dir: must not count as step 20.
    os.makedirs(os.path.join(root, "20.orbax-checkpoint-tmp-1234"))
    cases.append(("orbax tmp dir", root))
    # Torn write: mkdir landed, contents didn't.
    _make_ckpt(root, 30, complete=False)
    cases.append(("empty step dir", root))
    # Digit-named FILE (not a dir) and a sidecar file.
    with open(os.path.join(root, "40"), "w") as f:
        f.write("not a dir")
    with open(os.path.join(root, "ckpt_metadata"), "w") as f:
        f.write("{}")
    cases.append(("digit-named file", root))
    for label, d in cases:
        assert latest_checkpoint_step(d) == trainer_latest(d), label
    assert latest_checkpoint_step(root) == 10


def test_watcher_surfaces_each_step_once(tmp_path):
    workdir = str(tmp_path)
    root = os.path.join(workdir, "checkpoints")
    watcher = CheckpointWatcher(workdir)
    assert watcher.poll() is None
    os.makedirs(root)
    _make_ckpt(root, 2)
    assert watcher.poll() == 2
    assert watcher.poll() is None  # surfaced exactly once
    _make_ckpt(root, 4)
    assert watcher.poll() == 4
    assert watcher.pending_steps() == [2, 4]


def test_watcher_seeded_high_water_skips_incumbent(tmp_path):
    workdir = str(tmp_path)
    root = os.path.join(workdir, "checkpoints")
    os.makedirs(root)
    _make_ckpt(root, 2)
    watcher = CheckpointWatcher(workdir, seen_through=2)
    assert watcher.poll() is None  # the incumbent is not a candidate
    _make_ckpt(root, 4)
    assert watcher.poll() == 4
    watcher.dismiss(6)
    _make_ckpt(root, 6)
    assert watcher.poll() is None


# ------------------------------------------------------------------- verdict


def test_verdict_sign_write_verify_roundtrip(tmp_path):
    path = str(tmp_path / "deploy" / "verdict_4.json")
    key = verdict_lib.signing_key(str(tmp_path / "deploy"))
    # Key file generated once, 0600, stable across calls.
    keyfile = tmp_path / "deploy" / "deploy_key"
    assert keyfile.exists()
    assert (keyfile.stat().st_mode & 0o777) == 0o600
    assert verdict_lib.signing_key(str(tmp_path / "deploy")) == key

    signed = verdict_lib.write_verdict(
        path, {"passed": True, "candidate_step": 4}, key
    )
    assert signed["signature"]
    payload, ok = verdict_lib.verify_verdict(path, key)
    assert ok and payload["passed"] is True

    # Tampering with the payload breaks the signature.
    payload["passed"] = False
    with open(path, "w") as f:
        json.dump(payload, f)
    _, ok = verdict_lib.verify_verdict(path, key)
    assert not ok
    # Missing / torn files verify False instead of raising.
    assert verdict_lib.verify_verdict(str(tmp_path / "nope.json"), key) == (
        None,
        False,
    )
    with open(path, "w") as f:
        f.write("{torn")
    assert verdict_lib.verify_verdict(path, key) == (None, False)


def test_verdict_env_key_overrides_keyfile(tmp_path, monkeypatch):
    monkeypatch.setenv(verdict_lib.ENV_KEY, "fleet-secret")
    assert verdict_lib.signing_key(str(tmp_path)) == "fleet-secret"
    assert not (tmp_path / "deploy_key").exists()


# ------------------------------------------------------- router canary seam


@pytest.fixture()
def fleet():
    apps, servers = [], []
    router = Router(replica_timeout_s=5.0)
    for rid in range(2):
        app = StubReplicaApp(replica_id=rid)
        httpd = make_stub_server(app)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        host, port = httpd.server_address[:2]
        replica = router.add_replica(Replica(rid, url=f"http://{host}:{port}"))
        replica.state = READY
        apps.append(app)
        servers.append(httpd)
    yield router, apps
    faults.clear()
    for httpd in servers:
        try:
            httpd.shutdown()
            httpd.server_close()
        except OSError:
            pass


def _act(router, session_id):
    return router.route_act({"session_id": session_id, "image_b64": "AAAA"})


def test_weighted_placement_is_deterministic(fleet):
    router, _ = fleet
    router.set_canary(1, 0.25)
    placements = []
    for i in range(8):
        status, body = _act(router, f"w{i}")
        assert status == 200
        placements.append(body["replica_id"])
    # Bresenham at w=0.25: exactly fresh placements 4 and 8 (n=3, n=7)
    # land on the canary — no RNG, same split every run.
    assert placements == [0, 0, 0, 1, 0, 0, 0, 1]
    assert router.canary_status()["fresh_placements"] == 8
    # Existing sessions keep their affinity: re-acting every session
    # advances no Bresenham state and moves no session.
    again = []
    for i in range(8):
        status, body = _act(router, f"w{i}")
        again.append(body["replica_id"])
    assert again == placements
    assert router.canary_status()["fresh_placements"] == 8


def test_clear_canary_keeps_sessions_demote_evicts(fleet):
    router, _ = fleet
    router.set_canary(1, 1.0)  # every fresh session -> canary
    status, body = _act(router, "keep")
    assert body["replica_id"] == 1
    assert router.clear_canary() == 1
    # PROMOTE path: the session stays where it is, no restart.
    status, body = _act(router, "keep")
    assert body["replica_id"] == 1 and "restarted" not in body

    router.set_canary(1, 1.0)
    status, body = _act(router, "evict")
    assert body["replica_id"] == 1
    assert router.demote_canary() == 1
    # ROLLBACK path: the session re-homes with restarted:true, never 5xx.
    status, body = _act(router, "evict")
    assert status == 200
    assert body["restarted"] is True


def test_not_ready_canary_drops_out_of_the_split(fleet):
    router, _ = fleet
    router.set_canary(1, 1.0)
    router.set_state(1, NOTREADY)
    for i in range(3):
        status, body = _act(router, f"n{i}")
        assert status == 200 and body["replica_id"] == 0


def test_reload_one_swaps_a_single_replica(fleet):
    router, apps = fleet
    entry = router.reload_one(1, 7)
    assert entry["status"] == 200 and entry["recovered"] is True
    assert apps[1].checkpoint_step == 7
    assert apps[0].checkpoint_step == -1  # untouched
    assert router.reload_one(99, 7)["skipped"] == "unknown"


# --------------------------------------------------- controller deploy cycle


def _controller(router, workdir, **overrides):
    policy = CanaryPolicy(
        breach_ticks=2,
        clean_window_ticks=2,
        min_canary_requests=2,
        canary_weight=0.5,
    )
    kwargs = dict(gate_fn=_auto_pass, policy=policy, incumbent_step=2)
    kwargs.update(overrides)
    return PromotionController(router, workdir, **kwargs)


def _auto_pass(candidate_step, incumbent_step):
    return {
        "gate": "auto",
        "passed": True,
        "candidate_step": candidate_step,
        "incumbent_step": incumbent_step,
    }


def _events(controller):
    return [e["event"] for e in controller.timeline]


def test_good_candidate_canaried_then_promoted_fleet_wide(fleet, tmp_path):
    router, apps = fleet
    workdir = str(tmp_path)
    root = os.path.join(workdir, "checkpoints")
    _make_ckpt(root, 2)
    controller = _controller(router, workdir)

    controller.tick()  # nothing new: the incumbent is not a candidate
    assert controller.state == "idle" and controller.candidates_seen == 0

    _make_ckpt(root, 4)
    controller.tick()
    # Candidate gated, signed verdict written, canary loaded on the
    # highest-id replica at the configured weight.
    assert _events(controller) == ["candidate", "gate_passed",
                                   "canary_started"]
    assert controller.state == "canary"
    assert apps[1].checkpoint_step == 4
    assert apps[0].checkpoint_step == -1  # incumbent fleet untouched
    assert router.canary_status() == {
        "replica_id": 1, "weight": 0.5, "fresh_placements": 0,
    }
    payload, ok = verdict_lib.verify_verdict(
        controller.verdict_paths[0], controller.signing_key
    )
    assert ok and payload["passed"] and payload["candidate_step"] == 4

    # Fresh sessions split between canary and incumbent (w=0.5).
    for i in range(6):
        status, _ = _act(router, f"s{i}")
        assert status == 200
    assert router.replica_slo_snapshot()[1]["requests_total"] == 3

    controller.tick()  # clean tick 1: hold
    assert controller.state == "canary"
    controller.tick()  # clean tick 2: promote fleet-wide
    assert controller.state == "idle"
    assert controller.promotions == 1
    assert controller.incumbent_step == 4
    assert apps[0].checkpoint_step == 4  # rolling reload reached everyone
    assert apps[1].checkpoint_step == 4
    assert router.canary_status()["replica_id"] is None
    assert _events(controller)[-1] == "promoted"
    # Canary sessions stayed (already on the promoted params): acting
    # again restarts nothing.
    for i in range(6):
        status, body = _act(router, f"s{i}")
        assert status == 200 and "restarted" not in body
    # Zero failed requests; compile pinned at bucket count throughout.
    assert router.slo.gauges()["slo_requests_failed"] == 0
    for app in apps:
        assert app.compile_count == len(app.buckets)


def test_bad_candidate_rolled_back_on_injected_breach(fleet, tmp_path):
    router, apps = fleet
    workdir = str(tmp_path)
    root = os.path.join(workdir, "checkpoints")
    _make_ckpt(root, 2)
    _make_ckpt(root, 4)
    controller = _controller(router, workdir, incumbent_step=4)
    apps[0].checkpoint_step = 4
    apps[1].checkpoint_step = 4

    faults.install_from("canary_slo_breach@1")
    _make_ckpt(root, 6)
    controller.tick()
    assert controller.state == "canary"
    assert apps[1].checkpoint_step == 6
    # A session lands on the canary before the breach verdict.
    status, body = _act(router, "victim")  # n=0 -> incumbent
    status, body = _act(router, "canary-bound")  # n=1 -> canary
    assert body["replica_id"] == 1

    controller.tick()  # breach tick 1 (latched synthetic): hold
    assert controller.state == "canary"
    assert controller.watch_log[-1]["synthetic_breach"] is True
    controller.tick()  # breach tick 2: rollback
    assert controller.state == "idle"
    assert controller.rollbacks == 1
    assert controller.promotions == 0
    assert controller.incumbent_step == 4
    rolled = controller.timeline[-1]
    assert rolled["event"] == "rolled_back"
    assert rolled["reason"] == "slo_breach_injected"
    # The canary replica is back on the incumbent; the rest of the fleet
    # was never touched.
    assert apps[1].checkpoint_step == 4
    assert apps[0].checkpoint_step == 4
    # The canary's session re-homes with restarted:true — never a 5xx —
    # and the incumbent session never notices.
    status, body = _act(router, "canary-bound")
    assert status == 200 and body["restarted"] is True
    status, body = _act(router, "victim")
    assert status == 200 and "restarted" not in body
    assert router.slo.gauges()["slo_requests_failed"] == 0
    for app in apps:
        assert app.compile_count == len(app.buckets)


def test_failed_promote_rolls_back_fleet_wide(fleet, tmp_path):
    router, apps = fleet
    workdir = str(tmp_path)
    root = os.path.join(workdir, "checkpoints")
    _make_ckpt(root, 2)
    controller = _controller(router, workdir)
    apps[0].checkpoint_step = 2
    apps[1].checkpoint_step = 2

    faults.install_from("promote@1")
    _make_ckpt(root, 4)
    controller.tick()
    assert controller.state == "canary"
    for i in range(4):
        _act(router, f"p{i}")
    controller.tick()
    controller.tick()  # promote decision -> injected OSError -> rollback
    assert controller.state == "idle"
    assert controller.promotions == 0
    assert controller.rollbacks == 1
    assert controller.incumbent_step == 2  # incumbent untouched
    assert "promote_failed" in _events(controller)
    # Fleet-wide restore: every replica serves the incumbent again.
    assert apps[0].checkpoint_step == 2
    assert apps[1].checkpoint_step == 2
    assert router.slo.gauges()["slo_requests_failed"] == 0


def test_gate_rejection_keeps_fleet_untouched(fleet, tmp_path):
    router, apps = fleet
    workdir = str(tmp_path)
    root = os.path.join(workdir, "checkpoints")
    _make_ckpt(root, 2)

    def reject(candidate_step, incumbent_step):
        return {"passed": False, "candidate_mean_success": 0.0}

    controller = _controller(router, workdir, gate_fn=reject)
    _make_ckpt(root, 4)
    controller.tick()
    assert controller.state == "idle"
    assert controller.gates_failed == 1
    assert _events(controller) == ["candidate", "gate_rejected"]
    assert apps[1].checkpoint_step == -1  # never canaried
    # The rejection is recorded as a signed verdict too.
    payload, ok = verdict_lib.verify_verdict(
        controller.verdict_paths[0], controller.signing_key
    )
    assert ok and payload["passed"] is False


def test_crashing_gate_is_a_rejection_not_a_crash(fleet, tmp_path):
    router, _ = fleet
    workdir = str(tmp_path)
    root = os.path.join(workdir, "checkpoints")

    def explode(candidate_step, incumbent_step):
        raise RuntimeError("gate OOM")

    controller = _controller(router, workdir, gate_fn=explode)
    _make_ckpt(root, 4)
    controller.tick()
    assert controller.state == "idle"
    assert controller.gates_failed == 1
    payload, ok = verdict_lib.verify_verdict(
        controller.verdict_paths[0], controller.signing_key
    )
    assert ok and payload["passed"] is False and "gate OOM" in payload["error"]


def test_no_canary_capacity_holds_candidate(fleet, tmp_path):
    router, apps = fleet
    workdir = str(tmp_path)
    root = os.path.join(workdir, "checkpoints")
    router.set_state(1, NOTREADY)  # one ready replica: no headroom
    controller = _controller(router, workdir)
    _make_ckpt(root, 4)
    controller.tick()
    assert controller.state == "idle"
    assert "canary_unplaceable" in _events(controller)
    assert apps[1].checkpoint_step == -1


def test_deploy_gauges_shape(fleet, tmp_path):
    router, _ = fleet
    controller = _controller(router, str(tmp_path))
    gauges = controller.deploy_gauges()
    assert gauges["state"] == "idle"
    assert gauges["incumbent_step"] == 2
    assert gauges["candidate_step"] == -1
    assert gauges["canary_replica_id"] == -1
    for key, value in gauges.items():
        if key != "state":
            assert isinstance(value, (int, float)), key
    summary = controller.summary()
    assert summary["policy"]["canary_weight"] == 0.5
    assert summary["timeline"] == [] and summary["verdicts"] == []
