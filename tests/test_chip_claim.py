"""Chip-claim guard: the VERDICT r3 "mechanism, not a rule" requirement.

The decisive test is `test_second_process_gets_loud_refusal`: while one
live process holds the claim lock, a second axon-enabled process that
imports the framework must die loudly BEFORE any backend init — that exact
scenario (a stray interpreter start concurrent with a live bench claim)
wedged the chip for 10+ hours in round 3 (RESULTS.md timeline).
"""

import json
import os
import subprocess
import sys

import pytest

from rt1_tpu import chip_claim

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def lock(tmp_path, monkeypatch):
    """Point the module at a private lockfile and keep the token env clean."""
    path = str(tmp_path / "claim.lock")
    monkeypatch.setenv(chip_claim.LOCK_ENV, path)
    monkeypatch.delenv(chip_claim.TOKEN_ENV, raising=False)
    return path


def _spawn_holder():
    """A live python process to impersonate a claim holder."""
    return subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)"],
        stdout=subprocess.DEVNULL,
    )


def test_acquire_release_roundtrip(lock):
    claim = chip_claim.acquire("test", path=lock)
    assert claim.owned
    record = chip_claim.holder(lock)
    assert record["pid"] == os.getpid()
    assert record["tag"] == "test"
    assert os.environ[chip_claim.TOKEN_ENV] == claim.token
    claim.release()
    assert chip_claim.holder(lock) is None
    claim.release()  # idempotent


def test_contended_acquire_raises(lock):
    holder_proc = _spawn_holder()
    try:
        chip_claim._write_lock(
            lock, pid=holder_proc.pid, tag="other-bench", token="deadbeef"
        )
        with pytest.raises(chip_claim.ChipClaimHeld) as exc:
            chip_claim.acquire("test", path=lock)
        assert str(holder_proc.pid) in str(exc.value)
        assert "other-bench" in str(exc.value)
    finally:
        holder_proc.kill()
        holder_proc.wait()


def test_stale_lock_is_reaped(lock):
    # A dead pid (we just reaped it) with a python cmdline no longer exists.
    dead = _spawn_holder()
    dead.kill()
    dead.wait()
    chip_claim._write_lock(lock, pid=dead.pid, tag="crashed", token="feed")
    claim = chip_claim.acquire("test", path=lock)
    assert claim.owned
    assert chip_claim.holder(lock)["pid"] == os.getpid()
    claim.release()


def test_recycled_pid_lock_is_reaped(lock):
    """ADVICE r4: a recycled pid whose new occupant is a long-lived python
    process must not make a stale lock look held forever. The lock records
    the holder's kernel start time; same pid + different start time = dead
    holder."""
    # Use our own (live, python) pid so the cmdline marker check passes,
    # but stamp a start time that cannot match any live process.
    record = {"pid": os.getpid(), "tag": "ghost", "token": "dead",
              "pid_start": 1, "created": 0.0}
    with open(lock, "w") as f:
        json.dump(record, f)
    assert not chip_claim._record_alive(record)
    claim = chip_claim.acquire("test", path=lock)
    assert claim.owned
    assert chip_claim.holder(lock)["pid"] == os.getpid()
    claim.release()


def test_matching_pid_start_still_counts_as_held(lock):
    # A fresh acquire stamps our own start time; a second claimant reading
    # the record must agree the holder is alive (no false staleness).
    claim = chip_claim.acquire("self", path=lock)
    try:
        record = chip_claim.holder(lock)
        assert record["pid_start"] == chip_claim._pid_start(os.getpid())
        assert chip_claim._record_alive(record)
    finally:
        claim.release()


def test_token_umbrella_joins_parent_claim(lock, monkeypatch):
    parent = chip_claim.acquire("parent", path=lock)
    # A child inherits the token env; its acquire joins instead of raising.
    child_claim = chip_claim.acquire("child", path=lock)
    assert not child_claim.owned
    child_claim.release()
    assert chip_claim.holder(lock)["pid"] == os.getpid()  # parent's
    parent.release()


def test_transfer_hands_lock_to_dangling_probe(lock):
    claim = chip_claim.acquire("bench", path=lock)
    holder_proc = _spawn_holder()
    try:
        claim.transfer(holder_proc.pid, tag="dangling-chip-probe")
        record = chip_claim.holder(lock)
        assert record["pid"] == holder_proc.pid
        assert record["tag"] == "dangling-chip-probe"
        # The original owner must no longer delete the transferred lock.
        claim.release()
        assert chip_claim.holder(lock) is not None
        # Another process now has to wait for the probe child.
        with pytest.raises(chip_claim.ChipClaimHeld):
            os.environ.pop(chip_claim.TOKEN_ENV, None)
            chip_claim.acquire("next", path=lock)
    finally:
        holder_proc.kill()
        holder_proc.wait()


def test_wait_s_acquires_after_holder_exits(lock):
    holder_proc = _spawn_holder()
    chip_claim._write_lock(
        lock, pid=holder_proc.pid, tag="short-job", token="beef"
    )
    holder_proc.kill()
    holder_proc.wait()
    # Holder is already dead: even wait_s=0 reaps it via the liveness check;
    # wait_s just bounds how long a live holder is waited out.
    claim = chip_claim.acquire("test", path=lock, wait_s=5, poll_s=0.1)
    assert claim.owned
    claim.release()


def test_axon_active_env_matrix(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "")
    assert not chip_claim.axon_active()
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert not chip_claim.axon_active()
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert chip_claim.axon_active()
    monkeypatch.setenv("JAX_PLATFORMS", "")
    assert chip_claim.axon_active()


def test_second_process_gets_loud_refusal(lock, tmp_path):
    """VERDICT r3 #2 'done' condition: a second process gets a loud refusal.

    The child runs with the axon env shape (pool IPs + platform axon) but a
    scrubbed PYTHONPATH, so the real axon sitecustomize never loads and
    nothing can actually dial — `import rt1_tpu` must still refuse because
    a live holder owns the lock.
    """
    holder_proc = _spawn_holder()
    try:
        chip_claim._write_lock(
            lock, pid=holder_proc.pid, tag="bench:train", token="cafe"
        )
        env = {
            k: v
            for k, v in os.environ.items()
            if k not in (chip_claim.TOKEN_ENV, "PYTHONPATH")
        }
        env.update(
            {
                "PALLAS_AXON_POOL_IPS": "127.0.0.1",
                "JAX_PLATFORMS": "axon",
                chip_claim.LOCK_ENV: lock,
            }
        )
        probe = subprocess.run(
            [sys.executable, "-c", "import rt1_tpu"],
            capture_output=True,
            text=True,
            env=env,
            cwd=REPO,
            timeout=60,
        )
        assert probe.returncode != 0
        assert "ChipClaimHeld" in probe.stderr
        assert str(holder_proc.pid) in probe.stderr
        # And with the umbrella token it is allowed through.
        env[chip_claim.TOKEN_ENV] = "cafe"
        probe = subprocess.run(
            [sys.executable, "-c", "import rt1_tpu"],
            capture_output=True,
            text=True,
            env=env,
            cwd=REPO,
            timeout=60,
        )
        assert probe.returncode == 0, probe.stderr
        # Self-managed entrypoints (bench/tpu_validation/learn_proof) opt
        # out of the import-time guard so their explicit acquire() owns the
        # claim — the import itself must not refuse for them.
        env.pop(chip_claim.TOKEN_ENV)
        env[chip_claim.SELF_MANAGED_ENV] = "1"
        probe = subprocess.run(
            [sys.executable, "-c", "import rt1_tpu"],
            capture_output=True,
            text=True,
            env=env,
            cwd=REPO,
            timeout=60,
        )
        assert probe.returncode == 0, probe.stderr
    finally:
        holder_proc.kill()
        holder_proc.wait()


def test_acquire_leaves_no_tmp_droppings(lock, tmp_path):
    """The atomic link-based creation cleans its tmp file on every path."""
    claim = chip_claim.acquire("test", path=lock)
    claim.release()
    holder_proc = _spawn_holder()
    try:
        chip_claim._write_lock(
            lock, pid=holder_proc.pid, tag="busy", token="beef"
        )
        os.environ.pop(chip_claim.TOKEN_ENV, None)
        with pytest.raises(chip_claim.ChipClaimHeld):
            chip_claim.acquire("test", path=lock)
    finally:
        holder_proc.kill()
        holder_proc.wait()
    leftovers = [
        f for f in os.listdir(os.path.dirname(lock)) if ".acquire" in f
    ]
    assert leftovers == []


def test_cli_status_and_clear(lock):
    env = {**os.environ, chip_claim.LOCK_ENV: lock}
    env.pop(chip_claim.TOKEN_ENV, None)
    out = subprocess.run(
        [sys.executable, "-m", "rt1_tpu.chip_claim", "status"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60,
    )
    assert json.loads(out.stdout) == {"locked": False, "path": lock}

    holder_proc = _spawn_holder()
    try:
        chip_claim._write_lock(
            lock, pid=holder_proc.pid, tag="job", token="f00d"
        )
        out = subprocess.run(
            [sys.executable, "-m", "rt1_tpu.chip_claim", "status"],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=60,
        )
        status = json.loads(out.stdout)
        assert status["locked"] and status["holder_alive"]
        # clear refuses while the holder lives...
        out = subprocess.run(
            [sys.executable, "-m", "rt1_tpu.chip_claim", "clear"],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=60,
        )
        assert out.returncode == 1
    finally:
        holder_proc.kill()
        holder_proc.wait()
    # ...and clears once it is gone.
    out = subprocess.run(
        [sys.executable, "-m", "rt1_tpu.chip_claim", "clear"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60,
    )
    assert out.returncode == 0
    assert chip_claim.holder(lock) is None
