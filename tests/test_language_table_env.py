"""LanguageTable env integration tests.

Mirrors the intent of reference `environments/language_table_test.py`:
reset/step/observation containment across block modes, state save->replay
reproducibility (incl. rgb), and the instruction byte codec.
"""

import numpy as np
import pytest

from rt1_tpu.envs import LanguageTable, blocks, constants
from rt1_tpu.envs.rewards import BlockToBlockReward


def make_env(**kwargs):
    kwargs.setdefault("block_mode", blocks.BlockMode.BLOCK_4)
    kwargs.setdefault("reward_factory", BlockToBlockReward)
    kwargs.setdefault("seed", 0)
    return LanguageTable(**kwargs)


@pytest.mark.parametrize(
    "mode",
    [blocks.BlockMode.BLOCK_1, blocks.BlockMode.BLOCK_4,
     blocks.BlockMode.BLOCK_8, blocks.BlockMode.N_CHOOSE_K],
)
def test_reset_and_step_all_modes(mode):
    reward_factory = None if mode == blocks.BlockMode.BLOCK_1 else BlockToBlockReward
    env = LanguageTable(block_mode=mode, reward_factory=reward_factory, seed=1)
    obs = env.reset()
    assert set(obs) == {
        "effector_translation",
        "effector_target_translation",
        "instruction",
        "rgb",
    }
    assert obs["rgb"].shape == (constants.IMAGE_HEIGHT, constants.IMAGE_WIDTH, 3)
    assert obs["rgb"].dtype == np.uint8
    assert obs["instruction"].shape == (constants.INSTRUCTION_LENGTH,)
    obs, reward, done, info = env.step(np.array([0.02, -0.01]))
    assert np.isscalar(reward)
    assert isinstance(done, bool) or done in (True, False)


def test_instruction_codec_roundtrip():
    text = "push the red moon to the blue cube"
    enc = LanguageTable.encode_instruction(text)
    assert enc.shape == (constants.INSTRUCTION_LENGTH,)
    assert enc.dtype == np.int32
    assert LanguageTable.decode_instruction(enc) == text
    assert LanguageTable.decode_instruction(
        LanguageTable.encode_instruction("")
    ) == ""


def test_instruction_codec_backward_compat_short():
    env = make_env()
    state = env.get_board_state()
    # Simulate an old-format state with a shorter instruction buffer.
    state["instruction"] = state["instruction"][:100]
    env.set_board_state(state)
    assert env._instruction.shape == (constants.INSTRUCTION_LENGTH,)


def test_state_save_restore_reproduces_observation():
    env = make_env()
    env.reset()
    for _ in range(3):
        env.step(np.array([0.05, 0.02]))
    saved = env.get_board_state()
    obs_before = env._compute_observation()

    # Disturb the board.
    for _ in range(5):
        env.step(np.array([-0.08, 0.08]))

    env.set_board_state(saved)
    obs_after = env._compute_observation()
    np.testing.assert_allclose(
        obs_before["effector_translation"],
        obs_after["effector_translation"],
        atol=1e-6,
    )
    np.testing.assert_array_equal(
        obs_before["instruction"], obs_after["instruction"]
    )
    np.testing.assert_array_equal(obs_before["rgb"], obs_after["rgb"])


def test_action_clipped_to_workspace():
    env = make_env()
    env.reset()
    for _ in range(30):
        env.step(np.array([0.1, 0.1]))
    xy = env.backend.effector_target_xy()
    assert xy[0] <= constants.X_MAX + 1e-9
    assert xy[1] <= constants.Y_MAX + 1e-9


def test_block_push_moves_block():
    env = make_env()
    env.reset()
    state = env.compute_state(request_task_update=False)
    start_block = env._start_block
    block_xy = state[f"block_{start_block}_translation"].copy()
    # Drive the effector straight at the block.
    for _ in range(60):
        eff = env.backend.effector_target_xy()
        cur = env.compute_state(request_task_update=False)[
            f"block_{start_block}_translation"
        ]
        delta = np.clip(cur - eff, -0.05, 0.05)
        env.step(delta)
    end_xy = env.compute_state(request_task_update=False)[
        f"block_{start_block}_translation"
    ]
    assert np.linalg.norm(end_xy - block_xy) > 0.005


def test_succeeded_after_manual_goal_placement():
    env = make_env()
    env.reset()
    reward = env._reward_calculator
    # Teleport the start block onto the target block: sparse reward fires.
    target_xy, _ = env.backend.block_pose(reward._target_block)
    env.backend.set_block_pose(reward._start_block, target_xy + 0.01)
    assert env.succeeded


def test_seeded_reset_deterministic():
    env1 = make_env(seed=123)
    env2 = make_env(seed=123)
    obs1, obs2 = env1.reset(), env2.reset()
    np.testing.assert_array_equal(obs1["instruction"], obs2["instruction"])
    np.testing.assert_allclose(
        obs1["effector_translation"], obs2["effector_translation"]
    )
    np.testing.assert_array_equal(obs1["rgb"], obs2["rgb"])


def test_render_with_text_overlay():
    env = make_env()
    env.reset()
    frame = env.render()
    assert frame.ndim == 3 and frame.shape[2] == 3
    assert frame.shape[1] == 640  # upscaled with instruction strip


def test_state_restore_preserves_task_with_task_updating_reward():
    # Rewards that define get_current_task_info must not clobber a restored
    # task on the reset(reset_poses=False) path.
    from rt1_tpu.envs.rewards import BlockToAbsoluteLocationReward

    env = LanguageTable(
        block_mode=blocks.BlockMode.BLOCK_4,
        reward_factory=BlockToAbsoluteLocationReward,
        seed=5,
    )
    env.reset()
    saved = env.get_board_state()
    saved_instruction = env.instruction_str
    # New episode with a different task (re-seed until it differs).
    reseed = 100
    while env.instruction_str == saved_instruction:
        env.seed(reseed)
        env.reset()
        reseed += 1
    env.set_board_state(saved)
    assert env.instruction_str == saved_instruction
    # The restored task must survive stepping (reward internals restored too).
    env.step(np.array([0.0, 0.0]))
    assert env.instruction_str == saved_instruction
