"""Action tokenizer tests.

Mirrors the reference's `tokenizers/action_tokenizer_test.py` coverage: token
accounting, Discrete/Box tokenize, OOV detokenize, limit values mapping to
0/vocab-1, invalid 2-D Box rejection, and fuzzed tokenize∘detokenize round-trips
(including batched), plus numeric parity against the torch reference formulas.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rt1_tpu.models import action_tokenizer
from rt1_tpu.specs import (
    BoxSpec,
    DiscreteSpec,
    language_table_action_space,
    rt1_generic_action_space,
    sample_space,
)

VOCAB = 256


def test_tokens_per_action_language_table():
    # terminate Discrete(2) → 1 token, action Box(2,) → 2 tokens (distribute_train.py:40-46)
    assert action_tokenizer.tokens_per_action(language_table_action_space()) == 3


def test_tokens_per_action_generic_rt1():
    # transformer_network_test_set_up.py: 1 + 3 + 3 + 1 = 8
    assert action_tokenizer.tokens_per_action(rt1_generic_action_space()) == 8


def test_rank2_box_raises():
    space = {"bad": BoxSpec(low=(-1.0,), high=(1.0,), shape=(2, 2))}
    with pytest.raises(ValueError, match="single dimension"):
        action_tokenizer.tokens_per_action(space)


def test_discrete_tokenize_identity():
    space = {"terminate_episode": DiscreteSpec(2)}
    toks = action_tokenizer.tokenize(space, {"terminate_episode": jnp.asarray(1)}, VOCAB)
    assert toks.shape == (1,)
    assert int(toks[0]) == 1


def test_box_limits_map_to_extremes():
    # action_tokenizer_test.py:111-129: low → token 0, high → token vocab-1.
    space = language_table_action_space()
    act = {"terminate_episode": jnp.asarray(0), "action": jnp.asarray([-0.1, 0.1])}
    toks = action_tokenizer.tokenize(space, act, VOCAB)
    np.testing.assert_array_equal(np.asarray(toks), [0, 0, VOCAB - 1])
    # Out-of-bounds values clip first (action_tokenizer.py:119).
    act = {"terminate_episode": jnp.asarray(0), "action": jnp.asarray([-5.0, 5.0])}
    toks = action_tokenizer.tokenize(space, act, VOCAB)
    np.testing.assert_array_equal(np.asarray(toks), [0, 0, VOCAB - 1])


def test_tokenize_truncates_like_torch():
    # torch `.to(torch.int32)` truncates; e.g. normalized 0.9999 * 255 = 254.97 → 254.
    space = {"a": BoxSpec(low=(0.0,), high=(1.0,), shape=(1,))}
    toks = action_tokenizer.tokenize(space, {"a": jnp.asarray([0.9999])}, VOCAB)
    assert int(toks[0]) == 254


def test_discrete_detokenize_oov_to_zero():
    # Reference quirk is strictly-greater (action_tokenizer.py:145): token n passes.
    space = {"terminate_episode": DiscreteSpec(2)}
    out = action_tokenizer.detokenize(space, jnp.asarray([3]), VOCAB)
    assert int(out["terminate_episode"]) == 0
    out = action_tokenizer.detokenize(space, jnp.asarray([2]), VOCAB)
    assert int(out["terminate_episode"]) == 2  # reproduces `> n` behavior


def test_roundtrip_fuzz(rng):
    # action_tokenizer_test.py:141-179: detokenize(tokenize(a)) ≈ a (the reference
    # asserts value closeness, not token equality — truncation makes token-level
    # round-trips only stable to ±1 under float32).
    space = rt1_generic_action_space()
    vocab = 1024  # matches the reference fuzz test's vocab_size
    for i in range(10):
        act = sample_space(space, jax.random.fold_in(rng, i))
        toks = action_tokenizer.tokenize(space, act, vocab)
        act2 = action_tokenizer.detokenize(space, toks, vocab)
        for k in act:
            np.testing.assert_allclose(
                np.asarray(act[k], np.float32), np.asarray(act2[k], np.float32), atol=1e-2
            )
        toks2 = action_tokenizer.tokenize(space, act2, vocab)
        assert int(np.max(np.abs(np.asarray(toks) - np.asarray(toks2)))) <= 1


def test_roundtrip_batched(rng):
    space = language_table_action_space()
    act = sample_space(space, rng, batch_shape=(4, 6))
    toks = action_tokenizer.tokenize(space, act, VOCAB)
    assert toks.shape == (4, 6, 3)
    act2 = action_tokenizer.detokenize(space, toks, VOCAB)
    assert act2["terminate_episode"].shape == (4, 6)
    assert act2["action"].shape == (4, 6, 2)
    toks2 = action_tokenizer.tokenize(space, act2, VOCAB)
    assert int(np.max(np.abs(np.asarray(toks) - np.asarray(toks2)))) <= 1
    # Detokenized Box values are within a bucket of the (clipped) originals.
    bucket = 0.2 / (VOCAB - 1)
    np.testing.assert_allclose(
        np.asarray(act2["action"]), np.asarray(act["action"]), atol=bucket + 1e-6
    )


def test_jit_and_vmap():
    space = language_table_action_space()
    f = jax.jit(lambda a: action_tokenizer.tokenize(space, a, VOCAB))
    act = {"terminate_episode": jnp.ones((8,), jnp.int32), "action": jnp.zeros((8, 2))}
    toks = f(act)
    assert toks.shape == (8, 3)
    # mid-range value 0.0 → (0.0 - -0.1)/0.2 * 255 = 127.5 → truncates to 127
    assert int(toks[0, 1]) == 127
