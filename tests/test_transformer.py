"""Causal transformer tests (reference: transformer_test.py:34-52 + mask semantics)."""

import jax
import jax.numpy as jnp
import numpy as np

from rt1_tpu.models.rt1 import action_token_positions, rt1_attention_mask
from rt1_tpu.models.transformer import CausalTransformer


def tiny_transformer(**kw):
    cfg = dict(num_layers=2, key_dim=8, num_heads=2, d_model=16, dropout_rate=0.1,
               vocab_size=16, max_seq_len=64)
    cfg.update(kw)
    return CausalTransformer(**cfg)


def test_output_shape(rng):
    model = tiny_transformer()
    x = jax.random.normal(rng, (2, 10, 12))
    mask = jnp.tril(jnp.ones((10, 10), jnp.uint8))
    params = model.init(rng, x, mask)
    out = model.apply(params, x, mask)
    assert out.shape == (2, 10, 16)


def test_attention_scores_flag(rng):
    model = tiny_transformer(return_attention_scores=True)
    x = jax.random.normal(rng, (1, 6, 12))
    mask = jnp.tril(jnp.ones((6, 6), jnp.uint8))
    params = model.init(rng, x, mask)
    out, scores = model.apply(params, x, mask)
    assert out.shape == (1, 6, 16)
    assert len(scores) == 2
    assert scores[0].shape == (1, 2, 6, 6)
    # Attention rows are softmax-normalized.
    np.testing.assert_allclose(np.asarray(scores[0].sum(-1)), 1.0, rtol=1e-5)


def test_batched_mask_and_seq_len_guard(rng):
    model = tiny_transformer(dropout_rate=0.0)
    x = jax.random.normal(rng, (2, 8, 12))
    mask2d = jnp.tril(jnp.ones((8, 8), jnp.uint8))
    params = model.init(rng, x, mask2d)
    out2d = model.apply(params, x, mask2d)
    # A (b, s, s) mask equal to the broadcasted 2-D mask gives identical results.
    mask3d = jnp.tile(mask2d[None], (2, 1, 1))
    out3d = model.apply(params, x, mask3d)
    np.testing.assert_allclose(np.asarray(out2d), np.asarray(out3d), atol=1e-6)
    # Sequences longer than max_seq_len are rejected, not silently clamped.
    import pytest

    long_x = jax.random.normal(rng, (1, 65, 12))
    with pytest.raises(ValueError, match="max_seq_len"):
        model.apply(params, long_x, jnp.tril(jnp.ones((65, 65), jnp.uint8)))


def test_causal_mask_blocks_future(rng):
    """Zeroing future inputs must not change past outputs under a tril mask."""
    model = tiny_transformer(dropout_rate=0.0)
    x = jax.random.normal(rng, (1, 8, 12))
    mask = jnp.tril(jnp.ones((8, 8), jnp.uint8))
    params = model.init(rng, x, mask)
    full = model.apply(params, x, mask)
    x_cut = x.at[:, 5:, :].set(0.0)
    cut = model.apply(params, x_cut, mask)
    np.testing.assert_allclose(np.asarray(full[:, :5]), np.asarray(cut[:, :5]), atol=1e-5)
    assert not np.allclose(np.asarray(full[:, 5:]), np.asarray(cut[:, 5:]))


# ---------------------------------------------------------------- RT-1 mask unit

def brute_force_reference_mask(t, i_tok, a_tok):
    """Independent re-derivation of _generate_masks (:156-192) for cross-checking."""
    step = i_tok + a_tok
    size = t * step

    def action_index(k):
        if k % step < i_tok:
            return -1
        return k // step

    tril = np.tril(np.ones((size, size), int))
    action_mask = np.zeros((size, size), int)
    for i in range(size):
        for j in range(size):
            ai, aj = action_index(i), action_index(j)
            if ai != -1 and aj != -1:
                if aj < ai or (aj == ai and j <= i):
                    action_mask[i, j] = 1
    return tril - action_mask


def test_rt1_mask_matches_reference_semantics():
    for (t, i_tok, a_tok) in [(1, 2, 1), (2, 3, 2), (6, 8, 3), (3, 2, 4)]:
        got = rt1_attention_mask(t, i_tok, a_tok)
        want = brute_force_reference_mask(t, i_tok, a_tok)
        np.testing.assert_array_equal(got, want, err_msg=f"cfg {(t, i_tok, a_tok)}")
        assert got.min() >= 0  # subtracting never goes negative


def test_rt1_mask_properties():
    t, i_tok, a_tok = 6, 8, 3
    m = rt1_attention_mask(t, i_tok, a_tok)
    pos = set(action_token_positions(t, i_tok, a_tok).tolist())
    size = t * (i_tok + a_tok)
    for q in range(size):
        for k in range(size):
            if k > q:
                assert m[q, k] == 0  # causal
            elif q in pos and k in pos:
                assert m[q, k] == 0  # action tokens never read action tokens (≤ time)
            elif k in pos and q not in pos:
                # image queries MAY read past action positions (inputs are zeroed
                # anyway); reference only subtracts the action→action entries.
                assert m[q, k] == (1 if k <= q else 0)
    # every action query can still attend its own step's image tokens.
    for q in sorted(pos):
        assert m[q].sum() >= i_tok


def test_action_token_positions_values():
    np.testing.assert_array_equal(
        action_token_positions(2, 3, 2), [3, 4, 8, 9]
    )
