"""CLIP BPE tokenizer algorithm tests (synthetic merge table).

The real CLIP vocab gz isn't bundled; these verify the algorithm itself:
byte-unicode reversibility, merge application in rank order, </w> terminal
handling, CLIP vocab layout, SOT/EOT framing, and encode/decode round-trip.
"""

import numpy as np
import pytest

from rt1_tpu.text import ClipBPETokenizer, bytes_to_unicode

# A tiny merge table: builds "th", "the</w>", "he", etc.
MERGES = [
    ("t", "h"),
    ("th", "e</w>"),
    ("h", "e</w>"),
    ("l", "l"),
    ("b", "a"),
    ("ll", "o</w>"),
]


@pytest.fixture(scope="module")
def tok():
    return ClipBPETokenizer(MERGES)


def test_bytes_to_unicode_reversible():
    m = bytes_to_unicode()
    assert len(m) == 256
    assert len(set(m.values())) == 256


def test_vocab_layout(tok):
    # 256 bytes + 256 </w> variants + merges + SOT/EOT.
    assert tok.vocab_size == 512 + len(MERGES) + 2
    assert tok.sot_token == tok.vocab_size - 2
    assert tok.eot_token == tok.vocab_size - 1


def test_merges_applied_in_rank_order(tok):
    ids = tok.encode("the")
    # 'the' -> t h e</w> -> th e</w> -> the</w> (single merged token).
    assert ids == [tok.encoder["the</w>"]]


def test_unmerged_falls_back_to_pieces(tok):
    ids = tok.encode("ba")
    # 'ba' merge exists but 'a</w>' ending: b a</w> -> only ('b','a') rank
    # applies to non-terminal pair; final pieces exist in vocab.
    assert all(i in tok.decoder for i in ids)
    assert tok.decode(ids) == "ba"


def test_roundtrip_word_text(tok):
    # Word-only text round-trips exactly; punctuation gains CLIP's
    # token-boundary spaces (see test_contraction_split).
    for text in ["hello there", "the the the", "a b c"]:
        assert tok.decode(tok.encode(text)) == text.lower()
    # Digits tokenize one-at-a-time ([\p{N}]), so decode space-separates.
    assert tok.decode(tok.encode("123")) == "1 2 3"


def test_tokenize_text_framing(tok):
    arr = tok.tokenize_text(["the", "hello"])
    assert arr.shape == (2, 77)
    assert arr.dtype == np.int32
    assert arr[0, 0] == tok.sot_token
    row = arr[0]
    eot_pos = int(np.argwhere(row == tok.eot_token)[0])
    assert (row[eot_pos + 1 :] == 0).all()


def test_tokenize_text_too_long_raises(tok):
    with pytest.raises(ValueError, match="too long"):
        tok.tokenize_text(["z " * 60], context_length=16)


def test_whitespace_and_case_cleaning(tok):
    a = tok.encode("  The   THE\n the ")
    b = tok.encode("the the the")
    assert a == b


def test_contraction_split(tok):
    # "'s" splits off as its own token; CLIP's decode reinserts a space at
    # every token boundary (same as OpenAI SimpleTokenizer).
    ids = tok.encode("it's")
    assert tok.decode(ids) == "it 's"
    assert tok.decode(tok.encode("push the block!")) == "push the block !"
