"""Durable sessions (ISSUE 19): snapshot, replicate, and live-migrate
session state so no event resets a user's window.

In-process stub replicas + a Router instance, no subprocesses: tier-1
fast. The stub implements the exact wire contract of the real replica
(`rt1_tpu/serve/migrate.py` + `/session/export` + `/session/import`),
so these tests prove live migration, affinity remap, crash restore,
compatibility refusals, and the failed-import fallback with zero jax
boots.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from rt1_tpu.obs import prometheus as prom
from rt1_tpu.obs.alerts import default_ruleset
from rt1_tpu.resilience import faults
from rt1_tpu.serve import migrate
from rt1_tpu.serve.metrics import ServeMetrics
from rt1_tpu.serve.router import READY, Replica, Router, make_router_server
from rt1_tpu.serve.stub import (
    STUB_SCHEMA,
    STUB_WINDOW,
    StubReplicaApp,
    make_stub_server,
    stub_action,
)


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    faults.clear()
    yield
    faults.clear()


def _wire_snapshot(sid="s", step=3, generation=-1, window=STUB_WINDOW,
                   cached=False, version=migrate.SNAPSHOT_VERSION):
    """A stub-shaped snapshot, field-for-field what /session/export ships."""
    return {
        "version": version,
        "session_id": sid,
        "step_index": step,
        "checkpoint_generation": generation,
        "window": window,
        "cached_inference": cached,
        "schema": [[n, list(s), d] for n, s, d in STUB_SCHEMA],
        "state": {"stub_step": {"data": [step]}},
    }


def _post(url, payload, timeout=5.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


# ---------------------------------------------------------------- migrate.py


def test_encode_decode_state_roundtrip():
    state = {"w": [1.0, 2.0, -3.5], "b": [[0.5, 0.25]]}
    encoded = migrate.encode_state(state)
    for leaf in encoded.values():
        assert set(leaf) >= {"shape", "dtype", "b64"}
    decoded = migrate.decode_state(encoded)
    assert decoded["w"].tolist() == [1.0, 2.0, -3.5]
    assert decoded["b"].tolist() == [[0.5, 0.25]]
    # Jax-free stubs ship raw-list leaves; decode passes them through.
    assert migrate.decode_state({"s": {"data": [7]}})["s"] == [7]


def test_check_compatibility_refuses_by_named_field():
    snap = _wire_snapshot(generation=100)
    kwargs = dict(
        checkpoint_generation=100,
        window=STUB_WINDOW,
        cached_inference=False,
        schema=STUB_SCHEMA,
    )
    migrate.check_compatibility(snap, **kwargs)  # compatible: no raise
    for field, mutate in [
        ("version", {"version": migrate.SNAPSHOT_VERSION + 1}),
        ("checkpoint_generation", {"checkpoint_generation": 99}),
        ("window", {"window": STUB_WINDOW + 1}),
        ("cached_inference", {"cached_inference": True}),
    ]:
        with pytest.raises(migrate.SnapshotCompatibilityError) as exc:
            migrate.check_compatibility({**snap, **mutate}, **kwargs)
        assert field in str(exc.value), field
    # Schema skew is refused too — a leaf the importer doesn't expect.
    bad = dict(snap)
    bad["schema"] = [["other_leaf", [], "int64"]]
    with pytest.raises(migrate.SnapshotCompatibilityError) as exc:
        migrate.check_compatibility(bad, **kwargs)
    assert "schema" in str(exc.value)


def test_snapshot_ring_roundtrip_eviction_and_drop(tmp_path):
    ring = migrate.SnapshotRing(str(tmp_path), capacity=2)
    with pytest.raises(ValueError):
        ring.save({"step_index": 1})  # no session_id
    for i, sid in enumerate(["old", "mid", "new"]):
        ring.save(_wire_snapshot(sid=sid, step=i))
        time.sleep(0.05)  # distinct mtimes: eviction is oldest-by-mtime
    assert len(ring) == 2
    assert ring.evictions == 1
    assert ring.load("old") is None  # oldest evicted
    loaded = ring.load("new")
    assert loaded is not None
    record, age_s = loaded
    assert record["step_index"] == 2
    assert age_s is not None and age_s >= 0.0
    assert "saved_at" in record  # stamped on save
    ring.drop("new")
    assert ring.load("new") is None
    assert len(ring) == 1
    ring.drop("never-saved")  # best-effort: no raise


def test_snapshot_ring_survives_corrupt_record(tmp_path):
    ring = migrate.SnapshotRing(str(tmp_path))
    path = ring.save(_wire_snapshot(sid="torn"))
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("{not json")
    assert ring.load("torn") is None  # corrupt = miss, not crash


def test_migrate_session_never_raises_on_dead_source():
    result = migrate.migrate_session(
        "http://127.0.0.1:1", "http://127.0.0.1:1", "ghost", timeout_s=0.2
    )
    assert result["ok"] is False
    assert result["stage"] in ("export", "transport")
    assert result["error"]


# --------------------------------------------------------- stub wire contract


def test_stub_export_import_token_identical_continuation():
    src = StubReplicaApp(replica_id=0)
    dst = StubReplicaApp(replica_id=1)
    for _ in range(3):
        code, _ = src.act({"session_id": "mig", "image_b64": "AAAA"})
        assert code == 200
    code, body = src.session_export({"session_id": "mig"})
    assert code == 200 and body["ok"] is True
    snapshot = body["snapshot"]
    assert snapshot["step_index"] == 3
    assert snapshot["version"] == migrate.SNAPSHOT_VERSION
    # The continuation the user would have seen had nothing moved.
    code, ref = src.act({"session_id": "mig", "image_b64": "AAAA"})
    assert code == 200 and ref["step_index"] == 3

    code, imported = dst.session_import({"snapshot": snapshot})
    assert code == 200
    assert imported["session_id"] == "mig"
    assert imported["step_index"] == 3
    code, cont = dst.act({"session_id": "mig", "image_b64": "AAAA"})
    assert code == 200
    # Token-identical: same step, same action, same tokens, no restart.
    assert cont["step_index"] == ref["step_index"] == 3
    assert cont["action"] == ref["action"] == stub_action(3)
    assert cont["action_tokens"] == ref["action_tokens"]
    assert cont["session_started"] is False
    assert src.migration_exports == 1
    assert dst.migration_imports == 1


def test_stub_import_refusals_named_over_http():
    app = StubReplicaApp(replica_id=0)
    code, body = app.session_import({})
    assert code == 400  # no snapshot at all
    snap = _wire_snapshot(sid="x", generation=-1)
    for field, mutate in [
        ("checkpoint_generation", {"checkpoint_generation": 7}),
        ("window", {"window": STUB_WINDOW - 1}),
        ("cached_inference", {"cached_inference": True}),
    ]:
        code, body = app.session_import({"snapshot": {**snap, **mutate}})
        assert code == 409, field
        assert field in body["error"], field
    assert app.migration_import_failures == 3
    # Unknown-session export is a 404, not an invented snapshot.
    code, body = app.session_export({"session_id": "never-opened"})
    assert code == 404


def test_stub_ring_restore_after_respawn(tmp_path):
    """SIGKILL durability, mimicked: a fresh process sharing the snapshot
    directory resumes the window mid-episode at re-home time."""
    first = StubReplicaApp(replica_id=0, session_snapshot_dir=str(tmp_path))
    for _ in range(2):
        code, _ = first.act({"session_id": "dur", "image_b64": "AAAA"})
        assert code == 200
    # "Respawn": a new app over the same directory, empty session table.
    second = StubReplicaApp(replica_id=0, session_snapshot_dir=str(tmp_path))
    code, body = second.act({"session_id": "dur", "image_b64": "AAAA"})
    assert code == 200
    assert body["session_restored"] is True
    assert body["step_index_restored"] == 2
    assert body["step_index"] == 2  # continues, not restarts
    assert body["action"] == stub_action(2)
    assert body["session_started"] is False
    assert second.migration_restores == 1


def test_stub_ring_restore_staleness_bound(tmp_path):
    first = StubReplicaApp(replica_id=0, session_snapshot_dir=str(tmp_path))
    code, _ = first.act({"session_id": "stale", "image_b64": "AAAA"})
    assert code == 200
    second = StubReplicaApp(
        replica_id=0,
        session_snapshot_dir=str(tmp_path),
        snapshot_max_age_s=0.01,
    )
    time.sleep(0.05)
    code, body = second.act({"session_id": "stale", "image_b64": "AAAA"})
    assert code == 200  # degrades to a fresh window, never an error
    assert "session_restored" not in body
    assert body["step_index"] == 0 and body["session_started"] is True
    assert second.migration_restore_failures == 1
    # The stale record was dropped, then the fresh act re-saved the new
    # window: the ring now holds step 1, not the step-1-of-old-life junk.
    record, _age = second.snapshot_ring.load("stale")
    assert record["step_index"] == 1


def test_stub_ring_restore_fault_degrades_to_fresh_window(tmp_path):
    first = StubReplicaApp(replica_id=0, session_snapshot_dir=str(tmp_path))
    code, _ = first.act({"session_id": "chaos", "image_b64": "AAAA"})
    assert code == 200
    faults.install(faults.FaultPlan.parse("session_restore@1"))
    second = StubReplicaApp(replica_id=0, session_snapshot_dir=str(tmp_path))
    code, body = second.act({"session_id": "chaos", "image_b64": "AAAA"})
    assert code == 200
    assert "session_restored" not in body
    assert body["step_index"] == 0
    assert second.migration_restore_failures == 1


def test_release_keep_snapshot_preserves_ring_entry(tmp_path):
    """Migration cleanup releases the source's stale copy WITHOUT
    dropping the shared ring file — it now backs the importer's session,
    whose crash durability must not lapse until its next act."""
    app = StubReplicaApp(replica_id=0, session_snapshot_dir=str(tmp_path))
    code, _ = app.act({"session_id": "moved", "image_b64": "AAAA"})
    assert code == 200
    code, _ = app.release({"session_id": "moved", "keep_snapshot": True})
    assert code == 200
    assert "moved" not in app._sessions
    record, _age = app.snapshot_ring.load("moved")
    assert record["step_index"] == 1
    # A plain client release still drops it (forget-me semantics).
    code, _ = app.act({"session_id": "gone", "image_b64": "AAAA"})
    assert code == 200
    code, _ = app.release({"session_id": "gone"})
    assert code == 200
    assert app.snapshot_ring.load("gone") is None


def test_stub_reload_bumps_generation_and_preserves_sessions():
    app = StubReplicaApp(replica_id=0)
    code, _ = app.act({"session_id": "live", "image_b64": "AAAA"})
    assert code == 200
    code, _ = app.reload({"step": 42})
    assert code == 200
    assert app.checkpoint_generation == 42
    # In-place hot-swap preserves the window...
    code, body = app.act({"session_id": "live", "image_b64": "AAAA"})
    assert code == 200 and body["step_index"] == 1
    # ...while imports of pre-reload snapshots are refused by name.
    code, body = app.session_import(
        {"snapshot": _wire_snapshot(sid="old-gen", generation=-1)}
    )
    assert code == 409 and "checkpoint_generation" in body["error"]


# ------------------------------------------------------------- live migration


@pytest.fixture()
def fleet():
    apps, servers = [], []
    router = Router(replica_timeout_s=5.0)
    for rid in range(2):
        app = StubReplicaApp(replica_id=rid)
        httpd = make_stub_server(app)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        host, port = httpd.server_address[:2]
        replica = router.add_replica(Replica(rid, url=f"http://{host}:{port}"))
        replica.state = READY
        apps.append(app)
        servers.append(httpd)
    yield router, apps, servers
    for httpd in servers:
        try:
            httpd.shutdown()
            httpd.server_close()
        except OSError:
            pass


def _act(router, session_id):
    return router.route_act({"session_id": session_id, "image_b64": "AAAA"})


def test_router_migrates_drain_victims_with_token_identity(fleet):
    router, apps, _ = fleet
    # Least-loaded placement, lower-id tiebreak: "a" -> 0, "b" -> 1.
    for _ in range(3):
        status, body = _act(router, "a")
        assert status == 200 and body["replica_id"] == 0
    status, body = _act(router, "b")
    assert status == 200 and body["replica_id"] == 1

    summary = router.migrate_sessions_from(0, reason="drain")
    assert summary["migrated"] == 1 and summary["failed"] == 0
    assert summary["sessions"][0]["session_id"] == "a"
    assert summary["sessions"][0]["target_id"] == 1
    # The source's now-stale copy is freed: the slot doesn't leak, and a
    # later failover back can never silently continue the stale window.
    assert summary["sessions"][0]["source_released"] is True
    assert "a" not in apps[0]._sessions

    status, body = _act(router, "a")
    assert status == 200
    assert body["migrated"] is True
    assert "restarted" not in body
    assert body["replica_id"] == 1
    # The window survived the move: step 3 next, exactly as if nothing
    # had happened (the stub's action is a pure function of the step).
    assert body["step_index"] == 3
    assert body["action"] == stub_action(3)
    assert body["session_started"] is False
    # The flag is consumed: the act after reads as plain ok.
    status, body = _act(router, "a")
    assert status == 200 and "migrated" not in body

    assert apps[0].migration_exports == 1
    assert apps[1].migration_imports == 1
    assert router.slo.gauges()["slo_requests_migrated"] == 1
    # Migrated counts as GOOD for availability — the user kept their
    # window; only true restarts burn budget.
    assert router.slo.gauges()["slo_availability_rolling"] == 1.0


def test_failed_import_falls_back_to_restart_not_5xx(fleet):
    router, _, _ = fleet
    status, body = _act(router, "a")
    assert status == 200 and body["replica_id"] == 0
    faults.install(faults.FaultPlan.parse("migrate_import@1"))
    summary = router.migrate_sessions_from(
        0, reason="drain", orphan_on_failure=True
    )
    assert summary["failed"] == 1 and summary["migrated"] == 0
    entry = summary["sessions"][0]
    assert entry["orphaned"] is True
    assert "injected fault" in entry["error"]
    # The legacy restart path picks the orphan up: 200, never a 5xx.
    status, body = _act(router, "a")
    assert status == 200
    assert body["restarted"] is True
    assert "migrated" not in body
    assert router.slo.gauges()["slo_requests_restarted"] == 1


def test_cross_generation_target_is_skipped_without_orphaning(fleet):
    router, apps, _ = fleet
    status, body = _act(router, "a")
    assert status == 200 and body["replica_id"] == 0
    # Survivor reloads to a new checkpoint generation: its surface no
    # longer matches the source, so migration refuses pre-flight.
    code, _ = apps[1].reload({"step": 5})
    assert code == 200
    summary = router.migrate_sessions_from(0, reason="reload")
    assert summary["migrated"] == 0 and summary["failed"] == 1
    assert summary["attempted"] == 0  # no doomed import was even tried
    assert "no compatible ready survivor" in summary["sessions"][0]["error"]
    # Without orphan_on_failure the session stays home and keeps serving
    # (the rolling-reload path: the in-place swap preserves the window).
    status, body = _act(router, "a")
    assert status == 200 and body["replica_id"] == 0
    assert body["step_index"] == 1
    assert "restarted" not in body and "migrated" not in body


def test_rebalance_moves_hottest_sessions(fleet):
    router, _, _ = fleet
    status, body = _act(router, "a")  # -> 0
    assert status == 200 and body["replica_id"] == 0
    status, body = _act(router, "b")  # -> 1
    assert status == 200 and body["replica_id"] == 1
    status, body = _act(router, "c")  # -> 0 or 1; act again to heat "a"
    assert status == 200
    status, _ = _act(router, "a")
    assert router.hottest_sessions(0, 1) == ["a"]
    status, body = router.rebalance(0, 1)
    assert status == 200 and body["ok"] is True and body["migrated"] == 1
    assert body["sessions"][0]["source_released"] is True
    status, body = _act(router, "a")
    assert status == 200
    assert body["migrated"] is True and body["replica_id"] == 1
    assert body["step_index"] == 2
    # Unknown replica: a clean 404, not a silent no-op.
    status, body = router.rebalance(99, 1)
    assert status == 404


def test_router_http_surface_for_rebalance_and_scale_down(fleet):
    router, _, servers = fleet
    httpd = make_router_server(router)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    host, port = httpd.server_address[:2]
    url = f"http://{host}:{port}"
    try:
        code, body = _post(url + "/rebalance", {"replica_id": "zero"})
        assert code == 400 and "replica_id" in body["error"]
        code, body = _post(url + "/rebalance", {"replica_id": 0, "count": 0})
        assert code == 400 and "count" in body["error"]
        code, body = _post(url + "/rebalance", {"replica_id": 99})
        assert code == 404
        code, body = _post(url + "/act",
                           {"session_id": "h", "image_b64": "AAAA"})
        assert code == 200
        code, body = _post(url + "/rebalance", {"replica_id": 0, "count": 1})
        assert code == 200 and body["ok"] is True
        # Scale-down is a fleet-supervisor verb: 404 on a bare router...
        code, body = _post(url + "/scale_down", {})
        assert code == 404 and "no fleet supervisor armed" in body["error"]
        # ...200 through an armed hook, 400 when the hook refuses.
        router.scale_down_fn = lambda payload: {
            "ok": True, "replica_id": 1, "draining": True
        }
        code, body = _post(url + "/scale_down", {})
        assert code == 200 and body["draining"] is True

        def _refuse(payload):
            raise ValueError("cannot retire the last replica")

        router.scale_down_fn = _refuse
        code, body = _post(url + "/scale_down", {})
        assert code == 400 and "last replica" in body["error"]
    finally:
        httpd.shutdown()
        httpd.server_close()


# ------------------------------------------------- satellite 1: orphan bound


def test_orphan_bound_evicts_oldest_first():
    """Regression for the arbitrary-set.pop eviction: under pressure the
    OLDEST orphan flag is dropped, and re-orphaning refreshes recency."""
    router = Router(max_tracked_sessions=3)
    with router._lock:
        for sid in ("a", "b", "c"):
            router._mark_orphaned_locked(sid)
        router._mark_orphaned_locked("a")  # re-orphan: "a" is newest now
        router._mark_orphaned_locked("d")  # over bound: evict oldest ("b")
    assert list(router._orphaned) == ["c", "a", "d"]
    # The freshest orphan always survives eviction pressure.
    with router._lock:
        for i in range(10):
            router._mark_orphaned_locked(f"churn-{i}")
    assert list(router._orphaned) == ["churn-7", "churn-8", "churn-9"]
    # Same ordered-set discipline for the migrated-flag map.
    with router._lock:
        for sid in ("m1", "m2", "m3", "m4"):
            router._mark_migrated_locked(sid)
    assert list(router._migrated) == ["m2", "m3", "m4"]


# ------------------------------------------- satellite 5: naming + alerting


def test_migration_metric_families_follow_naming_contract():
    text = ServeMetrics().prometheus_text(
        sessions_migrated_total=3,
        migration_exports_total=1,
        migration_imports_total=2,
        migration_import_failures_total=0,
        migration_restores_total=0,
        migration_restore_failures_total=0,
    )
    for family in (
        "rt1_serve_sessions_migrated_total",
        "rt1_serve_migration_exports_total",
        "rt1_serve_migration_imports_total",
        "rt1_serve_migration_import_failures_total",
        "rt1_serve_migration_restores_total",
        "rt1_serve_migration_restore_failures_total",
    ):
        assert f"# TYPE {family} counter" in text, family
    assert "rt1_serve_migration_imports_total 2" in text
    # The fleet fan-out mirrors every replica family under the
    # rt1_serve_replica_ prefix — the names alert rules subscribe to.
    names = set(prom.fleet_metric_names())
    for family in (
        "rt1_serve_replica_migration_exports_total",
        "rt1_serve_replica_migration_imports_total",
        "rt1_serve_replica_migration_import_failures_total",
        "rt1_serve_replica_migration_restores_total",
        "rt1_serve_replica_migration_restore_failures_total",
    ):
        assert family in names, family


def test_migration_gauges_absent_until_armed():
    """An idle stub's /metrics stays byte-stable: migration families
    appear only once the machinery is armed or a counter moves."""
    app = StubReplicaApp(replica_id=0)
    assert "migration_exports_total" not in app.metrics_snapshot()
    code, _ = app.act({"session_id": "s", "image_b64": "AAAA"})
    assert code == 200
    assert "migration_exports_total" not in app.metrics_snapshot()
    code, body = app.session_export({"session_id": "s"})
    assert code == 200
    snap = app.metrics_snapshot()
    assert snap["migration_exports_total"] == 1


def test_migration_failure_storm_rule_in_default_ruleset():
    rules = {r.name: r for r in default_ruleset()}
    assert "MigrationFailureStorm" in rules
    rule = rules["MigrationFailureStorm"]
    assert rule.severity == "warn"
    assert "migration" in rule.annotations.get("summary", "").lower()


def test_migration_fault_sites_registered():
    for site in ("migrate_export", "migrate_import", "session_restore"):
        assert site in faults.KNOWN_SITES, site
