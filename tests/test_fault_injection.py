"""Fault-injection registry + the feeder's failure/stall diagnosis paths.

The registry's contract (rt1_tpu/resilience/faults.py): pure counting, no
clocks, no randomness — the same plan fires at the same places every run.
The feeder's contract (rt1_tpu/data/feeder.py): a worker that raises
surfaces loudly on the consumer thread; a worker that dies *silently* is
diagnosed by the stall timeout (FeederStalledError naming live/dead
workers and queue depths) instead of blocking the train loop forever.
"""

import os
import sys

import numpy as np
import pytest

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.data import pack as pack_lib
from rt1_tpu.data.feeder import FeederStalledError, SampleAheadFeeder
from rt1_tpu.resilience import faults

SRC_H, SRC_W = 24, 40
H, W = 16, 28
WINDOW = 3


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -------------------------------------------------------------- registry


def test_parse_grammar_and_validation():
    plan = faults.FaultPlan.parse("nan_batch@7, ckpt_save@2x3")
    assert len(plan) == 2
    # Serve-fleet chaos sites ride the same grammar; indices are chaos
    # ticks (fleet supervision cycles), matched index-based.
    serve_plan = faults.FaultPlan.parse(
        "replica_kill@1,serve_reload@2,replica_hang@3"
    )
    assert len(serve_plan) == 3
    assert serve_plan.should_fire("replica_kill", index=1)
    assert not serve_plan.should_fire("replica_kill", index=2)  # budget 1
    assert serve_plan.should_fire("serve_reload", index=2)
    assert serve_plan.should_fire("replica_hang", index=3)
    assert faults.FaultPlan.parse("").fired_counts() == {}
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.FaultPlan.parse("bogus_site@1")
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.FaultPlan.parse("nan_batch")
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.FaultPlan.parse("nan_batch@x")


def test_count_based_matching_fires_exact_occurrences():
    plan = faults.FaultPlan.parse("ckpt_save@2x2")
    fires = [plan.should_fire("ckpt_save") for _ in range(5)]
    assert fires == [False, True, True, False, False]
    assert plan.fired_counts() == {"ckpt_save@2x2": 2}


def test_index_based_matching_respects_budget_across_replays():
    """After a rollback the batch ordinals restart at 0 — an exhausted
    spec must NOT re-fire at the same indices."""
    plan = faults.FaultPlan.parse("nan_batch@3x2")
    first_pass = [plan.should_fire("nan_batch", index=i) for i in range(6)]
    assert first_pass == [False, False, False, True, True, False]
    replay = [plan.should_fire("nan_batch", index=i) for i in range(6)]
    assert replay == [False] * 6


def test_install_from_config_and_env(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "ckpt_save@1")
    plan = faults.install_from("nan_batch@2")
    assert plan is faults.active() and len(plan) == 2
    monkeypatch.delenv(faults.ENV_VAR)
    assert faults.install_from("") is None
    assert faults.active() is None


def test_maybe_fail_raises_injected_oserror_once():
    faults.install(faults.FaultPlan.parse("ckpt_save@1"))
    with pytest.raises(OSError, match=r"injected fault \[ckpt_save\]"):
        faults.maybe_fail("ckpt_save", what="save at step 2")
    faults.maybe_fail("ckpt_save")  # occurrence 2: no-op


def test_poison_batch_nans_floats_leaves_ints_and_source():
    batch = {
        "observations": {
            "image": np.zeros((2, 3), np.uint8),
            "natural_language_embedding": np.ones((2, 4), np.float32),
        },
        "actions": {
            "terminate_episode": np.ones(2, np.int32),
            "action": np.ones((2, 2), np.float32),
        },
    }
    out = faults.poison_batch(batch)
    assert np.isnan(out["observations"]["natural_language_embedding"]).all()
    assert np.isnan(out["actions"]["action"]).all()
    np.testing.assert_array_equal(
        out["observations"]["image"], np.zeros((2, 3), np.uint8)
    )
    np.testing.assert_array_equal(
        out["actions"]["terminate_episode"], np.ones(2, np.int32)
    )
    # The source batch is never mutated (it may be shared with a prefetch
    # queue).
    assert np.ones((2, 4), np.float32).sum() == batch["observations"][
        "natural_language_embedding"
    ].sum()


# ---------------------------------------------------------------- feeder


@pytest.fixture(scope="module")
def cache(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fault_corpus")
    rng = np.random.default_rng(0)
    paths = []
    for i in range(4):
        p = str(tmp / f"episode_{i}.npz")
        ep_lib.save_episode(
            p,
            ep_lib.generate_synthetic_episode(
                rng, num_steps=6, height=SRC_H, width=SRC_W
            ),
        )
        paths.append(p)
    out = str(tmp_path_factory.mktemp("fault_packed"))
    pack_lib.pack_episodes(paths, out, H, W, 0.95)
    return pack_lib.PackedEpisodeCache(out, window=WINDOW)


def test_feeder_kill_fault_surfaces_on_consumer_thread(cache):
    faults.install(faults.FaultPlan.parse("feeder_kill@1"))
    with SampleAheadFeeder(cache, 4, seed=0, num_threads=2) as feeder:
        with pytest.raises(RuntimeError, match="feeder worker failed") as ei:
            for _ in range(10):
                next(feeder)
    assert "feeder_kill" in str(ei.value.__cause__)


def test_feeder_hang_diagnosed_by_stall_timeout(cache):
    """Worker 1 dies silently at ticket 1 (the simulated deadlock); the
    consumer's stall timeout names the dead thread and the queue state
    instead of blocking forever."""
    faults.install(faults.FaultPlan.parse("feeder_hang@1"))
    feeder = SampleAheadFeeder(
        cache, 4, seed=0, num_threads=2, stall_timeout_s=0.6
    )
    try:
        next(feeder)  # ticket 0 (worker 0) is fine
        with pytest.raises(FeederStalledError) as ei:
            for _ in range(10):
                next(feeder)
        msg = str(ei.value)
        assert "rt1-feeder-1" in msg  # the dead worker is named
        assert "queue depths" in msg
    finally:
        feeder.close()


def test_feeder_all_workers_dead_diagnosed_without_timeout(cache):
    """Even with NO stall timeout configured, a feeder whose workers all
    died silently must not block the consumer forever."""
    faults.install(faults.FaultPlan.parse("feeder_hang@0x2"))
    feeder = SampleAheadFeeder(cache, 4, seed=0, num_threads=2)
    try:
        with pytest.raises(FeederStalledError, match="alive: NONE"):
            for _ in range(10):
                next(feeder)
    finally:
        feeder.close()


def test_feeder_stall_timeout_validation(cache):
    with pytest.raises(ValueError, match="stall_timeout_s"):
        SampleAheadFeeder(cache, 4, stall_timeout_s=0.0, start=False)


def test_feeder_stats_report_worker_liveness(cache):
    with SampleAheadFeeder(cache, 4, seed=0, num_threads=2) as feeder:
        next(feeder)
        assert feeder.stats()["workers_alive"] == 2


# ------------------------------------------------------------- chaos run


@pytest.mark.slow
def test_chaos_train_end_to_end(tmp_path):
    """The acceptance run: tiny packed training with one NaN batch, one
    transient ckpt-save IOError, and one mid-run SIGTERM + relaunch
    reaches the same final step as a fault-free run, with guard/retry/
    preempt events visible in the flight-recorder dump."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "scripts")
    )
    import chaos_train

    summary = chaos_train.main(
        ["--workdir", str(tmp_path / "chaos"), "--seed", "1"]
    )
    assert summary["ok"]
    assert summary["final_step"] == summary["reference_final_step"]
    assert summary["guard_device_skips"] >= 1
    assert summary["ckpt_save_retries"] >= 1
