"""Native C++ episode reader: build, parse parity, fallback."""

import numpy as np
import pytest

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.data import native


@pytest.fixture(scope="module")
def lib_available():
    if not native.available():
        pytest.skip("native reader could not be built (no g++/zlib)")
    return True


def _episode(rng):
    return ep_lib.generate_synthetic_episode(rng, num_steps=5, height=12, width=16)


def test_native_matches_numpy_npz(lib_available, tmp_path):
    rng = np.random.default_rng(0)
    ep = _episode(rng)
    path = str(tmp_path / "ep.npz")
    np.savez(path, **ep)  # stored (uncompressed) members -> zero-copy path

    got = native.load_episode_native(path)
    assert set(got) == set(ep)
    for k in ep:
        np.testing.assert_array_equal(got[k], ep[k])
        assert got[k].dtype == ep[k].dtype


def test_native_matches_numpy_compressed(lib_available, tmp_path):
    rng = np.random.default_rng(1)
    ep = _episode(rng)
    path = str(tmp_path / "ep_c.npz")
    np.savez_compressed(path, **ep)  # deflated members -> inflate path

    got = native.load_episode_native(path)
    for k in ep:
        np.testing.assert_array_equal(got[k], ep[k])


def test_native_single_npy(lib_available, tmp_path):
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    path = str(tmp_path / "a.npy")
    np.save(path, arr)
    with native.NativeEpisode(path) as h:
        assert h.keys() == ["data"]
        got = h.to_dict()["data"]
    np.testing.assert_array_equal(got, arr)


def test_native_open_missing_raises(lib_available, tmp_path):
    with pytest.raises(IOError):
        native.NativeEpisode(str(tmp_path / "nope.npz"))


def test_load_episode_uses_native_and_fallback(lib_available, tmp_path, monkeypatch):
    rng = np.random.default_rng(2)
    ep = _episode(rng)
    path = str(tmp_path / "ep2.npz")
    ep_lib.save_episode(path, ep)

    via_default = ep_lib.load_episode(path)
    monkeypatch.setenv("RT1_TPU_NO_NATIVE", "1")
    via_numpy = ep_lib.load_episode(path)
    for k in ep:
        np.testing.assert_array_equal(via_default[k], via_numpy[k])


def test_native_large_random_roundtrip(lib_available, tmp_path):
    # A bigger mixed-dtype file exercises header sizes and offsets.
    rng = np.random.default_rng(3)
    data = {
        "f32": rng.standard_normal((64, 33)).astype(np.float32),
        "f64": rng.standard_normal((7,)).astype(np.float64),
        "u8": rng.integers(0, 255, (31, 9, 3), dtype=np.uint8),
        "i64": rng.integers(-5, 5, (128,), dtype=np.int64),
        "bools": rng.integers(0, 2, (17,)).astype(bool),
    }
    path = str(tmp_path / "mixed.npz")
    np.savez(path, **data)
    got = native.load_episode_native(path)
    for k, v in data.items():
        np.testing.assert_array_equal(got[k], v)


def test_native_window_sampler_matches_cv2(tmp_path, monkeypatch):
    """The C++ crop+bilinear matches cv2.INTER_LINEAR to +/-1 LSB, and the
    pipeline produces the same sample distribution through either path."""
    cv2 = pytest.importorskip("cv2")
    from rt1_tpu.data import native
    from rt1_tpu.data.episodes import generate_synthetic_episode, save_episode
    from rt1_tpu.data.pipeline import WindowedEpisodeDataset

    if not native.sampler_available():
        pytest.skip("native window sampler not built")

    rng = np.random.default_rng(3)
    frames = [rng.integers(0, 256, (90, 160, 3), np.uint8) for _ in range(4)]
    boxes = np.array([[2, 5, 85, 152], [0, 0, 90, 160],
                      [4, 3, 85, 152], [1, 7, 85, 152]], np.int32)
    out = native.crop_resize_batch(frames, boxes, 64, 112)
    ref = np.stack([
        cv2.resize(
            f[t : t + ch, l : l + cw], (112, 64),
            interpolation=cv2.INTER_LINEAR,
        )
        for f, (t, l, ch, cw) in zip(frames, boxes)
    ])
    assert np.abs(out.astype(int) - ref.astype(int)).max() <= 1

    # Same pipeline sample through the forced-native path vs the cv2 path.
    ep = generate_synthetic_episode(rng, num_steps=5, height=90, width=160)
    p = str(tmp_path / "episode_0.npz")
    save_episode(p, ep)
    ds = WindowedEpisodeDataset([p], window=3, crop_factor=0.95,
                                height=64, width=112)
    monkeypatch.delenv("RT1_TPU_FORCE_NATIVE_SAMPLER", raising=False)
    s_cv2 = ds.get_window(2, np.random.default_rng(11))
    monkeypatch.setenv("RT1_TPU_FORCE_NATIVE_SAMPLER", "1")
    s_nat = ds.get_window(2, np.random.default_rng(11))
    a = s_cv2["observations"]["image"].astype(int)
    b = s_nat["observations"]["image"].astype(int)
    assert np.abs(a - b).max() <= 1
    np.testing.assert_array_equal(
        s_cv2["actions"]["action"], s_nat["actions"]["action"]
    )
