"""Native C++ episode reader: build, parse parity, fallback."""

import numpy as np
import pytest

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.data import native


@pytest.fixture(scope="module")
def lib_available():
    if not native.available():
        pytest.skip("native reader could not be built (no g++/zlib)")
    return True


def _episode(rng):
    return ep_lib.generate_synthetic_episode(rng, num_steps=5, height=12, width=16)


def test_native_matches_numpy_npz(lib_available, tmp_path):
    rng = np.random.default_rng(0)
    ep = _episode(rng)
    path = str(tmp_path / "ep.npz")
    np.savez(path, **ep)  # stored (uncompressed) members -> zero-copy path

    got = native.load_episode_native(path)
    assert set(got) == set(ep)
    for k in ep:
        np.testing.assert_array_equal(got[k], ep[k])
        assert got[k].dtype == ep[k].dtype


def test_native_matches_numpy_compressed(lib_available, tmp_path):
    rng = np.random.default_rng(1)
    ep = _episode(rng)
    path = str(tmp_path / "ep_c.npz")
    np.savez_compressed(path, **ep)  # deflated members -> inflate path

    got = native.load_episode_native(path)
    for k in ep:
        np.testing.assert_array_equal(got[k], ep[k])


def test_native_single_npy(lib_available, tmp_path):
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    path = str(tmp_path / "a.npy")
    np.save(path, arr)
    with native.NativeEpisode(path) as h:
        assert h.keys() == ["data"]
        got = h.to_dict()["data"]
    np.testing.assert_array_equal(got, arr)


def test_native_open_missing_raises(lib_available, tmp_path):
    with pytest.raises(IOError):
        native.NativeEpisode(str(tmp_path / "nope.npz"))


def test_load_episode_uses_native_and_fallback(lib_available, tmp_path, monkeypatch):
    rng = np.random.default_rng(2)
    ep = _episode(rng)
    path = str(tmp_path / "ep2.npz")
    ep_lib.save_episode(path, ep)

    via_default = ep_lib.load_episode(path)
    monkeypatch.setenv("RT1_TPU_NO_NATIVE", "1")
    via_numpy = ep_lib.load_episode(path)
    for k in ep:
        np.testing.assert_array_equal(via_default[k], via_numpy[k])


def test_native_large_random_roundtrip(lib_available, tmp_path):
    # A bigger mixed-dtype file exercises header sizes and offsets.
    rng = np.random.default_rng(3)
    data = {
        "f32": rng.standard_normal((64, 33)).astype(np.float32),
        "f64": rng.standard_normal((7,)).astype(np.float64),
        "u8": rng.integers(0, 255, (31, 9, 3), dtype=np.uint8),
        "i64": rng.integers(-5, 5, (128,), dtype=np.int64),
        "bools": rng.integers(0, 2, (17,)).astype(bool),
    }
    path = str(tmp_path / "mixed.npz")
    np.savez(path, **data)
    got = native.load_episode_native(path)
    for k, v in data.items():
        np.testing.assert_array_equal(got[k], v)
