"""Test configuration: force an 8-device virtual CPU platform.

Real multi-chip TPU hardware is not available in CI; sharding/parallelism tests run
on `--xla_force_host_platform_device_count=8` CPU devices, which exercises the same
GSPMD partitioner and collective lowering XLA uses on a TPU mesh.

This must run before the first `import jax` anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize imports jax at interpreter startup (before this
# conftest runs), so JAX_PLATFORMS from os.environ is already captured — override the
# live config too, or tests silently dispatch op-by-op to the remote TPU tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    import jax

    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.default_rng(0)
