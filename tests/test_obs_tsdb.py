"""The metrics plane's memory (ISSUE 18): `obs/tsdb.py` ring semantics,
`obs/alerts.py` lifecycle, and `obs/collector.py` scrape bookkeeping —
all under injected fake clocks and fetchers, no sockets, no sleeps.
"""

import json
import os
import threading

import pytest

from rt1_tpu.obs.alerts import (
    AlertManager,
    AlertRule,
    default_ruleset,
    threshold_condition,
)
from rt1_tpu.obs.collector import Collector, Target, flatten_json
from rt1_tpu.obs.prometheus import parse_exposition
from rt1_tpu.obs.tsdb import SNAPSHOT_BASENAME, TSDB, read_snapshot


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)
        return self.t


# ------------------------------------------------------------------ TSDB


def test_tsdb_point_cap_ring_overwrite():
    clock = FakeClock()
    db = TSDB(max_points=4, clock=clock)
    for i in range(10):
        db.append("f", float(i), t=clock.advance(1.0))
    pts = db.points("f")
    assert [v for _, v in pts] == [6.0, 7.0, 8.0, 9.0]
    assert db.points_evicted_total == 6


def test_tsdb_time_retention_applies_on_write_and_read():
    clock = FakeClock()
    db = TSDB(retention_s=10.0, clock=clock)
    db.append("f", 1.0, t=clock.t)
    clock.advance(5.0)
    db.append("f", 2.0, t=clock.t)
    assert len(db.points("f")) == 2
    # A quiet series must not serve stale samples: retention is enforced
    # at read time too, without any further append.
    clock.advance(20.0)
    assert db.points("f") == []
    assert db.latest("f") is None


def test_tsdb_max_series_evicts_quietest_not_oldest():
    clock = FakeClock()
    db = TSDB(max_series=2, clock=clock)
    db.append("a", 1.0)
    db.append("b", 1.0)
    db.append("a", 2.0)  # "a" re-appended: "b" is now the quietest
    db.append("c", 1.0)  # cap hit -> "b" dropped
    assert db.families() == ["a", "c"]
    assert db.series_dropped_total == 1


def test_tsdb_labels_key_series_independently():
    db = TSDB(clock=FakeClock())
    db.append("up", 1.0, labels={"replica_id": "0"})
    db.append("up", 0.0, labels={"replica_id": "1"})
    assert db.instances("up") == [
        {"replica_id": "0"},
        {"replica_id": "1"},
    ]
    assert db.latest("up", labels={"replica_id": "1"})[1] == 0.0
    index = {
        (row["family"], tuple(sorted(row["labels"].items())))
        for row in db.series_index()
    }
    assert ("up", (("replica_id", "0"),)) in index


def test_tsdb_query_aggregates_with_fake_clock_windows():
    clock = FakeClock()
    db = TSDB(clock=clock)
    for i, v in enumerate([1.0, 3.0, 2.0, 10.0]):
        db.append("g", v, t=1000.0 + 10.0 * i)
    clock.t = 1030.0
    q = lambda agg, w, **kw: db.query("g", agg, w, **kw)  # noqa: E731
    assert q("latest", 100.0) == 10.0
    assert q("avg", 100.0) == 4.0
    assert q("min", 100.0) == 1.0
    assert q("max", 100.0) == 10.0
    assert q("sum", 100.0) == 16.0
    assert q("count", 100.0) == 4.0
    assert q("delta", 100.0) == 9.0
    assert q("quantile", 100.0, q=0.5) == 3.0  # nearest-rank, upper
    # Window restriction: only the last two points (t=1020, 1030).
    assert q("avg", 15.0) == 6.0
    # Empty window -> None; unknown agg -> ValueError.
    assert q("avg", 10.0, now=1000.0 + 3600.0) is None
    with pytest.raises(ValueError):
        q("p99", 100.0)


def test_tsdb_increase_tolerates_counter_reset():
    clock = FakeClock(t=1040.0)
    db = TSDB(clock=clock)
    for i, v in enumerate([10.0, 15.0, 2.0, 7.0]):  # restart at i=2
        db.append("c_total", v, t=1000.0 + 10.0 * i)
    # Sum of positive steps only: 5 + 0 + 5; delta would say -3.
    assert db.query("c_total", "increase", 100.0) == 10.0
    assert db.query("c_total", "rate", 100.0) == pytest.approx(10.0 / 30.0)
    assert db.query("c_total", "delta", 100.0) == -3.0
    # Change aggregates need two points to say anything.
    db.append("single", 5.0, t=1040.0)
    assert db.query("single", "increase", 100.0) is None


def test_tsdb_append_many_shares_one_timestamp():
    clock = FakeClock()
    db = TSDB(clock=clock)
    n = db.append_many(
        [("a", None, 1.0), ("b", {"x": "1"}, 2.0)], t=1234.0
    )
    assert n == 2
    assert db.points("a")[0][0] == 1234.0
    assert db.points("b", labels={"x": "1"})[0][0] == 1234.0


def test_tsdb_concurrent_append_and_query():
    db = TSDB(max_points=256)
    errors = []

    def writer(wid):
        try:
            for i in range(300):
                db.append("w", float(i), labels={"writer": str(wid)})
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    def reader():
        try:
            for _ in range(200):
                db.query("w", "latest", 3600.0, labels={"writer": "0"})
                db.series_index()
                db.stats()
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert db.appends_total == 4 * 300


def test_tsdb_snapshot_round_trip(tmp_path):
    clock = FakeClock()
    db = TSDB(clock=clock)
    db.append("a", 1.5, t=1000.0)
    db.append("a", 2.5, t=1001.0)
    db.append("b", 7.0, labels={"k": "v"}, t=1000.0)
    path = db.write_snapshot(str(tmp_path / SNAPSHOT_BASENAME))
    loaded = read_snapshot(path)
    assert loaded["header"]["series"] == 2
    assert loaded["header"]["points"] == 3

    db2 = TSDB(clock=FakeClock())
    assert db2.restore(path) == 3
    assert [v for _, v in db2.points("a")] == [1.5, 2.5]
    assert db2.latest("b", labels={"k": "v"}) == (1000.0, 7.0)


def test_tsdb_snapshot_tolerates_torn_final_line(tmp_path):
    db = TSDB(clock=FakeClock())
    db.append("a", 1.0, t=1000.0)
    db.append("b", 2.0, t=1000.0)
    path = db.write_snapshot(str(tmp_path / SNAPSHOT_BASENAME))
    body = open(path).read().rstrip("\n")
    torn = str(tmp_path / "torn.jsonl")
    with open(torn, "w") as f:
        f.write(body[: len(body) - 10])  # hard kill mid-line
    loaded = read_snapshot(torn)
    # The torn line ends the parse; everything before it survives.
    assert [row["family"] for row in loaded["series"]] == ["a"]
    db2 = TSDB(clock=FakeClock())
    assert db2.restore(torn) == 1


def test_tsdb_snapshot_write_is_atomic(tmp_path):
    db = TSDB(clock=FakeClock())
    db.append("a", 1.0, t=1000.0)
    path = str(tmp_path / "snap" / SNAPSHOT_BASENAME)
    db.write_snapshot(path)  # creates the parent dir
    db.append("a", 2.0, t=1001.0)
    db.write_snapshot(path)  # os.replace over the old file
    assert not os.path.exists(path + ".tmp")
    assert read_snapshot(path)["header"]["points"] == 2


# ---------------------------------------------------------------- alerts


def _rule(for_duration_s=0.0, threshold=5.0, **kw):
    return AlertRule(
        name=kw.pop("name", "HighG"),
        condition=threshold_condition(
            "g", op=">=", threshold=threshold, agg="latest", window_s=60.0
        ),
        for_duration_s=for_duration_s,
        **kw,
    )


def test_alert_for_duration_gates_pending_to_firing():
    clock = FakeClock()
    db = TSDB(clock=clock)
    mgr = AlertManager(db, [_rule(for_duration_s=10.0)], clock=clock)

    db.append("g", 9.0, t=clock.t)
    assert mgr.evaluate() == []  # pending, not firing
    assert mgr.active()[0]["state"] == "pending"

    clock.advance(5.0)
    db.append("g", 9.0, t=clock.t)
    assert mgr.evaluate() == []  # still inside for_duration_s

    clock.advance(5.0)
    db.append("g", 9.0, t=clock.t)
    events = mgr.evaluate()
    assert [e["event"] for e in events] == ["firing"]
    assert mgr.active()[0]["state"] == "firing"
    assert mgr.counters()["fired_total"] == 1


def test_alert_zero_for_duration_fires_same_pass():
    clock = FakeClock()
    db = TSDB(clock=clock)
    mgr = AlertManager(db, [_rule()], clock=clock)
    db.append("g", 9.0, t=clock.t)
    assert [e["event"] for e in mgr.evaluate()] == ["firing"]


def test_alert_cleared_pending_rearms_silently():
    clock = FakeClock()
    db = TSDB(clock=clock)
    mgr = AlertManager(db, [_rule(for_duration_s=10.0)], clock=clock)
    db.append("g", 9.0, t=clock.t)
    mgr.evaluate()  # pending
    db.append("g", 1.0, t=clock.advance(1.0))
    assert mgr.evaluate() == []  # dropped without a resolved event
    assert mgr.active() == []
    assert mgr.history() == []
    # Re-breach restarts the pending timer from scratch.
    db.append("g", 9.0, t=clock.advance(1.0))
    mgr.evaluate()
    clock.advance(9.0)
    db.append("g", 9.0, t=clock.t)
    assert mgr.evaluate() == []  # 9s < 10s: must re-earn the duration
    clock.advance(1.0)
    db.append("g", 9.0, t=clock.t)
    assert [e["event"] for e in mgr.evaluate()] == ["firing"]


def test_alert_resolve_emits_event_with_duration():
    clock = FakeClock()
    db = TSDB(clock=clock)
    fired, resolved = [], []
    mgr = AlertManager(
        db,
        [_rule()],
        clock=clock,
        on_fire=fired.append,
        on_resolve=resolved.append,
    )
    db.append("g", 9.0, t=clock.t)
    mgr.evaluate()
    db.append("g", 1.0, t=clock.advance(30.0))
    events = mgr.evaluate()
    assert [e["event"] for e in events] == ["resolved"]
    assert events[0]["duration_s"] == 30.0
    assert len(fired) == 1 and len(resolved) == 1
    assert [e["event"] for e in mgr.history()] == ["firing", "resolved"]
    # Resolved instance must re-earn: a fresh breach fires again.
    db.append("g", 9.0, t=clock.advance(1.0))
    assert [e["event"] for e in mgr.evaluate()] == ["firing"]
    assert mgr.counters()["fired_total"] == 2


def test_alert_rule_error_freezes_instances():
    clock = FakeClock()
    db = TSDB(clock=clock)
    blow_up = {"on": False}

    def cond(tsdb, now):
        if blow_up["on"]:
            raise RuntimeError("scrape database on fire")
        pts = tsdb.points("g", window_s=60.0, now=now)
        return [({}, pts[-1][1])] if pts and pts[-1][1] >= 5.0 else []

    mgr = AlertManager(db, [AlertRule("X", cond)], clock=clock)
    db.append("g", 9.0, t=clock.t)
    mgr.evaluate()
    assert mgr.active()[0]["state"] == "firing"
    # Broken rule: the firing instance must NOT silently resolve.
    blow_up["on"] = True
    assert mgr.evaluate() == []
    assert mgr.active()[0]["state"] == "firing"
    assert mgr.counters()["rule_errors_total"] == 1


def test_alert_callback_errors_are_swallowed():
    clock = FakeClock()
    db = TSDB(clock=clock)

    def bad_cb(event):
        raise RuntimeError("pager webhook down")

    mgr = AlertManager(db, [_rule()], clock=clock, on_fire=bad_cb)
    db.append("g", 9.0, t=clock.t)
    events = mgr.evaluate()  # must not raise
    assert [e["event"] for e in events] == ["firing"]
    assert mgr.counters()["callback_errors_total"] == 1


def test_alert_per_instance_fanout_and_prometheus_text():
    clock = FakeClock()
    db = TSDB(clock=clock)
    mgr = AlertManager(db, [_rule(severity="page")], clock=clock)
    db.append("g", 9.0, labels={"replica_id": "0"}, t=clock.t)
    db.append("g", 1.0, labels={"replica_id": "1"}, t=clock.t)
    mgr.evaluate()
    active = mgr.active()
    assert len(active) == 1
    assert active[0]["labels"] == {"replica_id": "0"}
    parsed = parse_exposition(mgr.prometheus_text())
    samples = {
        (name, tuple(sorted(labels.items()))): value
        for name, labels, value in parsed.samples
    }
    assert (
        samples[
            (
                "rt1_alert_firing",
                (
                    ("alert", "HighG"),
                    ("replica_id", "0"),
                    ("severity", "page"),
                ),
            )
        ]
        == 1.0
    )
    assert samples[("rt1_alert_fired_total", ())] == 1.0


def test_alert_duplicate_rule_names_rejected():
    db = TSDB(clock=FakeClock())
    with pytest.raises(ValueError):
        AlertManager(db, [_rule(), _rule()])
    with pytest.raises(ValueError):
        AlertRule("bad", lambda tsdb, now: [], severity="sev1")
    with pytest.raises(ValueError):
        AlertRule("bad", lambda tsdb, now: [], for_duration_s=-1.0)


def test_default_ruleset_names_are_the_ops_contract():
    names = {r.name for r in default_ruleset()}
    assert {
        "SLOBurnRateFast",
        "SLOBurnRateSlow",
        "ReplicaDown",
        "CanarySLOBreach",
        "CompileCountDrift",
        "FeederStall",
        "AutoscalerFlapping",
        "CacheRebuildStorm",
        "CaptureDiskPressure",
    } <= names


def test_default_ruleset_is_quiet_on_empty_tsdb():
    clock = FakeClock()
    db = TSDB(clock=clock)
    mgr = AlertManager(db, default_ruleset(), clock=clock)
    assert mgr.evaluate() == []
    assert mgr.active() == []
    assert mgr.counters()["rule_errors_total"] == 0


def test_slo_burn_alerts_from_counter_deltas():
    """Multi-window multi-burn-rate over scraped counters: only an error
    rate above threshold x budget in BOTH windows pages."""
    clock = FakeClock()
    db = TSDB(clock=clock)
    mgr = AlertManager(db, default_ruleset(), clock=clock)
    total, ok = 0, 0
    # 10 minutes of clean traffic, then 60s of 50% failures.
    for _ in range(600):
        total, ok = total + 1, ok + 1
        db.append("rt1_serve_slo_requests_total", total, t=clock.t)
        db.append("rt1_serve_slo_requests_ok", ok, t=clock.advance(1.0))
    assert mgr.evaluate() == []
    for i in range(60):
        total += 1
        ok += i % 2
        db.append("rt1_serve_slo_requests_total", total, t=clock.t)
        db.append("rt1_serve_slo_requests_ok", ok, t=clock.advance(1.0))
    fired = {e["alert"] for e in mgr.evaluate() if e["event"] == "firing"}
    assert "SLOBurnRateFast" in fired  # 50% errors >> 8x the 1% budget
    # Clean again: the 60s window clears first, the fast page resolves.
    for _ in range(300):
        total, ok = total + 1, ok + 1
        db.append("rt1_serve_slo_requests_total", total, t=clock.t)
        db.append("rt1_serve_slo_requests_ok", ok, t=clock.advance(1.0))
        mgr.evaluate()
    assert "SLOBurnRateFast" not in {a["alert"] for a in mgr.active()}


# -------------------------------------------------------------- collector


def test_collector_ingests_and_books_per_target():
    clock = FakeClock()
    db = TSDB(clock=clock)
    bodies = {
        "http://a/metrics": (
            "# TYPE rt1_serve_replica_up gauge\n"
            'rt1_serve_replica_up{replica_id="0"} 1\n'
        ),
        "http://b/deploy/status": json.dumps(
            {"phase": "idle", "rollbacks_total": 2, "canary": {"armed": True}}
        ),
    }
    coll = Collector(
        db,
        [
            Target("fleet", "http://a/metrics"),
            Target(
                "deploy",
                "http://b/deploy/status",
                kind="json",
                prefix="rt1_deploy_status",
            ),
        ],
        clock=clock,
        fetch_fn=lambda url, timeout_s: bodies[url],
    )
    ingested = coll.scrape_once()
    assert ingested == {"fleet": 1, "deploy": 2}  # strings are skipped
    # One shared timestamp across every family in the cycle.
    t_up = db.latest("rt1_serve_replica_up", {"replica_id": "0"})[0]
    assert db.latest("rt1_deploy_status_rollbacks_total")[0] == t_up
    assert db.latest("rt1_deploy_status_canary_armed")[1] == 1.0
    stats = coll.stats()["targets"]
    assert stats["fleet"]["up"] == 1.0
    assert stats["deploy"]["samples_ingested_total"] == 2.0


def test_collector_failed_target_is_counted_not_fatal():
    clock = FakeClock()
    db = TSDB(clock=clock)

    def fetch(url, timeout_s):
        if "dead" in url:
            raise OSError("connection refused")
        return "# TYPE g gauge\ng 1\n"

    coll = Collector(
        db,
        [Target("live", "http://live/metrics"),
         Target("dead", "http://dead/metrics")],
        clock=clock,
        fetch_fn=fetch,
    )
    ingested = coll.scrape_once()
    assert ingested == {"live": 1, "dead": -1}
    stats = coll.stats()["targets"]
    assert stats["dead"]["up"] == 0.0
    assert stats["dead"]["scrape_errors_total"] == 1.0
    assert stats["live"]["up"] == 1.0
    # The live target's samples landed despite the dead sibling.
    assert db.latest("g")[1] == 1.0
    parsed = parse_exposition(coll.prometheus_text())
    samples = {
        (name, tuple(sorted(labels.items()))): value
        for name, labels, value in parsed.samples
    }
    assert samples[("rt1_obs_collector_up", (("target", "dead"),))] == 0.0
    assert samples[("rt1_obs_collector_cycles_total", ())] == 1.0


def test_collector_scrape_cadence_is_alert_cadence():
    clock = FakeClock()
    db = TSDB(clock=clock)
    mgr = AlertManager(db, default_ruleset(), clock=clock)
    coll = Collector(
        db,
        [Target("fleet", "http://a/metrics")],
        clock=clock,
        fetch_fn=lambda url, timeout_s: (
            "# TYPE rt1_serve_replica_up gauge\n"
            'rt1_serve_replica_up{replica_id="1"} 0\n'
        ),
        alert_manager=mgr,
    )
    coll.scrape_once()
    active = {a["alert"]: a for a in mgr.active()}
    assert active["ReplicaDown"]["state"] == "firing"
    assert active["ReplicaDown"]["labels"]["replica_id"] == "1"


def test_collector_rejects_bad_config():
    db = TSDB(clock=FakeClock())
    with pytest.raises(ValueError):
        Collector(db, [Target("a", "u"), Target("a", "u2")])
    with pytest.raises(ValueError):
        Collector(db, [Target("a", "u")], interval_s=0.0)
    with pytest.raises(ValueError):
        Target("a", "u", kind="xml")


def test_flatten_json_nested_bools_and_skips():
    samples = flatten_json(
        {
            "a": {"b": 1, "c": True},
            "d": 2.5,
            "skip_str": "READY",
            "skip_list": [1, 2],
        },
        "p",
    )
    assert sorted(samples) == [
        ("p_a_b", None, 1.0),
        ("p_a_c", None, 1.0),
        ("p_d", None, 2.5),
    ]


# ------------------------------------------- stub-fleet integration


def test_collector_over_stub_fleet_with_capture_mimicry():
    """ISSUE 18 satellite: the whole plane against an in-process stub
    fleet — capture-mimicking stub gauges ride the fan-out as
    rt1_serve_replica_capture_* families, the collector ingests the ONE
    aggregated scrape, ReplicaDown fires when a replica goes dark and
    resolves when it comes back. Zero jax, zero subprocesses."""
    import threading

    from rt1_tpu.serve.router import READY, Replica, Router
    from rt1_tpu.serve.stub import StubReplicaApp, make_stub_server

    router = Router(replica_timeout_s=5.0)
    servers = []
    try:
        for rid in range(2):
            app = StubReplicaApp(replica_id=rid, mimic_capture=True)
            httpd = make_stub_server(app)
            threading.Thread(
                target=httpd.serve_forever, daemon=True
            ).start()
            host, port = httpd.server_address[:2]
            replica = router.add_replica(
                Replica(rid, url=f"http://{host}:{port}")
            )
            replica.state = READY
            servers.append(httpd)

        router.route_act({"session_id": "s0", "image_b64": "AAAA"})

        clock = FakeClock()
        db = TSDB(clock=clock)
        mgr = AlertManager(db, default_ruleset(), clock=clock)
        coll = Collector(
            db,
            [Target("fleet", "ignored://the-fetch-is-in-process")],
            clock=clock,
            fetch_fn=lambda url, t: router.fleet_metrics_prometheus(),
            alert_manager=mgr,
        )
        assert coll.scrape_once()["fleet"] > 50
        # The stub's capture mimicry landed as per-replica history.
        for rid in ("0", "1"):
            assert db.latest(
                "rt1_serve_replica_capture_write_errors_total",
                {"replica_id": rid},
            ) is not None
            assert db.latest(
                "rt1_serve_replica_capture_enabled", {"replica_id": rid}
            )[1] == 1.0
        assert mgr.active() == []  # healthy fleet, quiet ruleset

        # Replica 1 goes dark: the fan-out probe books up=0, the next
        # scrape cycle fires ReplicaDown for exactly that instance.
        servers[1].shutdown()
        servers[1].server_close()
        clock.advance(2.0)
        coll.scrape_once()
        active = {
            (a["alert"], a["labels"].get("replica_id")): a["state"]
            for a in mgr.active()
        }
        assert active == {("ReplicaDown", "1"): "firing"}

        # The fleet heals (respawn into the same slot, supervisor-style):
        # a fresh up=1 sample overrides and the alert resolves.
        router.remove_replica(1)
        app = StubReplicaApp(replica_id=1, mimic_capture=True)
        httpd = make_stub_server(app)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        servers.append(httpd)
        host, port = httpd.server_address[:2]
        replica = router.add_replica(
            Replica(1, url=f"http://{host}:{port}")
        )
        replica.state = READY
        clock.advance(2.0)
        coll.scrape_once()
        assert [e["event"] for e in mgr.history()] == [
            "firing",
            "resolved",
        ]
        assert mgr.active() == []
    finally:
        for httpd in servers:
            try:
                httpd.shutdown()
                httpd.server_close()
            except OSError:
                pass
