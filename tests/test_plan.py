"""The declarative sharding plan (rt1_tpu/parallel/plan.py) + true mixed
precision (trainer/train.py mixed_precision).

Pins the PR's contracts:

* plan coverage — every weight matrix of the flagship, tiny, and MoE
  configs matches an explicit rule (no silent-replication fallthrough);
  strict mode raises, default warns loudly.
* auto mesh-shape selection by device count (SNIPPETS.md [1] ladder).
* config-only equivalence on a forced multi-device host mesh: dense vs
  fsdp vs tp vs pp train-step losses/updates agree within tolerance
  (conftest forces 8 virtual CPU devices; these tests carve the 4-device
  meshes the acceptance criteria name from that pool — same GSPMD
  partitioner and collective lowering either way).
* the f32 (non-mixed) path is bit-identical to the pre-plan step built
  from the PR-6 hand-written rule list.
* mixed precision keeps f32 masters + optimizer state while computing
  fwd/bwd on a bf16 cast, donation-safe, loss within tolerance of f32.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from rt1_tpu.parallel import (
    MeshConfig,
    PlanCoverageError,
    ShardingPlan,
    auto_mesh_shape,
    make_mesh,
    mixed_precision_from_config,
)
from rt1_tpu.trainer import create_train_state, make_optimizer, make_train_step_fns

sys.path.insert(0, "tests")
from test_rt1 import make_batch, tiny_policy  # noqa: E402


# --------------------------------------------------------------- coverage


def _param_shapes(model_config):
    """Abstract param tree for a config — eval_shape, so even the flagship
    B3 tokenizer enumerates in milliseconds (param shapes are spatial-dim
    independent, so small images suffice)."""
    from rt1_tpu.specs import language_table_action_space, sample_space
    from rt1_tpu.train.train import build_model

    model = build_model(model_config)
    rng = jax.random.PRNGKey(0)
    t = model_config.time_sequence_length
    obs = {
        "image": jnp.zeros((1, t, 64, 64, 3), jnp.float32),
        "natural_language_embedding": jnp.zeros((1, t, 512), jnp.float32),
    }
    actions = sample_space(
        language_table_action_space(), jax.random.fold_in(rng, 1), (1, t)
    )
    variables = jax.eval_shape(
        lambda r: model.init(
            {"params": r, "crop": r}, obs, actions, train=False
        ),
        rng,
    )
    return variables["params"]


def _flagship_model_config(**overrides):
    from rt1_tpu.train.configs import language_table

    mc = language_table.get_config().model
    for k, v in overrides.items():
        setattr(mc, k, v)
    return mc


def _tiny_model_config(**overrides):
    from rt1_tpu.train.configs import tiny

    mc = tiny.get_config().model
    for k, v in overrides.items():
        setattr(mc, k, v)
    return mc


@pytest.mark.parametrize(
    "name,mc_fn",
    [
        ("tiny", _tiny_model_config),
        ("tiny_moe", lambda: _tiny_model_config(ffn_impl="moe")),
        ("flagship", _flagship_model_config),
        ("flagship_moe", lambda: _flagship_model_config(ffn_impl="moe")),
        (
            "effnet_small",
            lambda: _tiny_model_config(image_tokenizer="efficientnet_small"),
        ),
    ],
)
def test_plan_covers_every_weight_matrix(name, mc_fn):
    """Satellite 1: flagship, tiny, and MoE configs match a non-default
    rule for every weight matrix — nothing falls through to P()."""
    params = _param_shapes(mc_fn())
    plan = ShardingPlan(mesh=make_mesh(MeshConfig()))
    assert plan.coverage(params) == [], (
        f"{name}: weight matrices with no plan rule"
    )


def test_plan_coverage_warns_and_strict_raises(caplog):
    import logging

    mesh = make_mesh(MeshConfig())
    tree = {
        "mystery_module": {"w": jnp.zeros((4, 4))},
        "small": jnp.zeros((4,)),  # rank<2: free to fall through
    }
    plan = ShardingPlan(mesh=mesh)
    assert plan.coverage(tree) == ["mystery_module/w"]
    with caplog.at_level(logging.WARNING, logger="rt1_tpu.parallel.plan"):
        plan.check_coverage(tree)
    assert any("mystery_module/w" in r.message for r in caplog.records)

    strict = ShardingPlan(mesh=mesh, strict=True)
    with pytest.raises(PlanCoverageError, match="mystery_module/w"):
        strict.check_coverage(tree)
    # A fully covered tree passes strict mode (params of the tiny policy).
    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=2)
    variables = model.init(
        {"params": rng, "crop": rng}, obs, actions, train=False
    )
    assert strict.check_coverage(variables["params"]) == []


def test_opt_state_masters_follow_param_shardings():
    """Adam mu/nu mirror the param tree under the same rules (the paths
    repeat inside opt_state), so FSDP shards the f32 masters too."""
    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=2)
    state = create_train_state(model, rng, (obs, actions), make_optimizer())
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2))
    plan = ShardingPlan(mesh=mesh)
    sh = plan.tree_shardings(state)
    qk = sh.params["transformer"]["layer_0"]["attn"]["query"]["kernel"]
    assert qk.spec == P("fsdp", "model")
    mu = sh.opt_state[0].mu["transformer"]["layer_0"]["attn"]["query"]["kernel"]
    assert mu.spec == P("fsdp", "model")


# --------------------------------------------------------------- resolution


def test_auto_mesh_shape_ladder():
    assert auto_mesh_shape(1) == (1, 1, 1)
    assert auto_mesh_shape(2) == (2, 1, 1)
    assert auto_mesh_shape(4) == (2, 2, 1)
    assert auto_mesh_shape(8) == (2, 2, 2)
    assert auto_mesh_shape(16) == (1, 4, 4)
    assert auto_mesh_shape(32) == (4, 4, 2)
    assert auto_mesh_shape(64) == (8, 4, 2)
    assert auto_mesh_shape(96) == (1, 96, 1)  # fallback: pure fsdp


def test_auto_mesh_shapes_products_equal_their_keys():
    """Satellite (ISSUE 14): every table row must cover its device count
    exactly — a row whose product drifts from its key would make `auto`
    silently build a mesh over the wrong device subset (the pre-table
    failure mode was the `(1, n, 1)` fallback flattening pods to pure
    fsdp)."""
    from rt1_tpu.parallel import AUTO_MESH_SHAPES

    for n, (dp, fsdp, tp) in AUTO_MESH_SHAPES.items():
        assert dp * fsdp * tp == n, (
            f"AUTO_MESH_SHAPES[{n}] = {(dp, fsdp, tp)} has product "
            f"{dp * fsdp * tp}"
        )


def test_auto_mesh_shape_host_contiguous_rebalance():
    """Multi-host rows keep fsdp×tp at or below one host's devices (fsdp
    all-gathers stay on intra-host ICI) by moving factors of 2 from fsdp
    to dp — the product is preserved and a single-host call is
    untouched."""
    for n in (16, 32, 64):
        for local in (2, 4, 8):
            dp, fsdp, tp = auto_mesh_shape(n, local)
            assert dp * fsdp * tp == n
            # tp is never rebalanced; fsdp shrinks until the model axes
            # fit in one host (or fsdp is exhausted).
            assert fsdp * tp <= max(local, tp)
    assert auto_mesh_shape(16, 8) == (2, 2, 4)
    assert auto_mesh_shape(32, 8) == (4, 4, 2)  # already host-contiguous
    assert auto_mesh_shape(64, 4) == (16, 2, 2)
    # local >= global (single host): the table row verbatim.
    assert auto_mesh_shape(16, 16) == (1, 4, 4)
    assert auto_mesh_shape(16, None) == (1, 4, 4)


def test_plan_from_config_parallel_block():
    cfg = {"parallel": {"dp": 2, "fsdp": 2, "tp": 2, "pp": 1, "sp": 1}}
    plan = ShardingPlan.from_config(cfg)
    assert plan.mesh.shape == {
        "data": 2, "stage": 1, "fsdp": 2, "seq": 1, "model": 2
    }
    assert plan.data_parallel_size == 4  # batch shards over dp x fsdp
    assert not plan.strict


def test_plan_from_config_auto():
    plan = ShardingPlan.from_config({"parallel": {"auto": True}})
    assert plan.mesh.shape == {
        "data": 2, "stage": 1, "fsdp": 2, "seq": 1, "model": 2
    }


def test_plan_from_config_auto_composes_with_pp():
    """auto splits only the devices left after pp/sp take theirs — auto+pp
    on 8 devices used to resolve a 16-device mesh and raise at startup."""
    plan = ShardingPlan.from_config({"parallel": {"auto": True, "pp": 2}})
    assert plan.mesh.shape == {
        "data": 2, "stage": 2, "fsdp": 2, "seq": 1, "model": 1
    }


def test_serving_plan_honors_auto_and_backend_fallback(monkeypatch):
    """serving_plan resolves `auto` against the serve host's own device
    count (data axis collapsed — sessions are slots, not shards) instead of
    silently serving dense, and returns None (plain placement) when jax has
    no initialized backend — the documented fallback."""
    from rt1_tpu.eval import restore as R

    plan = R.serving_plan({"parallel": {"auto": True}})
    # 8 forced host devices -> ladder (2, 2, 2); dp collapses to 1.
    assert plan.mesh.shape == {
        "data": 1, "stage": 1, "fsdp": 2, "seq": 1, "model": 2
    }

    def _no_backend(*a, **k):
        raise RuntimeError("Backend 'cpu' failed to initialize")

    monkeypatch.setattr(jax, "local_devices", _no_backend)
    assert R.serving_plan({"parallel": {"auto": True}}) is None


def test_indivisible_dims_fall_back_to_replication():
    """EfficientNet SE bottleneck kernels have cout as small as 6/10 —
    dims the fsdp axis cannot divide. The placement guard replicates
    exactly those dims instead of crashing device_put, so fsdp stays a
    config-only switch on every model size (review-pinned: (1,1,40,10)
    under P(None,None,None,'fsdp') on an fsdp=4 mesh used to raise)."""
    mesh = make_mesh(MeshConfig(data=2, fsdp=4))
    plan = ShardingPlan(mesh=mesh)
    tree = {
        "se": {"fc1": {"kernel": jnp.zeros((1, 1, 40, 10))}},
        "projection_add": {"kernel": jnp.zeros((512, 8))},
    }
    sh = plan.tree_shardings(tree)
    # cout=10 % 4 != 0 -> that dim replicates; the rule still applies
    # where it divides (512 % 4 == 0).
    assert sh["se"]["fc1"]["kernel"].spec == P()
    assert sh["projection_add"]["kernel"].spec == P("fsdp", None)
    placed = plan.place_variables(tree, check=False)  # used to ValueError
    assert placed["se"]["fc1"]["kernel"].shape == (1, 1, 40, 10)
    # Every flagship B3 leaf resolves to a spec its shape can satisfy.
    params = _param_shapes(_flagship_model_config())
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    shardings = jax.tree_util.tree_leaves(plan.tree_shardings(params))
    assert len(leaves) == len(shardings)
    for (path, leaf), sh in zip(leaves, shardings):
        for dim, entry in zip(leaf.shape, tuple(sh.spec)):
            if entry is None:
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            ways = 1
            for a in axes:
                ways *= mesh.shape[a]
            assert dim % ways == 0, (path, leaf.shape, sh.spec)


def test_trainer_check_coverage_gate(caplog):
    """check_coverage=False suppresses the RT-1-plan coverage warning
    (train.py passes it for family != 'rt1', whose param paths the default
    plan does not describe); the default stays loud."""
    import logging

    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=2)
    state = create_train_state(model, rng, (obs, actions), make_optimizer())
    state = state.replace(params={"mystery_module": {"w": jnp.zeros((4, 4))}})
    mesh = make_mesh(MeshConfig())

    def dummy_loss(params, batch_stats, batch, rng, train):
        return jnp.float32(0.0), {}

    with caplog.at_level(logging.WARNING, logger="rt1_tpu.parallel.plan"):
        make_train_step_fns(
            model, mesh, state, loss_fn=dummy_loss, check_coverage=False
        )
    assert not any("mystery_module" in r.message for r in caplog.records)
    with caplog.at_level(logging.WARNING, logger="rt1_tpu.parallel.plan"):
        make_train_step_fns(model, mesh, state, loss_fn=dummy_loss)
    assert any("mystery_module" in r.message for r in caplog.records)


def test_plan_from_config_legacy_mesh_fallback():
    """Configs that predate config.parallel (pinned proof configs) resolve
    through their old mesh block: data->dp, model->tp, seq->sp, stage->pp."""
    cfg = {"mesh": {"data": -1, "model": 2, "seq": 1, "stage": 1}}
    plan = ShardingPlan.from_config(cfg)
    assert plan.mesh.shape == {
        "data": 4, "stage": 1, "fsdp": 1, "seq": 1, "model": 2
    }
    # No block at all -> pure DP over every device.
    plan = ShardingPlan.from_config(None)
    assert plan.mesh.shape["data"] == len(jax.devices())


def test_mixed_precision_from_config():
    assert not mixed_precision_from_config(None)
    assert not mixed_precision_from_config({"parallel": {"dp": -1}})
    assert mixed_precision_from_config(
        {"parallel": {"mixed_precision": True}}
    )


def test_write_hparams_emits_parallel_block():
    """Satellite 6: the config.parallel block lands in the TB hparams table
    as dotted keys (the PR 5 flatten fix covers nested blocks)."""
    from rt1_tpu.train.configs import tiny
    from rt1_tpu.trainer.metrics import flatten_hparams

    flat = flatten_hparams(dict(tiny.get_config().to_dict()))
    for key in (
        "parallel.dp", "parallel.fsdp", "parallel.tp", "parallel.pp",
        "parallel.sp", "parallel.auto", "parallel.strict",
        "parallel.mixed_precision",
    ):
        assert key in flat, key


# --------------------------------------------------- config-only equivalence


def _train_once(model, mesh, state, batch, **kw):
    fns = make_train_step_fns(model, mesh, state, donate=False, **kw)
    s = fns.shard_state(state)
    b = fns.shard_batch(batch)
    new_state, metrics = fns.train_step(s, b, jax.random.PRNGKey(5))
    return float(metrics["loss"]), new_state


def test_dense_fsdp_tp_pp_equivalence_on_4_devices():
    """The acceptance gate: dense / fsdp / tp / pp are config-only switches
    whose train-step losses and updates agree within tolerance on a
    4-device host mesh. SGD, not Adam: the first Adam step is ~sign(g),
    which amplifies benign 1e-12 float reassociation between layouts into
    visible param deltas wherever g ~ 0 (same reasoning as
    test_pp_train_step_equals_dense)."""
    dev4 = jax.devices()[:4]
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=8)
    batch = (obs, actions)
    tx = optax.sgd(1e-2)

    meshes = {
        "dense": make_mesh(MeshConfig(data=4), devices=dev4),
        "fsdp": make_mesh(MeshConfig(data=1, fsdp=4), devices=dev4),
        "dp_fsdp": make_mesh(MeshConfig(data=2, fsdp=2), devices=dev4),
        "tp": make_mesh(MeshConfig(data=2, model=2), devices=dev4),
        "pp": make_mesh(MeshConfig(data=2, stage=2), devices=dev4),
    }
    results = {}
    for name, mesh in meshes.items():
        if name == "pp":
            model = tiny_policy(mesh=mesh, pipeline_microbatches=2)
        else:
            model = tiny_policy()
        state = create_train_state(model, rng, batch, tx)
        results[name] = _train_once(model, mesh, state, batch)

    ref_loss, ref_state = results["dense"]
    for name, (loss, new_state) in results.items():
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-5, err_msg=name)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
                err_msg=name,
            ),
            new_state.params,
            ref_state.params,
        )


# ------------------------------------------------------------- bit identity


# The PR-6 rule list, verbatim — the pre-plan layout the f32 path must
# reproduce bit-for-bit (specs named only the 'model' axis; everything
# else fell through to replication).
_PR6_RULES = [
    (r"transformer/layer_\d+/attn/(query|key|value)/kernel$", P(None, "model")),
    (r"transformer/layer_\d+/attn/(query|key|value)/bias$", P("model")),
    (r"transformer/layer_\d+/attn/out/kernel$", P("model", None)),
    (r"transformer/layer_\d+/ff/kernel$", P(None, "model")),
    (r"transformer/layer_\d+/ff/bias$", P("model")),
    (r"transformer/output_tokens/kernel$", P(None, "model")),
    (r"transformer/output_tokens/bias$", P("model")),
    (r"moe/(wi|wo)$", P("model", None, None)),
]


@pytest.mark.parametrize(
    "mesh_cfg,bitwise",
    [
        # Pure DP (the reference-parity configuration, and what every
        # existing run used): not a single f32 bit may move.
        (MeshConfig(), True),
        # dp x tp: the plan now shards the embeddings/head rows the old
        # rules replicated — an intentional layout extension, so the
        # program differs by collective schedule; reassociation-level
        # agreement is the contract.
        (MeshConfig(data=2, model=4), False),
    ],
)
def test_f32_path_bit_identical_to_pre_plan_rules(mesh_cfg, bitwise):
    """The plan refactor must not change f32 numerics: the default-plan
    step vs the step built from the PR-6 hand-written rule list."""
    mesh = make_mesh(mesh_cfg)
    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=8)
    batch = (obs, actions)
    state = create_train_state(model, rng, batch, make_optimizer())

    loss_new, state_new = _train_once(model, mesh, state, batch)
    loss_old, state_old = _train_once(
        model, mesh, state, batch, param_rules=_PR6_RULES,
        batch_axes=("data",),
    )
    if bitwise:
        assert loss_new == loss_old  # bitwise, not allclose
        assert_leaf = lambda a, b: np.testing.assert_array_equal(  # noqa: E731
            np.asarray(a), np.asarray(b)
        )
    else:
        np.testing.assert_allclose(loss_new, loss_old, rtol=1e-6)
        # atol covers Adam's first-step ~sign(g): reassociation between
        # collective schedules lands as O(1e-8) deltas on the ±lr elements
        # wherever g ~ 0 (same amplification test_pp_train_step_equals_
        # dense documents).
        assert_leaf = lambda a, b: np.testing.assert_allclose(  # noqa: E731
            np.asarray(a), np.asarray(b), rtol=2e-6, atol=2e-8
        )
    jax.tree.map(assert_leaf, state_new.params, state_old.params)


def test_mixed_precision_off_is_default_program():
    """mixed_precision=False is a Python-level gate: the step it builds is
    the exact default program (guard/health discipline from PR 4/5)."""
    mesh = make_mesh(MeshConfig())
    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=8)
    batch = (obs, actions)
    state = create_train_state(model, rng, batch, make_optimizer())
    loss_off, state_off = _train_once(
        model, mesh, state, batch, mixed_precision=False
    )
    loss_plain, state_plain = _train_once(model, mesh, state, batch)
    assert loss_off == loss_plain
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        state_off.params,
        state_plain.params,
    )


# ---------------------------------------------------------- mixed precision


def test_mixed_precision_masters_stay_f32_and_loss_tracks_f32():
    """True mixed precision: the state's params + Adam moments stay f32
    across a donated step while fwd/bwd runs on the bf16 cast; the loss
    stays within bf16 rounding of the f32 step's."""
    mesh = make_mesh(MeshConfig())
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=8)
    batch = (obs, actions)

    model_f32 = tiny_policy()
    model_bf16 = tiny_policy(dtype=jnp.bfloat16)
    state = create_train_state(model_f32, rng, batch, make_optimizer())

    fns = make_train_step_fns(
        model_bf16, mesh, state, mixed_precision=True
    )  # donate=True: the mp cast must be donation-safe
    assert fns.mixed_precision
    s = fns.shard_state(state)
    b = fns.shard_batch(batch)
    s, metrics = fns.train_step(s, b, jax.random.PRNGKey(5))
    s, metrics = fns.train_step(s, b, jax.random.PRNGKey(6))
    mp_loss = float(metrics["loss"])
    assert np.isfinite(mp_loss)
    for leaf in jax.tree_util.tree_leaves(s.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(s.opt_state):
        assert leaf.dtype in (jnp.float32, jnp.int32), leaf.dtype
    assert int(s.step) == 2

    # f32 reference on the same masters/batch/rng draw.
    loss_f32_0, state_f32 = _train_once(
        model_f32, mesh,
        create_train_state(model_f32, rng, batch, make_optimizer()),
        batch,
    )
    # Step-2 f32 loss (post one update) is the comparable scalar.
    fns32 = make_train_step_fns(model_f32, mesh, state_f32, donate=False)
    _, m32 = fns32.train_step(
        fns32.shard_state(state_f32), fns32.shard_batch(batch),
        jax.random.PRNGKey(6),
    )
    np.testing.assert_allclose(mp_loss, float(m32["loss"]), rtol=0.05)


def test_mixed_precision_casts_compute_not_masters():
    """The cast helper: f32 leaves -> bf16, everything else untouched."""
    from rt1_tpu.trainer.train import _bf16_compute_copy

    tree = {
        "w": jnp.ones((2, 2), jnp.float32),
        "i": jnp.ones((2,), jnp.int32),
        "h": jnp.ones((2,), jnp.bfloat16),
    }
    out = _bf16_compute_copy(tree)
    assert out["w"].dtype == jnp.bfloat16
    assert out["i"].dtype == jnp.int32
    assert out["h"].dtype == jnp.bfloat16
    assert tree["w"].dtype == jnp.float32  # masters untouched
