"""Packed mmap frame cache: pack round-trip, gather parity, crop distribution.

The contract (rt1_tpu/data/pack.py): packing is decode-once + resize-once to
augmentation-headroom resolution; a training window gathered from the cache
must (a) reproduce the packed bytes exactly (mmap slice, no resampling),
(b) draw its random crops from the *identical* distribution as the tf.data
path (`pipeline._crop_box` in source coordinates), and (c) — when the train
geometry aligns packed with source pixels — match `WindowedEpisodeDataset`
byte-for-byte under the same rng.
"""

import os

import numpy as np
import pytest

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.data import pack as pack_lib
from rt1_tpu.data.pipeline import WindowedEpisodeDataset, _crop_box, crop_resize_frames

SRC_H, SRC_W = 24, 40


def _make_corpus(tmp_path, n=3, steps=8):
    rng = np.random.default_rng(0)
    paths = []
    for i in range(n):
        p = str(tmp_path / f"episode_{i}.npz")
        ep = ep_lib.generate_synthetic_episode(
            rng, num_steps=steps, height=SRC_H, width=SRC_W
        )
        ep["instruction_text"] = ep_lib.encode_instruction_text(f"move block {i}")
        ep_lib.save_episode(p, ep)
        paths.append(p)
    return paths


# ---------------------------------------------------------------- geometry


def test_packed_dims_span_exact_crop():
    """A crop_factor source crop spans exactly (h, w) packed pixels."""
    for (sh, sw, h, w, cf) in [
        (180, 320, 256, 456, 0.95),
        (24, 40, 32, 56, 0.95),
        (24, 40, 22, 38, 0.95),
        (180, 320, 128, 224, 0.9),
    ]:
        ph, pw = pack_lib.packed_dims(sh, sw, h, w, cf)
        assert ph >= h and pw >= w
        # Every drawn box maps to an in-bounds (h, w) slice.
        rng = np.random.default_rng(1)
        for _ in range(50):
            box = _crop_box(sh, sw, cf, rng)
            top, left = pack_lib.map_box_to_packed(box, sh, sw, ph, pw, h, w)
            assert 0 <= top <= ph - h and 0 <= left <= pw - w


def test_packed_dims_crop_none_is_train_size():
    assert pack_lib.packed_dims(180, 320, 64, 96, None) == (64, 96)


# ---------------------------------------------------------------- packer


def test_pack_roundtrip_gather_equals_decoded_source(tmp_path):
    """pack -> gather == resize-once(decoded source), byte-exact.

    crop_factor None makes the gather the whole packed frame, so it must
    equal the packer's resize of the decoded source (computed independently
    here with the shared `crop_resize_frames` backend).
    """
    paths = _make_corpus(tmp_path)
    out = str(tmp_path / "packed")
    h, w = 16, 28
    pack_lib.pack_episodes(paths, out, h, w, None)
    cache = pack_lib.PackedEpisodeCache(out, window=4)
    for ep_i, path in enumerate(paths):
        src = ep_lib.load_episode(path)
        t = src["rgb"].shape[0]
        boxes = np.tile(np.array([[0, 0, SRC_H, SRC_W]], np.int32), (t, 1))
        want = crop_resize_frames(list(src["rgb"]), boxes, h, w)
        # Window at start=t-1 covers the last `window` real steps unpadded.
        got = cache.gather_frames(ep_i, t - 1, np.random.default_rng(0))
        np.testing.assert_array_equal(got, want[t - cache.window :])
        # Meta members survive the pack untouched.
        meta = cache.meta(ep_i)
        for k in ("action", "instruction", "is_first", "is_terminal"):
            np.testing.assert_array_equal(meta[k], src[k])


def test_pack_verbatim_when_geometry_aligns(tmp_path):
    """h=int(H0*cf), w=int(W0*cf) packs source frames byte-identical."""
    paths = _make_corpus(tmp_path, n=1)
    out = str(tmp_path / "packed")
    h, w = int(SRC_H * 0.95), int(SRC_W * 0.95)
    manifest = pack_lib.pack_episodes(paths, out, h, w, 0.95)
    assert manifest["packed"] == {"height": SRC_H, "width": SRC_W}
    src = ep_lib.load_episode(paths[0])
    frames = np.fromfile(
        os.path.join(out, pack_lib.FRAMES_NAME), np.uint8
    ).reshape(src["rgb"].shape)
    np.testing.assert_array_equal(frames, src["rgb"])


def test_pack_freshness_and_staleness(tmp_path):
    paths = _make_corpus(tmp_path)
    out = str(tmp_path / "packed")
    pack_lib.pack_episodes(paths, out, 16, 28, 0.95)
    assert pack_lib.pack_is_fresh(out, paths, 16, 28, 0.95)
    # Different geometry -> stale.
    assert not pack_lib.pack_is_fresh(out, paths, 16, 28, 0.9)
    assert not pack_lib.pack_is_fresh(out, paths, 18, 28, 0.95)
    # Different episode set -> stale.
    assert not pack_lib.pack_is_fresh(out, paths[:-1], 16, 28, 0.95)
    # Touched source -> stale; re-pack restores freshness.
    os.utime(paths[0], (0, 0))
    assert not pack_lib.pack_is_fresh(out, paths, 16, 28, 0.95)
    pack_lib.pack_episodes(paths, out, 16, 28, 0.95)
    assert pack_lib.pack_is_fresh(out, paths, 16, 28, 0.95)


def test_pack_rejects_mixed_resolutions(tmp_path):
    paths = _make_corpus(tmp_path, n=1)
    rng = np.random.default_rng(9)
    odd = str(tmp_path / "episode_9.npz")
    ep_lib.save_episode(
        odd, ep_lib.generate_synthetic_episode(rng, num_steps=4, height=12, width=20)
    )
    with pytest.raises(ValueError, match="corpus-wide"):
        pack_lib.pack_episodes(paths + [odd], str(tmp_path / "p"), 16, 28, 0.95)


# ---------------------------------------------------------------- parity


def test_crop_box_distribution_matches_tf_path(tmp_path):
    """`draw_box` IS `pipeline._crop_box` on source dims: same rng -> same
    boxes, bit for bit — the packed path cannot drift from the tf.data
    crop distribution."""
    paths = _make_corpus(tmp_path, n=1)
    out = str(tmp_path / "packed")
    pack_lib.pack_episodes(paths, out, 32, 56, 0.95)
    cache = pack_lib.PackedEpisodeCache(out, window=3)
    a, b = np.random.default_rng(42), np.random.default_rng(42)
    for _ in range(200):
        assert cache.draw_box(a) == _crop_box(SRC_H, SRC_W, 0.95, b)


def test_mapped_offsets_preserve_normalized_distribution(tmp_path):
    """Packed-coordinate offsets track the source offsets' normalized
    position to within one packed pixel (rounding), over the full range."""
    paths = _make_corpus(tmp_path, n=1)
    out = str(tmp_path / "packed")
    h, w = 32, 56
    pack_lib.pack_episodes(paths, out, h, w, 0.95)
    cache = pack_lib.PackedEpisodeCache(out, window=3)
    ph, pw = cache.packed_h, cache.packed_w
    ch0, cw0 = int(SRC_H * 0.95), int(SRC_W * 0.95)
    rng = np.random.default_rng(3)
    tops_src, tops_packed = [], []
    for _ in range(500):
        box = cache.draw_box(rng)
        top_p, left_p = pack_lib.map_box_to_packed(
            box, SRC_H, SRC_W, ph, pw, h, w
        )
        if SRC_H - ch0 > 0 and ph - h > 0:
            assert abs(top_p / (ph - h) - box[0] / (SRC_H - ch0)) <= 1.5 / (ph - h)
        tops_src.append(box[0])
        tops_packed.append(top_p)
    # Full range exercised on both sides (uniform draws, 500 samples).
    assert min(tops_packed) == 0 and max(tops_packed) == ph - h
    assert min(tops_src) == 0 and max(tops_src) == SRC_H - ch0


def test_window_matches_tf_path_exactly_when_aligned(tmp_path):
    """Aligned geometry: packed get_window == WindowedEpisodeDataset
    .get_window byte-for-byte under the same augmentation rng (same crop
    draws in source coordinates, verbatim packed pixels, identity resize)."""
    paths = _make_corpus(tmp_path)
    h, w = int(SRC_H * 0.95), int(SRC_W * 0.95)
    out = str(tmp_path / "packed")
    pack_lib.pack_episodes(paths, out, h, w, 0.95)
    window = 4
    cache = pack_lib.PackedEpisodeCache(out, window=window)
    ds = WindowedEpisodeDataset(
        paths, window=window, crop_factor=0.95, height=h, width=w
    )
    assert len(cache) == len(ds)
    for idx in range(0, len(ds), 3):
        a = cache.get_window(idx, np.random.default_rng(100 + idx))
        b = ds.get_window(idx, np.random.default_rng(100 + idx))
        np.testing.assert_array_equal(
            a["observations"]["image"], b["observations"]["image"]
        )
        np.testing.assert_array_equal(
            a["observations"]["natural_language_embedding"],
            b["observations"]["natural_language_embedding"],
        )
        np.testing.assert_array_equal(
            a["actions"]["terminate_episode"], b["actions"]["terminate_episode"]
        )
        np.testing.assert_array_equal(
            a["actions"]["action"], b["actions"]["action"]
        )


# ---------------------------------------------------------------- native


@pytest.fixture(scope="module")
def native_gather():
    from rt1_tpu.data import native

    if not native.packed_gather_available():
        pytest.skip(
            "native packed gather unavailable (build native/ with "
            "`make packed` or any g++ toolchain)"
        )
    return native


def test_native_gather_matches_python_fallback(tmp_path, native_gather, monkeypatch):
    paths = _make_corpus(tmp_path)
    out = str(tmp_path / "packed")
    pack_lib.pack_episodes(paths, out, 32, 56, 0.95)
    cache = pack_lib.PackedEpisodeCache(out, window=5)
    for idx in (0, 4, len(cache) - 1):
        ep_i, start = cache.index[idx]
        a = cache.gather_frames(ep_i, start, np.random.default_rng(idx))
        monkeypatch.setenv("RT1_TPU_NO_NATIVE", "1")
        b = cache.gather_frames(ep_i, start, np.random.default_rng(idx))
        monkeypatch.delenv("RT1_TPU_NO_NATIVE")
        np.testing.assert_array_equal(a, b)


def test_native_gather_resample_path(tmp_path, native_gather):
    """Boxes not at output size fall through to the bilinear resample and
    match the shared crop_resize backend to +/-1 LSB."""
    rng = np.random.default_rng(5)
    frames = rng.integers(0, 256, (3, 20, 30, 3), dtype=np.uint8)
    idx = np.array([2, 0, 1], np.int64)
    boxes = np.array([[1, 2, 16, 24]] * 3, np.int32)
    out = np.empty((3, 8, 12, 3), np.uint8)
    native_gather.packed_gather(frames, idx, boxes, out, threads=2)
    want = crop_resize_frames([frames[i] for i in idx], boxes, 8, 12)
    assert np.max(np.abs(out.astype(int) - want.astype(int))) <= 1


def test_native_gather_bounds_checks(native_gather):
    frames = np.zeros((2, 8, 8, 3), np.uint8)
    out = np.empty((1, 4, 4, 3), np.uint8)
    with pytest.raises(IndexError):
        native_gather.packed_gather(
            frames, np.array([2], np.int64), np.array([[0, 0, 4, 4]], np.int32), out
        )
    with pytest.raises(IndexError):
        native_gather.packed_gather(
            frames, np.array([0], np.int64), np.array([[6, 0, 4, 4]], np.int32), out
        )
