"""Sample-ahead feeder: determinism, tf.data-path parity, lifecycle.

The spec (rt1_tpu/data/feeder.py): the batch stream is a function of
(seed, epoch, batch-index) only — thread count and timing must not change a
single byte — finite epochs exhaust exactly, and close() stops promptly
from any state. Batch content parity with the existing loaders is pinned
against `WindowedEpisodeDataset.numpy_batches` (same windows, same padding,
same labels) and, with augmentation on, via the packed cache's crop-parity
guarantees (tests/test_packed_cache.py).
"""

import itertools

import numpy as np
import pytest

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.data import pack as pack_lib
from rt1_tpu.data.feeder import SampleAheadFeeder
from rt1_tpu.data.pipeline import WindowedEpisodeDataset

SRC_H, SRC_W = 24, 40
H, W = 16, 28
WINDOW = 3


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("feeder_corpus")
    rng = np.random.default_rng(0)
    paths = []
    for i in range(4):
        p = str(tmp / f"episode_{i}.npz")
        ep_lib.save_episode(
            p,
            ep_lib.generate_synthetic_episode(
                rng, num_steps=6, height=SRC_H, width=SRC_W
            ),
        )
        paths.append(p)
    return paths


def _cache(tmp_path_factory, paths, crop_factor):
    out = str(tmp_path_factory.mktemp("packed"))
    pack_lib.pack_episodes(paths, out, H, W, crop_factor)
    return pack_lib.PackedEpisodeCache(out, window=WINDOW)


@pytest.fixture(scope="module")
def cache(tmp_path_factory, corpus):
    return _cache(tmp_path_factory, corpus, 0.95)


@pytest.fixture(scope="module")
def cache_nocrop(tmp_path_factory, corpus):
    return _cache(tmp_path_factory, corpus, None)


def _batches_equal(a, b):
    np.testing.assert_array_equal(
        a["observations"]["image"], b["observations"]["image"]
    )
    np.testing.assert_array_equal(
        a["observations"]["natural_language_embedding"],
        b["observations"]["natural_language_embedding"],
    )
    np.testing.assert_array_equal(
        a["actions"]["terminate_episode"], b["actions"]["terminate_episode"]
    )
    np.testing.assert_array_equal(a["actions"]["action"], b["actions"]["action"])


def test_feeder_shapes_and_dtypes(cache):
    with SampleAheadFeeder(cache, 4, seed=0) as f:
        batch = next(f)
    img = batch["observations"]["image"]
    assert img.shape == (4, WINDOW, H, W, 3) and img.dtype == np.uint8
    assert batch["observations"]["natural_language_embedding"].shape == (4, WINDOW, 512)
    assert batch["actions"]["terminate_episode"].shape == (4, WINDOW)
    assert batch["actions"]["action"].shape == (4, WINDOW, 2)


def test_feeder_deterministic_across_thread_counts(cache):
    """1 thread == 3 threads, batch for batch — assembly parallelism is
    invisible in the stream."""
    streams = []
    for n_threads in (1, 3):
        with SampleAheadFeeder(
            cache, 4, seed=7, num_epochs=2, num_threads=n_threads
        ) as f:
            streams.append(list(f))
    assert len(streams[0]) == len(streams[1]) > 0
    for a, b in zip(*streams):
        _batches_equal(a, b)


def test_feeder_restart_reproduces_stream(cache):
    with SampleAheadFeeder(cache, 4, seed=3, num_epochs=1) as f:
        first = list(f)
    with SampleAheadFeeder(cache, 4, seed=3, num_epochs=1) as f:
        again = list(f)
    for a, b in zip(first, again):
        _batches_equal(a, b)


def test_feeder_seed_changes_stream(cache):
    with SampleAheadFeeder(cache, 4, seed=1, num_epochs=1) as f:
        a = next(f)
    with SampleAheadFeeder(cache, 4, seed=2, num_epochs=1) as f:
        b = next(f)
    assert not np.array_equal(
        a["observations"]["image"], b["observations"]["image"]
    )


def test_feeder_exhaustion_count(cache):
    n_windows = len(cache)
    batch = 4
    epochs = 3
    with SampleAheadFeeder(cache, batch, seed=0, num_epochs=epochs) as f:
        got = sum(1 for _ in f)
    assert got == (n_windows // batch) * epochs
    # Exhausted for good — StopIteration, not a hang.
    assert list(itertools.islice(f, 2)) == []


def test_feeder_close_midstream_and_joins(cache):
    f = SampleAheadFeeder(cache, 4, seed=0, num_threads=2, depth=1)
    next(f)
    f.close()
    assert list(itertools.islice(f, 2)) == []
    for t in f._threads:
        assert not t.is_alive()
    f.close()  # idempotent


def test_feeder_worker_error_surfaces_on_consumer(cache, monkeypatch):
    """A dying worker must raise on the train loop's thread, not strand it
    in an eternal queue wait."""
    boom = ValueError("frames.bin ate itself")

    def explode(*a, **k):
        raise boom

    monkeypatch.setattr(cache, "fill_batch", explode)
    f = SampleAheadFeeder(cache, 4, seed=0, num_threads=2)
    with pytest.raises(RuntimeError, match="feeder worker failed") as ei:
        next(f)
    assert ei.value.__cause__ is boom
    f.close()


def test_feeder_close_without_consuming(cache):
    """close() with full queues and nothing consumed must not deadlock."""
    f = SampleAheadFeeder(cache, 4, seed=0, num_threads=2, depth=1)
    import time

    time.sleep(0.2)  # let workers fill their queues
    f.close()
    for t in f._threads:
        assert not t.is_alive()


def test_feeder_process_sharding_partitions_windows(cache):
    """Two process shards see disjoint windows covering the full epoch."""
    seen = []
    for pi in (0, 1):
        with SampleAheadFeeder(
            cache, 2, seed=5, shuffle=False, num_epochs=1,
            process_index=pi, process_count=2,
        ) as f:
            n = sum(1 for _ in f)
        order = f._epoch_order(0)
        seen.append(set(order.tolist()))
        assert n == f.batches_per_epoch
    assert seen[0].isdisjoint(seen[1])
    assert seen[0] | seen[1] == set(range(len(cache)))


def test_feeder_rejects_oversized_batch(cache):
    with pytest.raises(ValueError, match="exceeds"):
        SampleAheadFeeder(cache, len(cache) + 1, start=False)


# ------------------------------------------- multi-host slices (ISSUE 14)


def test_feeder_host_slices_partition_single_host_stream(cache):
    """Satellite (ISSUE 14): for process_count ∈ {1, 2, 4} the per-host
    window streams are a permutation-free partition of the single-host
    stream — concatenating the hosts' blocks global-batch by global-batch
    reproduces the single-host order EXACTLY (not merely as a set), and
    the per-host orders are disjoint and jointly exhaustive over the
    batched prefix."""
    global_batch = 4
    ref = None
    for pc in (1, 2, 4):
        feeders = [
            SampleAheadFeeder(
                cache, global_batch // pc, seed=11, num_epochs=1,
                process_index=pi, process_count=pc, start=False,
            )
            for pi in range(pc)
        ]
        orders = [f.host_order(0) for f in feeders]
        for f in feeders:
            f.close()
        # Disjoint + exhaustive over the batched prefix.
        union = np.concatenate(orders)
        assert len(set(union.tolist())) == len(union) == len(cache)
        # Exact stream: interleave host blocks back into global batches.
        nb = len(orders[0]) * pc // global_batch
        merged = (
            np.stack(
                [o.reshape(nb, global_batch // pc) for o in orders], axis=1
            ).reshape(-1)
        )
        if ref is None:
            ref = merged
        np.testing.assert_array_equal(merged, ref)


def test_feeder_host_shards_concat_to_single_host_batch(cache):
    """Per-host BATCHES (pixels, crops, labels — everything) concatenate
    to the exact single-host batch: the layout
    `jax.make_array_from_process_local_data` lays out over a host-major
    mesh. Augmentation included — each host draws the GLOBAL batch's crop
    offsets from the shared rng and keeps its rows (pack.fill_batch's
    `offsets` seam)."""
    single = list(
        itertools.islice(
            SampleAheadFeeder(cache, 4, seed=11, num_epochs=1), 4
        )
    )
    shards = [
        list(
            itertools.islice(
                SampleAheadFeeder(
                    cache, 2, seed=11, num_epochs=1,
                    process_index=pi, process_count=2,
                ),
                4,
            )
        )
        for pi in range(2)
    ]
    for b, want in enumerate(single):
        got = _tree_concat(shards[0][b], shards[1][b])
        _batches_equal(got, want)


def _tree_concat(a, b):
    if isinstance(a, dict):
        return {k: _tree_concat(a[k], b[k]) for k in a}
    return np.concatenate([a, b])


def test_feeder_uniform_batch_count_across_hosts(tmp_path):
    """Every host sees the SAME per-epoch batch count even when the corpus
    is not process-divisible — a per-host strided split hands one host an
    extra batch, which on a real mesh deadlocks the epoch's last
    collective. 3 episodes × 6 steps = 18 windows, global batch 4: every
    host must see 4 batches, the 2-window tail dropped on all alike."""
    rng = np.random.default_rng(3)
    paths = []
    for i in range(3):
        p = str(tmp_path / f"episode_{i}.npz")
        ep_lib.save_episode(
            p,
            ep_lib.generate_synthetic_episode(
                rng, num_steps=6, height=SRC_H, width=SRC_W
            ),
        )
        paths.append(p)
    out = str(tmp_path / "packed")
    pack_lib.pack_episodes(paths, out, H, W, 0.95)
    c = pack_lib.PackedEpisodeCache(out, window=WINDOW)
    counts = []
    for pi in range(2):
        f = SampleAheadFeeder(
            c, 2, seed=0, num_epochs=1, process_index=pi, process_count=2,
            start=False,
        )
        counts.append(f.batches_per_epoch)
        f.close()
    assert counts == [4, 4]


def test_feeder_matches_numpy_loader_without_augmentation(corpus, cache_nocrop):
    """crop_factor None: the feeder's batches equal the existing numpy
    loader's byte-for-byte (same windows, same padding, same labels; images
    resized once by the same backend) — content parity with the tf.data
    family under a fixed (here: absent) augmentation draw."""
    ds = WindowedEpisodeDataset(
        corpus, window=WINDOW, crop_factor=None, height=H, width=W
    )
    want = list(
        itertools.islice(ds.numpy_batches(4, shuffle=False, num_epochs=1), 3)
    )
    with SampleAheadFeeder(
        cache_nocrop, 4, seed=0, shuffle=False, num_epochs=1
    ) as f:
        got = list(itertools.islice(f, 3))
    for a, b in zip(got, want):
        _batches_equal(a, b)


# ------------------------------------------------- task mixture (ISSUE 13)


@pytest.fixture(scope="module")
def tagged_cache(tmp_path_factory):
    """Packed corpus with per-episode task tags: 2x 'block2block' +
    2x 'corner' episodes, 6 steps each."""
    tmp = tmp_path_factory.mktemp("tagged_corpus")
    rng = np.random.default_rng(3)
    paths = []
    for i, task in enumerate(
        ("block2block", "block2block", "corner", "corner")
    ):
        ep = ep_lib.generate_synthetic_episode(
            rng, num_steps=6, height=SRC_H, width=SRC_W
        )
        ep["task"] = ep_lib.encode_instruction_text(task)
        p = str(tmp / f"episode_{i}.npz")
        ep_lib.save_episode(p, ep)
        paths.append(p)
    out = str(tmp_path_factory.mktemp("tagged_packed"))
    pack_lib.pack_episodes(paths, out, H, W, 0.95)
    return pack_lib.PackedEpisodeCache(out, window=WINDOW)


def test_parse_task_weights():
    from rt1_tpu.data.feeder import parse_task_weights

    assert parse_task_weights(None) is None
    assert parse_task_weights("") is None
    assert parse_task_weights("a:3,b:1") == {"a": 3.0, "b": 1.0}
    # Task slugs may contain ':' — the weight is after the LAST colon.
    assert parse_task_weights("unknown:mystery:2") == {
        "unknown:mystery": 2.0
    }
    assert parse_task_weights({"a": 1}) == {"a": 1.0}
    with pytest.raises(ValueError, match="not a number"):
        parse_task_weights("a:x")
    with pytest.raises(ValueError, match="no positive weight"):
        parse_task_weights("a:0,b:0")
    with pytest.raises(ValueError, match=">= 0"):
        parse_task_weights("a:-1")


def test_task_weights_none_is_pre_pr_stream(cache):
    """weights=None must be the EXACT pre-task order draw: the legacy
    (seed, epoch)-keyed permutation, bit-identical — and a feeder built
    with an explicit None matches one that never heard of the kwarg."""
    with SampleAheadFeeder(
        cache, 4, seed=11, num_epochs=1, task_weights=None
    ) as f:
        got = list(f)
    with SampleAheadFeeder(cache, 4, seed=11, num_epochs=1) as g:
        want = list(g)
    for a, b in zip(got, want):
        _batches_equal(a, b)
    assert "task_id" not in got[0]["observations"]
    # The order formula itself is the pinned pre-PR one.
    order = g._compute_order(0, len(cache))
    legacy = np.arange(len(cache))
    np.random.default_rng([11, 0]).shuffle(legacy)
    np.testing.assert_array_equal(order, legacy)


def test_task_weights_deterministic_across_threads(tagged_cache):
    """Same (seed, epoch, corpus, weights) -> byte-identical stream
    (images, labels, AND task ids) regardless of worker thread count."""
    streams = []
    for n_threads in (1, 3):
        with SampleAheadFeeder(
            tagged_cache, 4, seed=5, num_epochs=2, num_threads=n_threads,
            task_weights={"block2block": 3, "corner": 1},
            emit_task_ids=True,
        ) as f:
            streams.append(list(f))
    assert len(streams[0]) == len(streams[1]) > 0
    for a, b in zip(*streams):
        _batches_equal(a, b)
        np.testing.assert_array_equal(
            a["observations"]["task_id"], b["observations"]["task_id"]
        )


def test_task_weights_change_the_stream_key(tagged_cache):
    """Different weights -> a different (reproducible) order; the weights
    digest is folded into the shuffle key."""
    f1 = SampleAheadFeeder(
        tagged_cache, 4, seed=5, start=False,
        task_weights={"block2block": 3, "corner": 1},
    )
    f2 = SampleAheadFeeder(
        tagged_cache, 4, seed=5, start=False,
        task_weights={"block2block": 1, "corner": 3},
    )
    o1 = f1._compute_order(0, len(tagged_cache))
    o2 = f2._compute_order(0, len(tagged_cache))
    assert not np.array_equal(o1, o2)
    # Same weights -> same order (pure function, no feeder state).
    f3 = SampleAheadFeeder(
        tagged_cache, 4, seed=5, start=False,
        task_weights={"block2block": 3, "corner": 1},
    )
    np.testing.assert_array_equal(
        o1, f3._compute_order(0, len(tagged_cache))
    )


def test_task_weights_empirical_mixture_frequency(tagged_cache):
    """A 3:1 weighted mixture's empirical task frequencies land within
    tolerance of 0.75/0.25 over a few epochs (each task owns half the
    corpus windows, so the uniform draw would give 0.5/0.5)."""
    with SampleAheadFeeder(
        tagged_cache, 4, seed=9, num_epochs=4,
        task_weights={"block2block": 3, "corner": 1},
        emit_task_ids=True,
    ) as f:
        names = f.health_task_names
        counts = np.zeros(len(names), np.int64)
        for batch in f:
            tid = batch["observations"]["task_id"]
            assert tid.dtype == np.int32 and tid.shape == (4,)
            counts += np.bincount(tid, minlength=len(names))
    freq = counts / counts.sum()
    by_name = dict(zip(names, freq))
    assert names == ("block2block", "corner", "other")
    assert abs(by_name["block2block"] - 0.75) < 0.12
    assert abs(by_name["corner"] - 0.25) < 0.12
    assert by_name["other"] == 0.0


def test_task_weights_wildcard_and_unmatched(tagged_cache):
    """'*' weights every unnamed task; weights matching no corpus task
    raise loudly at order-draw time instead of feeding an empty epoch."""
    f = SampleAheadFeeder(
        tagged_cache, 4, seed=0, start=False,
        task_weights={"corner": 1, "*": 0.0},
    )
    order = f._compute_order(0, len(tagged_cache))
    # Only corner windows (episodes 2-3 -> windows 12..23) can be drawn.
    assert set(np.asarray(order) // 6) <= {2, 3}
    with pytest.raises(ValueError, match="zero total weight"):
        SampleAheadFeeder(
            tagged_cache, 4, seed=0, start=False,
            task_weights={"zebra": 1.0},
        )


def test_task_weights_require_shuffle(tagged_cache):
    with pytest.raises(ValueError, match="shuffle"):
        SampleAheadFeeder(
            tagged_cache, 4, seed=0, shuffle=False, start=False,
            task_weights={"corner": 1},
        )


def test_emit_task_ids_member_and_names(tagged_cache, cache):
    """emit_task_ids adds ONE (batch,) int32 member whose ids index the
    frozen health_task_names table (sorted unique tasks + 'other');
    untagged corpora map every window to 'unknown'."""
    with SampleAheadFeeder(
        tagged_cache, 4, seed=2, num_epochs=1, emit_task_ids=True
    ) as f:
        assert f.health_task_names == ("block2block", "corner", "other")
        batch = next(f)
        tid = batch["observations"]["task_id"]
        order = f._epoch_order(0)
        for j, idx in enumerate(order[:4]):
            task = tagged_cache.episode_task(
                tagged_cache.index[int(idx)][0]
            )
            assert f.health_task_names[tid[j]] == task
    # Untagged corpus: every episode reports the UNKNOWN_TASK slug.
    with SampleAheadFeeder(
        cache, 4, seed=2, num_epochs=1, emit_task_ids=True
    ) as g:
        assert g.health_task_names == ("unknown", "other")
        assert set(next(g)["observations"]["task_id"]) == {0}
    # Off (the default): no member, pre-PR batch structure.
    with SampleAheadFeeder(cache, 4, seed=2, num_epochs=1) as h:
        assert h.health_task_names == ()
        assert "task_id" not in next(h)["observations"]


def test_emit_task_ids_literal_other_task_no_duplicate(tmp_path_factory):
    """A corpus whose episodes are literally tagged 'other' must not
    produce a duplicate name in the frozen id table — the real task and
    the overflow bucket share the one 'other' entry."""
    tmp = tmp_path_factory.mktemp("other_corpus")
    rng = np.random.default_rng(4)
    paths = []
    for i, task in enumerate(("other", "corner")):
        ep = ep_lib.generate_synthetic_episode(
            rng, num_steps=6, height=SRC_H, width=SRC_W
        )
        ep["task"] = ep_lib.encode_instruction_text(task)
        p = str(tmp / f"episode_{i}.npz")
        ep_lib.save_episode(p, ep)
        paths.append(p)
    out = str(tmp_path_factory.mktemp("other_packed"))
    pack_lib.pack_episodes(paths, out, H, W, None)
    cache = pack_lib.PackedEpisodeCache(out, window=WINDOW)
    with SampleAheadFeeder(
        cache, 4, seed=0, num_epochs=1, emit_task_ids=True
    ) as f:
        names = f.health_task_names
        assert names == ("corner", "other")
        assert len(names) == len(set(names))
        batch = next(f)
        tid = batch["observations"]["task_id"]
        assert set(tid) <= set(range(len(names)))


def test_train_dataset_batches_packed_switch(tmp_path, corpus):
    """train.dataset_batches honors data.packed_cache: fresh cache feeds
    through the feeder; missing cache falls back to the tf.data path."""
    jax = pytest.importorskip("jax")
    del jax
    from rt1_tpu.train.configs import tiny
    from rt1_tpu.train.train import dataset_batches

    import os
    import shutil

    data_dir = str(tmp_path / "store")
    os.makedirs(os.path.join(data_dir, "train"))
    for p in corpus:
        shutil.copy(p, os.path.join(data_dir, "train", os.path.basename(p)))
    paths = sorted(
        os.path.join(data_dir, "train", f)
        for f in os.listdir(os.path.join(data_dir, "train"))
    )

    config = tiny.get_config()
    with config.unlocked():
        config.data.data_dir = data_dir
        config.data.packed_cache = True
        config.per_host_batch_size = 2
    # No pack built yet -> falls back (tf.data path still yields batches).
    it = dataset_batches(config, "train")
    assert not isinstance(it, SampleAheadFeeder)

    pack_lib.pack_episodes(
        paths,
        pack_lib.default_pack_dir(data_dir, "train"),
        config.data.height,
        config.data.width,
        config.data.crop_factor,
    )
    it = dataset_batches(config, "train")
    assert isinstance(it, SampleAheadFeeder)
    batch = next(it)
    assert batch["observations"]["image"].shape == (
        2,
        config.model.time_sequence_length,
        config.data.height,
        config.data.width,
        3,
    )
    # tiny config ships model_health off -> no task-id member, no
    # mixture: the pre-task stream byte-for-byte.
    assert "task_id" not in batch["observations"]
    assert it.task_weights is None and not it.emit_task_ids
    it.close()

    # With model_health on, the train feeder arms per-task telemetry and
    # honors config.data.task_weights ("task:weight,..." string).
    with config.unlocked():
        config.obs.model_health = True
        config.data.task_weights = "unknown:2"
    it = dataset_batches(config, "train")
    assert isinstance(it, SampleAheadFeeder)
    assert it.emit_task_ids
    # This corpus is untagged -> one real task ("unknown") + overflow.
    assert it.health_task_names == ("unknown", "other")
    assert it.task_weights == {"unknown": 2.0}
    batch = next(it)
    tid = batch["observations"]["task_id"]
    assert tid.shape == (2,) and tid.dtype == np.int32
    assert set(tid) == {0}
    it.close()
