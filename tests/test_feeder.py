"""Sample-ahead feeder: determinism, tf.data-path parity, lifecycle.

The spec (rt1_tpu/data/feeder.py): the batch stream is a function of
(seed, epoch, batch-index) only — thread count and timing must not change a
single byte — finite epochs exhaust exactly, and close() stops promptly
from any state. Batch content parity with the existing loaders is pinned
against `WindowedEpisodeDataset.numpy_batches` (same windows, same padding,
same labels) and, with augmentation on, via the packed cache's crop-parity
guarantees (tests/test_packed_cache.py).
"""

import itertools

import numpy as np
import pytest

from rt1_tpu.data import episodes as ep_lib
from rt1_tpu.data import pack as pack_lib
from rt1_tpu.data.feeder import SampleAheadFeeder
from rt1_tpu.data.pipeline import WindowedEpisodeDataset

SRC_H, SRC_W = 24, 40
H, W = 16, 28
WINDOW = 3


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("feeder_corpus")
    rng = np.random.default_rng(0)
    paths = []
    for i in range(4):
        p = str(tmp / f"episode_{i}.npz")
        ep_lib.save_episode(
            p,
            ep_lib.generate_synthetic_episode(
                rng, num_steps=6, height=SRC_H, width=SRC_W
            ),
        )
        paths.append(p)
    return paths


def _cache(tmp_path_factory, paths, crop_factor):
    out = str(tmp_path_factory.mktemp("packed"))
    pack_lib.pack_episodes(paths, out, H, W, crop_factor)
    return pack_lib.PackedEpisodeCache(out, window=WINDOW)


@pytest.fixture(scope="module")
def cache(tmp_path_factory, corpus):
    return _cache(tmp_path_factory, corpus, 0.95)


@pytest.fixture(scope="module")
def cache_nocrop(tmp_path_factory, corpus):
    return _cache(tmp_path_factory, corpus, None)


def _batches_equal(a, b):
    np.testing.assert_array_equal(
        a["observations"]["image"], b["observations"]["image"]
    )
    np.testing.assert_array_equal(
        a["observations"]["natural_language_embedding"],
        b["observations"]["natural_language_embedding"],
    )
    np.testing.assert_array_equal(
        a["actions"]["terminate_episode"], b["actions"]["terminate_episode"]
    )
    np.testing.assert_array_equal(a["actions"]["action"], b["actions"]["action"])


def test_feeder_shapes_and_dtypes(cache):
    with SampleAheadFeeder(cache, 4, seed=0) as f:
        batch = next(f)
    img = batch["observations"]["image"]
    assert img.shape == (4, WINDOW, H, W, 3) and img.dtype == np.uint8
    assert batch["observations"]["natural_language_embedding"].shape == (4, WINDOW, 512)
    assert batch["actions"]["terminate_episode"].shape == (4, WINDOW)
    assert batch["actions"]["action"].shape == (4, WINDOW, 2)


def test_feeder_deterministic_across_thread_counts(cache):
    """1 thread == 3 threads, batch for batch — assembly parallelism is
    invisible in the stream."""
    streams = []
    for n_threads in (1, 3):
        with SampleAheadFeeder(
            cache, 4, seed=7, num_epochs=2, num_threads=n_threads
        ) as f:
            streams.append(list(f))
    assert len(streams[0]) == len(streams[1]) > 0
    for a, b in zip(*streams):
        _batches_equal(a, b)


def test_feeder_restart_reproduces_stream(cache):
    with SampleAheadFeeder(cache, 4, seed=3, num_epochs=1) as f:
        first = list(f)
    with SampleAheadFeeder(cache, 4, seed=3, num_epochs=1) as f:
        again = list(f)
    for a, b in zip(first, again):
        _batches_equal(a, b)


def test_feeder_seed_changes_stream(cache):
    with SampleAheadFeeder(cache, 4, seed=1, num_epochs=1) as f:
        a = next(f)
    with SampleAheadFeeder(cache, 4, seed=2, num_epochs=1) as f:
        b = next(f)
    assert not np.array_equal(
        a["observations"]["image"], b["observations"]["image"]
    )


def test_feeder_exhaustion_count(cache):
    n_windows = len(cache)
    batch = 4
    epochs = 3
    with SampleAheadFeeder(cache, batch, seed=0, num_epochs=epochs) as f:
        got = sum(1 for _ in f)
    assert got == (n_windows // batch) * epochs
    # Exhausted for good — StopIteration, not a hang.
    assert list(itertools.islice(f, 2)) == []


def test_feeder_close_midstream_and_joins(cache):
    f = SampleAheadFeeder(cache, 4, seed=0, num_threads=2, depth=1)
    next(f)
    f.close()
    assert list(itertools.islice(f, 2)) == []
    for t in f._threads:
        assert not t.is_alive()
    f.close()  # idempotent


def test_feeder_worker_error_surfaces_on_consumer(cache, monkeypatch):
    """A dying worker must raise on the train loop's thread, not strand it
    in an eternal queue wait."""
    boom = ValueError("frames.bin ate itself")

    def explode(*a, **k):
        raise boom

    monkeypatch.setattr(cache, "fill_batch", explode)
    f = SampleAheadFeeder(cache, 4, seed=0, num_threads=2)
    with pytest.raises(RuntimeError, match="feeder worker failed") as ei:
        next(f)
    assert ei.value.__cause__ is boom
    f.close()


def test_feeder_close_without_consuming(cache):
    """close() with full queues and nothing consumed must not deadlock."""
    f = SampleAheadFeeder(cache, 4, seed=0, num_threads=2, depth=1)
    import time

    time.sleep(0.2)  # let workers fill their queues
    f.close()
    for t in f._threads:
        assert not t.is_alive()


def test_feeder_process_sharding_partitions_windows(cache):
    """Two process shards see disjoint windows covering the full epoch."""
    seen = []
    for pi in (0, 1):
        with SampleAheadFeeder(
            cache, 2, seed=5, shuffle=False, num_epochs=1,
            process_index=pi, process_count=2,
        ) as f:
            n = sum(1 for _ in f)
        order = f._epoch_order(0)
        seen.append(set(order.tolist()))
        assert n == f.batches_per_epoch
    assert seen[0].isdisjoint(seen[1])
    assert seen[0] | seen[1] == set(range(len(cache)))


def test_feeder_rejects_oversized_batch(cache):
    with pytest.raises(ValueError, match="exceeds"):
        SampleAheadFeeder(cache, len(cache) + 1, start=False)


def test_feeder_matches_numpy_loader_without_augmentation(corpus, cache_nocrop):
    """crop_factor None: the feeder's batches equal the existing numpy
    loader's byte-for-byte (same windows, same padding, same labels; images
    resized once by the same backend) — content parity with the tf.data
    family under a fixed (here: absent) augmentation draw."""
    ds = WindowedEpisodeDataset(
        corpus, window=WINDOW, crop_factor=None, height=H, width=W
    )
    want = list(
        itertools.islice(ds.numpy_batches(4, shuffle=False, num_epochs=1), 3)
    )
    with SampleAheadFeeder(
        cache_nocrop, 4, seed=0, shuffle=False, num_epochs=1
    ) as f:
        got = list(itertools.islice(f, 3))
    for a, b in zip(got, want):
        _batches_equal(a, b)


def test_train_dataset_batches_packed_switch(tmp_path, corpus):
    """train.dataset_batches honors data.packed_cache: fresh cache feeds
    through the feeder; missing cache falls back to the tf.data path."""
    jax = pytest.importorskip("jax")
    del jax
    from rt1_tpu.train.configs import tiny
    from rt1_tpu.train.train import dataset_batches

    import os
    import shutil

    data_dir = str(tmp_path / "store")
    os.makedirs(os.path.join(data_dir, "train"))
    for p in corpus:
        shutil.copy(p, os.path.join(data_dir, "train", os.path.basename(p)))
    paths = sorted(
        os.path.join(data_dir, "train", f)
        for f in os.listdir(os.path.join(data_dir, "train"))
    )

    config = tiny.get_config()
    with config.unlocked():
        config.data.data_dir = data_dir
        config.data.packed_cache = True
        config.per_host_batch_size = 2
    # No pack built yet -> falls back (tf.data path still yields batches).
    it = dataset_batches(config, "train")
    assert not isinstance(it, SampleAheadFeeder)

    pack_lib.pack_episodes(
        paths,
        pack_lib.default_pack_dir(data_dir, "train"),
        config.data.height,
        config.data.width,
        config.data.crop_factor,
    )
    it = dataset_batches(config, "train")
    assert isinstance(it, SampleAheadFeeder)
    batch = next(it)
    assert batch["observations"]["image"].shape == (
        2,
        config.model.time_sequence_length,
        config.data.height,
        config.data.width,
        3,
    )
    it.close()
