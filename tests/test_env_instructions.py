"""Instruction-set parity tests.

Mirrors reference `language_table/environments/rewards/instructions_test.py:
25-36`: the combined six-family instruction count per block mode is an exact
constant. Any drift in the grammar tables breaks this.
"""

import numpy as np
import pytest

from rt1_tpu.envs import blocks, language
from rt1_tpu.envs import rewards as rewards_module


@pytest.mark.parametrize(
    "mode,expected",
    [
        (blocks.BlockMode.BLOCK_4, 12652),
        (blocks.BlockMode.BLOCK_8, 30264),
        (blocks.BlockMode.N_CHOOSE_K, 80368),
    ],
)
def test_instruction_counts(mode, expected):
    assert len(rewards_module.generate_all_instructions(mode)) == expected


def test_vocab_size_positive():
    assert rewards_module.vocab_size(blocks.BlockMode.BLOCK_4) > 50


def test_block_synonyms_unique_color_and_shape():
    on_table = list(blocks.FIXED_4)
    syns = language.block_synonyms("red_moon", on_table)
    # All colors/shapes unique on the 4-block board: 3 ways to refer.
    assert syns == ["red block", "moon", "red moon"]


def test_block_synonyms_ambiguous():
    on_table = list(blocks.FIXED_8)
    syns = language.block_synonyms("red_moon", on_table)
    # Two reds and two moons on the 8-block board: only 'red moon' is valid.
    assert syns == ["red moon"]


def test_n_choose_k_split_sizes():
    total = len(blocks.TRAIN_COMBINATIONS) + len(blocks.TEST_COMBINATIONS)
    import math

    expected = sum(math.comb(16, k) for k in range(4, 11))
    assert total == expected
    assert len(blocks.TRAIN_COMBINATIONS) == int(total * 0.9)


def test_n_choose_k_split_deterministic():
    # The seeded shuffle must be reproducible across runs.
    train2, test2 = blocks._n_choose_k_combinations()
    assert train2[:5] == blocks.TRAIN_COMBINATIONS[:5]
    assert test2[:5] == blocks.TEST_COMBINATIONS[:5]


def test_block2block_relative_task_ids():
    from rt1_tpu.envs.rewards import block2block_relative as b2br

    assert b2br.NUM_UNIQUE_TASKS == 16 * 16 * 8
    # Stable sorted mapping.
    assert (
        b2br.UNIQUE_TASK_STRINGS["blue_cube-blue_cube-diagonal_down_left"]
        < b2br.NUM_UNIQUE_TASKS
    )


def test_instruction_grammar_spot_checks():
    insts = set(
        rewards_module.generate_all_instructions(blocks.BlockMode.BLOCK_4)
    )
    assert "push the red moon to the blue cube" in insts
    assert "point at the green star" in insts
    assert "slide the yellow pentagon to the center" in insts
    assert "separate the blue cube from the red moon" in insts
    assert "move the blue cube above the red moon" in insts
    assert "slightly push the green star up" in insts


def test_runtime_instructions_cover_all_samplers():
    """Every instruction a reward sampler emits at reset is in the runtime
    table (`generate_runtime_instructions`) — the guarantee an embedding
    table needs to never KeyError in closed-loop eval. Catches the
    enumeration/sampler verb divergences the reference carries
    (block2location + corner sample 'put the', which the 3-verb
    enumeration lacks)."""
    from rt1_tpu.envs import LanguageTable, blocks
    from rt1_tpu.envs import rewards as rewards_module

    table = set(
        rewards_module.generate_runtime_instructions(blocks.BlockMode.BLOCK_4)
    )
    families = [
        "block2block",
        "point2block",
        "block2relativelocation",
        "block2absolutelocation",
        "block2block_relative_location",
        "separate_blocks",
        "block1_to_corner",
        "play",
    ]
    for family in families:
        env = LanguageTable(
            block_mode=blocks.BlockMode.BLOCK_4,
            reward_factory=rewards_module.get_reward_factory(family),
            seed=5,
        )
        for _ in range(12):
            env.reset()
            assert env.instruction_str in table, (
                f"{family}: {env.instruction_str!r} not covered"
            )


def test_play_sampler_split_matches_runtime_table():
    """The play sampler and the runtime table must share one split constant:
    every instruction PlayReward can draw (its train split) is in the table,
    regardless of what NUM_TRAIN_PER_FAMILY is set to."""
    from rt1_tpu.envs import blocks
    from rt1_tpu.envs import rewards as rewards_module
    from rt1_tpu.envs.rewards import play

    table = set(
        rewards_module.generate_runtime_instructions(blocks.BlockMode.BLOCK_4)
    )
    sampler_pool = play.get_100_4block_instructions(
        num_train_per_family=play.NUM_TRAIN_PER_FAMILY
    )
    missing = set(sampler_pool) - table
    assert not missing, sorted(missing)[:5]


def test_runtime_superset_of_reference_enumeration():
    from rt1_tpu.envs import blocks
    from rt1_tpu.envs import rewards as rewards_module

    base = rewards_module.generate_all_instructions(blocks.BlockMode.BLOCK_4)
    runtime = rewards_module.generate_runtime_instructions(
        blocks.BlockMode.BLOCK_4
    )
    assert set(base) <= set(runtime)
    assert len(runtime) > len(base)  # the sampler-only strings exist
    assert len(base) == 12652  # reference parity untouched
