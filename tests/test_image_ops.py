"""Image preprocessing op tests (reference: film_efficientnet/preprocessors.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from rt1_tpu.ops import image as image_ops


def test_convert_dtype_uint8():
    img = jnp.full((2, 4, 4, 3), 255, jnp.uint8)
    out = image_ops.convert_dtype(img)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_random_shift_crop_shape_and_content(rng):
    b, h, w = 2, 30, 40
    img = jnp.arange(b * h * w * 3, dtype=jnp.float32).reshape(b, h, w, 3) / (b * h * w * 3)
    out = image_ops.random_shift_crop(img, rng, ratio=0.07)
    assert out.shape == img.shape
    # Every output pixel is either 0 (pad) or present in the input.
    out_np = np.asarray(out)
    in_vals = set(np.asarray(img).ravel().tolist())
    for v in out_np.ravel()[:100].tolist():
        assert v == 0.0 or v in in_vals


def test_random_shift_crop_zero_shift_identity():
    # With ratio small enough that pad = 0, crop is the identity.
    img = jnp.ones((1, 10, 10, 3))
    out = image_ops.random_shift_crop(img, jax.random.PRNGKey(0), ratio=0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(img))


def test_crop_is_jittable(rng):
    img = jnp.zeros((2, 6, 30, 40, 3))  # (b, t, h, w, c) — works with leading dims
    f = jax.jit(lambda x, r: image_ops.convert_dtype_and_crop_images(x, r))
    out = f(img, rng)
    assert out.shape == img.shape


def test_central_crop_and_resize():
    img = jnp.ones((1, 180, 320, 3))
    out = image_ops.central_crop_and_resize(img, crop_factor=0.95, height=256, width=456)
    assert out.shape == (1, 256, 456, 3)
    np.testing.assert_allclose(np.asarray(out), 1.0, atol=1e-6)
