"""Preemption coordinator: signal semantics + the loop's save-and-exit.

The contract (rt1_tpu/resilience/preempt.py + the train loop): the first
SIGTERM/SIGINT runs the dump callbacks and sets a flag; the loop then
force-saves at the current step, drains the feeder, and RETURNS (exit 0);
a relaunch resumes from that step. A second signal escalates to the
previous handler. Proven in-process and through a real subprocess.
"""

import json
import os
import signal
import subprocess
import sys
import threading

import pytest

from rt1_tpu.resilience import faults
from rt1_tpu.resilience.preempt import PreemptionCoordinator


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------------- coordinator


def test_first_signal_sets_flag_runs_callbacks_and_returns():
    ran = []
    c = PreemptionCoordinator(
        callbacks=[lambda: ran.append("dump")], signals=(signal.SIGTERM,)
    )
    assert c.install()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        # CPython delivers on the main thread at the next bytecode — we are
        # past it here, and crucially the process is still alive.
        assert c.triggered
        assert ran == ["dump"]
        assert c.signum == signal.SIGTERM
        assert c.triggered_at is not None
        assert c.counters() == {"preempt/triggered": 1.0}
    finally:
        c.uninstall()


def test_callback_exception_does_not_block_the_flag():
    def boom():
        raise RuntimeError("dump failed")

    c = PreemptionCoordinator(callbacks=[boom], signals=(signal.SIGTERM,))
    assert c.install()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert c.triggered
    finally:
        c.uninstall()


def test_second_signal_chains_to_previous_handler():
    """Escalation: the coordinator restores what was installed before it
    (here a recording handler standing in for the flight recorder's
    die-with-dump) and re-delivers the signal."""
    prev_calls = []

    def prev(signum, frame):
        prev_calls.append(signum)

    original = signal.signal(signal.SIGTERM, prev)
    try:
        c = PreemptionCoordinator(signals=(signal.SIGTERM,))
        assert c.install()
        os.kill(os.getpid(), signal.SIGTERM)
        assert c.triggered and prev_calls == []
        os.kill(os.getpid(), signal.SIGTERM)
        assert prev_calls == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, original)


def test_install_is_noop_off_main_thread():
    out = {}
    t = threading.Thread(
        target=lambda: out.update(r=PreemptionCoordinator().install())
    )
    t.start()
    t.join()
    assert out["r"] is False


def test_uninstall_restores_previous_handlers():
    def prev(signum, frame):
        pass

    original = signal.signal(signal.SIGTERM, prev)
    try:
        c = PreemptionCoordinator(signals=(signal.SIGTERM,))
        c.install()
        c.uninstall()
        assert signal.getsignal(signal.SIGTERM) is prev
    finally:
        signal.signal(signal.SIGTERM, original)


# ------------------------------------------------------------ loop, in-proc


def test_train_loop_sigterm_saves_drains_and_resumes(tmp_path):
    """In-process preemption: the sigterm fault delivers a REAL signal to
    this process; the loop saves the current step, dumps the flight
    record with reason 'preempt', and returns; a relaunch resumes to the
    full step count."""
    from rt1_tpu.train.configs import tiny
    from rt1_tpu.train.train import train_and_evaluate

    config = tiny.get_config()
    config.data.height, config.data.width = 32, 56
    config.num_steps = 10
    config.checkpoint_every_steps = 3
    config.log_every_steps = 1
    config.resilience.faults = "sigterm@5"
    workdir = str(tmp_path / "run")

    state = train_and_evaluate(config, workdir)
    assert int(state.step) == 6  # saved mid-run, not at num_steps
    assert os.path.isdir(os.path.join(workdir, "checkpoints", "6"))
    with open(os.path.join(workdir, "flight_record.jsonl")) as f:
        header = json.loads(f.readline())["flight_recorder"]
    assert header["reason"] == "preempt"

    config.resilience.faults = ""
    state2 = train_and_evaluate(config, workdir)
    assert int(state2.step) == 10
    assert os.path.isdir(os.path.join(workdir, "checkpoints", "10"))


# --------------------------------------------------------- loop, subprocess


def test_sigterm_subprocess_exits_zero_with_checkpoint(tmp_path):
    """The whole-process contract: a preempted training subprocess exits
    0 (the scheduler sees a clean shutdown, not a crash) having saved a
    resumable checkpoint."""
    workdir = str(tmp_path / "sub")
    code = (
        "import sys\n"
        "from rt1_tpu.train.configs import tiny\n"
        "from rt1_tpu.train.train import train_and_evaluate\n"
        "config = tiny.get_config()\n"
        "config.data.height, config.data.width = 32, 56\n"
        "config.num_steps = 50\n"
        "config.checkpoint_every_steps = 10\n"
        "config.log_every_steps = 1\n"
        "train_and_evaluate(config, sys.argv[1])\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RT1_FAULTS"] = "sigterm@3"
    proc = subprocess.run(
        [sys.executable, "-c", code, workdir],
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "resilience: preemption signal" in proc.stderr
    ckpts = os.listdir(os.path.join(workdir, "checkpoints"))
    assert "4" in ckpts  # saved at sigterm step + 1, far short of 50
    assert "50" not in ckpts
