"""SPMD trainer tests on the 8-device virtual CPU mesh.

What the reference never had (SURVEY.md §4 "Distributed testing: none"): multi-
device parity tests asserting the sharded pjit loss/updates equal single-device
ones — run here on `--xla_force_host_platform_device_count=8`, which exercises the
same GSPMD partitioner and collective lowering as a real TPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rt1_tpu.parallel import MeshConfig, make_mesh, rt1_parameter_rules, shard_pytree
from rt1_tpu.trainer import create_train_state, make_optimizer, make_train_step_fns, multistep_lr

from test_rt1 import tiny_policy, make_batch, T


def _setup(mesh, accum_steps=1, batch=8):
    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=batch)
    tx = make_optimizer(learning_rate=1e-3)
    state = create_train_state(model, rng, (obs, actions), tx)
    fns = make_train_step_fns(model, mesh, state, accum_steps=accum_steps)
    return model, fns, fns.shard_state(state), fns.shard_batch((obs, actions))


def test_multistep_lr_schedule():
    sched = multistep_lr(5e-4, milestones=[50, 75, 90], gamma=0.1, steps_per_epoch=10)
    assert np.isclose(sched(0), 5e-4)
    assert np.isclose(sched(499), 5e-4)
    assert np.isclose(sched(500), 5e-5)
    assert np.isclose(sched(750), 5e-6)
    assert np.isclose(sched(900), 5e-7)


def test_mesh_shapes():
    mesh = make_mesh(MeshConfig())
    assert mesh.shape == {
        "data": 8, "stage": 1, "fsdp": 1, "seq": 1, "model": 1
    }
    mesh = make_mesh(MeshConfig(model=4))
    assert mesh.shape == {
        "data": 2, "stage": 1, "fsdp": 1, "seq": 1, "model": 4
    }
    mesh = make_mesh(MeshConfig(fsdp=2, model=2))
    assert mesh.shape == {
        "data": 2, "stage": 1, "fsdp": 2, "seq": 1, "model": 2
    }
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(data=3, model=3))


def test_train_step_runs_and_learns():
    mesh = make_mesh(MeshConfig())  # pure DP over 8 devices
    model, fns, state, batch = _setup(mesh)
    rng = jax.random.PRNGKey(1)
    losses = []
    for i in range(5):
        state, metrics = fns.train_step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(metrics["loss"]))
    assert int(state.step) == 5
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # overfits a fixed batch


def test_dp_loss_equals_single_device():
    """8-way sharded loss == single-device loss on the same batch/params."""
    mesh8 = make_mesh(MeshConfig())
    mesh1 = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])

    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=8)
    tx = make_optimizer()
    state = create_train_state(model, rng, (obs, actions), tx)

    out = {}
    for name, mesh in [("dp8", mesh8), ("single", mesh1)]:
        fns = make_train_step_fns(model, mesh, state, donate=False)
        s = fns.shard_state(state)
        b = fns.shard_batch((obs, actions))
        new_state, metrics = fns.train_step(s, b, jax.random.PRNGKey(7))
        out[name] = (float(metrics["loss"]), new_state)

    np.testing.assert_allclose(out["dp8"][0], out["single"][0], rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        ),
        out["dp8"][1].params,
        out["single"][1].params,
    )


def test_tp_loss_equals_dp():
    """data=2 × model=4 tensor-parallel step == pure-DP step (same math, new layout)."""
    mesh_tp = make_mesh(MeshConfig(data=2, model=4))
    mesh_dp = make_mesh(MeshConfig())

    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=8)
    tx = make_optimizer()
    state = create_train_state(model, rng, (obs, actions), tx)

    results = {}
    for name, mesh in [("tp", mesh_tp), ("dp", mesh_dp)]:
        fns = make_train_step_fns(model, mesh, state, donate=False)
        s = fns.shard_state(state)
        b = fns.shard_batch((obs, actions))
        _, metrics = fns.train_step(s, b, jax.random.PRNGKey(3))
        results[name] = float(metrics["loss"])
    np.testing.assert_allclose(results["tp"], results["dp"], rtol=1e-5)


def test_param_sharding_rules_hit_transformer():
    model = tiny_policy()
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=2)
    variables = model.init({"params": rng, "crop": rng}, obs, actions, train=False)
    mesh = make_mesh(MeshConfig(data=2, model=4))
    sh = shard_pytree(variables["params"], mesh, rt1_parameter_rules())
    qk = sh["transformer"]["layer_0"]["attn"]["query"]["kernel"]
    assert qk.spec == jax.sharding.PartitionSpec("fsdp", "model")
    # The plan covers the WHOLE tree: every weight matrix (rank >= 2)
    # matches an explicit rule — nothing falls through to silent
    # replication (the plan-coverage guarantee, parallel/plan.py).
    from rt1_tpu.parallel import ShardingPlan

    plan = ShardingPlan(mesh=mesh)
    assert plan.coverage(variables["params"]) == []


def test_grad_accumulation_matches_full_batch():
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    # crop_ratio=0 → fully deterministic forward; with augmentation on, micro-
    # batches draw different crop rngs than the full batch and exact equality
    # cannot hold (nor does it need to).
    model = tiny_policy(crop_ratio=0.0)
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=8)
    tx = make_optimizer()
    state = create_train_state(model, rng, (obs, actions), tx)

    fns1 = make_train_step_fns(model, mesh, state, accum_steps=1, donate=False)
    fns4 = make_train_step_fns(model, mesh, state, accum_steps=4, donate=False)
    s1 = fns1.shard_state(state)
    s4 = fns4.shard_state(state)
    b = fns1.shard_batch((obs, actions))
    ns1, m1 = fns1.train_step(s1, b, jax.random.PRNGKey(5))
    ns4, m4 = fns4.train_step(s4, b, jax.random.PRNGKey(5))

    # Deterministic forward + loss a mean over independent examples → identical
    # update (incl. the reference-loss-scaling /accum correction, train.py).
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        ),
        ns1.params,
        ns4.params,
    )


def test_grad_accumulation_exact_with_aux_mse():
    """Accumulation exactness must survive aux_mse_weight > 0: the aux term
    shares the reference CE normalizer (∝ 1/(b·t·(I+A))), so the trainer's
    /accum correction applies to the whole loss, and the aux_mse metric is
    reported from the accumulated path too."""
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    model = tiny_policy(crop_ratio=0.0, aux_mse_weight=5.0)
    rng = jax.random.PRNGKey(0)
    obs, actions = make_batch(rng, b=8)
    tx = make_optimizer()
    state = create_train_state(model, rng, (obs, actions), tx)

    fns1 = make_train_step_fns(model, mesh, state, accum_steps=1, donate=False)
    fns4 = make_train_step_fns(model, mesh, state, accum_steps=4, donate=False)
    b = fns1.shard_batch((obs, actions))
    ns1, m1 = fns1.train_step(fns1.shard_state(state), b, jax.random.PRNGKey(5))
    ns4, m4 = fns4.train_step(fns4.shard_state(state), b, jax.random.PRNGKey(5))

    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-5)
    assert "aux_mse" in m1 and "aux_mse" in m4
    np.testing.assert_allclose(
        float(m1["aux_mse"]), float(m4["aux_mse"]), rtol=1e-4
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        ),
        ns1.params,
        ns4.params,
    )


def test_eval_step_metrics():
    mesh = make_mesh(MeshConfig())
    model, fns, state, batch = _setup(mesh)
    metrics = fns.eval_step(state, batch)
    assert set(metrics) >= {"loss", "token_accuracy"}
    assert 0.0 <= float(metrics["token_accuracy"]) <= 1.0


def test_write_hparams_flattens_nested_configs():
    """Regression: nested config blocks (config.data, config.obs, ...) were
    silently dropped by the top-level scalar filter — the TB hparams table
    lost everything an operator actually tunes. Nested dicts now flatten to
    dotted keys; non-scalar leaves (tuples, None placeholders) still skip."""
    from rt1_tpu.trainer.metrics import flatten_hparams, write_hparams

    config = {
        "learning_rate": 5e-4,
        "seed": 42,
        "lr_milestones": (50, 75, 90),  # non-scalar: skipped
        "data": {
            "height": 256,
            "packed_cache": True,
            "packed_cache_dir": None,  # placeholder: skipped
        },
        "obs": {"model_health": True, "prometheus_host": "127.0.0.1"},
        "model": {"lava": {"d_model": 128}},
    }
    flat = flatten_hparams(config)
    assert flat == {
        "learning_rate": 5e-4,
        "seed": 42,
        "data.height": 256,
        "data.packed_cache": True,
        "obs.model_health": True,
        "obs.prometheus_host": "127.0.0.1",
        "model.lava.d_model": 128,
    }

    class FakeWriter:
        def write_hparams(self, hparams):
            self.hparams = hparams

    writer = FakeWriter()
    write_hparams(writer, config)
    assert writer.hparams == flat
