"""scripts/run_report.py: post-mortem rendering pinned on canned artifacts.

A golden-ish contract: given a known goodput summary and flight-recorder
dump, the report's load-bearing lines (bucket rows, badput narrative,
flight tail, health gauges) must come out exactly — an operator reads
this under pressure, so format drift is a regression, not cosmetics.
"""

import json
import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts")
)
import run_report  # noqa: E402

from rt1_tpu.obs.goodput import GoodputLedger  # noqa: E402
from rt1_tpu.obs.recorder import FlightRecorder  # noqa: E402


def _canned_workdir(tmp_path):
    """A workdir as a preempted, once-rolled-back run would leave it."""
    wd = tmp_path / "run"
    wd.mkdir()

    clock = {"t": 0.0}

    def fake_clock():
        return clock["t"]

    led = GoodputLedger(clock=fake_clock)
    with led.phase("init"):
        clock["t"] += 8.0
        led.note_io("ckpt_restore", 2.0)
    clock["t"] += 20.0
    led.note_step({"total_ms": 20_000.0, "wait_data_ms": 0.0, "h2d_ms": 0.0})
    for _ in range(10):
        clock["t"] += 1.0
        led.note_step(
            {"total_ms": 1000.0, "wait_data_ms": 200.0, "h2d_ms": 50.0}
        )
    led.mark_rollback()
    for _ in range(4):
        clock["t"] += 1.0
        led.note_step({"total_ms": 1000.0}, replay=True)
    led.note_io("ckpt_save", 3.0)
    clock["t"] += 3.0
    led.mark_preempted()
    with led.phase("preempt_drain"):
        clock["t"] += 2.0
    led.set_flops_per_step(1.5e9, peak_flops=197e12, n_chips=1)
    led.write_summary(str(wd / "goodput_summary.json"))

    rec = FlightRecorder(capacity=8, path=str(wd / "flight_record.jsonl"))
    for step in range(30, 42):
        rec.record(
            step,
            total_ms=31.25,
            stall_pct=12.5,
            **({"loss": 2.5 - step * 0.01} if step % 2 == 0 else {}),
        )
    rec.record(
        42,
        total_ms=31.25,
        stall_pct=12.5,
        loss=2.08,
        health={"health/logit_entropy": 2.4587, "health/token_acc/dim0": 0.25},
        guard={"guard/device_skips_total": 1.0, "guard/rollbacks_total": 1.0},
    )
    rec.dump(reason="preempt")
    return str(wd)


def test_report_golden_sections(tmp_path):
    wd = _canned_workdir(tmp_path)
    goodput = run_report.load_goodput(wd)
    flight = run_report.load_flight(wd)
    report = run_report.render_report(wd, goodput, flight, tb=None, tail=4)
    lines = report.splitlines()

    # Goodput table rows: fixed-width bucket lines with shares.
    # Wall: 8 init + 20 compile + 10 productive + 4 replay + 3 between-
    # steps save + 2 drain = 47 s, every second attributed.
    assert "Wall time: 47.0 s" in report
    row = next(ln for ln in lines if ln.startswith("init"))
    assert row.startswith("init                  6.00   12.8%")
    assert "model/dataset/state setup" in row
    row = next(ln for ln in lines if ln.startswith("rollback_replay"))
    assert "4.00" in row and "steps re-run after guard rollback" in row
    assert any(
        ln.startswith("step") and "GOODPUT" in ln for ln in lines
    )
    # Narrative: goodput%, MFU, events.
    assert "Goodput 16.0% / badput 84.0% of wall time." in report
    assert "MFU" in report and "1.5e+09 FLOPs/step" in report
    assert "1 rollback(s), 4 step(s) replayed" in report
    assert "PREEMPTED" in report

    # Flight tail: capacity 8 with 13 records -> 8 retained, tail of 4.
    assert "Dump reason: preempt — 8 of 13 recorded steps retained." in report
    assert "      42      31.2    12.5        2.08" in report
    assert "      39      31.2    12.5           -" in report
    # Health gauges embedded in the final record surface in the report.
    assert "health/logit_entropy" in report and "2.4587" in report
    assert "Guard at the end: 1 device skips, 1 rollbacks." in report

    # TB-less degradation is a note, not a crash.
    assert "No TensorBoard events readable" in report


def test_report_all_sources_missing(tmp_path):
    wd = str(tmp_path / "empty")
    os.makedirs(wd)
    report = run_report.render_report(
        wd,
        run_report.load_goodput(wd),
        run_report.load_flight(wd),
        run_report.load_tb_scalars(wd),
    )
    assert "goodput_summary.json not found" in report
    assert "flight_record.jsonl not found" in report


def test_main_writes_out_file(tmp_path, capsys):
    wd = _canned_workdir(tmp_path)
    out = str(tmp_path / "report.md")
    run_report.main(["--workdir", wd, "--out", out])
    with open(out) as f:
        text = f.read()
    assert text.startswith(f"# RT-1 run report — {wd}")
    # stdout stays clean when --out is given (stderr gets the note).
    assert "Where the hours went" not in capsys.readouterr().out


def test_goodput_fractions_always_renderable(tmp_path):
    """A summary whose fractions were hand-edited out of range must not
    crash the bar renderer (clamped, not asserted)."""
    wd = tmp_path / "run"
    wd.mkdir()
    summary = {
        "wall_s": 10.0,
        "buckets_s": {b: 0.0 for b in run_report._BUCKET_NOTES},
        "fractions": {b: 0.0 for b in run_report._BUCKET_NOTES},
        "goodput_pct": 0.0,
        "badput_pct": 100.0,
        "steps_productive": 0,
        "steps_replayed": 0,
        "rollbacks": 0,
        "preempted": False,
    }
    summary["fractions"]["step"] = 1.7  # corrupt
    with open(wd / "goodput_summary.json", "w") as f:
        json.dump(summary, f)
    report = run_report.render_report(
        str(wd), run_report.load_goodput(str(wd)), None, None
    )
    assert "170.0%" in report  # reported honestly, bar clamped


def test_bar_rendering_bounds():
    assert run_report._bar(0.0) == "." * 30
    assert run_report._bar(100.0) == "#" * 30
    assert run_report._bar(250.0) == "#" * 30
    assert len(run_report._bar(33.3)) == 30


def _canned_serve_workdir(tmp_path):
    """A workdir as a fleet-3 chaos loadgen run leaves it: SLO summary,
    BENCH record, and a slow-request exemplar dump."""
    from rt1_tpu.obs.recorder import ExemplarRing
    from rt1_tpu.obs.slo import SLOLedger, SLOObjectives

    wd = tmp_path / "serve-run"
    wd.mkdir()
    ledger = SLOLedger(SLOObjectives(availability=0.99))
    for _ in range(996):
        ledger.observe("ok", 0.012)
    ledger.observe("restarted", 0.150)
    ledger.observe("restarted", 0.200)
    ledger.observe("rejected", 0.001)
    ledger.observe("failed", 0.0)
    ledger.write_summary(str(wd / "slo_summary.json"))

    bench = {
        "metric": "serve_requests_per_sec",
        "value": 93.5,
        "unit": "req/s",
        "requests_ok": 996,
        "requests_restarted": 2,
        "requests_rejected": 1,
        "requests_failed": 1,
        "fleet_replicas": 3,
        "faults": "replica_kill@1,serve_reload@2",
        "replica_restarts_total": 1,
        "replica_compile_counts": [1, 1, 1],
        "replicas_ready_at_end": 3,
    }
    with open(wd / "BENCH_serve_fleet.json", "w") as f:
        json.dump(bench, f)

    ring = ExemplarRing(capacity=8, threshold_ms=50.0)
    ring.offer(
        151.2,
        request_id="slowest-one",
        session="s3",
        outcome="restarted",
        phases={"queue_wait_ms": 80.0, "device_ms": 60.0},
    )
    ring.offer(
        72.0,
        request_id="also-slow",
        session="s1",
        outcome="ok",
        phases={"queue_wait_ms": 40.0, "device_ms": 30.0},
    )
    ring.dump(str(wd / "slow_requests.jsonl"), reason="supervisor_scrape")
    return str(wd)


def test_serve_postmortem_section(tmp_path):
    """The serve post-mortem: SLO verdict + outcome table + fleet/chaos
    evidence + slowest exemplars, merged from the serving artifacts."""
    wd = _canned_serve_workdir(tmp_path)
    serve = run_report.load_serve(wd)
    assert serve is not None
    report = run_report.render_report(wd, None, None, None, serve=serve)

    assert "## Serve post-mortem (SLO ledger)" in report
    # Verdict numbers: 996/1000 ok -> 99.6% availability vs 99% objective
    # -> 40% of the error budget burned; SLO met.
    assert "Availability 99.600%" in report
    assert "error budget burned 40.0%" in report
    assert "Objectives: availability >= 0.99" in report
    assert "SLO met." in report
    # Outcome table rows with per-class budget burn.
    lines = report.splitlines()
    ok_row = next(ln for ln in lines if ln.startswith("ok "))
    assert "996" in ok_row
    restarted_row = next(ln for ln in lines if ln.startswith("restarted"))
    assert "2" in restarted_row and "20.0%" in restarted_row
    # Fleet/chaos evidence from the BENCH record.
    assert "Loadgen: 93.5 req/s — 996 ok, 2 restarted, 1 rejected," in report
    assert "Fleet: 3 replicas" in report
    assert "replica_kill@1,serve_reload@2" in report
    assert "compile counts [1, 1, 1]" in report
    # Exemplars: slowest first, with phase columns.
    assert "Slow-request exemplars: 2 retained" in report
    assert "(threshold 50.0 ms" in report
    slowest = next(ln for ln in lines if ln.startswith("slowest-one"))
    also = next(ln for ln in lines if ln.startswith("also-slow"))
    assert lines.index(slowest) < lines.index(also)
    assert "151.20" in slowest and "80.00" in slowest and "60.00" in slowest
    assert slowest.rstrip().endswith("restarted")


def test_serve_quant_bench_renders_dtype_table(tmp_path):
    """ISSUE 9 satellite: BENCH_serve_quant.json folds into the serve
    post-mortem as a per-dtype latency/parity/bytes table next to the SLO
    verdict, honesty note included."""
    wd = _canned_serve_workdir(tmp_path)
    quant = {
        "metric": "serve_param_bytes_reduction_int8",
        "value": 3.71,
        "unit": "x",
        "per_dtype": {
            "f32": {
                "req_per_sec": 100.2, "latency_p50_ms": 66.1,
                "latency_p99_ms": 219.9, "requests_failed": 0,
                "param_bytes_device": 50528,
                "parity": {"agreement": 1.0},
            },
            "int8": {
                "req_per_sec": 150.6, "latency_p50_ms": 48.1,
                "latency_p99_ms": 92.3, "requests_failed": 0,
                "param_bytes_device": 29208,
                "parity": {"agreement": 0.997},
            },
        },
        "honesty_note": "XLA:CPU lacks native int8 matmul",
    }
    with open(os.path.join(wd, "BENCH_serve_quant.json"), "w") as f:
        json.dump(quant, f)
    serve = run_report.load_serve(wd)
    assert serve["quant_bench"]["value"] == 3.71
    report = run_report.render_report(wd, None, None, None, serve=serve)
    assert "int8 param-byte reduction 3.71x" in report
    lines = report.splitlines()
    f32_row = next(ln for ln in lines if ln.startswith("f32 "))
    int8_row = next(ln for ln in lines if ln.startswith("int8 "))
    assert "66.10" in f32_row and "100.0%" in f32_row
    assert "48.10" in int8_row and "99.7%" in int8_row
    assert "0.029" in int8_row  # device MB column
    assert "Note: XLA:CPU lacks native int8 matmul" in report
    # The SLO verdict still leads the section — the dtype table rides it.
    assert report.index("SLO met.") < report.index("int8 param-byte")


def test_serve_elastic_bench_renders_timeline_and_cost(tmp_path):
    """ISSUE 15: a BENCH_serve_elastic.json in the workdir renders as the
    per-phase A/B table, the scale-event timeline, and the cost-per-
    request comparison (with the p99-envelope verdict); a workdir without
    one keeps its report elastic-free."""
    wd = _canned_serve_workdir(tmp_path)
    elastic = {
        "metric": "serve_elastic_cost_ratio_fixed_over_elastic",
        "value": 2.004,
        "unit": "x",
        "headline_schedule": "diurnal",
        "schedules": ["diurnal"],
        "min_replicas": 1,
        "max_replicas": 3,
        "surge_dtype": "int8",
        "requests_failed": 0,
        "p99_peak_phase": {
            "diurnal": {
                "elastic_ms": 43.2,
                "fixed_max_ms": 46.0,
                "envelope_factor": 1.5,
                "within_envelope": True,
            }
        },
        "cost_per_request": {
            "diurnal": {"elastic": 0.010016, "fixed_max": 0.02007}
        },
        "sides": {
            "elastic": {
                "diurnal": {
                    "phases": [
                        {
                            "phase": "night", "clients": 2,
                            "req_per_sec": 67.5, "latency_p50_ms": 15.8,
                            "latency_p99_ms": 28.4, "requests_rejected": 0,
                            "requests_failed": 0, "replicas_after": 1,
                        },
                        {
                            "phase": "midday", "clients": 10,
                            "req_per_sec": 255.7, "latency_p50_ms": 26.4,
                            "latency_p99_ms": 43.2, "requests_rejected": 3,
                            "requests_failed": 0, "replicas_after": 3,
                        },
                    ],
                    "scale_events": [
                        {
                            "t_s": 4.6, "direction": "up",
                            "replica_id": 1, "dtype": "int8",
                            "reason": "occupancy 1.75 >= 0.75",
                        },
                        {
                            "t_s": 18.4, "direction": "down",
                            "replica_id": 1, "dtype": "int8",
                            "reason": "occupancy 0.17 <= 0.30 for 4 ticks",
                        },
                    ],
                    "replica_seconds_by_dtype": {
                        "f32": 18.9, "int8": 27.3
                    },
                }
            },
            "fixed_max": {
                "diurnal": {
                    "phases": [
                        {
                            "phase": "night", "clients": 2,
                            "req_per_sec": 74.7, "latency_p50_ms": 14.6,
                            "latency_p99_ms": 20.5, "requests_rejected": 0,
                            "requests_failed": 0, "replicas_after": 3,
                        },
                    ],
                    "replica_seconds_by_dtype": {"f32": 57.1},
                }
            },
        },
    }
    with open(os.path.join(wd, "BENCH_serve_elastic.json"), "w") as f:
        json.dump(elastic, f)
    serve = run_report.load_serve(wd)
    assert serve["elastic_bench"]["value"] == 2.004
    report = run_report.render_report(wd, None, None, None, serve=serve)
    assert (
        "cost-per-request ratio fixed-max/elastic 2.004x on the diurnal "
        "schedule" in report
    )
    assert "1..3 replicas, surge dtype int8, 0 failed requests" in report
    lines = report.splitlines()
    # Per-phase rows for both sides, replicas column included.
    midday = next(
        ln for ln in lines if "elastic" in ln and "midday" in ln
    )
    assert "255.7" in midday and midday.rstrip().endswith("3")
    night_fixed = next(
        ln for ln in lines if "fixed_max" in ln and "night" in ln
    )
    assert night_fixed.rstrip().endswith("3")
    # The scale-event timeline, up and down, with dtype + reason.
    assert (
        "t=    4.6s up    replica 1 (int8): occupancy 1.75 >= 0.75"
        in report
    )
    assert "t=   18.4s down  replica 1 (int8)" in report
    # Cost + envelope verdicts.
    assert (
        "Cost/request (byte-weighted replica-seconds): elastic 0.010016 "
        "vs fixed-max 0.02007" in report
    )
    assert (
        "Peak-phase p99: elastic 43.2 ms vs fixed-max 46.0 ms — within "
        "the 1.5x envelope." in report
    )
    # A workdir without the record keeps its report elastic-free.
    bare = run_report.render_report(
        wd, None, None, None,
        serve={"slo": serve["slo"]},
    )
    assert "Elastic fleet" not in bare


def test_serve_migration_bench_renders_event_table(tmp_path):
    """ISSUE 19: a BENCH_serve_migration.json in the workdir renders as
    the per-event durable-vs-legacy outcome table with the window-reset
    verdict and migration counters; a workdir without one keeps its
    report migration-free."""
    wd = _canned_serve_workdir(tmp_path)
    migration = {
        "metric": "serve_migration_window_resets",
        "value": 0,
        "unit": "resets",
        "fleet_replicas": 3,
        "events": ["kill", "drain", "rolling_reload", "rebalance"],
        "zero_window_resets": True,
        "legacy_window_resets": 3,
        "token_identical_continuations": True,
        "requests_failed": 0,
        "compile_pinned_at_bucket_count": True,
        "sides": {
            "durable": {
                "durable": True,
                "events": [
                    {"event": "warmup", "ok": 8, "migrated": 0,
                     "restarted": 0, "rejected": 0, "failed": 0,
                     "window_resets": 0, "continuity_ok": 8},
                    {"event": "kill", "ok": 5, "migrated": 3,
                     "restarted": 0, "rejected": 0, "failed": 0,
                     "window_resets": 0, "continuity_ok": 8},
                    {"event": "drain", "ok": 4, "migrated": 4,
                     "restarted": 0, "rejected": 0, "failed": 0,
                     "window_resets": 0, "continuity_ok": 8},
                    {"event": "rolling_reload", "ok": 4, "migrated": 4,
                     "restarted": 0, "rejected": 0, "failed": 0,
                     "window_resets": 0, "continuity_ok": 8},
                    {"event": "rebalance", "ok": 6, "migrated": 2,
                     "restarted": 0, "rejected": 0, "failed": 0,
                     "window_resets": 0, "continuity_ok": 8},
                ],
                "migration_counters": {
                    "migration_exports_total": 6,
                    "migration_imports_total": 10,
                    "migration_import_failures_total": 0,
                    "migration_restores_total": 1,
                    "migration_restore_failures_total": 0,
                },
            },
            "legacy": {
                "durable": False,
                "events": [
                    {"event": "kill", "ok": 5, "migrated": 0,
                     "restarted": 3, "rejected": 0, "failed": 0,
                     "window_resets": 3, "continuity_ok": 5},
                ],
                "migration_counters": {
                    "migration_exports_total": 6,
                    "migration_imports_total": 10,
                    "migration_import_failures_total": 0,
                    "migration_restores_total": 0,
                    "migration_restore_failures_total": 0,
                },
            },
        },
    }
    with open(os.path.join(wd, "BENCH_serve_migration.json"), "w") as f:
        json.dump(migration, f)
    serve = run_report.load_serve(wd)
    assert serve["migration_bench"]["value"] == 0
    report = run_report.render_report(wd, None, None, None, serve=serve)
    assert (
        "0 window reset(s) on the durable side vs 3 legacy" in report
    )
    assert "kill/drain/rolling_reload/rebalance gauntlet" in report
    assert "Continuations token-identical: yes" in report
    assert "compile pinned at bucket count: yes" in report
    lines = report.splitlines()
    # Per-event rows for both sides — the warmup row stays out of the
    # table (it is load, not a disruption).
    durable_kill = next(
        ln for ln in lines if "[durable]" in ln or (
            ln.strip().startswith("kill") and "3" in ln
        )
    )
    assert durable_kill is not None
    kill_rows = [ln for ln in lines if ln.strip().startswith("kill ")]
    assert len(kill_rows) == 2  # one per side
    assert not any("warmup" in ln for ln in lines)
    assert "ring restores 1 (0 failed)." in report
    assert "ring restores 0 (0 failed)." in report
    # A workdir without the record keeps its report migration-free.
    bare = run_report.render_report(
        wd, None, None, None, serve={"slo": serve["slo"]}
    )
    assert "Durable sessions" not in bare


def test_eval_matrix_section_renders_table(tmp_path):
    """ISSUE 13: a BENCH_eval_matrix.json in the workdir renders as a
    task × checkpoint success table (plus the oracle-fill note); a
    workdir without one keeps its report matrix-free."""
    wd = tmp_path / "run"
    wd.mkdir()
    record = {
        "bench": "eval_matrix",
        "unit": "mean_cell_success_rate",
        "value": 0.45,
        "tasks": ["block2block", "block1_to_corner"],
        "checkpoints": ["1950", "3900"],
        "episodes_per_cell": 5,
        "max_episode_steps": 80,
        "backend": "kinematic",
        "matrix": {
            "block2block": {
                "1950": {"successes": 2, "episodes": 5,
                         "success_rate": 0.4, "mean_episode_length": 61.0},
                "3900": {"successes": 4, "episodes": 5,
                         "success_rate": 0.8, "mean_episode_length": 48.0},
            },
            "block1_to_corner": {
                "1950": {"successes": 0, "episodes": 5,
                         "success_rate": 0.0, "mean_episode_length": 80.0},
                # 3900 cell absent: renders as '-', not a crash.
            },
        },
        "oracle_fill": {
            "episodes_appended": 8,
            "episodes_per_task": {"block1_to_corner": 8},
            "shards_after": 2,
            "freshness_epoch": 1,
        },
    }
    with open(wd / "BENCH_eval_matrix.json", "w") as f:
        json.dump(record, f)

    loaded = run_report.load_eval_matrix(str(wd))
    assert loaded is not None
    report = run_report.render_report(
        str(wd), None, None, None, eval_matrix=loaded
    )
    assert "Eval matrix (task × checkpoint success)" in report
    assert "2 task(s) × 2 checkpoint(s)" in report
    assert "mean cell success 0.450" in report
    assert "ckpt 1950" in report and "ckpt 3900" in report
    assert "4/5 (0.80)" in report
    assert "0/5 (0.00)" in report
    # The missing cell renders as '-'.
    corner_row = next(
        line for line in report.splitlines()
        if line.startswith("block1_to_corner")
    )
    assert corner_row.rstrip().endswith("-")
    assert "Oracle corpus fill: 8 episodes appended" in report
    # Absent record -> no matrix section at all.
    plain = run_report.render_report(str(wd), None, None, None)
    assert "Eval matrix" not in plain
    # A half-written record degrades to None, not a crash.
    with open(wd / "BENCH_eval_matrix.json", "w") as f:
        f.write('{"bench": "eval_ma')
    assert run_report.load_eval_matrix(str(wd)) is None


def _canned_multichip(wd):
    record = {
        "bench": "multihost_scaling",
        "groups": {
            "1proc": {
                "processes": 1, "devices_global": 2, "global_batch": 4,
                "mesh": {"data": 2, "fsdp": 1, "model": 1},
                "steps_per_sec": 240.8, "examples_per_sec": 963.2,
                "mfu_pct": 0.000127, "per_host_data_stall_pct": [1.7],
            },
            "2proc": {
                "processes": 2, "devices_global": 4, "global_batch": 8,
                "mesh": {"data": 2, "fsdp": 2, "model": 1},
                "steps_per_sec": 4.6, "examples_per_sec": 36.8,
                "mfu_pct": 2.4e-06,
                "per_host_data_stall_pct": [0.1, 0.2],
            },
        },
        "scaling": {
            "steps_per_sec_ratio_2p_over_1p": 0.019,
            "examples_per_sec_ratio_2p_over_1p": 0.038,
        },
        "methodology": {"caveats": "XLA:CPU gloo-over-loopback lower bound"},
    }
    with open(os.path.join(wd, "MULTICHIP_r06.json"), "w") as f:
        json.dump(record, f)
    return record


def test_multichip_section_renders_beside_goodput(tmp_path):
    """ISSUE 14 satellite: the MULTICHIP scale-out record renders right
    after the goodput section — per-topology steps/s + MFU + per-host
    data-stall, the weak-scaling ratio, and the record's own caveats."""
    wd = _canned_workdir(tmp_path)
    _canned_multichip(wd)
    record = run_report.load_multichip(wd)
    assert record is not None
    report = run_report.render_report(
        wd,
        run_report.load_goodput(wd),
        run_report.load_flight(wd),
        None,
        multichip=record,
    )
    assert "## Multi-host scaling (MULTICHIP record)" in report
    # Beside the goodput section: goodput first, scaling right after.
    assert report.index("Where the hours went") < report.index(
        "Multi-host scaling"
    ) < report.index("Flight recorder")
    assert "1proc" in report and "2proc" in report
    lines = report.splitlines()
    row = next(l for l in lines if l.startswith("2proc"))
    assert "4.60" in row  # steps/s
    assert "[0.1, 0.2]" in row  # per-host data-stall
    assert "examples/s x0.038" in report
    assert "gloo-over-loopback lower bound" in report


def test_multichip_loader_ignores_foreign_records(tmp_path):
    """Pre-ISSUE-14 MULTICHIP rounds (dryrun leg matrices) have no
    throughput table — the loader returns None instead of rendering a
    broken section; so do torn/invalid files."""
    wd = tmp_path / "run"
    wd.mkdir()
    with open(wd / "MULTICHIP_r05.json", "w") as f:
        json.dump({"dryrun_multichip": 8, "legs": {"pp": "ok"}}, f)
    assert run_report.load_multichip(str(wd)) is None
    with open(wd / "MULTICHIP_r07.json", "w") as f:
        f.write('{"bench": "multihost_sc')
    assert run_report.load_multichip(str(wd)) is None
    # An EXPLICITLY named path fails loudly instead of degrading to the
    # "no record found" note — the operator typed it.
    with pytest.raises(ValueError, match="unreadable"):
        run_report.load_multichip(str(wd), str(wd / "nope.json"))
    with pytest.raises(ValueError, match="not a multihost_scaling"):
        run_report.load_multichip(str(wd), str(wd / "MULTICHIP_r05.json"))


def test_serve_section_absent_for_training_only_run(tmp_path):
    """A pure training workdir renders NO serve section — the golden
    training report stays byte-stable."""
    wd = _canned_workdir(tmp_path)
    assert run_report.load_serve(wd) is None
    report = run_report.render_report(
        wd, run_report.load_goodput(wd), run_report.load_flight(wd), None
    )
    assert "Serve post-mortem" not in report


def test_slo_violation_renders_loudly(tmp_path):
    """An out-of-objective run must say so, naming the violated axis."""
    from rt1_tpu.obs.slo import SLOLedger, SLOObjectives

    wd = tmp_path / "bad-run"
    wd.mkdir()
    ledger = SLOLedger(SLOObjectives(availability=0.99))
    for _ in range(90):
        ledger.observe("ok", 0.010)
    for _ in range(10):
        ledger.observe("failed", 0.0)
    ledger.write_summary(str(wd / "slo_summary.json"))
    report = run_report.render_report(
        str(wd), None, None, None, serve=run_report.load_serve(str(wd))
    )
    assert "SLO VIOLATED — availability outside objective." in report


def test_main_renders_serve_section(tmp_path, capsys):
    wd = _canned_serve_workdir(tmp_path)
    run_report.main(["--workdir", wd])
    out = capsys.readouterr().out
    assert "Serve post-mortem" in out
    assert "Availability 99.600%" in out


def _canned_deploy_workdir(tmp_path):
    """A workdir as scripts/deploy_loop.py leaves it: one promoted and
    one rolled-back fleet episode in BENCH_deploy.json."""
    wd = tmp_path / "deploy-run"
    wd.mkdir()
    record = {
        "bench": "deploy_e2e",
        "verdict": "deploy_cycle_proven",
        "total_seconds": 812.4,
        "config": {"gate_tasks": "block2block"},
        "promote": {
            "episode": "promote",
            "faults": None,
            "final_deploy": {
                "incumbent_step": 4,
                "promotions_total": 1,
                "rollbacks_total": 0,
            },
            "timeline": [
                {"tick": 3, "event": "candidate", "step": 4, "incumbent": 2},
                {"tick": 3, "event": "gate_passed", "step": 4},
                {"tick": 3, "event": "canary_started", "step": 4,
                 "replica": 1, "weight": 0.5},
                {"tick": 9, "event": "promoted", "step": 4,
                 "previous_incumbent": 2, "replicas": 2},
            ],
            "traffic": {
                "requests_ok": 1480, "failures": [], "restarts": [],
                "sessions_created": 31,
            },
            "post_sweep_restarted": [],
            "verdicts": [
                {"path": "deploy/verdict_4.json", "candidate_step": 4,
                 "incumbent_step": 2, "passed": True, "signature_ok": True},
            ],
        },
        "rollback": {
            "episode": "rollback",
            "faults": "canary_slo_breach@4",
            "final_deploy": {
                "incumbent_step": 4,
                "promotions_total": 0,
                "rollbacks_total": 1,
            },
            "timeline": [
                {"tick": 2, "event": "candidate", "step": 6, "incumbent": 4},
                {"tick": 2, "event": "gate_passed", "step": 6},
                {"tick": 2, "event": "canary_started", "step": 6,
                 "replica": 1, "weight": 0.5},
                {"tick": 8, "event": "rolled_back", "step": 6, "replica": 1,
                 "reason": "slo_breach_injected", "incumbent": 4},
            ],
            "traffic": {
                "requests_ok": 960,
                "failures": [],
                "restarts": [{"session": "probe-9", "unix_time": 1.0}],
                "sessions_created": 22,
            },
            "post_sweep_restarted": ["probe-11"],
            "verdicts": [
                {"path": "deploy/verdict_6.json", "candidate_step": 6,
                 "incumbent_step": 4, "passed": True, "signature_ok": True},
            ],
        },
    }
    with open(wd / "BENCH_deploy.json", "w") as f:
        json.dump(record, f)
    return str(wd)


def test_deploy_section_renders_timeline_and_verdicts(tmp_path):
    """ISSUE 16 satellite: BENCH_deploy.json renders as the promotion
    timeline + signed-verdict table, ahead of the serve post-mortem."""
    wd = _canned_deploy_workdir(tmp_path)
    deploy = run_report.load_deploy(wd)
    assert deploy is not None
    report = run_report.render_report(wd, None, None, None, deploy=deploy)

    assert "## Deployment (promotion controller)" in report
    assert (
        "Verdict 'deploy_cycle_proven' in 812.4 s (2 fleet episode(s), "
        "gate tasks 'block2block')." in report
    )
    lines = report.splitlines()
    # Both episodes, each with its headline and timeline rows.
    promote_hdr = next(ln for ln in lines if ln.startswith("[promote]"))
    assert "faults=none" in promote_hdr
    assert "incumbent 4, 1 promotion(s), 0 rollback(s)." in promote_hdr
    rollback_hdr = next(ln for ln in lines if ln.startswith("[rollback]"))
    assert "faults=canary_slo_breach@4" in rollback_hdr
    assert "0 promotion(s), 1 rollback(s)." in rollback_hdr
    assert (
        "  tick    3  canary_started    step=4 replica=1 weight=0.5"
        in lines
    )
    assert (
        "  tick    9  promoted          step=4 previous_incumbent=2 "
        "replicas=2" in lines
    )
    rolled = next(
        ln for ln in lines if "rolled_back" in ln and "tick" in ln
    )
    assert "reason=slo_breach_injected" in rolled
    # Traffic honesty: re-homed count folds live restarts + post sweep.
    promote_traffic = next(
        ln for ln in lines if "1480 ok" in ln
    )
    assert "0 failed, 0 re-homed" in promote_traffic
    rollback_traffic = next(ln for ln in lines if "960 ok" in ln)
    assert "2 re-homed (restarted: true)" in rollback_traffic
    # The signed-verdict table.
    v4 = next(ln for ln in lines if ln.startswith("deploy/verdict_4.json"))
    assert "ok" in v4 and "True" in v4
    assert any(ln.startswith("deploy/verdict_6.json") for ln in lines)


def test_deploy_section_absent_without_record(tmp_path):
    """A workdir with no BENCH_deploy.json renders no deployment section
    — the golden training report stays byte-stable."""
    wd = _canned_workdir(tmp_path)
    assert run_report.load_deploy(wd) is None
    report = run_report.render_report(
        wd, run_report.load_goodput(wd), run_report.load_flight(wd), None
    )
    assert "Deployment (promotion controller)" not in report


def test_deploy_loader_tolerates_torn_record(tmp_path):
    wd = tmp_path / "torn"
    wd.mkdir()
    (wd / "BENCH_deploy.json").write_text('{"bench": "deploy_e2e", ')
    assert run_report.load_deploy(str(wd)) is None


# -------------------------------------------------- alerts & history


def _canned_obs_workdir(tmp_path):
    """A workdir as an armed `fleet --collector` run leaves it: a TSDB
    snapshot holding serve/deploy history plus the scraped-back
    rt1_alert_* families from a ReplicaDown incident."""
    from rt1_tpu.obs.tsdb import SNAPSHOT_BASENAME, TSDB

    wd = tmp_path / "obsrun"
    wd.mkdir()
    clock = {"t": 1000.0}
    db = TSDB(clock=lambda: clock["t"])
    for cycle in range(10):
        down = 3 <= cycle < 7  # replica 1 dead for scrape cycles 3..6
        db.append_many(
            [
                ("rt1_serve_replica_up", {"replica_id": "0"}, 1.0),
                (
                    "rt1_serve_replica_up",
                    {"replica_id": "1"},
                    0.0 if down else 1.0,
                ),
                ("rt1_serve_slo_requests_total", None, 10.0 * (cycle + 1)),
                (
                    "rt1_serve_slo_error_budget_burn_rolling",
                    None,
                    25.0 if down else 0.0,
                ),
                ("rt1_alert_fired_total", None, 1.0 if cycle >= 3 else 0.0),
                (
                    "rt1_alert_resolved_total",
                    None,
                    1.0 if cycle >= 7 else 0.0,
                ),
                ("rt1_obs_collector_cycles_total", None, float(cycle + 1)),
            ],
            t=clock["t"],
        )
        if down:
            db.append(
                "rt1_alert_firing",
                1.0,
                labels={
                    "alert": "ReplicaDown",
                    "severity": "page",
                    "replica_id": "1",
                },
                t=clock["t"],
            )
        clock["t"] += 2.0
    db.write_snapshot(str(wd / SNAPSHOT_BASENAME))
    return str(wd)


def test_obs_section_golden(tmp_path):
    wd = _canned_obs_workdir(tmp_path)
    obs = run_report.load_obs(wd)
    assert obs is not None
    report = run_report.render_report(wd, None, None, None, obs=obs)
    lines = report.splitlines()
    assert "## Alerts & history (metrics plane)" in lines

    # The snapshot header line names the file and its bounds.
    snap_line = next(ln for ln in lines if ln.startswith("Snapshot "))
    assert "8 series" in snap_line and "74 points" in snap_line

    # The alert timeline reconstructs the incident span from the series:
    # firing at cycles 3..6 = 6 seconds of scrape coverage, with the
    # instance labels and lifecycle counters intact.
    assert any(
        "fired_total=1" in ln and "resolved_total=1" in ln for ln in lines
    )
    incident = next(ln for ln in lines if "ReplicaDown" in ln)
    assert "[page]" in incident
    assert "firing" in incident
    assert "seen    6.0s" in incident
    assert "replica_id=1" in incident

    # Key signals render as sparklines with the last value, labeled
    # instances fanned out.
    assert any(
        "rt1_serve_replica_up{replica_id=1}" in ln and ln.endswith(" 1")
        for ln in lines
    )
    burn = next(
        ln
        for ln in lines
        if "rt1_serve_slo_error_budget_burn_rolling" in ln
        and "Key signals" not in ln
    )
    assert burn.endswith(" 0")  # decayed back by the last scrape
    # The non-spark families are counted, not silently dropped.
    assert any("more stored series" in ln for ln in lines)


def test_obs_section_absent_without_snapshot(tmp_path):
    """A training-only workdir renders no metrics-plane section at all:
    the golden training report stays byte-stable."""
    wd = _canned_workdir(tmp_path)
    assert run_report.load_obs(wd) is None
    report = run_report.render_report(
        wd, run_report.load_goodput(wd), run_report.load_flight(wd), None
    )
    assert "Alerts & history" not in report


def test_obs_loader_tolerates_torn_snapshot(tmp_path):
    """A SIGKILLed collector's half-written snapshot still loads (torn
    tail dropped) — the post-mortem exists exactly for that run."""
    wd = _canned_obs_workdir(tmp_path)
    from rt1_tpu.obs.tsdb import SNAPSHOT_BASENAME

    path = os.path.join(wd, SNAPSHOT_BASENAME)
    body = open(path).read().rstrip("\n")
    with open(path, "w") as f:
        f.write(body[:-20])
    obs = run_report.load_obs(wd)
    assert obs is not None
    report = run_report.render_report(wd, None, None, None, obs=obs)
    assert "## Alerts & history (metrics plane)" in report
