"""DAgger corrective relabeling (rt1_tpu/data/dagger.py; VERDICT r3 #4).

The collector is the round-3 diagnostics rollout (policy acts, oracle
queried per-step on the same states) plus recording in the standard
episode format; these tests pin the label/execution split, the episode
format contract, and the manifest bookkeeping after aggregation.
"""

import json
import os

import numpy as np
import pytest

from rt1_tpu.data.collect import read_manifest, write_manifest
from rt1_tpu.data.dagger import (
    DAGGER_HISTORY_KEYS,
    append_episodes_to_corpus,
    collect_dagger_episode,
)
from rt1_tpu.data.episodes import load_episode
from rt1_tpu.envs import blocks
from rt1_tpu.envs.oracles import RRTPushOracle
from rt1_tpu.eval.evaluate import build_eval_env


class ConstantPolicy:
    """The measured copycat failure mode: a near-constant tiny action."""

    def __init__(self, action=(0.004, 0.0)):
        self._action = np.asarray(action, np.float32)
        self.calls = 0

    def reset(self):
        pass

    def action(self, observation):
        assert "rgb_sequence" in observation  # the policy-facing view
        self.calls += 1
        return self._action


def _dagger_env(seed=7):
    return build_eval_env(
        reward_name="block2block",
        block_mode=blocks.BlockMode.BLOCK_4,
        seed=seed,
        embedder="hash",
        target_height=32,
        target_width=56,
        sequence_length=2,
        history_keys=DAGGER_HISTORY_KEYS,
    )


def test_collect_dagger_episode_labels_are_oracle_not_executed():
    env = _dagger_env()
    oracle = RRTPushOracle(env, use_ee_planner=True)
    policy = ConstantPolicy()
    episode = None
    for _ in range(5):  # init validation can re-randomize
        episode, success = collect_dagger_episode(
            env, policy, oracle, max_steps=10
        )
        if episode is not None:
            break
    assert episode is not None
    t = episode["action"].shape[0]
    assert 0 < t <= 10
    # The POLICY drove every step...
    assert policy.calls == t
    # ...but the recorded labels are the oracle's corrective actions, not
    # the constant executed action (the whole point of relabeling).
    assert episode["action"].shape == (t, 2)
    assert episode["action"].dtype == np.float32
    assert not np.allclose(episode["action"], policy._action)
    assert np.all(np.isfinite(episode["action"]))
    # Standard episode-format contract (matches collect_episode).
    assert episode["rgb"].dtype == np.uint8
    assert episode["rgb"].shape[0] == t
    assert episode["rgb"].shape[1:] != (32, 56, 3)  # native, not policy-view
    assert episode["instruction"].shape == (t, 512)
    # Same embedding every step (instruction fixed within an episode).
    assert np.allclose(episode["instruction"][0], episode["instruction"][-1])
    assert episode["is_first"].tolist() == [True] + [False] * (t - 1)
    # is_terminal is the terminate_episode ACTION LABEL downstream, so it
    # must be honest: a constant near-zero policy cannot have finished the
    # task in 10 steps — a forced end-of-horizon terminal would teach the
    # policy to emit terminate=1 mid-task on every failed rollout.
    assert not success
    assert not episode["is_terminal"].any()
    # encode_instruction_text yields a uint8 byte array (episodes.py).
    assert episode["instruction_text"].dtype == np.uint8
    assert episode["instruction_text"].size > 0


def test_collect_dagger_beta_one_executes_oracle():
    """beta=1.0 degenerates to oracle execution: the policy is still
    *queried* per step (it must see on-policy obs in mixed rollouts) but
    never drives; with the expert driving, a solvable init makes progress
    the constant policy never does."""
    env = _dagger_env(seed=11)
    oracle = RRTPushOracle(env, use_ee_planner=True)
    policy = ConstantPolicy()
    rng = np.random.default_rng(0)
    episode = None
    for _ in range(5):
        episode, success = collect_dagger_episode(
            env, policy, oracle, max_steps=80, beta=1.0, rng=rng
        )
        if episode is not None:
            break
    assert episode is not None
    # With the oracle executing its own plan, labels == executed actions,
    # and the rollout must not sit still: the effector moved.
    assert float(np.abs(episode["action"]).max()) > 1e-4
    # The policy was QUERIED at every step even though it never drove
    # (ADVICE r4): RT1EvalPolicy advances its rolling network_state only
    # inside action(), so a gapped query stream would condition later
    # actions on a stale temporal window unlike eval-time execution.
    assert policy.calls == episode["action"].shape[0]


def test_collect_dagger_beta_requires_rng():
    env = _dagger_env()
    oracle = RRTPushOracle(env, use_ee_planner=True)
    with pytest.raises(ValueError, match="rng"):
        collect_dagger_episode(env, ConstantPolicy(), oracle, beta=0.5)


def test_append_episodes_to_corpus_bookkeeping(tmp_path):
    data_dir = str(tmp_path / "data")
    os.makedirs(os.path.join(data_dir, "train"))
    # Pre-existing corpus: 2 episodes + manifest truth.
    for i in range(2):
        with open(
            os.path.join(data_dir, "train", f"episode_{i}.npz"), "wb"
        ) as f:
            f.write(b"x")
    write_manifest(data_dir, episodes=2, embedder="hash", seed=0)

    def fake_episode(k):
        return {
            "action": np.zeros((3, 2), np.float32),
            "is_first": np.array([True, False, False]),
            "is_terminal": np.array([False, False, True]),
            "rgb": np.full((3, 4, 6, 3), k, np.uint8),
            "instruction": np.zeros((3, 512), np.float32),
            "instruction_text": b"push it",
        }

    total = append_episodes_to_corpus(
        data_dir, [fake_episode(1), fake_episode(2)]
    )
    assert total == 4
    names = sorted(os.listdir(os.path.join(data_dir, "train")))
    assert "episode_2.npz" in names and "episode_3.npz" in names
    manifest = read_manifest(data_dir)
    assert manifest["episodes"] == 4
    assert manifest["dagger_episodes"] == 2
    assert manifest["embedder"] == "hash"  # stamps untouched
    # Appended episodes are loadable by the standard reader.
    ep = load_episode(os.path.join(data_dir, "train", "episode_3.npz"))
    assert ep["rgb"].shape == (3, 4, 6, 3)
    # Second aggregation keeps counting.
    total = append_episodes_to_corpus(data_dir, [fake_episode(3)])
    assert total == 5
    assert read_manifest(data_dir)["dagger_episodes"] == 3


def test_append_reconciles_orphans_from_crashed_aggregation(tmp_path):
    """ADVICE r4: a kill between episode writes and the manifest update
    leaves orphan episode files the manifest never counted. The next
    successful aggregation must absorb them (manifest == disk) instead of
    letting accounting silently diverge."""
    data_dir = str(tmp_path / "data")
    os.makedirs(os.path.join(data_dir, "train"))
    for i in range(2):
        with open(
            os.path.join(data_dir, "train", f"episode_{i}.npz"), "wb"
        ) as f:
            f.write(b"x")
    write_manifest(data_dir, episodes=2, embedder="hash", seed=0)
    # Simulate the crash artifact: two orphan episodes on disk, manifest
    # still says 2.
    for i in (2, 3):
        with open(
            os.path.join(data_dir, "train", f"episode_{i}.npz"), "wb"
        ) as f:
            f.write(b"x")

    episode = {
        "action": np.zeros((3, 2), np.float32),
        "is_first": np.array([True, False, False]),
        "is_terminal": np.array([False, False, True]),
        "rgb": np.zeros((3, 4, 6, 3), np.uint8),
        "instruction": np.zeros((3, 512), np.float32),
        "instruction_text": b"push it",
    }
    total = append_episodes_to_corpus(data_dir, [episode])
    assert total == 5  # numbering continued after the orphans
    manifest = read_manifest(data_dir)
    assert manifest["episodes"] == 5  # disk truth, orphans included
    assert manifest["collected_episodes"] == 2
    assert manifest["dagger_episodes"] == 3  # 2 orphans + 1 appended
    # No staging dir left behind.
    assert not [
        d for d in os.listdir(os.path.join(data_dir, "train"))
        if d.startswith(".dagger_stage")
    ]


def test_append_requires_manifest(tmp_path):
    data_dir = str(tmp_path / "bare")
    os.makedirs(os.path.join(data_dir, "train"))
    with pytest.raises(FileNotFoundError, match="manifest"):
        append_episodes_to_corpus(data_dir, [])
