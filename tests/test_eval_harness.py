"""Eval-harness tests: wrappers, embedders, jitted policy, full protocol.

The protocol test runs the real `evaluate_policy` loop end to end with a
tiny RT-1 model (random weights) on the kinematic backend — the same code
path as real checkpoint evaluation, shrunken.
"""

import numpy as np
import pytest

from rt1_tpu.envs import LanguageTable, blocks, constants
from rt1_tpu.envs.rewards import BlockToBlockReward
from rt1_tpu.eval import (
    CentralCropImageWrapper,
    HashInstructionEmbedder,
    HistoryWrapper,
    InstructionEmbeddingWrapper,
    RT1EvalPolicy,
    TableInstructionEmbedder,
    evaluate_policy,
)


def test_hash_embedder_deterministic_unit_norm():
    e = HashInstructionEmbedder()
    v1 = e("push the red moon to the blue cube")
    v2 = HashInstructionEmbedder()("push the red moon to the blue cube")
    np.testing.assert_array_equal(v1, v2)
    assert v1.shape == (512,)
    assert abs(np.linalg.norm(v1) - 1.0) < 1e-5
    assert not np.allclose(v1, e("a different instruction"))


def test_table_embedder_roundtrip(tmp_path):
    insts = ["push the red moon to the blue cube", "point at the star"]
    hash_e = HashInstructionEmbedder()
    path = str(tmp_path / "table.npz")
    TableInstructionEmbedder.build(insts, hash_e, path=path)
    table_e = TableInstructionEmbedder(path)
    np.testing.assert_array_equal(table_e(insts[0]), hash_e(insts[0]))
    with pytest.raises(KeyError):
        table_e("unknown instruction")


def _wrapped_env(seed=0, seq_len=3, h=64, w=114):
    env = LanguageTable(
        block_mode=blocks.BlockMode.BLOCK_4,
        reward_factory=BlockToBlockReward,
        seed=seed,
    )
    env = InstructionEmbeddingWrapper(env, HashInstructionEmbedder())
    env = CentralCropImageWrapper(
        env, target_height=h, target_width=w, random_crop_factor=0.95
    )
    return HistoryWrapper(
        env,
        history_length=seq_len,
        keys=("rgb_sequence", "natural_language_embedding"),
    )


def test_wrapper_chain_shapes():
    env = _wrapped_env()
    obs = env.reset()
    assert obs["rgb_sequence"].shape == (3, 64, 114, 3)
    assert obs["rgb_sequence"].dtype == np.float32
    assert obs["rgb_sequence"].max() <= 1.0
    assert obs["natural_language_embedding"].shape == (3, 512)
    # tile_first_step_obs: all history rows identical at reset.
    np.testing.assert_array_equal(
        obs["rgb_sequence"][0], obs["rgb_sequence"][-1]
    )
    obs2, _, _, _ = env.step(np.array([0.01, 0.01]))
    assert obs2["rgb_sequence"].shape == (3, 64, 114, 3)
    # history rolls: last row differs from first after motion.
    assert not np.array_equal(obs2["rgb_sequence"][0], obs2["rgb_sequence"][-1])


def test_embedding_constant_within_episode():
    env = _wrapped_env()
    obs = env.reset()
    e0 = obs["natural_language_embedding"][-1].copy()
    obs, _, _, _ = env.step(np.array([0.02, 0.0]))
    np.testing.assert_array_equal(obs["natural_language_embedding"][-1], e0)


@pytest.fixture(scope="module")
def tiny_policy_setup():
    import jax

    from tests.test_rt1 import tiny_policy

    model = tiny_policy(time_sequence_length=3)
    rng = jax.random.PRNGKey(0)
    obs = {
        "image": np.zeros((1, 3, 64, 114, 3), np.float32),
        "natural_language_embedding": np.zeros((1, 3, 512), np.float32),
    }
    from rt1_tpu.specs import language_table_action_space, sample_space

    actions = sample_space(
        language_table_action_space(), jax.random.fold_in(rng, 1), (1, 3)
    )
    variables = model.init({"params": rng, "crop": rng}, obs, actions, train=False)
    return model, variables


def test_eval_policy_action_bounds(tiny_policy_setup):
    model, variables = tiny_policy_setup
    policy = RT1EvalPolicy(model, variables)
    env = _wrapped_env()
    obs = env.reset()
    for _ in range(4):
        action = policy.action(obs)
        assert action.shape == (2,)
        assert (np.abs(action) <= 0.03 + 1e-9).all()
        obs, _, _, _ = env.step(action)
    assert int(policy.network_state["seq_idx"]) == 3  # saturates at T


def test_full_protocol_tiny(tiny_policy_setup):
    model, variables = tiny_policy_setup
    policy = RT1EvalPolicy(model, variables)
    results = evaluate_policy(
        policy,
        reward_names=("block2block",),
        num_evals_per_reward=2,
        max_episode_steps=5,
        block_mode=blocks.BlockMode.BLOCK_4,
        seed=0,
        env_kwargs=dict(
            target_height=64, target_width=114, sequence_length=3
        ),
    )
    assert "block2block" in results["successes"]
    assert 0 <= results["successes"]["block2block"] <= 2
    assert results["episodes_per_reward"] == 2


def test_eval_matrix_sweep_and_record(tiny_policy_setup, tmp_path):
    """ISSUE 13 tentpole: run_matrix sweeps (policy × task) cells through
    the closed-loop protocol, the state renders live rt1_eval_* gauges
    mid-sweep, and matrix_record emits the BENCH shape run_report reads."""
    from rt1_tpu.eval import matrix as matrix_lib

    model, variables = tiny_policy_setup
    policy = RT1EvalPolicy(model, variables)
    seen = []
    state = matrix_lib.run_matrix(
        [("42", policy)],
        ("block2block", "block1_to_corner"),
        episodes_per_cell=1,
        max_episode_steps=4,
        block_mode="BLOCK_4",
        seed=0,
        env_kwargs=dict(
            target_height=64, target_width=114, sequence_length=3
        ),
        progress=lambda task, label, cell: seen.append((task, label)),
    )
    assert seen == [("block2block", "42"), ("block1_to_corner", "42")]
    matrix = state.matrix()
    assert set(matrix) == {"block2block", "block1_to_corner"}
    for row in matrix.values():
        cell = row["42"]
        assert cell["episodes"] == 1
        assert 0.0 <= cell["success_rate"] <= 1.0
    text = state.render_prometheus()
    assert 'rt1_eval_episodes_total{task="block2block",checkpoint="42"} 1' in text
    record = matrix_lib.matrix_record(
        state,
        episodes_per_cell=1,
        max_episode_steps=4,
        seed=0,
        embedder="hash",
        backend="kinematic",
        block_mode="BLOCK_4",
        wall_seconds=1.0,
    )
    assert record["bench"] == "eval_matrix"
    assert record["checkpoints"] == ["42"]
    assert set(record["tasks"]) == {"block2block", "block1_to_corner"}
    out = str(tmp_path / "BENCH_eval_matrix.json")
    assert matrix_lib.write_record(record, out, "") == [out]
    import json

    with open(out) as f:
        assert json.load(f)["bench"] == "eval_matrix"


def test_eval_matrix_checkpoint_steps(tmp_path):
    """checkpoint_steps resolves 'all' / 'latest:N' / explicit lists from
    the on-disk step dirs, skipping Orbax tmp dirs and torn mkdirs."""
    from rt1_tpu.eval.matrix import checkpoint_steps

    ckpts = tmp_path / "run" / "checkpoints"
    for step in (2, 4, 10):
        d = ckpts / str(step)
        d.mkdir(parents=True)
        (d / "payload").write_text("x")
    (ckpts / "7.orbax-checkpoint-tmp-123").mkdir()  # in-flight write
    (ckpts / "9").mkdir()  # torn mkdir: empty, not a checkpoint
    wd = str(tmp_path / "run")
    assert checkpoint_steps(wd) == [2, 4, 10]
    assert checkpoint_steps(wd, "latest:2") == [4, 10]
    assert checkpoint_steps(wd, "4,2") == [2, 4]
    with pytest.raises(ValueError, match="not found"):
        checkpoint_steps(wd, "3")
    assert checkpoint_steps(str(tmp_path / "nowhere")) == []


def test_oracle_eval_policy_protocol():
    """The privileged expert baseline under the standard protocol: bind_env
    wiring, lazy per-episode planning, and a sanity bar — the RRT oracle
    solves most block2block episodes within 200 steps (it is the same
    policy that produced the training demos)."""
    from rt1_tpu.eval.evaluate import OracleEvalPolicy

    results = evaluate_policy(
        OracleEvalPolicy(seed=7),
        reward_names=("block2block",),
        num_evals_per_reward=3,
        max_episode_steps=200,
        block_mode=blocks.BlockMode.BLOCK_4,
        seed=7,
        env_kwargs=dict(
            target_height=64, target_width=114, sequence_length=3
        ),
    )
    assert results["successes"]["block2block"] >= 1
    assert len(results["mean_episode_length"]) == 1


def test_env_bench_mode(capsys):
    """bench.py --mode env: host-only simulator throughput, no accelerator
    claim, one parseable JSON headline — and --steps is honored (ADVICE
    r3: it used to be silently ignored in env mode)."""
    import argparse
    import json

    import bench

    bench.env_bench(argparse.Namespace(steps=1))
    headline = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert headline["metric"] == "env_control_steps_per_sec"
    assert headline["value"] > 0
    assert headline["unit"] == "steps/s"


def test_oracle_eval_policy_requires_bind():
    from rt1_tpu.eval.evaluate import OracleEvalPolicy

    with pytest.raises(RuntimeError, match="bind_env"):
        OracleEvalPolicy().reset()


def test_full_protocol_tiny_t1(tiny_policy_setup):
    """Closed-loop eval at time_sequence_length=1 — the Markovian
    mitigation arm (`scripts/learn_proof.py --seq_len 1`) must not hit a
    T=1-only eval bug hours into an unattended pipeline. Params are
    T-invariant (test_rt1.py::test_params_are_time_sequence_length_invariant),
    so the T=3 fixture's variables drive a T=1 clone directly."""
    model, variables = tiny_policy_setup
    policy = RT1EvalPolicy(model.clone(time_sequence_length=1), variables)
    results = evaluate_policy(
        policy,
        reward_names=("block2block",),
        num_evals_per_reward=1,
        max_episode_steps=5,
        block_mode=blocks.BlockMode.BLOCK_4,
        seed=0,
        env_kwargs=dict(
            target_height=64, target_width=114, sequence_length=1
        ),
    )
    assert results["episodes_per_reward"] == 1
    assert 0 <= results["successes"]["block2block"] <= 1


@pytest.mark.slow
def test_lava_eval_policy_paths():
    """LavaEvalPolicy: history slicing, clip tokenization from instruction
    bytes, action clipping (the Stack-B BCJaxPyPolicy role,
    reference eval/main.py:54-145)."""
    import jax
    import numpy as np

    from rt1_tpu.eval.policy import LavaEvalPolicy
    from rt1_tpu.models.lava import SequenceLAVMSE
    from rt1_tpu.text.clip_bpe import default_tokenizer

    tok = default_tokenizer()
    t = 2
    model = SequenceLAVMSE(
        action_size=2,
        dense_resnet_width=16,
        dense_resnet_num_blocks=1,
        lava_d_model=16,
        lava_sequence_length=t,
        lava_pyramid_fuse_layers=(2, 3, 4),
        lava_image_encoder="conv_maxpool",
        lava_lang_encoder="clip",
        text_encoder_def=None,  # default tower; vocab >= tokenizer's 514
    )
    obs_init = {
        "rgb": np.zeros((1, t, 64, 64, 3), np.float32),
        "instruction_tokenized_clip": np.zeros((1, t, 77), np.int32),
    }
    variables = model.init({"params": jax.random.PRNGKey(0)}, obs_init,
                           train=False)
    policy = LavaEvalPolicy(
        model, variables, sequence_length=t, clip_tokenizer=tok
    )
    policy.reset()

    # History longer than the model window: only the last t frames are used.
    k = 4
    instruction = np.zeros((k, 512), np.int32)
    raw = np.frombuffer(b"push the red moon", np.uint8).astype(np.int32)
    instruction[:, : raw.shape[0]] = raw
    observation = {
        "rgb_sequence": np.random.default_rng(0).random((k, 64, 64, 3)),
        "natural_language_embedding": np.zeros((k, 512), np.float32),
        "instruction": instruction,
    }
    action = policy.action(observation)
    assert action.shape == (2,)
    assert np.all(action >= -0.03) and np.all(action <= 0.03)
